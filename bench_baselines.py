"""Hand-tuned vectorized numpy implementations of the bench TPC-H
queries — the honest single-core CPU baseline.

VERDICT round 1 called out that the sqlite oracle flatters the engine
(sqlite is a single-threaded row store).  These are the strongest
straight-line numpy pipelines we can write for the same queries over the
same generated arrays (hash-free: searchsorted joins, bincount
aggregations) — closer to what a tuned columnar CPU engine (DuckDB-class
per-core) does for these shapes.  Reference for the role:
presto-benchmark/src/main/java/com/facebook/presto/benchmark/HandTpchQuery1.java
(hand-built operator pipelines as the perf yardstick).
"""

import numpy as np

from presto_tpu.connectors.tpch import _days


def _col(table, name):
    a = table.read([name])[name]
    return np.asarray(a)


def q1(tables):
    li = tables["lineitem"]
    ship = _col(li, "l_shipdate")
    m = ship <= _days("1998-09-02")
    rf = _col(li, "l_returnflag")[m]
    ls = _col(li, "l_linestatus")[m]
    qty = _col(li, "l_quantity")[m]
    px = _col(li, "l_extendedprice")[m]
    disc = _col(li, "l_discount")[m]
    tax = _col(li, "l_tax")[m]
    # group codes: returnflag/linestatus are low-cardinality strings
    rf_codes, rf_inv = np.unique(rf, return_inverse=True)
    ls_codes, ls_inv = np.unique(ls, return_inverse=True)
    gid = rf_inv * len(ls_codes) + ls_inv
    n = len(rf_codes) * len(ls_codes)
    disc_px = px * (1.0 - disc)
    out = []
    sums = {
        "qty": np.bincount(gid, qty, n),
        "base": np.bincount(gid, px, n),
        "disc": np.bincount(gid, disc_px, n),
        "charge": np.bincount(gid, disc_px * (1.0 + tax), n),
        "count": np.bincount(gid, minlength=n),
        "disc_sum": np.bincount(gid, disc, n),
    }
    for g in np.flatnonzero(sums["count"]):
        out.append((rf_codes[g // len(ls_codes)], ls_codes[g % len(ls_codes)],
                    sums["qty"][g], sums["base"][g], sums["disc"][g],
                    sums["charge"][g]))
    return out


def q6(tables):
    li = tables["lineitem"]
    ship = _col(li, "l_shipdate")
    disc = _col(li, "l_discount")
    qty = _col(li, "l_quantity")
    m = ((ship >= _days("1994-01-01")) & (ship < _days("1995-01-01"))
         & (disc >= 0.05) & (disc <= 0.07) & (qty < 24))
    return float(np.sum(_col(li, "l_extendedprice")[m] * disc[m]))


def q3(tables):
    cu, od, li = tables["customer"], tables["orders"], tables["lineitem"]
    seg = _col(cu, "c_mktsegment")
    bkeys = np.sort(_col(cu, "c_custkey")[seg == "BUILDING"])
    o_date = _col(od, "o_orderdate")
    om = o_date < _days("1995-03-15")
    o_ck = _col(od, "o_custkey")[om]
    pos = np.clip(np.searchsorted(bkeys, o_ck), 0, max(len(bkeys) - 1, 0))
    om2 = (bkeys[pos] == o_ck) if len(bkeys) else np.zeros(len(o_ck), bool)
    o_key = _col(od, "o_orderkey")[om][om2]
    o_dt = o_date[om][om2]
    o_pri = _col(od, "o_shippriority")[om][om2]
    o_order = np.argsort(o_key)
    o_key_s = o_key[o_order]
    ship = _col(li, "l_shipdate")
    lm = ship > _days("1995-03-15")
    l_ok = _col(li, "l_orderkey")[lm]
    rev = (_col(li, "l_extendedprice")[lm]
           * (1.0 - _col(li, "l_discount")[lm]))
    p = np.clip(np.searchsorted(o_key_s, l_ok), 0,
                max(len(o_key_s) - 1, 0))
    hit = (o_key_s[p] == l_ok) if len(o_key_s) \
        else np.zeros(len(l_ok), bool)
    l_ok = l_ok[hit]
    rev = rev[hit]
    p = p[hit]
    # group by matched order row (o_orderkey unique per order)
    uniq, inv = np.unique(p, return_inverse=True)
    rsum = np.bincount(inv, rev, len(uniq))
    k = min(10, len(uniq))
    # top 10 by revenue desc, date asc
    dt = o_dt[o_order][uniq]
    order = np.lexsort((dt, -rsum))[:k]
    rows = [(int(o_key_s[uniq[i]]), float(rsum[i]),
             int(dt[i]), int(o_pri[o_order][uniq[i]])) for i in order]
    return rows


def q18(tables):
    cu, od, li = tables["customer"], tables["orders"], tables["lineitem"]
    l_ok = _col(li, "l_orderkey")
    qty = _col(li, "l_quantity")
    # dense bincount over orderkey (keys are bounded by 4*orders)
    hi = int(l_ok.max()) + 1 if len(l_ok) else 1
    qsum = np.bincount(l_ok, qty, hi)
    big = np.flatnonzero(qsum > 300.0)
    o_key = _col(od, "o_orderkey")
    om = np.isin(o_key, big)
    o_key = o_key[om]
    o_ck = _col(od, "o_custkey")[om]
    o_dt = _col(od, "o_orderdate")[om]
    o_tp = _col(od, "o_totalprice")[om]
    c_key = _col(cu, "c_custkey")
    c_order = np.argsort(c_key)
    cpos = np.clip(np.searchsorted(c_key[c_order], o_ck), 0,
                   max(len(c_key) - 1, 0))
    cname = _col(cu, "c_name")[c_order][cpos]
    tq = qsum[o_key]
    order = np.lexsort((o_dt, -o_tp))[:100]
    return [(cname[i], int(o_ck[i]), int(o_key[i]), int(o_dt[i]),
             float(o_tp[i]), float(tq[i])) for i in order]


NUMPY_QUERIES = {1: q1, 3: q3, 6: q6, 18: q18}
