"""Per-query session context visible to scalar-function emitters.

The function registry's emit callbacks receive only argument ColVals
(exec/compiler.eval_expr), but a few functions depend on the session:
the session time zone (reference: ConnectorSession.getTimeZoneKey used
throughout operator/scalar/DateTimeFunctions.java) and the query start
instant (reference: session.getStartTime() — now() is per-QUERY stable,
not per-row).  The executor stamps these at query start; cluster workers
stamp them from the shipped session properties before running a
fragment, so zone-dependent expressions agree across the mesh.
"""

from __future__ import annotations

import contextvars
import time

import itertools

_TZ = contextvars.ContextVar("presto_tpu_session_tz", default="UTC")
_START_US = contextvars.ContextVar("presto_tpu_query_start_us", default=None)
_USER = contextvars.ContextVar("presto_tpu_session_user", default="user")
#: monotonically increasing per-query id (volatile-function cache nonce;
#: the start instant alone could collide within one microsecond)
_QSEQ_COUNTER = itertools.count(1)
_QSEQ = contextvars.ContextVar("presto_tpu_query_seq", default=0)
#: current expression-eval batch capacity (per-row volatile functions
#: like random() need a row count; emitters only see argument ColVals)
_BATCH_CAP = contextvars.ContextVar("presto_tpu_batch_capacity",
                                    default=None)


def current_zone() -> str:
    return _TZ.get()


def current_user() -> str:
    return _USER.get()


def query_start_us() -> int:
    v = _START_US.get()
    if v is None:  # direct emitter calls outside a query (tests)
        return int(time.time() * 1_000_000)
    return v


def query_seq() -> int:
    """Per-query nonce (see executor._volatile_nonce)."""
    return _QSEQ.get()


def batch_capacity() -> int | None:
    return _BATCH_CAP.get()


def set_batch_capacity(n: int) -> None:
    _BATCH_CAP.set(n)


def activate(session) -> None:
    """Stamp the context from a Session at query start."""
    _TZ.set(str(session.properties.get("time_zone", "UTC")))
    _START_US.set(int(time.time() * 1_000_000))
    _USER.set(str(getattr(session, "user", "user")))
    _QSEQ.set(next(_QSEQ_COUNTER))


def activate_raw(tz: str, start_us: int | None) -> None:
    """Worker-side: restore the coordinator's stamped context."""
    _TZ.set(tz or "UTC")
    if start_us is not None:
        _START_US.set(int(start_us))
