"""Serving tier: admission control, prepared statements, result cache.

Reference parity: the dispatcy layer of the reference coordinator —
dispatcher/DispatchManager + execution/resourceGroups (admission),
QueryPreparer + ParameterRewriter (prepared statements), and the query
JSON's resourceGroupId/queuedTime surface — rebuilt around this engine's
compile economics.  The reference rewrites `?` parameters to constants
during analysis and replans per EXECUTE; we keep parameters SYMBOLIC
(ir.Param) so one plan and ONE XLA executable serve every parameter
value of a given type signature: a warm EXECUTE is a registry dict hit
plus a device transfer, never a parse, plan, or compile
(exec/compile_cache.py is the executable memo underneath).

Three pieces, composable and individually optional:

- `PreparedRegistry` (per session == per server: the protocol server
  multiplexes one session): PREPARE parses + validates the template
  once; EXECUTE binds parameter values to engine types, types a
  deep-copied template per type signature, and routes through
  `run_compiled(params=...)` (compiled/auto) or a memoized dynamic plan.
  Bindings the symbolic path cannot carry — strings (device columns are
  dictionary-encoded; a traced string scalar does not exist), NULLs,
  long decimals, parameters inside subqueries (their values bake into
  the compiled program via eager subplan evaluation), static positions
  like `LIMIT ?`, volatile templates, distributed/chunked sessions —
  fall back to the classic text-substitution path, counted as
  `prepared_fallbacks` (plans then key per VALUE, exactly the
  reference's semantics).
- `AdmissionController`: the resource-group tree
  (server/resource_groups.py) behind one `admit`/`release` surface with
  queue-depth gauges, shed counters, and a drain switch graceful
  shutdown uses to cancel queued-but-not-started queries.
- `QueryCoalescer`: the admission-side micro-batcher behind query
  coalescing — concurrent EXECUTEs of the SAME prepared signature that
  arrive within `coalesce_window_ms` of each other stack their bound
  parameters into a leading batch axis and ride ONE vmap-batched XLA
  launch (exec/executor.run_compiled_batched), so one device dispatch
  serves N users.  Default `auto`: a window only opens when another
  same-signature query is already in flight, so an idle EXECUTE never
  pays the window latency.  Anything that cannot batch (substitution
  fallbacks, volatile templates, long decimals, oversized results,
  tripped guards, a faulted leader) exits the batch and runs solo —
  never a wrong result, never a stall beyond the window.
- `ResultCache`: a bounded LRU serving IDENTICAL re-submitted SELECTs
  without execution, keyed by query text x catalog token+version x the
  session property map.  Any engine write bumps the catalog version, so
  staleness is structural, not temporal; `invalidate()` is the explicit
  hook and stale-version entries are swept on store.  Volatile queries
  (now()/random()), non-SELECT statements, open-transaction sessions,
  and oversized results are never cached.

`ServingTier` composes the three for the protocol server
(server/protocol.py) and `bench.py --serve` (the closed-loop QPS
benchmark with the SERVE_r01.json record).
"""

from __future__ import annotations

import copy
import dataclasses
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from presto_tpu.server.resource_groups import (QueryRejected,
                                               ResourceGroupManager)
from presto_tpu.sql import ast

#: hard bound on memoized dynamic plans / typed templates per registry —
#: a runaway generator of distinct type signatures must not grow memory
MAX_TYPED_ENTRIES = 256


# ---------------------------------------------------------------------------
# prepared statements
# ---------------------------------------------------------------------------


class PreparedStatement:
    """One PREPARE'd template (reference: Session.preparedStatements
    value, plus the analysis the reference redoes per EXECUTE)."""

    __slots__ = ("name", "text", "n_params", "template", "subst_only",
                 "subquery_params", "param_types", "typed")

    def __init__(self, name: str, text: str):
        self.name = name
        self.text = text
        self.n_params = 0
        self.template = None  # parsed AST with ast.Parameter nodes
        self.subst_only = False  # `?` in a static position (LIMIT ?)
        self.subquery_params = False  # `?` inside a subquery
        self.param_types: List[str] = []  # inferred, for DESCRIBE INPUT
        self.typed: Dict[tuple, object] = {}  # type sig -> typed AST


class PreparedRegistry:
    """Session-and-server-level prepared-statement registry (the
    protocol server embeds ONE session, so the session registry IS the
    server registry).  Thread-safe: the protocol server binds from
    concurrent worker threads."""

    def __init__(self):
        self._stmts: Dict[str, PreparedStatement] = {}
        self._lock = threading.Lock()

    def prepare(self, session, name: str, text: str) -> PreparedStatement:
        from presto_tpu.sql.parser import ParseError, parse

        entry = PreparedStatement(name, text)
        try:
            entry.template = parse(text)
            entry.n_params = _count_ast_params(entry.template)
        except ParseError:
            # `?` in a position the grammar types statically (LIMIT ?):
            # validate by substituting a literal that parses everywhere,
            # exactly the pre-serving behaviour; EXECUTE then always
            # substitutes text (plans key per value)
            parse(text.replace("?", "0"))
            entry.subst_only = True
            entry.n_params = _count_placeholders(text)
        if entry.template is not None:
            entry.subquery_params = _params_under_subquery(entry.template)
            entry.param_types = _infer_param_types(
                session, entry.template, entry.n_params)
        else:
            entry.param_types = ["unknown"] * entry.n_params
        with self._lock:
            self._stmts[name] = entry
        return entry

    def get(self, name: str) -> Optional[PreparedStatement]:
        with self._lock:
            return self._stmts.get(name)

    def deallocate(self, name: str) -> bool:
        with self._lock:
            return self._stmts.pop(name, None) is not None

    def names(self) -> list:
        with self._lock:
            return sorted(self._stmts)


def registry_for(session) -> PreparedRegistry:
    """The session's registry, created on first use.  Mirrors into
    `session.prepared_statements` ({name: text}, the pre-serving compat
    surface) — both views always agree."""
    reg = getattr(session, "prepared_registry", None)
    if reg is None:
        reg = session.prepared_registry = PreparedRegistry()
    if not hasattr(session, "prepared_statements"):
        session.prepared_statements = {}
    # adopt entries planted directly on the compat dict
    for name, text in list(session.prepared_statements.items()):
        if reg.get(name) is None:
            reg.prepare(session, name, text)
    return reg


def prepare(session, name: str, text: str):
    reg = registry_for(session)
    entry = reg.prepare(session, name, text)
    session.prepared_statements[name] = text
    return entry


def deallocate(session, name: str) -> None:
    from presto_tpu.exec.executor import ExecutionError

    reg = registry_for(session)
    if not reg.deallocate(name):
        raise ExecutionError(f"prepared statement '{name}' not found")
    session.prepared_statements.pop(name, None)


def describe_input(session, name: str) -> list:
    """(position, type) rows for DESCRIBE INPUT: parameter types
    inferred from the template's column comparisons (reference:
    DescribeInputRewrite reporting the analyzer's parameter types)."""
    from presto_tpu.exec.executor import ExecutionError

    entry = registry_for(session).get(name)
    if entry is None:
        raise ExecutionError(f"prepared statement '{name}' not found")
    return [(i, t) for i, t in enumerate(entry.param_types)]


def execute_prepared(session, stmt: ast.Execute, mon, dispatch):
    """EXECUTE dispatch: the typed aval-abstracted path when every
    binding supports it, else classic text substitution.  `dispatch` is
    executor._dispatch_statement (fallback re-entry)."""
    from presto_tpu import types as T
    from presto_tpu.exec import compile_cache as CC
    from presto_tpu.exec import executor as EX

    entry = registry_for(session).get(stmt.name)
    if entry is None:
        raise EX.ExecutionError(
            f"prepared statement '{stmt.name}' not found")

    def fallback():
        mon.stats.prepared_fallbacks += 1
        sql = EX._substitute_parameters(entry.text, stmt.parameters)
        from presto_tpu.sql.parser import parse
        return dispatch(session, sql, parse(sql), mon)

    if entry.subst_only or entry.subquery_params \
            or not bool(session.properties.get("prepared_typed_binding",
                                               True)) \
            or bool(session.properties.get("distributed", False)) \
            or session.properties.get("execution_mode") == "chunked" \
            or EX._VOLATILE_RE.search(entry.text) is not None:
        return fallback()

    # bind values: literal -> (host value, engine Type) via the SAME
    # lowering the substitution path's re-parse would apply, so the two
    # paths type identically
    lits = _fold_param_literals(stmt.parameters)
    if lits is None or len(lits) != entry.n_params:
        # non-literal parameters or a count mismatch: the substitution
        # path raises the canonical errors
        return fallback()
    bound = []
    for lit in lits:
        try:
            from presto_tpu.plan.planner import _literal_to_ir
            il = _literal_to_ir(lit)
        except Exception:
            return fallback()
        t = il.type
        if t == T.UNKNOWN or t.is_string \
                or (t.is_decimal and t.is_long_decimal) \
                or t.name in ("VARBINARY", "TIMESTAMP_TZ", "TIME_TZ"):
            return fallback()
        bound.append((il.value, t))
    sig = tuple(str(t) for _v, t in bound)

    # typed template per signature (deep copy: Parameter.type_ is bound
    # per signature and templates are shared across threads)
    typed = entry.typed.get(sig)
    if typed is None:
        typed = copy.deepcopy(entry.template)
        types_by_pos = {i: t for i, (_v, t) in enumerate(bound)}
        for p in _walk_params(typed):
            p.type_ = types_by_pos[p.position]
        if len(entry.typed) >= MAX_TYPED_ENTRIES:
            entry.typed.clear()
        entry.typed[sig] = typed
    mon.stats.prepared_binds += 1

    # the VALUE-free cache key: template text + type signature (+ the
    # session fingerprint inside run_compiled's own key)
    key_text = "$prepared$" + CC.fingerprint(entry.text, sig)

    # result cache, per rider and BEFORE any batching: the substituted
    # template text is the canonical cache identity (identical to what
    # a client submitting the rendered SELECT directly would key on),
    # so identical re-submitted EXECUTE values serve from the cache
    # without joining a batch, and hit accounting is independent of
    # whether the original execution was coalesced
    tier = getattr(session, "_serving_tier", None)
    cache_sql = None
    if tier is not None and tier.result_cache is not None:
        cache_sql = _prepared_cache_text(entry, stmt)
    if cache_sql is not None:
        hit = tier.result_lookup(cache_sql)
        if hit is not None:
            mon.stats.result_cache_hit = 1
            mon.stats.execution_mode = "cached"
            return _result_from_cache(hit)

    mode = session.properties.get("execution_mode", "auto")

    def run_typed_solo():
        compiled_cache = getattr(session, "_compiled_cache", {})
        marker = compiled_cache.get(
            (key_text, getattr(session.catalog, "version", 0),
             tuple(sorted((k, repr(v))
                          for k, v in session.properties.items())), 0))
        if mode in ("auto", "compiled") and marker != "DYNAMIC":
            import jax

            try:
                if marker is not None:
                    # warm bind: plan + executable replay from the
                    # session view over the process-wide memo — zero
                    # parse/plan work
                    mon.stats.prepared_plan_hits += 1
                with mon.phase("execute"):
                    mon.stats.execution_mode = "compiled"
                    return EX.run_compiled(session, key_text, typed,
                                           mon=mon, params=bound)
            except (EX.StaticFallback,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError):
                if mode == "compiled":
                    raise
        # dynamic path: plan memoized per key (value-free — ir.Param
        # reads the binding at evaluation time)
        plans = session.__dict__.setdefault("_prepared_dyn_plans", {})
        dyn_key = (key_text, getattr(session.catalog, "version", 0),
                   tuple(sorted((k, repr(v))
                                for k, v in session.properties.items())))
        plan = plans.get(dyn_key)
        if plan is None:
            with mon.phase("plan"):
                plan = EX.plan_statement(session, typed)
            if len(plans) >= MAX_TYPED_ENTRIES:
                plans.clear()
            plans[dyn_key] = plan
        else:
            mon.stats.prepared_plan_hits += 1
        mon.stats.execution_mode = "dynamic"
        host_params = tuple((v, None) for v, _t in bound)
        with mon.phase("execute"):
            ex = EX.Executor(session, monitor=mon, params=host_params)
            return ex.run(plan)

    # coalescing needs ≥1 bound scalar to stack (a 0-param template has
    # no batch axis to map) and a compiled-capable mode
    if mode in ("auto", "compiled") and bound \
            and coalesce_mode(session) != "off":
        gk = (key_text,) + CC.session_fingerprint(session)

        def run_batched(riders, rider_mons):
            with mon.phase("execute"):
                return EX.run_compiled_batched(session, key_text, typed,
                                               riders, rider_mons)

        result = coalescer_for(session).submit(
            session, gk, bound, mon, run_batched, run_typed_solo)
    else:
        result = run_typed_solo()
    if cache_sql is not None and result is not None:
        cols = [{"name": n, "type": str(t).lower()}
                for n, t in result.columns]
        tier.result_store(cache_sql, cols, [list(r) for r in result.rows])
    return result


def _prepared_cache_text(entry, stmt) -> Optional[str]:
    """The canonical result-cache identity of a typed EXECUTE: the
    substituted template text — the SAME key an ad-hoc submission of the
    rendered SELECT produces, so prepared and ad-hoc reads of identical
    values share cache entries.  None when rendering fails (the
    execution path raises the canonical error instead)."""
    from presto_tpu.exec import executor as EX

    try:
        return EX._substitute_parameters(entry.text, stmt.parameters)
    except Exception:
        return None


def _result_from_cache(hit):
    """Result-cache entry -> QueryResult.  Entries store the protocol
    wire shape ({"name","type"} column dicts + list rows), shared with
    direct SELECT submissions through server/protocol.py."""
    from presto_tpu import types as T
    from presto_tpu.session import QueryResult

    columns, rows, _size = hit
    cols = []
    for c in columns:
        try:
            typ = T.parse_type(c["type"])
        except Exception:
            typ = T.VARCHAR
        cols.append((c["name"], typ))
    return QueryResult(cols, [tuple(r) for r in rows])


def _fold_param_literals(parameters) -> Optional[list]:
    """EXECUTE argument exprs -> ast.Literal list (folding unary minus),
    or None when any argument is not a literal."""
    out = []
    for p in parameters:
        neg = False
        while isinstance(p, ast.UnaryOp) and p.op == "-" \
                and isinstance(p.operand, ast.Literal) \
                and isinstance(p.operand.value, (int, float)):
            neg = not neg
            p = p.operand
        if not isinstance(p, ast.Literal):
            return None
        if neg:
            p = ast.Literal(-p.value, p.type_hint)
        out.append(p)
    return out


def _walk_params(node):
    if isinstance(node, ast.Parameter):
        yield node
    if isinstance(node, ast.Node):
        for c in node.children():
            yield from _walk_params(c)


def _count_ast_params(node) -> int:
    return sum(1 for _ in _walk_params(node))


def _count_placeholders(sql: str) -> int:
    n = 0
    in_str = False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
        elif ch == "?" and not in_str:
            n += 1
    return n


def _params_under_subquery(node) -> bool:
    """True when any `?` sits inside a scalar/EXISTS/IN subquery: the
    compiled path evaluates subplans EAGERLY and bakes their values into
    the executable, which would pin the FIRST binding's values."""

    def walk(n, under):
        if isinstance(n, ast.Parameter) and under:
            return True
        u = under or isinstance(
            n, (ast.ScalarSubquery, ast.Exists, ast.InSubquery))
        if isinstance(n, ast.Node):
            return any(walk(c, u) for c in n.children())
        return False

    return walk(node, False)


def _infer_param_types(session, template, n_params: int) -> list:
    """Best-effort parameter types for DESCRIBE INPUT: a `?` compared
    (or combined arithmetically) with a column takes the column's type
    (reference: the analyzer's coercion assigns parameter types the
    same way).  Unresolvable positions report 'unknown'."""
    # column name -> type over every table the template references
    col_types: Dict[str, str] = {}
    for t in _walk_nodes(template, ast.Table):
        try:
            tab = session.catalog.get(t.name)
        except Exception:
            continue
        for c, ty in tab.schema.items():
            col_types.setdefault(c, str(ty).lower())
    out = ["unknown"] * n_params

    def note(param, other):
        if not isinstance(param, ast.Parameter):
            return
        if isinstance(other, ast.Identifier) \
                and other.name in col_types \
                and 0 <= param.position < n_params \
                and out[param.position] == "unknown":
            out[param.position] = col_types[other.name]

    for n in _walk_nodes(template, ast.BinaryOp):
        note(n.left, n.right)
        note(n.right, n.left)
    for n in _walk_nodes(template, ast.Between):
        note(n.low, n.value)
        note(n.high, n.value)
    for n in _walk_nodes(template, ast.InList):
        for item in n.items:
            note(item, n.value)
    for n in _walk_nodes(template, ast.Like):
        if isinstance(n.pattern, ast.Parameter) \
                and 0 <= n.pattern.position < n_params \
                and out[n.pattern.position] == "unknown":
            out[n.pattern.position] = "varchar"
    return out


def _walk_nodes(node, cls):
    if isinstance(node, cls):
        yield node
    if isinstance(node, ast.Node):
        for c in node.children():
            yield from _walk_nodes(c, cls)


# ---------------------------------------------------------------------------
# query coalescing
# ---------------------------------------------------------------------------

#: micro-batch window (ms) a leader holds open collecting riders; a few
#: ms is the point where one saved device dispatch repays the wait many
#: times over (tools/roofline.py --sweep coalesce measures the curve)
COALESCE_WINDOW_MS_DEFAULT = 2.0
#: batch-size ceiling (stacked parameters quantize to pow2 below this)
COALESCE_MAX_BATCH_DEFAULT = 16
#: rider backstop on the leader's batched launch: generous — the first
#: batch of a size bucket pays an XLA compile — and load-bearing only
#: if a leader thread dies without running its finally block (the
#: leader ALWAYS sets the group's done event; an expired rider re-runs
#: solo, same as any other batch fallback)
COALESCE_RIDER_WAIT_S = 300.0


def coalesce_mode(session) -> str:
    """'off' | 'on' | 'auto'.  Env PRESTO_TPU_QUERY_COALESCING=off is
    the process kill switch; session property `query_coalescing`
    accepts off/on/auto or a bool.  `auto` (the default) opens a batch
    window only when another query of the same prepared signature is
    already in flight — an idle EXECUTE never pays the window."""
    env = os.environ.get("PRESTO_TPU_QUERY_COALESCING", "").lower()
    if env in ("off", "0", "false"):
        return "off"
    v = session.properties.get("query_coalescing", "auto")
    if isinstance(v, str):
        lv = v.lower()
        if lv in ("off", "false", "0"):
            return "off"
        if lv in ("on", "true", "1", "force"):
            return "on"
        return "auto"
    return "on" if v else "off"


class _CoalesceGroup:
    """One micro-batch rendezvous: the leader (rider 0) holds the
    window open, closes the group, runs the batched launch, and
    distributes results; riders block on `done` and read their slot."""

    __slots__ = ("riders", "mons", "closed", "full", "done", "results",
                 "fallback")

    def __init__(self, bound, mon):
        self.riders = [bound]
        self.mons = [mon]
        self.closed = False
        self.full = threading.Event()
        self.done = threading.Event()
        self.results = None
        self.fallback = False


class QueryCoalescer:
    """Admission-side query coalescing (ROADMAP 3(a)): concurrent
    EXECUTEs of one prepared signature — same plan fingerprint x
    catalog token x property map, i.e. the same `gk` — that arrive
    within the micro-batch window are grouped, their bound parameters
    stacked into a leading axis, and dispatched as ONE vmap-batched
    executable (exec/executor.run_compiled_batched).  The first
    arrival leads: it waits out `coalesce_window_ms` (or until
    `coalesce_max_batch` riders joined), runs the batch, and hands each
    rider its slot.  ANY batch failure — Unbatchable shapes, tripped
    guards, an injected leader fault — flips the group to fallback and
    every member re-runs solo in its own thread: zero wrong results,
    zero surfaced failures, bounded added latency (the window).

    Per-session like the prepared registry (the protocol server
    multiplexes one session, so this is the server's coalescer)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: Dict[tuple, _CoalesceGroup] = {}
        self._active: Dict[tuple, int] = {}  # gk -> in-flight count
        self.batches = 0
        self.riders_coalesced = 0
        self.fallbacks = 0
        self.window_timeouts = 0  # windows that closed with one member

    def submit(self, session, gk, bound, mon, run_batched, run_solo):
        """Coalescing entry point for one EXECUTE.  `bound`: the
        rider's (value, Type) parameter pairs.  `run_batched(riders,
        mons)` runs the stacked launch; `run_solo()` is the classic
        typed path.  Returns the rider's QueryResult either way."""
        window_s = max(float(session.properties.get(
            "coalesce_window_ms", COALESCE_WINDOW_MS_DEFAULT)), 0.0) / 1e3
        max_batch = max(int(session.properties.get(
            "coalesce_max_batch", COALESCE_MAX_BATCH_DEFAULT)), 1)
        mode = coalesce_mode(session)
        g = None
        idx = 0
        with self._lock:
            cur = self._groups.get(gk)
            if cur is not None and not cur.closed \
                    and len(cur.riders) < max_batch:
                g = cur
                idx = len(g.riders)
                g.riders.append(bound)
                g.mons.append(mon)
                if len(g.riders) >= max_batch:
                    g.full.set()
            elif max_batch > 1 and (
                    mode == "on"
                    or (mode == "auto" and self._active.get(gk, 0) > 0)):
                g = _CoalesceGroup(bound, mon)
                self._groups[gk] = g
            self._active[gk] = self._active.get(gk, 0) + 1
        try:
            if g is None:
                # no concurrency observed (auto mode): run solo, but the
                # _active mark lets the NEXT same-signature arrival open
                # a window while this one executes
                return run_solo()
            if idx > 0:
                return self._ride(g, idx, mon, run_solo)
            return self._lead(gk, g, mon, window_s, run_batched, run_solo)
        finally:
            with self._lock:
                n = self._active.get(gk, 0) - 1
                if n > 0:
                    self._active[gk] = n
                else:
                    self._active.pop(gk, None)

    # -- leader --------------------------------------------------------
    def _lead(self, gk, g, mon, window_s, run_batched, run_solo):
        t0 = time.monotonic()
        if window_s > 0:
            g.full.wait(timeout=window_s)
        with self._lock:
            g.closed = True  # late arrivals form their own group
            if self._groups.get(gk) is g:
                del self._groups[gk]
        mon.stats.coalesce_ms += (time.monotonic() - t0) * 1000.0
        if len(g.riders) == 1:
            # window expired with no riders: solo, nothing to unstack
            with self._lock:
                self.window_timeouts += 1
            try:
                return run_solo()
            finally:
                g.done.set()
        try:
            # deterministic chaos hook (parallel/faults.py):
            # coalesce:BATCH:<path>:nth:fail kills the leader's launch
            from presto_tpu.parallel import faults as F

            rule = F.client_plan().match("coalesce", "BATCH", str(gk[0]))
            if rule is not None and rule.action == "fail":
                raise RuntimeError("injected fault: coalesce batch leader")
            g.results = run_batched(list(g.riders), list(g.mons))
            mon.stats.coalesce_batches += 1
            with self._lock:
                self.batches += 1
                self.riders_coalesced += len(g.riders)
        except Exception:
            # Unbatchable shapes, tripped guards, injected faults: the
            # whole group degrades to solo re-runs — a genuine query
            # error resurfaces identically from run_solo below
            g.fallback = True
        finally:
            g.done.set()
        if g.fallback:
            mon.stats.coalesce_fallbacks += 1
            with self._lock:
                self.fallbacks += 1
            return run_solo()
        return g.results[0]

    # -- rider ---------------------------------------------------------
    def _ride(self, g, idx, mon, run_solo):
        g.done.wait(timeout=COALESCE_RIDER_WAIT_S)
        if g.fallback or g.results is None:
            mon.stats.coalesce_fallbacks += 1
            with self._lock:
                self.fallbacks += 1
            return run_solo()  # the rider's own thread re-runs solo
        return g.results[idx]

    def stats(self) -> dict:
        with self._lock:
            return {
                "batches": self.batches,
                "ridersCoalesced": self.riders_coalesced,
                "fallbacks": self.fallbacks,
                "windowTimeouts": self.window_timeouts,
                "meanBatchSize": round(
                    self.riders_coalesced / self.batches, 2)
                if self.batches else 0.0,
            }


def coalescer_for(session) -> QueryCoalescer:
    """The session's coalescer, created on first use (same lifetime
    rule as the prepared registry)."""
    c = getattr(session, "_query_coalescer", None)
    if c is None:
        c = session._query_coalescer = QueryCoalescer()
    return c


# ---------------------------------------------------------------------------
# result cache
# ---------------------------------------------------------------------------

#: statement head keywords eligible for result caching: pure reads whose
#: results are functions of (text, catalog state, session properties)
_CACHEABLE_HEADS = ("SELECT", "WITH", "VALUES")


def _norm_table_names(names) -> frozenset:
    """Normalize table names for scoped-invalidation matching: both the
    full lowered name and its bare last component, so a write to
    'memory.default.t' still clears entries that read 't'."""
    out = set()
    for n in names:
        n = str(n).lower()
        out.add(n)
        out.add(n.split(".")[-1])
    return frozenset(out)


def referenced_tables(sql: str):
    """Tables a read statement touches (frozenset of normalized names),
    or None when the text cannot be analyzed — None-scoped entries fall
    on EVERY invalidation, so a parse failure degrades to the old
    clear-the-world behavior, never to a stale hit."""
    from presto_tpu.sql import ast
    from presto_tpu.sql.parser import parse

    try:
        stmt = parse(sql)
    except Exception:
        return None
    names = set()

    def walk(node):
        if isinstance(node, ast.Table):
            names.add(node.name)
        if dataclasses.is_dataclass(node):
            for f in dataclasses.fields(node):
                walk(getattr(node, f.name))
        elif isinstance(node, (list, tuple)):
            for x in node:
                walk(x)
        elif isinstance(node, dict):
            for x in node.values():
                walk(x)

    try:
        walk(stmt)
    except Exception:
        return None
    return _norm_table_names(names)


def write_targets(sql: str):
    """Tables a write/DDL statement mutates, or None when the statement
    shape is not recognized (None broadcasts a FULL invalidation)."""
    from presto_tpu.sql import ast
    from presto_tpu.sql.parser import parse

    try:
        stmt = parse(sql)
    except Exception:
        return None
    name = getattr(stmt, "name", None) or getattr(stmt, "table", None)
    if isinstance(stmt, (ast.CreateTableAs, ast.CreateTable,
                         ast.InsertInto, ast.DropTable, ast.Delete,
                         ast.CreateMaterializedView,
                         ast.RefreshMaterializedView,
                         ast.DropMaterializedView)) \
            and isinstance(name, str):
        return _norm_table_names([name])
    return None


class ResultCache:
    """Bounded LRU over materialized results (reference analog: none in
    the OSS reference — this is the hot-dashboard tier every production
    deployment bolts on).  Keys are (text, catalog token, catalog
    version, property fingerprint): an engine write bumps the catalog
    version, so a stale hit is structurally impossible; external
    mutation (e.g. the sqlite connector's backing file) is the
    documented exception, handled by `invalidate()`."""

    def __init__(self, max_entries: int = 256,
                 max_bytes: int = 64 << 20,
                 max_result_rows: int = 10_000):
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.max_result_rows = max_result_rows
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        # parallel map key -> frozenset of referenced tables (or None
        # when the text resisted analysis); entries stay 3-tuples so
        # the protocol wire consumers are untouched
        self._entry_tables: Dict[tuple, Optional[frozenset]] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0
        self.invalidations = 0
        self.invalidations_scoped = 0
        self.invalidations_full = 0

    # -- keying --------------------------------------------------------
    @staticmethod
    def cacheable(session, sql: str) -> bool:
        from presto_tpu.exec.executor import _VOLATILE_RE

        head = sql.lstrip().split(None, 1)
        if not head or head[0].upper() not in _CACHEABLE_HEADS:
            return False
        if _VOLATILE_RE.search(sql) is not None:
            return False
        if getattr(session.txn, "current", None) is not None:
            return False  # snapshot reads must not outlive their txn
        return True

    @staticmethod
    def key(session, sql: str) -> tuple:
        from presto_tpu.exec.compile_cache import catalog_token

        return (sql, catalog_token(session.catalog),
                getattr(session.catalog, "version", 0),
                tuple(sorted((k, repr(v))
                             for k, v in session.properties.items())))

    # -- operations ----------------------------------------------------
    def get(self, session, sql: str):
        if not self.cacheable(session, sql):
            return None
        k = self.key(session, sql)
        with self._lock:
            e = self._entries.get(k)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(k)
            self.hits += 1
            return e

    def put(self, session, sql: str, columns, rows) -> bool:
        if not self.cacheable(session, sql):
            return False
        if len(rows) > self.max_result_rows:
            return False
        size = _result_bytes(rows)
        if size > self.max_bytes:
            return False
        k = self.key(session, sql)
        version = k[2]
        tables = referenced_tables(sql)
        with self._lock:
            if k in self._entries:
                return True
            self._entries[k] = (columns, rows, size)
            self._entry_tables[k] = tables
            self._bytes += size
            self.stores += 1
            # sweep entries from older catalog versions: they can never
            # hit again (the version is in the key) and would otherwise
            # squat the byte budget until LRU pressure finds them
            stale = [ok for ok in self._entries
                     if ok[1] == k[1] and ok[2] != version]
            for ok in stale:
                self._bytes -= self._entries.pop(ok)[2]
                self._entry_tables.pop(ok, None)
                self.evictions += 1
            while len(self._entries) > self.max_entries \
                    or self._bytes > self.max_bytes:
                ok, (_c, _r, sz) = self._entries.popitem(last=False)
                self._entry_tables.pop(ok, None)
                self._bytes -= sz
                self.evictions += 1
        return True

    def invalidate(self, tables=None) -> None:
        """Explicit invalidation (DDL/DML through the serving tier, or
        external catalog mutation the version cannot see).  With a
        `tables` set, only entries that REFERENCE one of those tables
        fall (plus entries whose reads resisted analysis); None keeps
        the old clear-the-world behavior."""
        with self._lock:
            self.invalidations += 1
            if tables is None:
                self.invalidations_full += 1
                self._entries.clear()
                self._entry_tables.clear()
                self._bytes = 0
                return
            self.invalidations_scoped += 1
            touched = _norm_table_names(tables)
            doomed = [k for k in self._entries
                      if self._entry_tables.get(k) is None
                      or (self._entry_tables[k] & touched)]
            for k in doomed:
                self._bytes -= self._entries.pop(k)[2]
                self._entry_tables.pop(k, None)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses,
                    "stores": self.stores, "evictions": self.evictions,
                    "invalidations": self.invalidations,
                    "invalidationsScoped": self.invalidations_scoped,
                    "invalidationsFull": self.invalidations_full,
                    "hitRate": round(self.hits / total, 4) if total else 0.0}


def _result_bytes(rows) -> int:
    """Cheap result-size estimate: sampled row cost x row count (exact
    accounting would walk every cell of every row on the store path)."""
    if not rows:
        return 64
    sample = rows[:32]
    per_row = 0
    for r in sample:
        per_row += 16
        for v in r:
            per_row += len(v) + 40 if isinstance(v, str) else 16
    return int(per_row / len(sample) * len(rows)) + 64


# ---------------------------------------------------------------------------
# admission + the composed tier
# ---------------------------------------------------------------------------


class AdmissionSlot:
    """One admitted query: the group plus the reservations release must
    return."""

    __slots__ = ("group", "memory_bytes", "wait_ms")

    def __init__(self, group, memory_bytes: int, wait_ms: float):
        self.group = group
        self.memory_bytes = memory_bytes
        self.wait_ms = wait_ms


class ServingTier:
    """Admission + prepared statements + result cache behind one
    surface, embedded by the protocol server and the QPS benchmark."""

    def __init__(self, session, resource_groups: Optional[
            ResourceGroupManager] = None, result_cache: Optional[
            ResultCache] = None):
        self.session = session
        self.resource_groups = resource_groups
        if result_cache is None and bool(
                session.properties.get("result_cache_enabled", True)):
            result_cache = ResultCache(
                max_entries=int(session.properties.get(
                    "result_cache_max_entries", 256)),
                max_bytes=int(session.properties.get(
                    "result_cache_max_bytes", 64 << 20)),
                max_result_rows=int(session.properties.get(
                    "result_cache_max_rows", 10_000)))
        self.result_cache = result_cache
        # engine-path writes (session.sql CTAS/INSERT through
        # exec/writer.py) invalidate through this back-reference — the
        # belt on top of the catalog-version keying, same rule as the
        # protocol path's textual detection
        session._serving_tier = self
        # coordinator fleet (server/fleet.FleetMember): when attached,
        # engine writes broadcast a version-stamped invalidation to peer
        # coordinators and peer broadcasts clear THIS tier's cache.
        # Best-effort both ways — the catalog token+version in every
        # cache key is the correctness backstop (a missed broadcast
        # degrades to a key miss, never a stale hit).
        self.fleet = None
        self.draining = threading.Event()
        self._lock = threading.Lock()
        self.queries_admitted = 0
        self.queries_shed = 0
        self.queries_drained = 0
        self.peak_queue_depth = 0

    # -- admission -----------------------------------------------------
    def admit(self, user: str = "", source: str = "",
              priority: int = 0, abort=None) -> Optional[AdmissionSlot]:
        """Admission BEFORE execution resources: may block (QUEUED),
        raises QueryRejected on shed/timeout/drain.  Returns None when
        no resource-group tree is configured (admission disabled)."""
        rgm = self.resource_groups
        if rgm is None:
            return None

        def aborted():
            if self.draining.is_set():
                return True
            return abort() if abort is not None else False

        mem = int(self.session.properties.get("query_max_memory_bytes", 0))
        timeout = float(self.session.properties.get(
            "admission_queue_timeout_s", 60.0))
        t0 = time.monotonic()
        try:
            group = rgm.acquire(user, source, priority=priority,
                                timeout=timeout, memory_bytes=mem,
                                abort=aborted)
        except QueryRejected as e:
            with self._lock:
                if e.code == "QUEUE_FULL":
                    self.queries_shed += 1
                elif e.code == "SERVER_SHUTTING_DOWN":
                    self.queries_drained += 1
            raise
        wait_ms = (time.monotonic() - t0) * 1000.0
        with self._lock:
            self.queries_admitted += 1
            depth = sum(i["queued"] for i in rgm.info()
                        if i["name"] == "global")
            self.peak_queue_depth = max(self.peak_queue_depth, depth)
        return AdmissionSlot(group, mem, wait_ms)

    def release(self, slot: Optional[AdmissionSlot],
                cpu_s: float = 0.0) -> None:
        if slot is not None and self.resource_groups is not None:
            self.resource_groups.release(slot.group, cpu_s=cpu_s,
                                         memory_bytes=slot.memory_bytes)

    def drain(self) -> None:
        """Graceful shutdown: queued admission waiters abort with
        SERVER_SHUTTING_DOWN instead of holding the drain open."""
        self.draining.set()

    # -- result cache --------------------------------------------------
    def result_lookup(self, sql: str):
        if self.result_cache is None:
            return None
        return self.result_cache.get(self.session, sql)

    def result_store(self, sql: str, columns, rows) -> None:
        if self.result_cache is not None:
            self.result_cache.put(self.session, sql, columns, rows)

    def on_write_statement(self, tables=None) -> None:
        """Explicit invalidation rule: any non-read statement through
        the tier invalidates the cache (belt) on top of the catalog-
        version keying (suspenders).  `tables` scopes the invalidation
        to entries referencing the written tables — a write to one hot
        table no longer evicts every OTHER dashboard's entries; None
        (unanalyzable statement) keeps the full clear.  With a fleet
        attached, the write also broadcasts a version-stamped
        invalidation carrying the same table set so PEER coordinators
        drop their pre-write entries promptly (fleet_invalidate knob;
        a dropped broadcast still misses on the bumped version key)."""
        if self.result_cache is not None:
            self.result_cache.invalidate(tables=tables)
        if self.fleet is not None and bool(
                self.session.properties.get("fleet_invalidate", True)):
            from presto_tpu.exec.compile_cache import catalog_token

            self.fleet.broadcast_invalidate(
                catalog_token(self.session.catalog),
                getattr(self.session.catalog, "version", 0),
                tables=tables)

    def attach_fleet(self, member) -> None:
        """Join this tier to a coordinator fleet: writes broadcast
        invalidations (see on_write_statement) and peer broadcasts clear
        this tier's result cache (scoped to the broadcast table set)."""
        self.fleet = member

        def on_invalidate(_token: str, _version: int,
                          tables=None) -> None:
            if self.result_cache is not None:
                self.result_cache.invalidate(tables=tables)

        member.subscribe(on_invalidate=on_invalidate)

    # -- introspection -------------------------------------------------
    def coalescer_stats(self) -> Optional[dict]:
        c = getattr(self.session, "_query_coalescer", None)
        return c.stats() if c is not None else None

    def stats(self) -> dict:
        out = {"admitted": self.queries_admitted,
               "shed": self.queries_shed,
               "drained": self.queries_drained,
               "peakQueueDepth": self.peak_queue_depth,
               "coalescing": self.coalescer_stats(),
               "resultCache": (self.result_cache.stats()
                               if self.result_cache is not None else None)}
        if self.resource_groups is not None:
            out["resourceGroups"] = self.resource_groups.info()
        return out
