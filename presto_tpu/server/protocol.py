"""The client protocol server.

Reference parity: server/protocol/StatementResource.java:88-134 —
`POST /v1/statement` returns QueryResults{id, nextUri, columns, data,
stats, error}; the client polls nextUri
(`GET /v1/statement/{queryId}/{token}`) until no nextUri remains;
`DELETE /v1/statement/{queryId}` cancels.  Tokens are cumulative page
sequence numbers: re-fetching a token re-serves the same page
(at-least-once delivery with client dedup, the elasticity seam of
SURVEY.md §2.6).  Also serves the introspection endpoints
(server/QueryResource.java `/v1/query`, ClusterStatsResource
`/v1/cluster`), the Prometheus scrape (`/v1/metrics`,
observe/metrics.py — the primary metrics surface; /v1/info remains as
the JSON compatibility view), per-query chrome traces
(`/v1/query/{id}/trace`, observe/trace.py — loads in Perfetto), node
info/status for the failure detector, and the graceful-shutdown state
machine (server/GracefulShutdownHandler.java).

Execution is in-process on the embedded engine (the coordinator IS the
mesh driver under SPMD — workers are TPU chips, not task servers; the
reference ships plan fragments to worker JVMs, SURVEY.md §3.1).

Fault tolerance (docs/ROBUSTNESS.md): with a fleet attached, in-flight
read queries journal their resumable state (parallel/journal.py) and a
peer coordinator's death triggers ADOPTION on its ring successor — the
adopted query re-runs under its ORIGINAL query id, so a client polling
nextUri through any surviving door completes: the unknown-qid chain
falls through proxied_owner -> journal_lookup, which proxies to the
entry's (re-homed) coordinator or holds the client in RUNNING while
the adoption races.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

PAGE_ROWS = 4096  # rows per protocol page (client re-chunks as needed)

# the ONLY timing constants of the protocol loop (the serving lint rule
# forbids inline timeout literals in this module): first-response grace
# for fast queries, the long-poll bound, and the drain poll period
FIRST_RESPONSE_GRACE_S = 0.05
LONG_POLL_S = 1.0
DRAIN_POLL_S = 0.05
DEFAULT_DRAIN_TIMEOUT_S = 30.0


@dataclasses.dataclass
class _QueryJob:
    query_id: str
    sql: str
    state: str = "QUEUED"  # QUEUED RUNNING FINISHED FAILED CANCELED
    columns: Optional[List[dict]] = None
    rows: Optional[list] = None
    error: Optional[str] = None
    error_code: Optional[str] = None  # e.g. QUEUE_FULL (clean shed)
    resource_group: str = ""
    stats: Optional[dict] = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    cancel: threading.Event = dataclasses.field(default_factory=threading.Event)


class PrestoTpuServer:
    """Embeds a Session behind the REST protocol; queries run on a worker
    thread pool so the HTTP loop never blocks on execution."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0,
                 max_concurrent: int = 4, resource_groups=None,
                 authenticator=None, serving=None, fleet=None):
        from presto_tpu.server.serving import ServingTier

        self.session = session
        self.resource_groups = resource_groups  # ResourceGroupManager | None
        # the serving tier (server/serving.py): admission over the
        # resource-group tree + the result cache; every submit routes
        # through it (docs/SERVING.md)
        self.serving = serving if serving is not None else ServingTier(
            session, resource_groups=resource_groups)
        if serving is not None and resource_groups is None:
            self.resource_groups = serving.resource_groups
        # coordinator fleet (server/fleet.FleetMember): the front door
        # routes same-signature EXECUTEs (and cacheable reads) to their
        # ring owner — proxy by default, 307-redirect for clients that
        # follow it — so coalescing batches and cache hits concentrate
        # instead of fragmenting 1/N per coordinator.  `fleet=None` is
        # the single-coordinator path, byte-identical to round 18.
        self.fleet = None
        self._journal = None
        if fleet is not None:
            self.attach_fleet(fleet)
        self._proxied: Dict[str, str] = {}  # proxied query id -> owner uri
        self._proxied_lock = threading.Lock()
        self.fleet_counters = {"proxied": 0, "redirected": 0,
                               "proxy_failures": 0, "journal_writes": 0,
                               "queries_adopted": 0, "adoption_ms": 0}
        # security.PasswordAuthenticator | None — when set, every /v1
        # request must carry HTTP Basic credentials (reference:
        # password authenticators wired through http-server.authentication)
        self.authenticator = authenticator
        self.jobs: Dict[str, _QueryJob] = {}
        self.jobs_lock = threading.Lock()
        self.node_id = f"node_{uuid.uuid4().hex[:8]}"
        from presto_tpu.observe import trace as TR

        self.start_time = TR.wall_s()
        self.shutting_down = threading.Event()
        self.active_queries = 0
        self._sema = threading.Semaphore(max_concurrent)
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "PrestoTpuServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    def graceful_shutdown(self,
                          timeout: float = DEFAULT_DRAIN_TIMEOUT_S) -> None:
        """Drain: refuse new queries, cancel QUEUED (admitted-but-not-
        started) jobs with a terminal CANCELED state their waiting
        clients can read, wait for RUNNING ones, stop (reference:
        GracefulShutdownHandler — worker waits for active tasks before
        exiting; queued queries are failed with SERVER_SHUTTING_DOWN)."""
        self.shutting_down.set()
        # wakes every admission waiter: their jobs turn CANCELED and
        # decrement active_queries, so the drain below only ever waits
        # on genuinely RUNNING queries
        self.serving.drain()
        deadline = time.monotonic() + timeout
        ticker = threading.Event()  # never set: a lint-clean sleep
        while time.monotonic() < deadline:
            with self.jobs_lock:
                if self.active_queries == 0:
                    break
            ticker.wait(timeout=DRAIN_POLL_S)
        self.stop()

    @property
    def uri(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- query execution ----------------------------------------------
    def submit(self, sql: str) -> _QueryJob:
        if self.shutting_down.is_set():
            raise RuntimeError("server is shutting down")
        job = _QueryJob(query_id=f"qs_{uuid.uuid4().hex[:12]}", sql=sql)
        with self.jobs_lock:
            self.jobs[job.query_id] = job
            self.active_queries += 1
        threading.Thread(target=self._run_job, args=(job,), daemon=True).start()
        return job

    def _run_job(self, job: _QueryJob) -> None:
        from presto_tpu.server.resource_groups import QueryRejected

        slot = None
        try:
            # admission BEFORE the worker semaphore: a query queued on
            # a saturated group must not hold a worker slot (it would
            # starve other groups — head-of-line blocking).  The abort
            # hook drains the wait on client cancel AND on graceful
            # shutdown (queued jobs then end CANCELED, terminally).
            slot = self.serving.admit(self.session.user,
                                      self.session.source,
                                      abort=job.cancel.is_set)
        except QueryRejected as e:
            if e.code == "SERVER_SHUTTING_DOWN" or job.cancel.is_set():
                job.error = "Query was canceled: server is shutting down"
                job.error_code = e.code
                job.state = "CANCELED"
            else:  # QUEUE_FULL shed / QUEUE_TIMEOUT: a clean query error
                job.error = str(e)
                job.error_code = e.code
                job.state = "FAILED"
            job.done.set()
            with self.jobs_lock:
                self.active_queries -= 1
            return
        except Exception as e:  # noqa: BLE001 — rejection is a query error
            job.error = f"{type(e).__name__}: {e}"
            job.state = "FAILED"
            job.done.set()
            with self.jobs_lock:
                self.active_queries -= 1
            return
        if slot is not None:
            job.resource_group = slot.group.full_name
        t0 = time.monotonic()
        journaled = False
        with self._sema:
            try:
                if job.cancel.is_set():
                    job.state = "CANCELED"
                    return
                head = job.sql.lstrip().upper()
                if head.startswith(("START", "COMMIT", "ROLLBACK")):
                    # the protocol server multiplexes ONE session across
                    # clients; an explicit transaction here could roll
                    # back another client's acknowledged writes
                    raise RuntimeError(
                        "explicit transactions are not supported over the "
                        "shared protocol server; use an embedded session")
                job.state = "RUNNING"
                if self._journal is not None:
                    first = job.sql.lstrip().split(None, 1)[0].upper()
                    if first in ("SELECT", "WITH", "VALUES", "EXECUTE"):
                        # journal the in-flight query (read statements
                        # only: adoption RE-EXECUTES, so a journaled
                        # write could double-apply) under its protocol
                        # query id — the id the client's nextUri holds
                        from presto_tpu.parallel import journal as _J

                        ent = _J.entry_for(job.query_id, job.sql,
                                           self.fleet.coord_id,
                                           self.session.properties)
                        if self._journal.write(ent):
                            journaled = True
                            self.fleet_counters["journal_writes"] += 1
                            self.fleet.replicate_journal(ent)
                self.session.apply_property_manager()
                cached = self.serving.result_lookup(job.sql)
                if cached is not None:
                    # identical re-submitted query served straight from
                    # the result cache — no parse, no plan, no execution
                    self._finish_cached(job, cached, slot)
                    return
                result = self.session.sql(job.sql)
                if job.cancel.is_set():
                    job.state = "CANCELED"
                    return
                job.columns = [{"name": n, "type": str(t).lower()}
                               for n, t in result.columns]
                job.rows = [list(r) for r in result.rows]
                st = result.stats  # this query's stats (not last_stats —
                job.stats = {      # concurrent jobs would race)
                    "state": "FINISHED",
                    "elapsedTimeMillis": int((st.total_ns if st else 0) / 1e6),
                    "processedRows": len(job.rows),
                    "peakMemoryBytes": getattr(st, "peak_memory_bytes", 0),
                    "spilledBytes": getattr(st, "spilled_bytes", 0),
                }
                job.state = "FINISHED"
                if st is not None:
                    # admission facts ride the query's own stats object
                    # (already in session.history) for /v1/query/{id}
                    st.resource_group = job.resource_group
                    if slot is not None:
                        st.admission_wait_ms = slot.wait_ms
                if self.serving.result_cache is not None:
                    first = job.sql.lstrip().split(None, 1)[0].upper()
                    if first in ("SELECT", "WITH", "VALUES"):
                        self.serving.result_store(job.sql, job.columns,
                                                  job.rows)
                    elif first in ("INSERT", "DELETE", "UPDATE", "CREATE",
                                   "DROP", "ALTER", "REFRESH"):
                        # write/DDL statement: explicit invalidation on
                        # top of the catalog-version keying, SCOPED to
                        # the written tables when the statement parses
                        # (with a fleet attached this also broadcasts
                        # the same table set to peers)
                        from presto_tpu.server.serving import write_targets

                        self.serving.on_write_statement(
                            tables=write_targets(job.sql))
                if self.fleet is not None and job.sql.lstrip().split(
                        None, 1)[0].upper() == "PREPARE":
                    # best-effort signature replication: an EXECUTE
                    # routed or failed over to any peer should find the
                    # prepared name (a peer it never reached answers
                    # the typed unknown-statement error instead)
                    self.fleet.replicate_prepare(job.sql)
            except Exception as e:  # noqa: BLE001 — protocol reports all errors
                job.error = f"{type(e).__name__}: {e}"
                job.state = "FAILED"
            finally:
                # charge the query's elapsed time as CPU usage for
                # the group's soft/hard CPU limits (reference:
                # per-query cpuUsageMillis charged on completion)
                self.serving.release(slot, cpu_s=time.monotonic() - t0)
                if journaled:
                    # alive to observe the outcome => clean up; only a
                    # coordinator that DIED leaves entries for adoption
                    self._journal.remove(job.query_id)
                job.done.set()
                with self.jobs_lock:
                    self.active_queries -= 1

    def _finish_cached(self, job: _QueryJob, cached, slot) -> None:
        """Complete a job from a result-cache entry, recording a history
        stats row so /v1/query shows the (cached) execution."""
        from presto_tpu.observe.stats import QueryMonitor

        columns, rows, _size = cached
        job.columns = columns
        job.rows = rows
        mon = QueryMonitor.begin(self.session, job.sql)
        mon.stats.execution_mode = "cached"
        mon.stats.result_cache_hit = 1
        mon.stats.resource_group = job.resource_group
        if slot is not None:
            mon.stats.admission_wait_ms = slot.wait_ms
        mon.finish(rows)
        job.stats = {"state": "FINISHED", "elapsedTimeMillis": 0,
                     "processedRows": len(rows), "peakMemoryBytes": 0,
                     "spilledBytes": 0, "resultCacheHit": True}
        job.state = "FINISHED"

    # -- fleet front door ---------------------------------------------
    def route_target(self, sql: str) -> Optional[str]:
        """The owning coordinator's URI when this statement belongs to a
        ring peer, else None (execute locally).  Routing is an
        optimization: any error resolves to local execution."""
        if self.fleet is None:
            return None
        mode = str(self.session.properties.get(
            "fleet_affinity", "proxy")).lower()
        if mode == "off":
            return None
        from presto_tpu.server import fleet as FL

        key = FL.affinity_key(sql)
        if key is None:
            return None
        return self.fleet.owner_uri(key)

    def proxy_submit(self, sql: str, owner: str) -> Optional[dict]:
        """Forward a statement to its owning coordinator and re-home the
        payload's URIs so the (dumb) client keeps talking to THIS
        server; follow-up polls forward through the proxied-query map.
        None on any proxy failure — the caller executes locally."""
        import urllib.request

        from presto_tpu.server import fleet as FL

        try:
            req = urllib.request.Request(
                f"{owner}/v1/statement", data=sql.encode(), method="POST")
            with urllib.request.urlopen(
                    req, timeout=FL.PROXY_TIMEOUT_S) as resp:
                payload = json.loads(resp.read().decode())
        except Exception:  # noqa: BLE001 — degrade to local execution
            self.fleet_counters["proxy_failures"] += 1
            return None
        qid = payload.get("id")
        if qid:
            with self._proxied_lock:
                self._proxied[qid] = owner
        self.fleet_counters["proxied"] += 1
        self.fleet.counters["routed_away"] += 1
        return self._rehome(payload, owner)

    def proxy_fetch(self, owner: str, path: str,
                    method: str = "GET") -> Optional[dict]:
        """Forward a follow-up (page poll / cancel) for a proxied query
        to its owner; None when the owner is unreachable."""
        import urllib.request

        from presto_tpu.server import fleet as FL

        from presto_tpu.parallel import faults as F

        if F.client_plan().match("client", "PROXY",
                                 f"{owner}{path}") is not None:
            # scripted coordinator-death-mid-poll: the owner door is
            # unreachable at exactly the nth proxied poll (any action)
            self.fleet_counters["proxy_failures"] += 1
            return None
        try:
            req = urllib.request.Request(f"{owner}{path}", method=method)
            with urllib.request.urlopen(
                    req, timeout=FL.PROXY_TIMEOUT_S) as resp:
                return self._rehome(json.loads(resp.read().decode()),
                                    owner)
        except Exception:  # noqa: BLE001
            self.fleet_counters["proxy_failures"] += 1
            return None

    def _rehome(self, payload: dict, owner: str) -> dict:
        for k in ("nextUri", "infoUri"):
            v = payload.get(k)
            if isinstance(v, str) and v.startswith(owner):
                payload[k] = self.uri + v[len(owner):]
        return payload

    def proxied_owner(self, qid: str) -> Optional[str]:
        with self._proxied_lock:
            return self._proxied.get(qid)

    # -- journaled failover (parallel/journal.py) ----------------------
    def attach_fleet(self, fleet) -> None:
        """Wire a FleetMember into this door: ring-affinity routing in
        the serving tier, query journaling + adoption (with `query_journal`
        not explicitly off, this door journals in-flight read queries
        and adopts a dead peer's journaled queries when discovery/gossip
        declares the death — the ring successor is the deterministic
        adopter), and the peer journal/death subscriptions.  `fleet=None`
        at construction is the single-coordinator path, byte-identical
        to round 18."""
        from presto_tpu.parallel import journal as _J

        self.fleet = fleet
        self.serving.attach_fleet(fleet)
        if _J.enabled(self.session.properties, fleet_attached=True):
            self._journal = _J.QueryJournal(
                _J.root_dir(self.session.properties),
                coord_id=fleet.coord_id)
        fleet.subscribe(on_death=self._on_peer_death,
                        on_journal=self._on_peer_journal)

    def _on_peer_journal(self, entry: dict) -> None:
        """Best-effort replication receive: persist a peer's journal
        entry locally so adoption works even when the journal root is
        not a genuinely shared directory (idempotent when it is)."""
        if self._journal is not None and entry.get("queryId"):
            self._journal.write(dict(entry))

    def _on_peer_death(self, dead_id: str) -> None:
        """Fleet death relay (discovery.watch_fleet -> directory.leave
        -> on_death): the ring SUCCESSOR of the dead coordinator — a
        pure function of the post-leave ring, so every survivor picks
        the same adopter — resumes its journaled in-flight queries."""
        if self._journal is None or self.fleet is None \
                or not self.fleet.should_adopt(dead_id):
            return
        threading.Thread(target=self._adopt_from, args=(dead_id,),
                         daemon=True).start()

    def _adopt_from(self, dead_id: str) -> None:
        t0 = time.monotonic()
        adopted = 0
        for e in self._journal.entries(coord=dead_id):
            qid = str(e.get("queryId", ""))
            sql = str(e.get("sql", ""))
            if not qid or not sql:
                continue
            with self.jobs_lock:
                if qid in self.jobs:
                    continue  # already adopted (or raced a re-submit)
                job = _QueryJob(query_id=qid, sql=sql)
                self.jobs[qid] = job
                self.active_queries += 1
            # re-home the entry FIRST: peers' journal_lookup proxies
            # the client's polls here while the query re-runs
            e["coord"] = self.fleet.coord_id
            if self._journal.write(e):
                self.fleet.replicate_journal(e)
            adopted += 1
            self._run_adopted(job, e)
        if adopted:
            from presto_tpu.observe import metrics as M

            self.fleet_counters["queries_adopted"] += adopted
            self.fleet_counters["adoption_ms"] += max(
                int((time.monotonic() - t0) * 1000.0), 1)
            M.record_recovery("queries_adopted", adopted)

    def _run_adopted(self, job: _QueryJob, entry: dict) -> None:
        """Execute one adopted query under its ORIGINAL query id.  A
        journaled durable-exchange dir routes through the session's
        resume path (completed tasks replay from the durable store);
        otherwise the statement re-executes — reads only, so re-running
        is safe (see the journaling filter in _run_job)."""
        try:
            job.state = "RUNNING"
            if entry.get("ddir") and hasattr(self.session, "resume_sql"):
                result = self.session.resume_sql(
                    job.sql, entry.get("ddir"),
                    int(entry.get("attempt", 0)),
                    query_id=job.query_id)
            else:
                result = self.session.sql(job.sql)
            job.columns = [{"name": n, "type": str(t).lower()}
                           for n, t in result.columns]
            job.rows = [list(r) for r in result.rows]
            job.stats = {"state": "FINISHED",
                         "processedRows": len(job.rows),
                         "adopted": True}
            job.state = "FINISHED"
        except Exception as e:  # noqa: BLE001 — adoption reports all errors
            job.error = f"{type(e).__name__}: {e}"
            job.state = "FAILED"
        finally:
            self._journal.remove(job.query_id)
            job.done.set()
            with self.jobs_lock:
                self.active_queries -= 1

    def journal_lookup(self, qid: str, path: str) -> Optional[dict]:
        """Coordinator-death-mid-poll fallback for the unknown-qid
        chain: a query id that appears in the fleet journal is in
        flight SOMEWHERE — proxy the poll to the entry's (re-homed)
        coordinator, then to the dead owner's ring successor, and as a
        last resort hold the client in RUNNING against THIS door while
        the adoption races the poll."""
        if self._journal is None or self.fleet is None:
            return None
        e = self._journal.read(qid)
        if e is None:
            return None
        coord = str(e.get("coord", ""))
        if coord and coord != self.fleet.coord_id:
            target = self.fleet.coordinator_uri(coord)
            if target is not None and target != self.uri:
                got = self.proxy_fetch(target, path)
                if got is not None:
                    return got
            # journaled owner unreachable (it probably just died):
            # its ring successor is the deterministic adopter
            succ = self.fleet.adopter_of(coord)
            if succ and succ != self.fleet.coord_id:
                target = self.fleet.coordinator_uri(succ)
                if target is not None and target != self.uri:
                    got = self.proxy_fetch(target, path)
                    if got is not None:
                        return got
        return {"id": qid,
                "infoUri": f"{self.uri}/v1/query/{qid}",
                "stats": {"state": "RUNNING"},
                "nextUri": f"{self.uri}{path}"}

    # -- protocol payloads --------------------------------------------
    def results_payload(self, job: _QueryJob, token: int) -> dict:
        base = f"{self.uri}/v1/statement/{job.query_id}"
        out = {"id": job.query_id,
               "infoUri": f"{self.uri}/v1/query/{job.query_id}"}
        if job.state in ("QUEUED", "RUNNING"):
            out["stats"] = {"state": job.state}
            out["nextUri"] = f"{base}/{token}"  # poll same token until data
            return out
        if job.state == "FAILED":
            out["error"] = {"message": job.error,
                            "errorCode": job.error_code or "QUERY_FAILED"}
            out["stats"] = {"state": "FAILED"}
            return out
        if job.state == "CANCELED":
            out["stats"] = {"state": "CANCELED"}
            if job.error:  # drained by graceful shutdown: say why
                out["error"] = {"message": job.error,
                                "errorCode": job.error_code or "USER_CANCELED"}
            return out
        start = token * PAGE_ROWS
        page = job.rows[start:start + PAGE_ROWS]
        out["columns"] = job.columns
        if page:
            out["data"] = page
        out["stats"] = job.stats
        if start + PAGE_ROWS < len(job.rows):
            out["nextUri"] = f"{base}/{token + 1}"
        else:
            self._prune_done()
        return out

    MAX_DONE_JOBS = 64

    def _prune_done(self) -> None:
        """Bound retained results: keep the newest MAX_DONE_JOBS finished
        jobs so recent pages stay refetchable (at-least-once) while the
        server never accumulates every result ever produced (reference:
        QueryTracker expiry, execution/QueryTracker.java)."""
        with self.jobs_lock:
            done = [qid for qid, j in self.jobs.items() if j.done.is_set()]
            for qid in done[:-self.MAX_DONE_JOBS]:
                del self.jobs[qid]

    def query_list_payload(self) -> list:
        out = []
        for st in self.session.history_snapshot():
            out.append({
                "queryId": st.query_id, "query": st.sql, "state": st.state,
                "executionMode": st.execution_mode,
                "elapsedTimeMillis": int(st.total_ns / 1e6),
                "outputRows": st.output_rows, "error": st.error,
                "peakMemoryBytes": st.peak_memory_bytes,
                "createTime": st.create_time, "endTime": st.end_time,
            })
        return out

    def query_detail_payload(self, st) -> dict:
        """Query-detail view for the web UI's plan/stage/timeline panes
        (reference: webapp query.jsx + plan.jsx + stage.jsx consuming
        /v1/query/{id})."""
        plan_text = st.plan_text
        if not plan_text:
            # plans are pure functions of (sql, catalog): render on
            # demand for queries that ran through the fused paths
            try:
                from presto_tpu.exec.executor import explain_text
                from presto_tpu.sql import ast as _ast
                from presto_tpu.sql.parser import parse as _parse

                stmt = _parse(st.sql)
                if isinstance(stmt, _ast.QueryStatement):
                    plan_text = explain_text(self.session, stmt)
            except Exception:
                plan_text = ""
        nodes = []
        for ns in st.node_stats.values():
            nodes.append({"kind": ns.node_kind, "rowsOut": ns.rows_out,
                          "wallMillis": round(ns.wall_ns / 1e6, 2),
                          "invocations": ns.invocations})
        nodes.sort(key=lambda n: -n["wallMillis"])
        return {
            "queryId": st.query_id, "query": st.sql,
            "state": st.state, "error": st.error,
            "executionMode": st.execution_mode,
            "createTime": st.create_time, "endTime": st.end_time,
            "phaseMillis": {k: v / 1e6 for k, v in st.phase_ns.items()},
            "outputRows": st.output_rows,
            "peakMemoryBytes": st.peak_memory_bytes,
            "spilledBytes": st.spilled_bytes,
            # dynamic filtering (plan/runtime_filters.py): per-query
            # filter economics for the UI's query pane
            "dynamicFilters": {
                "produced": getattr(st, "df_filters_produced", 0),
                "applied": getattr(st, "df_filters_applied", 0),
                "rowsPruned": getattr(st, "df_rows_pruned", 0),
                "chunksPruned": getattr(st, "df_chunks_pruned", 0),
                "splitsPruned": getattr(st, "df_splits_pruned", 0),
                "waitMillis": round(getattr(st, "df_wait_ms", 0.0), 1),
            },
            # fragment fusion (plan/fusion_cost.py): the per-edge
            # fuse-vs-cut economics — edges spliced vs kept on the HTTP
            # path, memo-vs-model disagreements, the pricing wall, and
            # the per-reason skip counts that make a cost-cut edge
            # distinguishable from a kind-filtered or cross-host one
            "fragmentFusion": {
                "fragmentsFused": getattr(st, "fragments_fused", 0),
                "edgesFused": getattr(st, "fusion_edges_fused", 0),
                "edgesCut": getattr(st, "fusion_edges_cut", 0),
                "edgesMispredicted": getattr(
                    st, "fusion_edges_mispredicted", 0),
                "costMillis": round(
                    getattr(st, "fusion_cost_ms", 0.0), 2),
                "skips": dict(getattr(st, "fusion_skips", None) or {}),
                "exchangeBytesHost": getattr(
                    st, "exchange_bytes_host", 0),
                "exchangeBytesCollective": getattr(
                    st, "exchange_bytes_collective", 0),
            },
            # serving tier (server/serving.py): admission + prepared +
            # result-cache facts (reference parity: the query JSON's
            # resourceGroupId and queuedTime)
            "resourceGroupId": getattr(st, "resource_group", "") or None,
            "admissionWaitMillis": round(
                getattr(st, "admission_wait_ms", 0.0), 1),
            "resultCacheHit": bool(getattr(st, "result_cache_hit", 0)),
            "prepared": {
                "binds": getattr(st, "prepared_binds", 0),
                "planHits": getattr(st, "prepared_plan_hits", 0),
                "fallbacks": getattr(st, "prepared_fallbacks", 0),
            },
            # query coalescing (server/serving.QueryCoalescer): how many
            # queries shared this query's XLA launch (0 = solo), the
            # micro-batch window wait the leader paid, and batch
            # memberships abandoned for a solo re-run
            "coalescing": {
                "batchSize": getattr(st, "coalesced_batch_size", 0),
                "windowWaitMillis": round(
                    getattr(st, "coalesce_ms", 0.0), 2),
                "batchesLed": getattr(st, "coalesce_batches", 0),
                "fallbacks": getattr(st, "coalesce_fallbacks", 0),
            },
            # tracing (observe/trace.py): the chrome trace lives at
            # /v1/query/{id}/trace; spanCount hints whether it's worth
            # fetching (0 = tracing was off for this query)
            "traceId": getattr(st, "trace_id", "") or None,
            "traceUri": f"/v1/query/{st.query_id}/trace",
            "spanCount": len(getattr(st, "trace_spans", None) or []),
            "planText": plan_text,
            "nodes": nodes,
        }

    def metrics_payload(self) -> str:
        """GET /v1/metrics: the Prometheus text exposition of the
        process-wide registry (observe/metrics.py), which every
        QueryStats counter / recovery action / serving decision rolls
        into at query completion.  Serving-tier aggregates are exported
        as gauges at scrape time."""
        from presto_tpu.observe import metrics as M

        M.REGISTRY.gauge("presto_tpu_server_active_queries",
                         "Queries admitted and not yet finished") \
            .set(self.active_queries)
        M.REGISTRY.gauge("presto_tpu_serving_admitted_total",
                         "Queries admitted by the serving tier") \
            .set(self.serving.queries_admitted)
        M.REGISTRY.gauge("presto_tpu_serving_shed_total",
                         "Queries shed by admission control") \
            .set(self.serving.queries_shed)
        M.REGISTRY.gauge("presto_tpu_serving_drained_total",
                         "Queued queries drained at shutdown") \
            .set(self.serving.queries_drained)
        M.REGISTRY.gauge("presto_tpu_serving_peak_queue_depth",
                         "Peak admission queue depth") \
            .set(self.serving.peak_queue_depth)
        co = self.serving.coalescer_stats()
        if co is not None:
            import re as _re

            for k, v in co.items():
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    snake = _re.sub(r"(?<!^)(?=[A-Z])", "_", k).lower()
                    M.REGISTRY.gauge(
                        f"presto_tpu_coalesce_{snake}",
                        f"Query coalescer {k}").set(v)
        if self.serving.result_cache is not None:
            rc = self.serving.result_cache.stats()
            for k, v in rc.items():
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    M.REGISTRY.gauge(
                        f"presto_tpu_result_cache_{k}",
                        f"Result cache {k}").set(v)
        if self.fleet is not None:
            M.set_fleet_gauges({**self.fleet.stats(),
                                **self.fleet_counters})
        return M.render_scrape()

    def trace_payload(self, st) -> dict:
        """GET /v1/query/{id}/trace: the query's chrome trace-event
        JSON (observe/trace.py) — open in Perfetto / chrome://tracing."""
        from presto_tpu.observe import trace as TR

        return TR.chrome_trace(st.trace_spans or [],
                               getattr(st, "trace_id", ""))

    def info_payload(self) -> dict:
        from presto_tpu.observe import trace as TR

        out = {
            "nodeId": self.node_id,
            "uptimeMillis": int((TR.wall_s() - self.start_time) * 1000),
            "state": "SHUTTING_DOWN" if self.shutting_down.is_set()
                     else "ACTIVE",
            "coordinator": True,
        }
        # per-group running/queued/shed counters (reference parity:
        # /v1/resourceGroupState folded into the node info for the
        # serving dashboards) + serving-tier aggregates
        rgm = self.resource_groups
        if rgm is not None:
            out["resourceGroups"] = rgm.info()
        out["serving"] = {
            "admitted": self.serving.queries_admitted,
            "shed": self.serving.queries_shed,
            "drained": self.serving.queries_drained,
            "peakQueueDepth": self.serving.peak_queue_depth,
            "coalescing": self.serving.coalescer_stats(),
            "resultCache": (self.serving.result_cache.stats()
                            if self.serving.result_cache is not None
                            else None),
        }
        if self.fleet is not None:
            out["fleet"] = {**self.fleet.stats(), **self.fleet_counters}
        return out


def _make_handler(server: PrestoTpuServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # silence default stderr noise
            pass

        def _json(self, payload, code: int = 200):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _authenticate(self) -> bool:
            """HTTP Basic against the configured PasswordAuthenticator;
            True == proceed.  401 + WWW-Authenticate on failure."""
            if server.authenticator is None:
                return True
            import base64 as _b64

            from presto_tpu.security import AuthenticationError

            hdr = self.headers.get("Authorization", "")
            if hdr.startswith("Basic "):
                try:
                    user, _, pw = _b64.b64decode(
                        hdr[6:]).decode("utf-8").partition(":")
                    server.authenticator.authenticate(user, pw)
                    return True
                except (AuthenticationError, ValueError):
                    pass
            # drain a BOUNDED amount of request body so small keep-alive
            # requests can retry cleanly; oversized unauthenticated bodies
            # are not buffered (pre-auth memory safety) — the connection
            # closes instead
            n = int(self.headers.get("Content-Length", 0) or 0)
            drained = 0
            while drained < min(n, 1 << 20):
                chunk = self.rfile.read(min(65536, n - drained))
                if not chunk:
                    break
                drained += len(chunk)
            self.close_connection = True
            self.send_response(401)
            self.send_header("WWW-Authenticate",
                             'Basic realm="presto_tpu"')
            self.send_header("Content-Length", "0")
            self.send_header("Connection", "close")
            self.end_headers()
            return False

        def do_POST(self):
            if not self._authenticate():
                return
            parts = [p for p in self.path.split("/") if p]
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            if parts[:2] == ["v1", "fleet"] and len(parts) == 3:
                return self._fleet_post(parts[2], body)
            if self.path != "/v1/statement":
                return self._json({"error": "not found"}, 404)
            if server.shutting_down.is_set():
                return self._json({"error": "shutting down"}, 503)
            sql = body.decode()
            owner = server.route_target(sql)
            if owner is not None:
                mode = str(server.session.properties.get(
                    "fleet_affinity", "proxy")).lower()
                if mode == "redirect":
                    # dumb-LB escape hatch: clients that follow 307
                    # (method+body preserved) talk to the owner directly
                    # from here on — no proxy hop per page
                    server.fleet_counters["redirected"] += 1
                    server.fleet.counters["routed_away"] += 1
                    loc = f"{owner}/v1/statement"
                    payload = json.dumps({"redirect": loc}).encode()
                    self.send_response(307)
                    self.send_header("Location", loc)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                proxied = server.proxy_submit(sql, owner)
                if proxied is not None:
                    return self._json(proxied)
                # owner unreachable: routing is an optimization — run it
                # here (the version-keyed caches keep this correct)
            job = server.submit(sql)
            # brief grace so fast queries return data on the first response
            job.done.wait(timeout=FIRST_RESPONSE_GRACE_S)
            self._json(server.results_payload(job, 0))

        def _fleet_post(self, action: str, body: bytes):
            """Peer-to-peer fleet bus: invalidation broadcast, health
            gossip, prepared replication (server/fleet.py)."""
            if server.fleet is None:
                return self._json({"error": "no fleet attached"}, 404)
            try:
                payload = json.loads(body.decode() or "{}")
            except ValueError:
                return self._json({"error": "bad fleet payload"}, 400)
            if action == "invalidate":
                tables = payload.get("tables")
                server.fleet.on_invalidate(
                    str(payload.get("origin", "")),
                    str(payload.get("token", "")),
                    int(payload.get("version", 0) or 0),
                    tables=set(tables) if tables else None)
                return self._json({"ok": True})
            if action == "health":
                server.fleet.on_health(
                    str(payload.get("origin", "")),
                    str(payload.get("worker", "")),
                    str(payload.get("verdict", "open")))
                return self._json({"ok": True})
            if action == "prepare":
                try:
                    server.session.sql(str(payload.get("sql", "")))
                except Exception as e:  # noqa: BLE001 — reported to peer
                    return self._json(
                        {"error": f"{type(e).__name__}: {e}"}, 400)
                return self._json({"ok": True})
            if action == "journal":
                server.fleet.on_journal(
                    str(payload.get("origin", "")),
                    payload.get("entry") or {})
                return self._json({"ok": True})
            return self._json({"error": "not found"}, 404)

        def do_GET(self):
            if not self._authenticate():
                return
            parts = [p for p in self.path.split("/") if p]
            if parts[:2] == ["v1", "statement"] and len(parts) == 4:
                job = server.jobs.get(parts[2])
                if job is None:
                    owner = server.proxied_owner(parts[2])
                    if owner is not None:
                        proxied = server.proxy_fetch(owner, self.path)
                        if proxied is not None:
                            return self._json(proxied)
                    # coordinator-death-mid-poll: an unknown qid that
                    # the fleet journal knows is in flight elsewhere
                    # (or being adopted right here) keeps the client
                    # polling instead of 404ing
                    adopted = server.journal_lookup(parts[2], self.path)
                    if adopted is not None:
                        return self._json(adopted)
                    return self._json({"error": "unknown query"}, 404)
                try:
                    token = int(parts[3])
                except ValueError:
                    return self._json({"error": "bad page token"}, 400)
                if token < 0:
                    return self._json({"error": "bad page token"}, 400)
                if job.state in ("QUEUED", "RUNNING"):
                    job.done.wait(timeout=LONG_POLL_S)  # long poll
                return self._json(server.results_payload(job, token))
            if parts == ["v1", "query"]:
                return self._json(server.query_list_payload())
            if parts[:2] == ["v1", "query"] and len(parts) == 4 \
                    and parts[3] == "trace":
                for st in server.session.history_snapshot():
                    if st.query_id == parts[2]:
                        return self._json(server.trace_payload(st))
                return self._json({"error": "unknown query"}, 404)
            if parts[:2] == ["v1", "query"] and len(parts) == 3:
                for st in server.session.history_snapshot():
                    if st.query_id == parts[2]:
                        return self._json(server.query_detail_payload(st))
                return self._json({"error": "unknown query"}, 404)
            if parts == ["v1", "metrics"]:
                body = server.metrics_payload().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parts == ["v1", "info"]:
                return self._json(server.info_payload())
            if parts == ["v1", "status"]:  # heartbeat probe target
                return self._json({"nodeId": server.node_id, "alive": True})
            if parts == ["ui"] or parts == []:
                # the web UI (reference: presto-main webapp/); the static
                # page is cached on the server object at first request
                body = getattr(server, "_ui_bytes", None)
                if body is None:
                    import os as _os

                    path = _os.path.join(
                        _os.path.dirname(_os.path.abspath(__file__)),
                        "ui.html")
                    try:
                        with open(path, "rb") as f:
                            body = server._ui_bytes = f.read()
                    except OSError:
                        return self._json({"error": "ui not installed"}, 404)
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parts == ["v1", "resourceGroupState"]:
                rgm = server.resource_groups
                return self._json(rgm.info() if rgm is not None else [])
            if parts == ["v1", "cluster"]:
                with server.jobs_lock:
                    active = server.active_queries
                return self._json({
                    "runningQueries": active,
                    "totalQueries": len(server.session.history)})
            return self._json({"error": "not found"}, 404)

        def do_DELETE(self):
            if not self._authenticate():
                return
            parts = [p for p in self.path.split("/") if p]
            if parts[:2] == ["v1", "statement"] and len(parts) >= 3:
                job = server.jobs.get(parts[2])
                if job is not None:
                    job.cancel.set()
                    if job.state in ("QUEUED",):
                        job.state = "CANCELED"
                    return self._json({"canceled": True}, 200)
                owner = server.proxied_owner(parts[2])
                if owner is not None:
                    proxied = server.proxy_fetch(owner, self.path,
                                                 method="DELETE")
                    if proxied is not None:
                        return self._json(proxied)
            self._json({"error": "not found"}, 404)

        def do_PUT(self):
            if self.path == "/v1/info/state":
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode().strip().strip('"')
                if body == "SHUTTING_DOWN":
                    threading.Thread(target=server.graceful_shutdown,
                                     daemon=True).start()
                    return self._json({"state": "SHUTTING_DOWN"})
            self._json({"error": "bad request"}, 400)

    return Handler
