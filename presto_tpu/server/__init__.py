"""HTTP server layer: client protocol, introspection, cluster control.

Reference parity: presto-main server/ — StatementResource (client
protocol), QueryResource (introspection), ClusterStatsResource,
GracefulShutdownHandler — plus the discovery/failure-detection loop
(failureDetector/HeartbeatFailureDetector.java).
"""

from presto_tpu.server.protocol import PrestoTpuServer

__all__ = ["PrestoTpuServer", "ServingTier", "FleetDirectory",
           "FleetMember", "OwnershipRing"]


def __getattr__(name):  # lazy: serving pulls in the executor stack
    if name == "ServingTier":
        from presto_tpu.server.serving import ServingTier

        return ServingTier
    if name in ("FleetDirectory", "FleetMember", "OwnershipRing"):
        from presto_tpu.server import fleet as _fleet

        return getattr(_fleet, name)
    raise AttributeError(name)
