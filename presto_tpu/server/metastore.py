"""Metastore service: table/partition metadata behind an HTTP boundary.

Reference parity: the Hive metastore as the reference consumes it —
presto-hive/.../metastore/HiveMetastore.java (getTable /
getPartitionNames / addPartitions / dropTable) with the file-backed
implementation shape of FileHiveMetastore (one JSON document per table,
partitions listed alongside).  The service is deliberately REMOTE: the
connector talks to it over HTTP exactly the way the reference talks
thrift to a metastore process, so the connector SPI exercises a real
network metadata round trip (VERDICT r4: "the SPI has never met a
remote metastore-shaped system").

Three pieces:
  Metastore        — file-backed store (thread-safe, crash-consistent
                     via write-temp-then-rename)
  MetastoreServer  — ThreadingHTTPServer exposing the store as JSON
  MetastoreClient  — urllib client used by connectors/hive.py

`python -m presto_tpu.server.metastore --root DIR [--port N]` runs the
service standalone (the separate-process deployment the reference
assumes).
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

#: directory-name encoding of a NULL partition value (hive's exact token)
NULL_PARTITION = "__HIVE_DEFAULT_PARTITION__"


class MetastoreError(Exception):
    def __init__(self, msg: str, status: int = 400):
        super().__init__(msg)
        self.status = status


class Metastore:
    """File-backed metadata store.  Layout under `root`:

        <root>/<db>.db/<table>/.ptms_table.json

    The JSON document carries columns, partition columns, storage format,
    data location, table parameters, and the partition list (spec values
    + location + parameters such as numRows) — FileHiveMetastore keeps
    the same shape in .prestoSchema/.prestoPermissions documents."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        #: bumps on every mutation; clients cache partition lists per seq
        self.sequence = 0

    # ---- paths -------------------------------------------------------
    def _db_dir(self, db: str) -> str:
        if not db or "/" in db or db.startswith("."):
            raise MetastoreError(f"invalid database name '{db}'")
        return os.path.join(self.root, db + ".db")

    def _table_doc(self, db: str, table: str) -> str:
        if not table or "/" in table or table.startswith("."):
            raise MetastoreError(f"invalid table name '{table}'")
        return os.path.join(self._db_dir(db), table, ".ptms_table.json")

    # ---- databases ---------------------------------------------------
    def create_database(self, db: str) -> None:
        with self._lock:
            os.makedirs(self._db_dir(db), exist_ok=True)
            self.sequence += 1

    def databases(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(d[:-3] for d in os.listdir(self.root)
                      if d.endswith(".db")
                      and os.path.isdir(os.path.join(self.root, d)))

    # ---- tables ------------------------------------------------------
    def create_table(self, db: str, table: str, doc: dict) -> None:
        for field in ("columns", "partition_columns", "format", "location"):
            if field not in doc:
                raise MetastoreError(f"table document missing '{field}'")
        if doc["format"] not in ("parquet", "orc", "csv"):
            raise MetastoreError(f"unknown storage format '{doc['format']}'")
        data_cols = {c for c, _t in doc["columns"]}
        for c, _t in doc["partition_columns"]:
            if c in data_cols:
                raise MetastoreError(
                    f"partition column '{c}' duplicates a data column")
        doc = dict(doc)
        doc.setdefault("parameters", {})
        doc["partitions"] = {}  # spec-path -> {values, location, parameters}
        with self._lock:
            path = self._table_doc(db, table)
            if os.path.exists(path):
                raise MetastoreError(
                    f"table '{db}.{table}' already exists", status=409)
            if not os.path.isdir(self._db_dir(db)):
                raise MetastoreError(
                    f"database '{db}' does not exist", status=404)
            self._write(path, doc)
            self.sequence += 1

    def get_table(self, db: str, table: str) -> dict:
        doc = self._read(self._table_doc(db, table))
        if doc is None:
            raise MetastoreError(
                f"table '{db}.{table}' does not exist", status=404)
        return doc

    def tables(self, db: str) -> List[str]:
        d = self._db_dir(db)
        if not os.path.isdir(d):
            raise MetastoreError(f"database '{db}' does not exist",
                                 status=404)
        out = []
        for t in os.listdir(d):
            if os.path.exists(os.path.join(d, t, ".ptms_table.json")):
                out.append(t)
        return sorted(out)

    def drop_table(self, db: str, table: str) -> None:
        with self._lock:
            path = self._table_doc(db, table)
            if not os.path.exists(path):
                raise MetastoreError(
                    f"table '{db}.{table}' does not exist", status=404)
            os.remove(path)
            try:
                os.rmdir(os.path.dirname(path))
            except OSError:
                pass  # table dir shared with data files
            self.sequence += 1

    def update_parameters(self, db: str, table: str, params: dict) -> None:
        """Merge table-level parameters (stats like numRows ride here,
        the way hive stores them in Table.parameters)."""
        with self._lock:
            doc = self.get_table(db, table)
            doc["parameters"].update(params)
            self._write(self._table_doc(db, table), doc)
            self.sequence += 1

    # ---- partitions --------------------------------------------------
    def add_partitions(self, db: str, table: str,
                       parts: List[dict]) -> None:
        """Upsert partitions: [{values: [...], location, parameters}].
        Values align with the table's partition_columns; None encodes a
        NULL partition key (reference: Partition.getValues)."""
        with self._lock:
            doc = self.get_table(db, table)
            pcols = doc["partition_columns"]
            for p in parts:
                vals = p.get("values")
                if vals is None or len(vals) != len(pcols):
                    raise MetastoreError(
                        f"partition values {vals!r} do not match partition "
                        f"columns {[c for c, _ in pcols]}")
                key = partition_path(
                    [c for c, _ in pcols], vals)
                old = doc["partitions"].get(key, {})
                merged_params = dict(old.get("parameters", {}))
                merged_params.update(p.get("parameters", {}))
                doc["partitions"][key] = {
                    "values": list(vals),
                    "location": p.get("location", key),
                    "parameters": merged_params,
                }
            self._write(self._table_doc(db, table), doc)
            self.sequence += 1

    def partitions(self, db: str, table: str) -> List[dict]:
        doc = self.get_table(db, table)
        return [dict(p, name=k) for k, p in
                sorted(doc["partitions"].items())]

    def drop_partition(self, db: str, table: str, name: str) -> None:
        with self._lock:
            doc = self.get_table(db, table)
            if name not in doc["partitions"]:
                raise MetastoreError(
                    f"partition '{name}' does not exist", status=404)
            del doc["partitions"][name]
            self._write(self._table_doc(db, table), doc)
            self.sequence += 1

    # ---- document IO -------------------------------------------------
    @staticmethod
    def _write(path: str, doc: dict) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)  # atomic: readers never see a torn doc

    @staticmethod
    def _read(path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None


def partition_path(cols: List[str], values: List) -> str:
    """Hive-style partition directory name: col=value/col=value with
    %-escaping of separator bytes; NULL encodes as the hive default
    token (reference: FileUtils.makePartName)."""
    segs = []
    for c, v in zip(cols, values):
        if v is None:
            enc = NULL_PARTITION
        else:
            enc = urllib.parse.quote(str(v), safe="")
        segs.append(f"{c}={enc}")
    return "/".join(segs)


def parse_partition_path(name: str) -> List[Optional[str]]:
    """Inverse of partition_path: directory name -> string values
    (None for the NULL token); types re-apply in the connector."""
    vals: List[Optional[str]] = []
    for seg in name.split("/"):
        _c, _eq, enc = seg.partition("=")
        vals.append(None if enc == NULL_PARTITION
                    else urllib.parse.unquote(enc))
    return vals


# ---------------------------------------------------------------------
# HTTP service
# ---------------------------------------------------------------------

class MetastoreServer:
    """The metastore behind HTTP (reference deployment shape: a thrift
    metastore process the connector dials; JSON replaces thrift).  A
    shared `secret` token, when set, must ride the X-Metastore-Token
    header on every request."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 secret: Optional[str] = None):
        self.store = Metastore(root)
        self.secret = secret
        handler = _make_handler(self.store, secret)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetastoreServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="metastore", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def _make_handler(store: Metastore, secret: Optional[str]):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _send(self, status: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _authed(self) -> bool:
            if secret is None:
                return True
            import hmac as _hmac

            given = self.headers.get("X-Metastore-Token", "")
            return _hmac.compare_digest(given, secret)

        def _route(self, method: str):
            if not self._authed():
                return self._send(401, {"error": "bad metastore token"})
            parts = [urllib.parse.unquote(p) for p in
                     self.path.split("?")[0].strip("/").split("/")]
            body = None
            n = int(self.headers.get("Content-Length") or 0)
            if n:
                try:
                    body = json.loads(self.rfile.read(n))
                except (ValueError, UnicodeDecodeError):
                    return self._send(400, {"error": "bad JSON body"})
            try:
                out = self._dispatch(method, parts, body)
            except MetastoreError as e:
                return self._send(e.status, {"error": str(e)})
            self._send(200, out)

        def _dispatch(self, method: str, parts: List[str], body):
            # /v1/sequence
            if parts == ["v1", "sequence"]:
                return {"sequence": store.sequence}
            # /v1/database[/db[/table[/tbl[/partition]]]]
            if len(parts) < 2 or parts[0] != "v1" \
                    or parts[1] != "database":
                raise MetastoreError(f"no route {self.path}", status=404)
            rest = parts[2:]
            if not rest:
                return {"databases": store.databases()}
            db = rest[0]
            if len(rest) == 1:
                if method == "POST":
                    store.create_database(db)
                    return {"ok": True}
                return {"tables": store.tables(db)}
            if rest[1] != "table":
                raise MetastoreError(f"no route {self.path}", status=404)
            if len(rest) == 2:
                return {"tables": store.tables(db)}
            tbl = rest[2]
            if len(rest) == 3:
                if method == "POST":
                    store.create_table(db, tbl, body or {})
                    return {"ok": True}
                if method == "DELETE":
                    store.drop_table(db, tbl)
                    return {"ok": True}
                doc = store.get_table(db, tbl)
                doc = {k: v for k, v in doc.items() if k != "partitions"}
                return doc
            if rest[3] == "parameters" and method == "POST":
                store.update_parameters(db, tbl, body or {})
                return {"ok": True}
            if rest[3] == "partition":
                if len(rest) == 4:
                    if method == "POST":
                        store.add_partitions(
                            db, tbl, (body or {}).get("partitions", []))
                        return {"ok": True, "sequence": store.sequence}
                    return self._partitions_snapshot(db, tbl)
                if method == "DELETE":
                    store.drop_partition(db, tbl, "/".join(rest[4:]))
                    return {"ok": True}
            raise MetastoreError(f"no route {self.path}", status=404)

        @staticmethod
        def _partitions_snapshot(db, tbl):
            # sequence BEFORE the list: if a mutation interleaves, the
            # stamp is stale and the client cache refreshes next call —
            # the inverse order could stamp an old list with a new
            # sequence and pin it stale forever
            seq = store.sequence
            return {"partitions": store.partitions(db, tbl),
                    "sequence": seq}

        def do_GET(self):
            self._route("GET")

        def do_POST(self):
            self._route("POST")

        def do_DELETE(self):
            self._route("DELETE")

    return Handler


class MetastoreClient:
    """HTTP client for the metastore service (the connector's analog of
    ThriftHiveMetastoreClient)."""

    def __init__(self, uri: str, secret: Optional[str] = None,
                 timeout: float = 10.0):
        self.uri = uri.rstrip("/")
        self.secret = secret
        self.timeout = timeout

    def _call(self, method: str, path: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.uri + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        if self.secret is not None:
            req.add_header("X-Metastore-Token", self.secret)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:
                msg = str(e)
            raise MetastoreError(msg, status=e.code) from None
        except (urllib.error.URLError, OSError) as e:
            # connection refused / timeout: callers handle MetastoreError,
            # not raw urllib internals
            raise MetastoreError(
                f"metastore unreachable at {self.uri}: {e}",
                status=503) from None

    def sequence(self) -> int:
        return self._call("GET", "/v1/sequence")["sequence"]

    def databases(self) -> List[str]:
        return self._call("GET", "/v1/database")["databases"]

    def create_database(self, db: str) -> None:
        self._call("POST", f"/v1/database/{urllib.parse.quote(db)}")

    def tables(self, db: str) -> List[str]:
        return self._call(
            "GET", f"/v1/database/{urllib.parse.quote(db)}/table")["tables"]

    def create_table(self, db: str, table: str, doc: dict) -> None:
        self._call("POST", f"/v1/database/{urllib.parse.quote(db)}/table/"
                   f"{urllib.parse.quote(table)}", doc)

    def get_table(self, db: str, table: str) -> dict:
        return self._call(
            "GET", f"/v1/database/{urllib.parse.quote(db)}/table/"
            f"{urllib.parse.quote(table)}")

    def drop_table(self, db: str, table: str) -> None:
        self._call("DELETE", f"/v1/database/{urllib.parse.quote(db)}/table/"
                   f"{urllib.parse.quote(table)}")

    def update_parameters(self, db: str, table: str, params: dict) -> None:
        self._call("POST", f"/v1/database/{urllib.parse.quote(db)}/table/"
                   f"{urllib.parse.quote(table)}/parameters", params)

    def add_partitions(self, db: str, table: str,
                       parts: List[dict]) -> int:
        r = self._call(
            "POST", f"/v1/database/{urllib.parse.quote(db)}/table/"
            f"{urllib.parse.quote(table)}/partition",
            {"partitions": parts})
        return r.get("sequence", -1)

    def partitions(self, db: str, table: str) -> tuple:
        r = self._call(
            "GET", f"/v1/database/{urllib.parse.quote(db)}/table/"
            f"{urllib.parse.quote(table)}/partition")
        return r["partitions"], r.get("sequence", -1)

    def drop_partition(self, db: str, table: str, name: str) -> None:
        # full-quote each segment (the name itself carries %-escapes and
        # '='; the server unquotes path parts once)
        enc = "/".join(urllib.parse.quote(s, safe="")
                       for s in name.split("/"))
        self._call("DELETE", f"/v1/database/{urllib.parse.quote(db)}/table/"
                   f"{urllib.parse.quote(table)}/partition/{enc}")


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description="presto_tpu metastore service")
    ap.add_argument("--root", required=True,
                    help="metadata root directory")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9083)
    ap.add_argument("--secret", default=None)
    args = ap.parse_args(argv)
    srv = MetastoreServer(args.root, args.host, args.port,
                          secret=args.secret)
    print(json.dumps({"uri": srv.uri}), flush=True)
    try:
        srv.httpd.serve_forever()
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
