"""Resource groups: hierarchical scheduling + admission control.

Reference parity: execution/resourceGroups/InternalResourceGroup(+Manager)
and the file-backed config in presto-resource-group-managers — a tree of
groups with concurrency/queue limits, per-group scheduling policies
(FAIR / WEIGHTED / WEIGHTED_FAIR / QUERY_PRIORITY), CPU limits with
quota regeneration, selectors mapping (user, source) to a group, and
dispatch of queued queries when capacity frees.

Deviations, documented: WEIGHTED picks deterministically by stride
(min served/weight) instead of the reference's stochastic
proportional draw — same long-run shares, reproducible tests; CPU
usage is charged at release (per-query), not sampled mid-flight.
"""

from __future__ import annotations

import itertools
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional

POLICIES = ("fair", "weighted", "weighted_fair", "query_priority")


#: admission-wait slice while a cancellable waiter is queued: the ticket
#: event is re-checked (and the abort callable polled) at this period —
#: the ONE timing constant of the admission path (serving lint rule:
#: waits use named constants, never inline numbers)
ADMIT_POLL_S = 0.02


class _Ticket:
    """One queued admission request (reference: the queued-query state
    inside InternalResourceGroup)."""

    __slots__ = ("group", "priority", "seq", "granted", "event",
                 "memory_bytes")

    def __init__(self, group: "ResourceGroup", priority: int, seq: int,
                 memory_bytes: int = 0):
        self.group = group
        self.priority = priority
        self.seq = seq
        self.granted = False
        self.event = threading.Event()
        self.memory_bytes = memory_bytes


class ResourceGroup:
    """One node of the group tree (reference: InternalResourceGroup)."""

    def __init__(self, name: str, hard_concurrency_limit: int = 100,
                 max_queued: int = 1000,
                 parent: Optional["ResourceGroup"] = None):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self.parent = parent
        self.children: Dict[str, ResourceGroup] = {}
        self.running = 0
        self.queued = 0  # includes descendants (reference semantics)
        self.total_admitted = 0
        self.total_rejected = 0
        self.total_shed = 0  # queue-full rejections only (load shedding)
        # memory governance (reference: softMemoryLimit — a group whose
        # reserved memory is at/over the limit is ineligible to START
        # new queries; running ones are never killed by admission)
        self.soft_memory_limit_bytes: Optional[int] = None
        self.memory_reserved_bytes = 0
        # scheduling (applies to choosing among THIS group's children)
        self.scheduling_policy = "fair"
        self.scheduling_weight = 1
        self._served = 0  # stride counter for the WEIGHTED policy
        # CPU governance (reference: softCpuLimit/hardCpuLimit +
        # cpuQuotaGenerationMillisPerSecond)
        self.soft_cpu_limit_s: Optional[float] = None
        self.hard_cpu_limit_s: Optional[float] = None
        self.cpu_quota_generation_per_s: float = 1.0
        self.cpu_usage_s = 0.0
        self._last_regen: Optional[float] = None
        # leaf admission queue
        self._queue: deque = deque()

    @property
    def full_name(self) -> str:
        if self.parent is None:
            return self.name
        return f"{self.parent.full_name}.{self.name}"

    # ---- CPU quota ---------------------------------------------------
    def _regenerate(self, now: float) -> None:
        if self._last_regen is None:
            self._last_regen = now
            return
        dt = max(0.0, now - self._last_regen)
        self._last_regen = now
        if self.cpu_usage_s > 0.0:
            self.cpu_usage_s = max(
                0.0, self.cpu_usage_s - dt * self.cpu_quota_generation_per_s)

    def _cpu_blocked(self, now: float) -> bool:
        self._regenerate(now)
        return self.hard_cpu_limit_s is not None \
            and self.cpu_usage_s > self.hard_cpu_limit_s

    def _effective_weight(self, now: float) -> float:
        """Soft CPU limit halves the group's share until quota
        regenerates (reference: weight reduction past softCpuLimit)."""
        self._regenerate(now)
        w = float(max(self.scheduling_weight, 1))
        if self.soft_cpu_limit_s is not None \
                and self.cpu_usage_s > self.soft_cpu_limit_s:
            w /= 2.0
        return w

    # ---- capacity ----------------------------------------------------
    def _can_run_here(self, now: float) -> bool:
        if self.soft_memory_limit_bytes is not None \
                and self.memory_reserved_bytes >= self.soft_memory_limit_bytes:
            return False
        return self.running < self.hard_concurrency_limit \
            and not self._cpu_blocked(now)

    def can_run(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        g: Optional[ResourceGroup] = self
        while g is not None:
            if not g._can_run_here(now):
                return False
            g = g.parent
        return True

    def _for_ancestors(self, fn) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            fn(g)
            g = g.parent

    # ---- queue introspection ----------------------------------------
    def _head_ticket(self, now: float) -> Optional[_Ticket]:
        """Best dispatchable ticket under this subtree, chosen by THIS
        group's scheduling policy at each internal node (reference:
        InternalResourceGroup.internalStartNext)."""
        if not self._can_run_here(now):
            return None
        local = None
        if self._queue:
            if self.scheduling_policy == "query_priority":
                local = min(self._queue,
                            key=lambda t: (-t.priority, t.seq))
            else:
                local = self._queue[0]
        best_child: Optional[_Ticket] = None
        candidates = []
        for c in self.children.values():
            t = c._head_ticket(now)
            if t is not None:
                candidates.append((c, t))
        if candidates:
            pol = self.scheduling_policy
            if pol == "weighted":
                c, best_child = min(
                    candidates,
                    key=lambda ct: (ct[0]._served
                                    / ct[0]._effective_weight(now),
                                    ct[1].seq))
            elif pol == "weighted_fair":
                c, best_child = min(
                    candidates,
                    key=lambda ct: (ct[0].running
                                    / ct[0]._effective_weight(now),
                                    ct[1].seq))
            elif pol == "query_priority":
                c, best_child = min(candidates,
                                    key=lambda ct: (-ct[1].priority,
                                                    ct[1].seq))
            else:  # fair: global arrival order
                c, best_child = min(candidates, key=lambda ct: ct[1].seq)
        if local is not None and best_child is not None:
            if self.scheduling_policy == "query_priority":
                return local if (-local.priority, local.seq) <= \
                    (-best_child.priority, best_child.seq) else best_child
            return local if local.seq <= best_child.seq else best_child
        return local or best_child


class QueryRejected(Exception):
    """Admission refusal (reference: QUERY_QUEUE_FULL /
    QUERY_REJECTED).  `code` is the protocol-visible error code:
    QUEUE_FULL (shed past max_queued), QUEUE_TIMEOUT (waited out), or
    SERVER_SHUTTING_DOWN (drained by graceful shutdown)."""

    def __init__(self, message: str, code: str = "QUEUE_FULL"):
        super().__init__(message)
        self.code = code


class ResourceGroupManager:
    """Selector-driven admission with policy-based dispatch (reference:
    InternalResourceGroupManager + StaticSelector).  `acquire` blocks
    while the group is saturated (the QUEUED state), raises
    QueryRejected past max_queued or on timeout; `release` charges CPU
    usage and dispatches the next eligible queued queries."""

    def __init__(self, now_fn=time.monotonic):
        self.root = ResourceGroup("global")
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._now = now_fn  # injectable clock (CPU-quota tests)
        self.selectors: List[tuple] = []  # (user_re, source_re, group)

    # ---- configuration ----------------------------------------------
    def add_group(self, path: str, hard_concurrency_limit: int = 100,
                  max_queued: int = 1000,
                  scheduling_policy: str = "fair",
                  scheduling_weight: int = 1,
                  soft_cpu_limit_s: Optional[float] = None,
                  hard_cpu_limit_s: Optional[float] = None,
                  cpu_quota_generation_per_s: float = 1.0,
                  soft_memory_limit_bytes: Optional[int] = None
                  ) -> ResourceGroup:
        if scheduling_policy not in POLICIES:
            raise ValueError(f"unknown scheduling policy "
                             f"'{scheduling_policy}' (one of {POLICIES})")
        parts = path.split(".")
        assert parts[0] == "global", "group paths are rooted at 'global'"
        g = self.root
        for p in parts[1:]:
            if p not in g.children:
                g.children[p] = ResourceGroup(p, parent=g)
            g = g.children[p]
        g.hard_concurrency_limit = hard_concurrency_limit
        g.max_queued = max_queued
        g.scheduling_policy = scheduling_policy
        g.scheduling_weight = scheduling_weight
        g.soft_cpu_limit_s = soft_cpu_limit_s
        g.hard_cpu_limit_s = hard_cpu_limit_s
        g.cpu_quota_generation_per_s = cpu_quota_generation_per_s
        g.soft_memory_limit_bytes = soft_memory_limit_bytes
        return g

    def add_selector(self, group_path: str, user: Optional[str] = None,
                     source: Optional[str] = None) -> None:
        self.selectors.append(
            (re.compile(user) if user else None,
             re.compile(source) if source else None,
             group_path))

    def load_config(self, config: dict) -> None:
        """File-config shape (reference: resource-groups.json):
        {"groups": [{"name": "global.etl", "hardConcurrencyLimit": 2,
                     "maxQueued": 5, "schedulingPolicy": "weighted_fair",
                     "schedulingWeight": 3, "softCpuLimit": "2s",
                     "hardCpuLimit": "5s"}],
         "selectors": [{"user": "etl.*", "group": "global.etl"}]}"""
        for g in config.get("groups", []):
            self.add_group(
                g["name"],
                g.get("hardConcurrencyLimit", 100),
                g.get("maxQueued", 1000),
                str(g.get("schedulingPolicy", "fair")).lower(),
                g.get("schedulingWeight", 1),
                _parse_duration_s(g.get("softCpuLimit")),
                _parse_duration_s(g.get("hardCpuLimit")),
                g.get("cpuQuotaGenerationPerSecond", 1.0),
                _parse_bytes(g.get("softMemoryLimit")))
        for s in config.get("selectors", []):
            self.add_selector(s["group"], s.get("user"), s.get("source"))

    # ---- admission ---------------------------------------------------
    def select_group(self, user: str = "", source: str = "") -> ResourceGroup:
        for user_re, source_re, path in self.selectors:
            if user_re is not None and not user_re.fullmatch(user or ""):
                continue
            if source_re is not None and not source_re.fullmatch(source or ""):
                continue
            return self._resolve(path)
        return self.root

    def _resolve(self, path: str) -> ResourceGroup:
        g = self.root
        for p in path.split(".")[1:]:
            g = g.children[p]
        return g

    def acquire(self, user: str = "", source: str = "",
                priority: int = 0,
                timeout: Optional[float] = 60.0,
                memory_bytes: int = 0,
                abort=None) -> ResourceGroup:
        """Admit one query (blocking while the group is saturated).

        `memory_bytes`: the query's memory ask, reserved against the
        group's softMemoryLimit for the query's lifetime.  `abort`: an
        optional callable polled while queued — True drains the wait
        (graceful shutdown / client cancel) with a
        SERVER_SHUTTING_DOWN-coded rejection instead of a timeout."""
        group = self.select_group(user, source)
        with self._lock:
            now = self._now()
            if not group._queue and group.can_run(now):
                self._start(group, memory_bytes)
                return group
            if group.queued >= group.max_queued:
                group.total_rejected += 1
                group.total_shed += 1
                raise QueryRejected(
                    f"Too many queued queries for '{group.full_name}'",
                    code="QUEUE_FULL")
            t = _Ticket(group, priority, next(self._seq), memory_bytes)
            group._queue.append(t)
            group._for_ancestors(
                lambda g: setattr(g, "queued", g.queued + 1))
        aborted = False
        if abort is None:
            t.event.wait(timeout=timeout)
        else:
            # slice the wait so the abort signal is seen promptly; real
            # wall clock on purpose (the injectable _now clock only
            # drives CPU-quota arithmetic, not queue waits)
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while not t.event.is_set():
                if abort():
                    aborted = True
                    break
                slice_s = ADMIT_POLL_S
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0.0:
                        break
                    slice_s = min(slice_s, left)
                t.event.wait(timeout=slice_s)
        with self._lock:
            if t.granted:
                # covers the grant-at-timeout-boundary race: a granted
                # slot is never abandoned (it would leak `running`)
                return group
            try:
                group._queue.remove(t)
            except ValueError:
                pass
            group._for_ancestors(
                lambda g: setattr(g, "queued", max(0, g.queued - 1)))
            group.total_rejected += 1
        if aborted:
            raise QueryRejected(
                f"Query drained from '{group.full_name}' queue",
                code="SERVER_SHUTTING_DOWN")
        raise QueryRejected(
            f"Query queue timeout in '{group.full_name}'",
            code="QUEUE_TIMEOUT")

    def _start(self, group: ResourceGroup, memory_bytes: int = 0) -> None:
        def bump(g):
            g.running += 1
            g.memory_reserved_bytes += memory_bytes

        group._for_ancestors(bump)
        group.total_admitted += 1
        group._served += 1

    def release(self, group: ResourceGroup, cpu_s: float = 0.0,
                memory_bytes: int = 0) -> None:
        """Finish a query: free the slot, return its memory reservation,
        charge its CPU time up the tree (reference: InternalResource-
        Group.updateGroupsAndProcessQueuedQueries charging
        cpuUsageMillis), dispatch queued work."""
        with self._lock:
            def unbump(g):
                g.running = max(0, g.running - 1)
                g.memory_reserved_bytes = max(
                    0, g.memory_reserved_bytes - memory_bytes)

            group._for_ancestors(unbump)
            if cpu_s:
                group._for_ancestors(
                    lambda g: setattr(g, "cpu_usage_s",
                                      g.cpu_usage_s + cpu_s))
            self._dispatch()

    def _dispatch(self) -> None:
        """Grant as many queued tickets as capacity allows, choosing the
        next ticket by walking the tree under each node's policy."""
        now = self._now()
        while True:
            t = self.root._head_ticket(now)
            if t is None:
                return
            g = t.group
            g._queue.remove(t)
            g._for_ancestors(
                lambda a: setattr(a, "queued", max(0, a.queued - 1)))
            self._start(g, t.memory_bytes)
            t.granted = True
            t.event.set()

    def info(self) -> list:
        """Flat group stats (reference: /v1/resourceGroupState)."""
        out = []

        def walk(g):
            out.append({"name": g.full_name, "running": g.running,
                        "queued": g.queued,
                        "hardConcurrencyLimit": g.hard_concurrency_limit,
                        "maxQueued": g.max_queued,
                        "schedulingPolicy": g.scheduling_policy,
                        "schedulingWeight": g.scheduling_weight,
                        "cpuUsageSeconds": round(g.cpu_usage_s, 6),
                        "memoryReservedBytes": g.memory_reserved_bytes,
                        "softMemoryLimitBytes": g.soft_memory_limit_bytes,
                        "totalAdmitted": g.total_admitted,
                        "totalRejected": g.total_rejected,
                        "totalShed": g.total_shed})
            for c in g.children.values():
                walk(c)

        walk(self.root)
        return out


def _parse_bytes(v) -> Optional[int]:
    """'512MB' / '2GB' / bare number (bytes) -> bytes (reference:
    io.airlift.units.DataSize in resource-groups.json)."""
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return int(v)
    m = re.fullmatch(r"\s*([\d.]+)\s*(B|kB|KB|MB|GB|TB)?\s*", str(v))
    if not m:
        raise ValueError(f"bad data size: {v!r}")
    n = float(m.group(1))
    return int(n * {"B": 1, "kB": 1 << 10, "KB": 1 << 10, "MB": 1 << 20,
                    "GB": 1 << 30, "TB": 1 << 40, None: 1}[m.group(2)])


def _parse_duration_s(v) -> Optional[float]:
    """'5s' / '100ms' / '2m' / bare number (seconds) -> seconds."""
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    m = re.fullmatch(r"\s*([\d.]+)\s*(ms|s|m|h)?\s*", str(v))
    if not m:
        raise ValueError(f"bad duration: {v!r}")
    n = float(m.group(1))
    return n * {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
                None: 1.0}[m.group(2)]
