"""Resource groups: admission control for concurrent queries.

Reference parity: execution/resourceGroups/InternalResourceGroup(+Manager)
and the file-backed config in presto-resource-group-managers — a tree of
groups with concurrency/queue limits, selectors mapping (user, source) to
a group, and fair scheduling of queued queries.  Trimmed to the engine's
process model: admission happens at submit time (the protocol server or
the embedded session), release at completion; weighted subgroup
scheduling collapses to FIFO-fair per group.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional


class ResourceGroup:
    """One node of the group tree (reference: InternalResourceGroup)."""

    def __init__(self, name: str, hard_concurrency_limit: int = 100,
                 max_queued: int = 1000,
                 parent: Optional["ResourceGroup"] = None):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self.parent = parent
        self.children: Dict[str, ResourceGroup] = {}
        self.running = 0
        self.queued = 0
        self.total_admitted = 0
        self.total_rejected = 0

    @property
    def full_name(self) -> str:
        if self.parent is None:
            return self.name
        return f"{self.parent.full_name}.{self.name}"

    def can_run(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.hard_concurrency_limit:
                return False
            g = g.parent
        return True

    def _for_ancestors(self, fn) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            fn(g)
            g = g.parent


class QueryRejected(Exception):
    """Queue full (reference: QUERY_QUEUE_FULL error)."""


class ResourceGroupManager:
    """Selector-driven admission (reference: InternalResourceGroupManager
    + StaticSelector).  `acquire` blocks while the group is saturated
    (the QUEUED state), raises QueryRejected past max_queued."""

    def __init__(self):
        self.root = ResourceGroup("global")
        self._lock = threading.Lock()
        self._wakeup = threading.Condition(self._lock)
        self.selectors: List[tuple] = []  # (user_re, source_re, group)

    # ---- configuration ----------------------------------------------
    def add_group(self, path: str, hard_concurrency_limit: int = 100,
                  max_queued: int = 1000) -> ResourceGroup:
        parts = path.split(".")
        assert parts[0] == "global", "group paths are rooted at 'global'"
        g = self.root
        for p in parts[1:]:
            if p not in g.children:
                g.children[p] = ResourceGroup(p, parent=g)
            g = g.children[p]
        g.hard_concurrency_limit = hard_concurrency_limit
        g.max_queued = max_queued
        return g

    def add_selector(self, group_path: str, user: Optional[str] = None,
                     source: Optional[str] = None) -> None:
        self.selectors.append(
            (re.compile(user) if user else None,
             re.compile(source) if source else None,
             group_path))

    def load_config(self, config: dict) -> None:
        """File-config shape (reference: resource-groups.json):
        {"groups": [{"name": "global.etl", "hardConcurrencyLimit": 2,
                     "maxQueued": 5}],
         "selectors": [{"user": "etl.*", "group": "global.etl"}]}"""
        for g in config.get("groups", []):
            self.add_group(g["name"],
                           g.get("hardConcurrencyLimit", 100),
                           g.get("maxQueued", 1000))
        for s in config.get("selectors", []):
            self.add_selector(s["group"], s.get("user"), s.get("source"))

    # ---- admission ---------------------------------------------------
    def select_group(self, user: str = "", source: str = "") -> ResourceGroup:
        for user_re, source_re, path in self.selectors:
            if user_re is not None and not user_re.fullmatch(user or ""):
                continue
            if source_re is not None and not source_re.fullmatch(source or ""):
                continue
            return self._resolve(path)
        return self.root

    def _resolve(self, path: str) -> ResourceGroup:
        g = self.root
        for p in path.split(".")[1:]:
            g = g.children[p]
        return g

    def acquire(self, user: str = "", source: str = "",
                timeout: float = 60.0) -> ResourceGroup:
        group = self.select_group(user, source)
        with self._lock:
            if not group.can_run():
                if group.queued >= group.max_queued:
                    group.total_rejected += 1
                    raise QueryRejected(
                        f"Too many queued queries for '{group.full_name}'")
                group.queued += 1
                try:
                    deadline = threading.TIMEOUT_MAX if timeout is None \
                        else timeout
                    ok = self._wakeup.wait_for(group.can_run, timeout=deadline)
                    if not ok:
                        group.total_rejected += 1
                        raise QueryRejected(
                            f"Query queue timeout in '{group.full_name}'")
                finally:
                    group.queued -= 1
            group._for_ancestors(lambda g: setattr(g, "running", g.running + 1))
            group.total_admitted += 1
            return group

    def release(self, group: ResourceGroup) -> None:
        with self._lock:
            group._for_ancestors(
                lambda g: setattr(g, "running", max(0, g.running - 1)))
            self._wakeup.notify_all()

    def info(self) -> list:
        """Flat group stats (reference: /v1/resourceGroupState)."""
        out = []

        def walk(g):
            out.append({"name": g.full_name, "running": g.running,
                        "queued": g.queued,
                        "hardConcurrencyLimit": g.hard_concurrency_limit,
                        "maxQueued": g.max_queued,
                        "totalAdmitted": g.total_admitted,
                        "totalRejected": g.total_rejected})
            for c in g.children.values():
                walk(c)

        walk(self.root)
        return out
