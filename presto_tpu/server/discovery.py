"""Node discovery + heartbeat failure detection.

Reference parity: failureDetector/HeartbeatFailureDetector.java:77-393 —
the coordinator pings every discovered service's /v1/status, tracks an
exponentially-decayed failure ratio per node, and marks nodes failed
above a threshold; DiscoveryNodeManager announces membership and
ClusterSizeMonitor gates query admission on a minimum node count
(execution/ClusterSizeMonitor.java).  In the TPU runtime this guards the
multi-host DCN control plane: each JAX host process runs a server; the
coordinator host watches the rest.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional

from presto_tpu.observe import trace as TR

# A single observation contributes DECAY_ALPHA to the ratio, so the
# threshold must exceed it by enough that one transient miss (GC pause,
# dropped packet) cannot flip a node: with alpha=0.05, three consecutive
# misses (~0.143) cross 0.1, one or two do not.
FAILURE_RATIO_THRESHOLD = 0.1  # HeartbeatFailureDetector.java FAILURE_RATIO
DECAY_ALPHA = 0.05  # exponential decay weight per observation


class NodeState:
    def __init__(self, uri: str):
        self.uri = uri
        self.failure_ratio = 0.0
        self.last_seen = 0.0
        self.last_error: Optional[str] = None

    @property
    def alive(self) -> bool:
        return self.failure_ratio < FAILURE_RATIO_THRESHOLD


class HeartbeatFailureDetector:
    def __init__(self, interval: float = 0.5,
                 on_failure: Optional[Callable[[str], None]] = None):
        self.nodes: Dict[str, NodeState] = {}
        self.interval = interval
        self.on_failure = on_failure
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def register(self, uri: str) -> None:
        """A node announcing itself (reference: discovery announcement)."""
        with self._lock:
            if uri not in self.nodes:
                self.nodes[uri] = NodeState(uri)

    def unregister(self, uri: str) -> None:
        with self._lock:
            self.nodes.pop(uri, None)

    def start(self) -> "HeartbeatFailureDetector":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.ping_all()

    def ping_all(self) -> None:
        with self._lock:
            nodes = list(self.nodes.values())
        for node in nodes:
            was_alive = node.alive
            ok = self._ping(node)
            # exponentially-decayed failure ratio
            # (HeartbeatFailureDetector.java:360 Stats.recordSuccess/Failure)
            obs = 0.0 if ok else 1.0
            node.failure_ratio = (DECAY_ALPHA * obs
                                  + (1 - DECAY_ALPHA) * node.failure_ratio)
            if ok:
                node.last_seen = TR.wall_s()
            if was_alive and not node.alive and self.on_failure is not None:
                self.on_failure(node.uri)

    def _ping(self, node: NodeState) -> bool:
        try:
            with urllib.request.urlopen(f"{node.uri}/v1/status",
                                        timeout=1.0) as resp:
                payload = json.loads(resp.read().decode())
                return bool(payload.get("alive"))
        except Exception as e:  # noqa: BLE001 — any failure counts
            node.last_error = f"{type(e).__name__}: {e}"
            return False

    def alive_nodes(self) -> List[str]:
        with self._lock:
            return [u for u, n in self.nodes.items() if n.alive]

    def failed_nodes(self) -> List[str]:
        with self._lock:
            return [u for u, n in self.nodes.items() if not n.alive]


def watch_fleet(directory, interval: float = 0.5,
                ) -> HeartbeatFailureDetector:
    """Pin coordinator-fleet membership (server/fleet.FleetDirectory) to
    the heartbeat failure detector: every registered coordinator is
    pinged like any other node, and one that crosses the failure
    threshold LEAVES the fleet — its ring arc reassigns to survivors,
    its worker slot leases are reclaimed in one sweep, and the death is
    relayed to every survivor (FleetDirectory.leave -> relay_death) so
    the ring successor ADOPTS its journaled in-flight queries
    (server/protocol._on_peer_death + parallel/journal.py).  A dead
    coordinator can neither own signatures, squat fleet capacity, nor
    strand a polling client.  The caller starts/stops the returned
    detector."""

    def on_failure(uri: str) -> None:
        for cid, curi in list(directory.coordinators().items()):
            if curi == uri:
                directory.leave(cid)
                det.unregister(uri)

    det = HeartbeatFailureDetector(interval=interval,
                                   on_failure=on_failure)
    for uri in directory.coordinators().values():
        det.register(uri)
    return det


class ClusterSizeMonitor:
    """Gates query admission on minimum cluster size (reference:
    execution/ClusterSizeMonitor.java, used at SqlQueryExecution.java:342)."""

    def __init__(self, detector: HeartbeatFailureDetector, min_nodes: int):
        self.detector = detector
        self.min_nodes = min_nodes

    def wait_for_minimum_nodes(self, timeout: float = 10.0) -> bool:
        deadline = TR.wall_s() + timeout
        while TR.wall_s() < deadline:
            if len(self.detector.alive_nodes()) >= self.min_nodes:
                return True
            time.sleep(0.05)
        return False
