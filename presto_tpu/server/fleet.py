"""Coordinator fleet control plane: N coordinators, one worker fleet.

Reference parity: the OSS reference runs exactly ONE coordinator per
cluster (SURVEY.md §L4 — parse/plan/schedule serialize through a single
JVM); disaggregated-coordinator Presto (and every production fork) adds
what this module provides: a consistent-hash ownership ring mapping hot
serving state (prepared-statement signatures, result-cache keys) to an
owning coordinator, a membership directory behind the discovery
service, per-worker task-slot leases so N schedulers share one worker
fleet without oversubscribing it, and best-effort gossip (health
verdicts, cache invalidations) between coordinators.

Design rules (docs/SERVING.md "Multi-coordinator topology"):

- ROUTING IS AN OPTIMIZATION, NEVER A CORRECTNESS SURFACE.  Any
  coordinator can execute any statement; the ring only concentrates
  same-signature EXECUTEs on one owner so vmap query-coalescing batches
  (server/serving.QueryCoalescer) still form at fleet scale instead of
  fragmenting 1/N per coordinator.
- INVALIDATION IS BELT, VERSION KEYS ARE SUSPENDERS.  Result-cache /
  prepared keys already carry the catalog token+version (PR-9), so a
  peer that never hears a write broadcast degrades to a key MISS on the
  bumped version — never a stale hit.  The broadcast exists to free
  peer memory promptly and to cover catalogs mutated out-of-band.
- LEASES ARE THE ONLY OVERSUBSCRIPTION GUARD.  A coordinator must hold
  a worker's slot lease before POSTing a task to it; releases are
  idempotent and a dead coordinator's leases are reclaimed when the
  directory unregisters it (heartbeat failure or explicit leave) or,
  per-task, when a worker reaps the orphaned task itself
  (`SlotLeaseBoard.reclaim_task`).
- DEATH IS A RELAYED EVENT, ADOPTION IS DETERMINISTIC.  `leave()`
  relays the death to every survivor after the ring shrank;
  `adopter_of(dead)` — the dead id re-hashed onto the shrunk ring —
  names the ONE ring successor that adopts the dead door's journaled
  in-flight queries (parallel/journal.py, server/protocol.py).

The lint suite (tests/test_lint.py) confines ring-hash/ownership and
slot-lease arithmetic to THIS module, the same discipline that keeps
spill math in exec/spill_exec.py and fusion pricing in
plan/fusion_cost.py.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import threading
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# named timing constants (the serving lint rule forbids inline timeout
# literals in server modules)
# ---------------------------------------------------------------------------

# virtual nodes per coordinator on the ring: enough that a join/leave
# moves ~K/N keys with low variance, few enough that membership changes
# rebuild the ring in microseconds
FLEET_VNODES = 64
# peer RPC budget for best-effort gossip (invalidation broadcast, health
# verdicts, prepared replication): these NEVER block a query result, so
# the budget is short and a miss just degrades to the version-key check
GOSSIP_TIMEOUT_S = 2.0
# front-door proxy budget: a proxied statement's full round trip to its
# owning coordinator (submit + first-response grace), NOT the query
# deadline — long queries continue through the returned nextUri
PROXY_TIMEOUT_S = 60.0
# slot-lease acquisition bound: a coordinator that cannot lease a slot
# within this budget surfaces a typed error instead of oversubscribing
LEASE_TIMEOUT_S = 30.0


def _ring_hash(key: str) -> int:
    """Stable 64-bit point on the ring.  blake2b, NOT hash(): Python's
    string hash is per-process salted, and every fleet member must
    compute the IDENTICAL ring from the same membership."""
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")


class OwnershipRing:
    """Consistent-hash ring: signature/cache keys -> owning coordinator.

    Each member contributes FLEET_VNODES virtual points; a key is owned
    by the first point clockwise from its hash.  Join/leave therefore
    moves only ~K/N of K keys (tests/test_fleet.py asserts the bound),
    so a coordinator crash reshuffles one ring arc, not the whole key
    space — riders of unaffected signatures keep their coalescing owner.
    """

    def __init__(self, vnodes: int = FLEET_VNODES):
        self.vnodes = max(int(vnodes), 1)
        self._points: List[Tuple[int, str]] = []  # sorted (hash, member)
        self._members: set = set()
        self._lock = threading.Lock()

    def add(self, member: str) -> None:
        with self._lock:
            if member in self._members:
                return
            self._members.add(member)
            for v in range(self.vnodes):
                h = _ring_hash(f"{member}#vn{v}")
                bisect.insort(self._points, (h, member))

    def remove(self, member: str) -> None:
        with self._lock:
            if member not in self._members:
                return
            self._members.discard(member)
            self._points = [p for p in self._points if p[1] != member]

    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def owner(self, key: str) -> Optional[str]:
        """The coordinator owning `key` (None on an empty ring)."""
        with self._lock:
            if not self._points:
                return None
            h = _ring_hash(key)
            i = bisect.bisect_right(self._points, (h, "￿"))
            if i >= len(self._points):
                i = 0  # wrap: first point clockwise from 0
            return self._points[i][1]


def affinity_key(sql: str) -> Optional[str]:
    """The ring key of a statement, or None when the statement has no
    affinity (writes/DDL/PREPARE run wherever they land).

    EXECUTEs key on the prepared-statement NAME — every bind of one
    signature routes to one owner so coalescing batches form fleet-wide.
    Ad-hoc reads key on their text so identical dashboard queries hit
    one coordinator's result cache instead of N cold ones."""
    head = sql.lstrip().split(None, 2)
    if not head:
        return None
    kw = head[0].upper()
    if kw == "EXECUTE" and len(head) > 1:
        name = head[1].split("(", 1)[0].strip().strip(";")
        return f"prepared::{name.lower()}"
    if kw in ("SELECT", "WITH", "VALUES", "TABLE"):
        return f"sql::{' '.join(sql.split())}"
    return None


class SlotLeaseBoard:
    """Per-worker task-slot accounting for the WHOLE fleet: the ONLY
    place slot arithmetic happens (lint-confined).  A worker advertises
    `slots` concurrent tasks; every coordinator leases before POSTing
    and releases after DELETE, so N schedulers can never oversubscribe
    one worker.  Leases are tagged by coordinator so a dead
    coordinator's leases are reclaimed in one sweep."""

    def __init__(self):
        self._cap: Dict[str, int] = {}
        self._held: Dict[str, Dict[str, int]] = {}  # url -> coord -> n
        self._cond = threading.Condition()
        self.leases_granted = 0
        self.lease_waits = 0
        self.leases_reclaimed = 0

    def register_worker(self, url: str, slots: int) -> None:
        with self._cond:
            self._cap[url] = max(int(slots), 1)
            self._held.setdefault(url, {})
            self._cond.notify_all()

    def unregister_worker(self, url: str) -> None:
        with self._cond:
            self._cap.pop(url, None)
            self._held.pop(url, None)
            self._cond.notify_all()

    def _in_flight(self, url: str) -> int:
        return sum(self._held.get(url, {}).values())

    def lease(self, coord_id: str, url: str,
              timeout_s: float = LEASE_TIMEOUT_S) -> bool:
        """Acquire one task slot on `url`; blocks while the worker is
        saturated.  False on timeout (the caller surfaces a typed
        error rather than oversubscribing).  Unregistered workers are
        unmanaged: lease freely (single-coordinator compatibility)."""
        with self._cond:
            if url not in self._cap:
                return True
            if self._in_flight(url) >= self._cap[url]:
                self.lease_waits += 1
                granted = self._cond.wait_for(
                    lambda: url not in self._cap
                    or self._in_flight(url) < self._cap[url],
                    timeout=timeout_s)
                if not granted:
                    return False
            if url in self._cap:
                held = self._held.setdefault(url, {})
                held[coord_id] = held.get(coord_id, 0) + 1
                self.leases_granted += 1
            return True

    def release(self, coord_id: str, url: str) -> None:
        with self._cond:
            held = self._held.get(url)
            if held and held.get(coord_id, 0) > 0:
                held[coord_id] -= 1
                if held[coord_id] == 0:
                    del held[coord_id]
                self._cond.notify_all()

    def reclaim(self, coord_id: str) -> int:
        """Release EVERY lease a (dead) coordinator holds; returns the
        count so recovery tests can assert the sweep."""
        with self._cond:
            n = 0
            for held in self._held.values():
                n += held.pop(coord_id, 0)
            if n:
                self.leases_reclaimed += n
                self._cond.notify_all()
            return n

    def reclaim_task(self, coord_id: str, url: str) -> bool:
        """Release ONE lease tag because the worker reaped the task it
        covered (`WorkerServer.reap_expired`): the orphan's slot frees
        as soon as the task does, not only at the directory sweep.
        Counts toward `leases_reclaimed` — the coordinator-crash chaos
        test asserts reaped tasks and reclaimed leases agree.  False
        when nothing was held (the directory sweep already ran, or the
        task was DELETEd normally) — double release must no-op."""
        with self._cond:
            held = self._held.get(url)
            if not held or held.get(coord_id, 0) <= 0:
                return False
            held[coord_id] -= 1
            if held[coord_id] == 0:
                del held[coord_id]
            self.leases_reclaimed += 1
            self._cond.notify_all()
            return True

    def in_flight(self) -> Dict[str, int]:
        with self._cond:
            return {url: self._in_flight(url) for url in self._cap}

    def stats(self) -> dict:
        with self._cond:
            return {"workers": len(self._cap),
                    "inFlight": sum(self._in_flight(u) for u in self._cap),
                    "leasesGranted": self.leases_granted,
                    "leaseWaits": self.lease_waits,
                    "leasesReclaimed": self.leases_reclaimed}


class FleetDirectory:
    """The discovery-service side of the fleet: coordinator membership
    (feeding the ownership ring), the slot-lease board, and the gossip
    relay.  One instance per fleet; in-process coordinators share it
    directly, and server/discovery.watch_fleet() pins membership to the
    heartbeat failure detector so a dead coordinator leaves the ring
    (and its leases are reclaimed) without an explicit goodbye."""

    def __init__(self, vnodes: int = FLEET_VNODES):
        self.ring = OwnershipRing(vnodes=vnodes)
        self.slots = SlotLeaseBoard()
        self._uris: Dict[str, str] = {}
        self._members: Dict[str, "FleetMember"] = {}
        self._lock = threading.Lock()
        self.epoch = 0  # bumps on every membership change

    # -- membership ----------------------------------------------------
    def join(self, coord_id: str, uri: str) -> "FleetMember":
        member = FleetMember(coord_id, uri, self)
        with self._lock:
            self._uris[coord_id] = uri
            self._members[coord_id] = member
            self.epoch += 1
        self.ring.add(coord_id)
        return member

    def leave(self, coord_id: str) -> int:
        """Remove a coordinator (crash or drain): ring shrinks, leases
        reclaim, and the death is relayed to every survivor so the ring
        successor can adopt the journaled in-flight queries
        (server/protocol._on_peer_death).  Returns the reclaimed-lease
        count."""
        self.ring.remove(coord_id)
        with self._lock:
            was_member = coord_id in self._members
            self._uris.pop(coord_id, None)
            self._members.pop(coord_id, None)
            self.epoch += 1
        n = self.slots.reclaim(coord_id)
        if was_member:
            self.relay_death(coord_id)
        return n

    def uri_of(self, coord_id: str) -> Optional[str]:
        with self._lock:
            return self._uris.get(coord_id)

    def coordinators(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._uris)

    # -- gossip relay (in-process members get direct callbacks; remote
    # members are reached over their /v1/fleet endpoints by the sender)
    def relay_invalidate(self, origin_id: str, token: str,
                         version: int, tables=None) -> None:
        with self._lock:
            members = [m for cid, m in self._members.items()
                       if cid != origin_id]
        for m in members:
            m.on_invalidate(origin_id, token, version, tables=tables)

    def relay_health(self, origin_id: str, worker_url: str,
                     verdict: str) -> None:
        with self._lock:
            members = [m for cid, m in self._members.items()
                       if cid != origin_id]
        for m in members:
            m.on_health(origin_id, worker_url, verdict)

    def relay_death(self, dead_id: str) -> None:
        """Tell every SURVIVOR a coordinator is gone (leave() calls this
        after the ring shrank, so `adopter_of` answers identically on
        every survivor)."""
        with self._lock:
            members = [m for cid, m in self._members.items()
                       if cid != dead_id]
        for m in members:
            m.on_death(dead_id)

    def relay_journal(self, origin_id: str, entry: dict) -> None:
        with self._lock:
            members = [m for cid, m in self._members.items()
                       if cid != origin_id]
        for m in members:
            m.on_journal(origin_id, entry)


class FleetMember:
    """One coordinator's fleet handle: ring view, lease client, and the
    gossip send/receive surface.  Attach it to a ServingTier
    (serving.attach_fleet) and/or a ClusterSession (fleet= kwarg); the
    protocol server routes through it when present."""

    def __init__(self, coord_id: str, uri: str,
                 directory: Optional[FleetDirectory] = None,
                 peers: Optional[Dict[str, str]] = None):
        self.coord_id = coord_id
        self.uri = uri
        self.directory = directory
        # static peer map for cross-process fleets (bench subprocess
        # coordinators): same ids => every process derives the SAME ring
        self._static_peers = dict(peers or {})
        self._static_ring: Optional[OwnershipRing] = None
        if directory is None:
            self._static_ring = OwnershipRing()
            self._static_ring.add(coord_id)
            for cid in self._static_peers:
                self._static_ring.add(cid)
        # receive-side hooks, wired by the embedding tier
        self._invalidate_cbs: List[Callable[[str, int], None]] = []
        self._health_cbs: List[Callable[[str, str], None]] = []
        self._death_cbs: List[Callable[[str], None]] = []
        self._journal_cbs: List[Callable[[dict], None]] = []
        # test hook for the dropped-broadcast fault leg: when set, sends
        # are counted as dropped instead of delivered (the version-key
        # check must then carry correctness alone)
        self.drop_broadcasts = False
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "invalidations_sent": 0, "invalidations_received": 0,
            "invalidations_dropped": 0, "health_gossip_sent": 0,
            "health_gossip_received": 0, "prepares_replicated": 0,
            "routed_away": 0, "routed_here": 0}

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    # -- ring view -----------------------------------------------------
    def _ring(self) -> OwnershipRing:
        return self.directory.ring if self.directory is not None \
            else self._static_ring

    def owner_of(self, key: str) -> Optional[str]:
        return self._ring().owner(key)

    def owns(self, key: str) -> bool:
        owner = self.owner_of(key)
        return owner is None or owner == self.coord_id

    def owner_uri(self, key: str) -> Optional[str]:
        """The owning coordinator's URI, or None when this member owns
        the key (or the owner is unknown — execute locally, routing is
        an optimization)."""
        owner = self.owner_of(key)
        if owner is None or owner == self.coord_id:
            return None
        if self.directory is not None:
            return self.directory.uri_of(owner)
        return self._static_peers.get(owner)

    def peer_uris(self) -> Dict[str, str]:
        if self.directory is not None:
            return {cid: uri for cid, uri
                    in self.directory.coordinators().items()
                    if cid != self.coord_id}
        return dict(self._static_peers)

    def coordinator_uri(self, coord_id: str) -> Optional[str]:
        """A specific coordinator's door URI (None when unknown)."""
        if coord_id == self.coord_id:
            return self.uri
        if self.directory is not None:
            return self.directory.uri_of(coord_id)
        return self._static_peers.get(coord_id)

    # -- adoption (journaled-query failover) ---------------------------
    def adopter_of(self, dead_id: str) -> Optional[str]:
        """The ring SUCCESSOR that adopts a dead coordinator's journaled
        queries: the dead id re-hashed onto the ring AFTER it left.
        Deterministic — every survivor derives the same ring from the
        same membership, so they all name the same adopter and exactly
        one door resumes each orphaned query."""
        return self._ring().owner(f"adopt::{dead_id}")

    def should_adopt(self, dead_id: str) -> bool:
        who = self.adopter_of(dead_id)
        return who is not None and who == self.coord_id

    # -- gossip send ---------------------------------------------------
    def _post_peer(self, uri: str, path: str, payload: dict) -> bool:
        try:
            req = urllib.request.Request(
                f"{uri}{path}", data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=GOSSIP_TIMEOUT_S):
                return True
        except Exception:  # noqa: BLE001 — gossip is best-effort
            return False

    def broadcast_invalidate(self, token: str, version: int,
                             tables=None) -> int:
        """Version-stamped invalidation to every peer; best-effort (a
        missed peer degrades to a version-key miss).  `tables` scopes
        the peers' eviction to entries referencing the written tables
        (None = clear everything).  Returns the delivered-peer count."""
        if self.drop_broadcasts:
            self._count("invalidations_dropped")
            return 0
        payload = {"origin": self.coord_id, "token": token,
                   "version": int(version),
                   "tables": sorted(tables) if tables else None}
        delivered = 0
        if self.directory is not None:
            self.directory.relay_invalidate(self.coord_id, token,
                                            int(version), tables=tables)
            delivered = len(self.peer_uris())
        else:
            for uri in self._static_peers.values():
                if self._post_peer(uri, "/v1/fleet/invalidate", payload):
                    delivered += 1
        self._count("invalidations_sent", delivered)
        return delivered

    def gossip_health(self, worker_url: str, verdict: str) -> None:
        """Relay a HealthBoard verdict ('open' = breaker tripped,
        'closed' = worker recovered) so peers stop scheduling onto a
        worker one coordinator already found dead."""
        if self.drop_broadcasts:
            return
        self._count("health_gossip_sent")
        if self.directory is not None:
            self.directory.relay_health(self.coord_id, worker_url, verdict)
        else:
            payload = {"origin": self.coord_id, "worker": worker_url,
                       "verdict": verdict}
            for uri in self._static_peers.values():
                self._post_peer(uri, "/v1/fleet/health", payload)

    def replicate_prepare(self, sql: str) -> int:
        """Best-effort PREPARE replication so an EXECUTE routed (or
        failed over) to any coordinator finds the signature.  A peer the
        replication never reached answers with the typed
        unknown-prepared error and the client re-PREPAREs."""
        if self.drop_broadcasts:
            return 0
        delivered = 0
        for uri in self.peer_uris().values():
            if self._post_peer(uri, "/v1/fleet/prepare", {"sql": sql}):
                delivered += 1
        self._count("prepares_replicated", delivered)
        return delivered

    def replicate_journal(self, entry: dict) -> int:
        """Best-effort journal-entry replication over the peer bus
        (`/v1/fleet/journal`), so an adopter whose filesystem does NOT
        share the journal dir still holds the resumable state.  Shared-
        dir fleets get an idempotent re-write of the same entry.  Like
        every broadcast: a miss never fails the query — the shared dir
        (when present) is belt, replication is suspenders."""
        if self.drop_broadcasts:
            return 0
        delivered = 0
        if self.directory is not None:
            self.directory.relay_journal(self.coord_id, entry)
            delivered = len(self.peer_uris())
        else:
            payload = {"origin": self.coord_id, "entry": entry}
            for uri in self._static_peers.values():
                if self._post_peer(uri, "/v1/fleet/journal", payload):
                    delivered += 1
        self._count("journal_replicated", delivered)
        return delivered

    # -- gossip receive ------------------------------------------------
    def subscribe(self, on_invalidate: Optional[Callable] = None,
                  on_health: Optional[Callable] = None,
                  on_death: Optional[Callable] = None,
                  on_journal: Optional[Callable] = None) -> None:
        with self._lock:
            if on_invalidate is not None:
                self._invalidate_cbs.append(on_invalidate)
            if on_health is not None:
                self._health_cbs.append(on_health)
            if on_death is not None:
                self._death_cbs.append(on_death)
            if on_journal is not None:
                self._journal_cbs.append(on_journal)

    def on_invalidate(self, origin_id: str, token: str,
                      version: int, tables=None) -> None:
        self._count("invalidations_received")
        with self._lock:
            cbs = list(self._invalidate_cbs)
        for cb in cbs:
            try:
                try:
                    cb(token, int(version), tables)
                except TypeError:
                    cb(token, int(version))  # two-arg subscribers
            except Exception:  # noqa: BLE001 — receive is best-effort too
                pass

    def on_health(self, origin_id: str, worker_url: str,
                  verdict: str) -> None:
        self._count("health_gossip_received")
        with self._lock:
            cbs = list(self._health_cbs)
        for cb in cbs:
            try:
                cb(worker_url, verdict)
            except Exception:  # noqa: BLE001
                pass

    def on_death(self, dead_id: str) -> None:
        self._count("deaths_observed")
        with self._lock:
            cbs = list(self._death_cbs)
        for cb in cbs:
            try:
                cb(dead_id)
            except Exception:  # noqa: BLE001 — adoption is best-effort
                pass

    def on_journal(self, origin_id: str, entry: dict) -> None:
        self._count("journal_received")
        with self._lock:
            cbs = list(self._journal_cbs)
        for cb in cbs:
            try:
                cb(entry)
            except Exception:  # noqa: BLE001
                pass

    # -- slot leases ---------------------------------------------------
    def lease_slot(self, worker_url: str,
                   timeout_s: float = LEASE_TIMEOUT_S) -> bool:
        if self.directory is None:
            return True  # no shared board: unmanaged fleet
        return self.directory.slots.lease(self.coord_id, worker_url,
                                          timeout_s=timeout_s)

    def release_slot(self, worker_url: str) -> None:
        if self.directory is not None:
            self.directory.slots.release(self.coord_id, worker_url)

    # -- introspection -------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            out = {"coordId": self.coord_id,
                   "ring": self._ring().members(),
                   **dict(self.counters)}
        if self.directory is not None:
            out["epoch"] = self.directory.epoch
            out["slots"] = self.directory.slots.stats()
        return out
