"""Client protocol implementation.

Reference parity: presto-client StatementClientV1.java — submit via
`POST /v1/statement`, follow `nextUri` pages until absent, surface
columns/data/stats/error; `DELETE` cancels (QueryResults.java:35-55).
"""

from presto_tpu.client.statement import Cursor, StatementClient, connect_http

__all__ = ["StatementClient", "Cursor", "connect_http"]
