"""HTTP statement client (stdlib urllib; no external deps).

Reference parity: StatementClientV1 state machine — advance() fetches
the next QueryResults page; duplicate token fetches are safe
(at-least-once + dedup, server/TaskResource.java:244-307 analog).

Fleet failover: `backup_uris` names the OTHER doors of a coordinator
fleet.  When the door this client is polling stops answering, the same
path is retried against each backup — any door resolves a journaled
in-flight query through its proxied/journal_lookup chain
(server/protocol.py), so a coordinator death mid-poll degrades to a
door switch instead of a client error.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Iterator, List, Optional, Tuple


class QueryError(Exception):
    pass


class StatementClient:
    def __init__(self, server_uri: str, sql: str,
                 poll_interval: float = 0.05,
                 backup_uris: Optional[List[str]] = None):
        self.server_uri = server_uri.rstrip("/")
        self.sql = sql
        self.poll_interval = poll_interval
        self.backup_uris = [u.rstrip("/") for u in (backup_uris or [])]
        self.query_id: Optional[str] = None
        self.columns: Optional[List[dict]] = None
        self.stats: dict = {}
        self._next_uri: Optional[str] = None
        self._current_data: list = []
        self._started = False

    # one re-dispatch per request: a fleet front door in redirect mode
    # answers 307 with the owning coordinator's Location, and urllib
    # refuses to auto-follow a redirected POST body — follow it here
    MAX_REDIRECTS = 4

    def _request_once(self, method: str, url: str,
                      body: Optional[bytes] = None):
        for _ in range(self.MAX_REDIRECTS):
            req = urllib.request.Request(url, data=body, method=method)
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return json.loads(resp.read().decode())
            except urllib.error.HTTPError as e:
                loc = e.headers.get("Location") if e.code in (307, 308) \
                    else None
                if not loc:
                    raise
                url = loc
        raise QueryError(f"redirect loop at {url}")

    def _request(self, method: str, url: str, body: Optional[bytes] = None):
        try:
            return self._request_once(method, url, body)
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            if isinstance(e, urllib.error.HTTPError):
                raise  # the door answered; failover is for dead doors
            last = e
        # the door died (connection refused/reset): replay the SAME
        # path through each backup door — its journal_lookup/proxy
        # chain resolves the query wherever it now lives, and from here
        # on this client polls the door that answered
        prefix_len = len(self.server_uri)
        path = url[prefix_len:] if url.startswith(self.server_uri) else None
        if path is not None:
            for backup in self.backup_uris:
                if backup == self.server_uri:
                    continue
                try:
                    payload = self._request_once(method,
                                                 f"{backup}{path}", body)
                except (urllib.error.HTTPError, QueryError):
                    raise
                except (urllib.error.URLError, ConnectionError, OSError):
                    continue
                self.server_uri = backup
                return payload
        raise last

    def _absorb(self, payload: dict) -> None:
        self.query_id = payload.get("id", self.query_id)
        if payload.get("columns"):
            self.columns = payload["columns"]
        self.stats = payload.get("stats", self.stats)
        self._current_data = payload.get("data", [])
        self._next_uri = payload.get("nextUri")
        err = payload.get("error")
        if err:
            raise QueryError(err.get("message", "query failed"))
        if self.stats.get("state") == "CANCELED":
            # a silent stop would be indistinguishable from completion
            raise QueryError("query was canceled")

    def advance(self) -> bool:
        """Fetch the next page; returns False when the stream is done."""
        if not self._started:
            self._started = True
            payload = self._request("POST", f"{self.server_uri}/v1/statement",
                                    self.sql.encode())
            self._absorb(payload)
            return True
        if self._next_uri is None:
            return False
        payload = self._request("GET", self._next_uri)
        self._absorb(payload)
        return True

    def rows(self) -> Iterator[tuple]:
        """Stream all result rows, polling while queued/running."""
        while self.advance():
            for r in self._current_data:
                yield tuple(r)
            state = self.stats.get("state")
            if state in ("QUEUED", "RUNNING") and not self._current_data:
                time.sleep(self.poll_interval)

    def cancel(self) -> None:
        if self.query_id is not None:
            try:
                self._request(
                    "DELETE",
                    f"{self.server_uri}/v1/statement/{self.query_id}/0")
            except urllib.error.URLError:
                pass


class Cursor:
    """DB-API-flavored convenience over StatementClient (the role the
    JDBC driver plays for the reference; reference: presto-jdbc)."""

    def __init__(self, server_uri: str):
        self.server_uri = server_uri
        self.description: Optional[List[Tuple[str, str]]] = None
        self._rows: list = []
        self._idx = 0

    def execute(self, sql: str) -> "Cursor":
        client = StatementClient(self.server_uri, sql)
        self._rows = list(client.rows())
        self.description = ([(c["name"], c["type"]) for c in client.columns]
                            if client.columns else None)
        self._idx = 0
        self.stats = client.stats
        return self

    def fetchall(self) -> list:
        rows, self._idx = self._rows[self._idx:], len(self._rows)
        return rows

    def fetchone(self):
        if self._idx >= len(self._rows):
            return None
        row = self._rows[self._idx]
        self._idx += 1
        return row


def connect_http(server_uri: str) -> Cursor:
    return Cursor(server_uri)
