"""Logical SQL type system.

Reference parity: presto-spi/src/main/java/com/facebook/presto/spi/type/
(45+ classes) and presto-main/.../type/TypeRegistry.  We keep the same
*logical* surface (BOOLEAN..BIGINT, DOUBLE, DECIMAL, VARCHAR, DATE,
TIMESTAMP, ARRAY/MAP/ROW stubs) but map each logical type onto a
TPU-friendly *physical* representation:

  BOOLEAN              -> bool_
  TINYINT..BIGINT      -> int32 / int64
  DOUBLE / REAL        -> float64 / float32
  DECIMAL(p,s), p<=18  -> int64 scaled by 10**s (exact, MXU/ALU friendly;
                          the reference uses Slice-backed Int128 for long
                          decimals — long decimal is deferred)
  VARCHAR / CHAR       -> int32 dictionary codes (dictionary on host);
                          the reference's VariableWidthBlock/DictionaryBlock
                          (presto-spi/.../spi/block/) collapse into
                          dictionary-always, because TPUs hate ragged data
  DATE                 -> int32 days since 1970-01-01
  TIMESTAMP            -> int64 microseconds since epoch
  INTERVAL DAY/MONTH   -> int64 (micros / months) — session-side only
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Type:
    """A logical SQL type. Comparable/hashable; parametric via params."""

    name: str
    params: tuple = ()

    def __str__(self) -> str:
        if self.name == "TIMESTAMP_TZ":
            return "TIMESTAMP WITH TIME ZONE"
        if self.name == "TIME_TZ":
            return "TIME WITH TIME ZONE"
        if self.params:
            return f"{self.name}({','.join(str(p) for p in self.params)})"
        return self.name

    # ---- classification helpers -------------------------------------
    @property
    def is_integer(self) -> bool:
        return self.name in ("TINYINT", "SMALLINT", "INTEGER", "BIGINT")

    @property
    def is_floating(self) -> bool:
        return self.name in ("REAL", "DOUBLE")

    @property
    def is_decimal(self) -> bool:
        return self.name == "DECIMAL"

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self.is_floating or self.is_decimal

    @property
    def is_string(self) -> bool:
        # JSON/VARBINARY are distinct logical types (spi/type/JsonType,
        # VarbinaryType) but share the dictionary-encoded physical form
        # and string compute paths (VARBINARY dictionary values are
        # python bytes)
        return self.name in ("VARCHAR", "CHAR", "JSON", "VARBINARY")

    @property
    def is_temporal(self) -> bool:
        return self.name in ("DATE", "TIMESTAMP", "TIMESTAMP_TZ")

    @property
    def tz(self) -> Optional[str]:
        """Zone name for TIMESTAMP_TZ / offset-minutes for TIME_TZ.

        TPU-native departure from the reference: the reference packs a
        12-bit zone key into every VALUE (spi/type/
        TimestampWithTimeZoneType + DateTimeEncoding.packDateTimeWithZone);
        here the zone rides the column TYPE and the device lane stays
        pure UTC int64 micros, so compare/join/sort/group need no unpack."""
        if self.name in ("TIMESTAMP_TZ", "TIME_TZ") and self.params:
            return self.params[0]
        return None

    @property
    def is_orderable(self) -> bool:
        return self.name not in ("UNKNOWN",)

    @property
    def decimal_scale(self) -> int:
        assert self.is_decimal
        return self.params[1] if len(self.params) > 1 else 0

    @property
    def decimal_precision(self) -> int:
        assert self.is_decimal
        return self.params[0] if self.params else 18

    @property
    def is_long_decimal(self) -> bool:
        """Precision 19..38: two-limb Int128 storage (reference:
        Decimals.MAX_SHORT_PRECISION boundary, Int128ArrayBlock)."""
        return self.is_decimal and self.decimal_precision > 18

    # ---- physical representation ------------------------------------
    def numpy_dtype(self) -> np.dtype:
        return np.dtype(_PHYSICAL[self.name])

    def integer_bounds(self):
        """LOGICAL (min, max) for integer types.  Distinct from the
        physical dtype: TINYINT/SMALLINT are stored as int32 lanes, but
        CAST overflow semantics follow the SQL type (reference raises
        out-of-range, e.g. IntegerOperators.saturatedFloorCastToSmallint)."""
        bits = {"TINYINT": 8, "SMALLINT": 16, "INTEGER": 32, "BIGINT": 64}[
            self.name]
        return -(1 << (bits - 1)), (1 << (bits - 1)) - 1


BOOLEAN = Type("BOOLEAN")
TINYINT = Type("TINYINT")
SMALLINT = Type("SMALLINT")
INTEGER = Type("INTEGER")
BIGINT = Type("BIGINT")
REAL = Type("REAL")
DOUBLE = Type("DOUBLE")
VARCHAR = Type("VARCHAR")
DATE = Type("DATE")
TIMESTAMP = Type("TIMESTAMP")
INTERVAL_DAY_TIME = Type("INTERVAL_DAY_TIME")
INTERVAL_YEAR_MONTH = Type("INTERVAL_YEAR_MONTH")
JSON = Type("JSON")
VARBINARY = Type("VARBINARY")
UNKNOWN = Type("UNKNOWN")  # the NULL literal's type
TIME = Type("TIME")  # int64 microseconds since midnight (zone-less)


def timestamp_tz(zone: Optional[str] = None) -> Type:
    """TIMESTAMP WITH TIME ZONE in `zone` (reference:
    spi/type/TimestampWithTimeZoneType).  Lane: UTC int64 micros; the
    zone is column metadata — see Type.tz.  zone=None (e.g. a CAST
    target written without a zone) means "the session zone", resolved
    when the cast/function emits."""
    return Type("TIMESTAMP_TZ", () if zone is None else (zone,))


def time_tz(offset_minutes: Optional[int] = None) -> Type:
    """TIME WITH TIME ZONE at a fixed UTC offset (reference:
    spi/type/TimeWithTimeZoneType; named zones degenerate to their
    offset for TIME, as in the reference's packed offset encoding).
    Lane: int64 micros since midnight LOCAL to the offset.
    offset_minutes=None (a zone-less CAST target) means "the session
    zone's offset", resolved when the cast emits."""
    return Type("TIME_TZ", () if offset_minutes is None
                else (int(offset_minutes),))


def decimal(precision: int, scale: int) -> Type:
    """DECIMAL(p,s).  p <= 18 ("short"): unscaled int64.  p in 19..38
    ("long"): two int64 limbs per value, shape (n, 2) — exact Int128
    semantics through arithmetic, comparison, sort and SUM/MIN/MAX
    aggregation (reference: spi/type/DecimalType,
    UnscaledDecimal128Arithmetic, Int128ArrayBlock; device kernels in
    exec/dec128.py)."""
    if precision > 38:
        raise ValueError(f"DECIMAL precision {precision} exceeds 38")
    return Type("DECIMAL", (precision, scale))


def decimal_add_type(a: "Type", b: "Type") -> "Type":
    """Presto result type of decimal +/- (DecimalOperators.ADD)."""
    s = max(a.decimal_scale, b.decimal_scale)
    p = min(38, max(a.decimal_precision - a.decimal_scale,
                    b.decimal_precision - b.decimal_scale) + s + 1)
    return decimal(p, s)


def decimal_mul_type(a: "Type", b: "Type") -> "Type":
    """Presto result type of decimal * (DecimalOperators.MULTIPLY)."""
    s = a.decimal_scale + b.decimal_scale
    p = min(38, a.decimal_precision + b.decimal_precision)
    if s > 38:
        raise ValueError("DECIMAL multiply scale exceeds 38")
    return decimal(p, s)


def varchar(length: Optional[int] = None) -> Type:
    return VARCHAR  # length is not semantically enforced (same as reference in practice)


def array_of(elem: Type) -> Type:
    """ARRAY(elem) — physically int32 codes into a dictionary of tuples
    (the DictionaryBlock treatment extended to nested values; reference:
    spi/block/ArrayBlock, which TPUs would hate as ragged offsets)."""
    return Type("ARRAY", (elem,))


def char(length: int) -> Type:
    return Type("CHAR", (length,))


def function_type(ret: Type) -> Type:
    """FUNCTION(ret) — the type of a lambda argument (reference:
    spi/type/FunctionType.java).  Never materialized as a column."""
    return Type("FUNCTION", (ret,))


def map_of(key: Type, value: Type) -> Type:
    """MAP(K,V) — physically int32 codes into a dictionary whose entries
    are key-sorted tuples of (key, value) pairs (reference: spi/type/MapType
    + spi/block/MapBlock; same DictionaryBlock treatment as ARRAY)."""
    return Type("MAP", (key, value))


def row_of(fields) -> Type:
    """ROW(name type, ...) — dictionary of value tuples; field names ride
    the type (reference: spi/type/RowType).  `fields` is a sequence of
    (name-or-None, Type)."""
    return Type("ROW", tuple((n.lower() if n else None, t)
                             for n, t in fields))


def row_field_types(t: Type):
    return tuple(ft for _, ft in t.params)


def row_field_index(t: Type, name: str) -> Optional[int]:
    for i, (n, _) in enumerate(t.params):
        if n == name.lower():
            return i
    return None


_PHYSICAL = {
    "BOOLEAN": np.bool_,
    "TINYINT": np.int32,
    "SMALLINT": np.int32,
    "INTEGER": np.int32,
    "BIGINT": np.int64,
    "REAL": np.float32,
    "DOUBLE": np.float64,
    "DECIMAL": np.int64,
    "VARCHAR": np.int32,  # dictionary code
    "CHAR": np.int32,  # dictionary code
    "JSON": np.int32,  # dictionary code
    "VARBINARY": np.int32,  # dictionary code over bytes values
    "DATE": np.int32,
    "TIMESTAMP": np.int64,
    "TIMESTAMP_TZ": np.int64,  # UTC micros; zone in the type (Type.tz)
    "TIME": np.int64,  # micros since midnight
    "TIME_TZ": np.int64,  # micros since midnight at the type's offset
    "INTERVAL_DAY_TIME": np.int64,
    "INTERVAL_YEAR_MONTH": np.int64,
    "UNKNOWN": np.bool_,
    "ARRAY": np.int32,  # dictionary code over unique element-tuples
    "MAP": np.int32,  # dictionary code over unique pair-tuples
    "ROW": np.int32,  # dictionary code over unique field-tuples
    "HLL": np.int32,  # dictionary code over serialized sketch bytes
    "P4HLL": np.int32,  # dictionary code over serialized sketch bytes
    "QDIGEST": np.int32,  # dictionary code over serialized sketch bytes
    "TDIGEST": np.int32,  # dictionary code over serialized sketch bytes
    "HLL_STATE": np.uint8,  # device HLL registers: (n, m) matrix column
    "KLL_STATE": np.float64,  # device quantile summary: (n, 2K) matrix
}

HLL = Type("HLL")
# Dense-format HyperLogLog (reference: spi/type/P4HyperLogLogType —
# the fixed-register airlift P4 layout; this engine's HLL blobs are
# always dense, so the two types share the physical form and casts
# between them are re-tags)
P4HLL = Type("P4HLL")


def hll_state(m: int) -> Type:
    """Device-native HyperLogLog partial state: each "value" is a row of
    m uint8 registers, so the column is an (n_groups, m) matrix.  Unlike
    the reference's Slice-typed HyperLogLog blobs, the state never
    serializes on device — partials fold with elementwise max and only
    the final BIGINT estimate reaches the client.  The register count
    rides the TYPE so exchange pricing (fusion_cost._row_bytes) and
    serde know the fixed row width."""
    return Type("HLL_STATE", (int(m),))


def kll_state(width: int) -> Type:
    """Device-native quantile-summary partial state: each value is a row
    of width float64s (K summary values + K weights), an (n_groups,
    width) matrix column.  Mergeable by concat-sort-prune; width rides
    the type for pricing/serde like HLL_STATE."""
    return Type("KLL_STATE", (int(width),))


def qdigest_of(elem: Type) -> Type:
    return Type("QDIGEST", (elem,))


def tdigest_of(elem: Type) -> Type:
    """reference: TDigestParametricType (tdigest(double))."""
    return Type("TDIGEST", (elem,))


def parse_type(text: str) -> Type:
    """Parse a type name as written in SQL (CAST target etc.), including
    nested ARRAY(T) / MAP(K,V) / ROW(name T, ...) (reference:
    TypeSignature.parseTypeSignature)."""
    t = text.strip().upper()
    if "(" in t:
        base, rest = t.split("(", 1)
        base = base.strip()
        inner = rest.rstrip()
        if inner.endswith(")"):
            inner = inner[:-1]
        if base == "QDIGEST":
            return qdigest_of(parse_type(inner))
        if base == "TDIGEST":
            return tdigest_of(parse_type(inner))
        if base in ("ARRAY", "MAP", "ROW"):
            parts = _split_type_args(inner)
            if base == "ARRAY":
                return array_of(parse_type(parts[0]))
            if base == "MAP":
                return map_of(parse_type(parts[0]), parse_type(parts[1]))
            fields = []
            for p in parts:
                # `name TYPE` vs bare `TYPE`: try the named form first so
                # field names that prefix a type word (rowid, mapping...)
                # still parse; fall back to an anonymous field
                bits = p.strip().split(None, 1)
                if len(bits) == 2 and "(" not in bits[0]:
                    try:
                        fields.append((bits[0].lower(), parse_type(bits[1])))
                        continue
                    except ValueError:
                        pass
                fields.append((None, parse_type(p)))
            return row_of(fields)
        args = [int(a) for a in inner.split(",") if a.strip().isdigit()]
        if base == "HLL_STATE":
            return hll_state(args[0] if args else 1024)
        if base == "KLL_STATE":
            return kll_state(args[0] if args else 400)
        if base == "DECIMAL":
            return decimal(*args) if args else decimal(18, 0)
        if base in ("VARCHAR", "CHAR"):
            return VARCHAR if base == "VARCHAR" else char(args[0] if args else 1)
        raise ValueError(f"unknown parametric type: {text}")
    if t == "ARRAY":
        return array_of(UNKNOWN)
    aliases = {
        "INT": INTEGER,
        "INTEGER": INTEGER,
        "BIGINT": BIGINT,
        "SMALLINT": SMALLINT,
        "TINYINT": TINYINT,
        "BOOLEAN": BOOLEAN,
        "DOUBLE": DOUBLE,
        "DOUBLE PRECISION": DOUBLE,
        "REAL": REAL,
        "FLOAT": REAL,
        "VARCHAR": VARCHAR,
        "CHAR": Type("CHAR", (1,)),
        "STRING": VARCHAR,
        "DATE": DATE,
        "TIMESTAMP": TIMESTAMP,
        "TIMESTAMP WITH TIME ZONE": timestamp_tz(),
        "TIME": TIME,
        "TIME WITH TIME ZONE": time_tz(),
        "DECIMAL": decimal(18, 0),
        "JSON": JSON,
        "VARBINARY": VARBINARY,
        "HLL": HLL,
        "HYPERLOGLOG": HLL,
        "QDIGEST": qdigest_of(DOUBLE),
        "TDIGEST": tdigest_of(DOUBLE),
        "P4HYPERLOGLOG": P4HLL,
    }
    if t in aliases:
        return aliases[t]
    raise ValueError(f"unknown type: {text}")


DECIMAL_UNSCALED_LIMIT = 2.0 ** 62  # int64 headroom (~19 digits)


def check_decimal_overflow(unscaled, valid=None, what: str = "value"):
    """Shared float64-shadow guard for the int64 unscaled-decimal
    boundary; NULL lanes are excluded (they carry garbage payloads)."""
    shadow = np.abs(np.asarray(unscaled, dtype=np.float64))
    if valid is not None:
        v = np.asarray(valid)
        if v.ndim > 0:
            shadow = np.where(v, shadow, 0.0)
        elif not bool(v):
            return
    with np.errstate(invalid="ignore"):
        if shadow.size and np.nanmax(shadow) >= DECIMAL_UNSCALED_LIMIT:
            raise ValueError(
                f"DECIMAL overflow: {what} exceeds the int64 unscaled "
                "range (~19 significant digits)")


def _split_type_args(s: str):
    """Split 'K, V' at top-level commas (parens may nest)."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


# ---------------------------------------------------------------------------
# Coercion lattice — mirrors the reference's implicit-cast rules
# (presto-main/.../type/TypeRegistry + sql/analyzer/ExpressionAnalyzer).
# ---------------------------------------------------------------------------

_NUMERIC_ORDER = ["TINYINT", "SMALLINT", "INTEGER", "BIGINT", "DECIMAL", "REAL", "DOUBLE"]


def common_super_type(a: Type, b: Type) -> Optional[Type]:
    """The least common type both operands coerce to, or None."""
    if a == b:
        return a
    if a == UNKNOWN:
        return b
    if b == UNKNOWN:
        return a
    if a.is_numeric and b.is_numeric:
        ia, ib = _NUMERIC_ORDER.index(a.name), _NUMERIC_ORDER.index(b.name)
        hi = a if ia >= ib else b
        lo = b if ia >= ib else a
        if hi.is_decimal:
            if lo.is_decimal:
                scale = max(a.decimal_scale, b.decimal_scale)
                intd = max(
                    a.decimal_precision - a.decimal_scale,
                    b.decimal_precision - b.decimal_scale,
                )
                return decimal(min(intd + scale, 38), scale)
            return hi  # integer + decimal -> decimal
        if hi.is_floating and lo.is_decimal:
            return DOUBLE
        return hi
    if a.is_string and b.is_string:
        return VARCHAR
    if a.name == b.name == "ARRAY":
        et = common_super_type(a.params[0], b.params[0])
        return array_of(et) if et is not None else None
    if a.name == b.name == "MAP":
        kt = common_super_type(a.params[0], b.params[0])
        vt = common_super_type(a.params[1], b.params[1])
        return map_of(kt, vt) if kt is not None and vt is not None \
            else None
    if {a.name, b.name} == {"DATE", "TIMESTAMP"}:
        return TIMESTAMP
    if a.name == "TIMESTAMP_TZ" and b.name == "TIMESTAMP_TZ":
        # same instant lane; zones differ only as display metadata —
        # keep the left zone (the reference keeps per-value zones; a
        # documented single-zone-per-column simplification)
        return a
    if "TIMESTAMP_TZ" in (a.name, b.name) \
            and {a.name, b.name} <= {"TIMESTAMP_TZ", "TIMESTAMP", "DATE"}:
        return a if a.name == "TIMESTAMP_TZ" else b
    if a.name == "TIME_TZ" and b.name == "TIME_TZ":
        return a
    if {a.name, b.name} == {"TIME", "TIME_TZ"}:
        return a if a.name == "TIME_TZ" else b
    if a.name == "DATE" and b.name == "INTERVAL_DAY_TIME":
        return DATE
    return None


def can_coerce(frm: Type, to: Type) -> bool:
    if frm == to or frm == UNKNOWN:
        return True
    return common_super_type(frm, to) == to
