"""presto_tpu — a TPU-native distributed SQL query engine.

A ground-up reimagining of a coordinator/worker SQL engine (reference:
Presto, see SURVEY.md) around the XLA execution model:

- Columnar "Pages" of "Blocks" (reference: presto-spi/.../spi/Page.java:34)
  become fixed-shape device arrays with validity masks (`presto_tpu.batch`).
- The interpreted per-page operator loop (reference:
  presto-main/.../operator/Driver.java:347) becomes whole-fragment
  jit-compiled XLA programs (`presto_tpu.exec`).
- JVM bytecode codegen (reference: presto-bytecode, sql/gen/) becomes JAX
  tracing (`presto_tpu.functions`, `presto_tpu.exec.compiler`).
- HTTP shuffle exchanges (reference: execution/buffer/, ExchangeClient)
  become ICI collectives under shard_map (`presto_tpu.parallel`).
"""

import jax

# The engine's BIGINT/DOUBLE are 64-bit end to end (reference: long/double
# Blocks); must be set before any jnp array is created.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache + the engine-level executable memo
# (exec/compile_cache.py): the analog of the reference's codegen cache
# (presto-main/.../sql/gen/PageFunctionCompiler.java memoizes compiled
# projections/filters; compiled classes are reused across queries).  XLA
# compiles a whole fragment per (query shape, sf) — at SF100 a single
# compile runs tens of minutes, so cold costs must be paid once per
# machine, not once per process.  Dir from PRESTO_TPU_COMPILE_CACHE
# (legacy alias PRESTO_TPU_XLA_CACHE, =0 disables) or the
# compile_cache_dir session property, re-checked per query.
from presto_tpu.exec import compile_cache as _compile_cache  # noqa: E402

_compile_cache.configure()

from presto_tpu.session import Session, connect  # noqa: E402

__version__ = "0.1.0"

__all__ = ["Session", "connect", "__version__"]
