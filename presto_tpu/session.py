"""Session: the user-facing entry point (reference: Session.java +
SqlQueryManager orchestration, trimmed to an embeddable engine API).

`connect()` returns a Session bound to a catalog of connectors;
`Session.sql(text)` runs parse -> analyze -> plan -> optimize -> execute
and returns a host-side result table — the in-process analog of the
reference's LocalQueryRunner (presto-main/.../testing/LocalQueryRunner.java),
which is also exactly how its own planner/operator tests drive the engine.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


DEFAULT_SESSION_PROPERTIES: Dict[str, Any] = {
    # Reference: SystemSessionProperties.java:56 (81 typed properties).
    "join_distribution_type": "AUTOMATIC",  # BROADCAST | PARTITIONED | AUTOMATIC
    "hash_partition_count": 8,
    "task_concurrency": 1,
    "agg_capacity_hint": 0,  # 0 = derive from input size
    "optimizer_enabled": True,
    "execution_mode": "auto",  # auto | compiled | dynamic
    # distributed execution over the device mesh (parallel/dist_executor.py)
    "distributed": False,
    "mesh_devices": 0,  # 0 = all local devices
    "broadcast_join_threshold_rows": 1_000_000,  # DetermineJoinDistributionType
    # below this row estimate ORDER BY gathers + sorts on one shard
    # instead of the P11 range-exchange sample sort
    "distributed_sort_threshold_rows": 100_000,
    # persist per-bucket grouped-execution results so a re-run after a
    # failure resumes from completed buckets (P8 recoverable execution).
    # In CLUSTER mode the same knob gates the durable exchange store
    # (replayable task output, parallel/cluster.py).  "auto" (default):
    # ON for multi-worker cluster queries whenever a spill/durable path
    # is configured (spill_enabled or an explicit spill_path) — the
    # fault-tolerant execution default — and OFF for the single-node
    # checkpoint path, which stays opt-in (True/"on").
    "recoverable_grouped_execution": "auto",
    # test hook: abort after N grouped buckets (0 = off)
    "fault_injection_fail_after_buckets": 0,
    # fuse sum-shaped aggregates into one Pallas pass (kernels.fused_group_sums)
    "pallas_fused_agg": True,
    # ordering-aware execution (plan/properties.py): exploit connector-
    # declared / operator-derived sort orders via presorted kernel
    # variants, the sort-permutation memo, and ORDER BY elision — all
    # behind runtime monotonicity guards.  Kill switch for A/B runs.
    "ordering_aware_execution": True,
    # execute DOUBLE expressions in float32 on device (cross-block
    # aggregate merges stay f64).  Default off: exact f64 semantics.  On
    # TPU, f64 is software-emulated (~10-20x per op), so benchmarks turn
    # this on; money-valued data (2-decimal) keeps comparisons stable
    # because literals and data round identically.
    "float32_compute": False,
    "partial_aggregation_max_groups": 8192,  # partial+gather vs repartition agg
    # adaptive aggregation economics (plan/agg_strategy.py, docs/PERF.md
    # round 17): the planner picks one_pass / final_only / two_phase per
    # grouped Aggregate from ordering facts + NDV estimates, and the
    # runtime monitors every two-phase partial stage's reduction ratio
    # (rows in / groups out), flipping it to pass-through when the
    # partial stops paying for itself — per-fragment, hysteresis-
    # guarded, revisitable, checksum-neutral.  Kill switches: this
    # property or env PRESTO_TPU_ADAPTIVE_AGG=off.
    # partial_agg_min_reduction: reduction below this flips the stage
    # (default measured by tools/roofline.py's `agg` sweep).
    # agg_final_only_max_groups: NDV-estimate ceiling for the planner's
    # single global-table route (no partial stage planned at all).
    "adaptive_partial_agg": True,
    "partial_agg_min_reduction": 1.3,
    "agg_final_only_max_groups": 4096,
    # sketch aggregates (exec/kernels.py HLL/KLL, docs/PERF.md):
    # prefer_approx_distinct opts the planner into rewriting
    # count(DISTINCT x) -> approx_distinct(x) (~3.25% std error at the
    # default 1024 registers; counted in QueryStats.approx_rewrites).
    # approx_percentile_accuracy sizes the mergeable quantile summary —
    # rank error ~accuracy, state width 2*ceil(2/accuracy) f64 per group.
    "prefer_approx_distinct": False,
    "approx_percentile_accuracy": 0.01,
    # materialized views (exec/matview.py, docs/SERVING.md): routing
    # sends contained SELECTs to the freshest MV snapshot (env kill:
    # PRESTO_TPU_MV_ROUTING=off); refresh mode auto|delta|full — auto
    # delta-folds appends and degrades LOUDLY to full recompute, delta
    # errors when a delta is impossible, full always recomputes.
    "materialized_view_routing": True,
    "mv_refresh_mode": "auto",
    # per-plan-node stats collection in dynamic mode (forced by EXPLAIN
    # ANALYZE; costs one host sync per operator — reference: OperationTimer)
    "collect_node_stats": False,
    # observability (observe/trace.py + observe/profile.py,
    # docs/OBSERVABILITY.md): span recording detail — "basic" (default)
    # records query/phase/fragment/task/attempt/compile spans and
    # merges worker spans into one trace; "full" adds per-page-pull
    # spans in cluster mode; "off" disables the recorder entirely (the
    # observability_overhead A/B lever; /v1/query/{id}/trace then
    # serves an empty trace).  profile_query: a directory path to
    # capture a jax.profiler trace of each query into (also env
    # PRESTO_TPU_PROFILE; "" = off) — jax.named_scope annotations at
    # every operator-lowering site map the profiler timeline back to
    # plan node names.
    "trace_detail": "basic",
    "profile_query": "",
    # memory management (reference: query.max-memory-per-node +
    # experimental.spill-enabled, FeaturesConfig/MemoryManagerConfig)
    "query_max_memory_bytes": 4 << 30,
    "memory_pool_bytes": 16 << 30,  # per-process pool (MemoryPool capacity)
    "spill_enabled": True,
    "spill_encryption": False,  # AES-256-CTR at rest (AesSpillCipher)
    # session time zone for the WITH TIME ZONE surface (reference:
    # Session.getTimeZoneKey / SystemSessionProperties)
    "time_zone": "UTC",
    # fragment fusion (plan/distribute.fuse_fragments, ROADMAP item 1):
    # mesh-local exchange edges of a cluster plan splice back into ONE
    # traced shard_map program whose exchanges lower to ICI collectives
    # — zero host round-trips between fused stages.  A worker is a
    # fusion target only when it DECLARES an exclusively-owned mesh
    # (PRESTO_TPU_WORKER_MESH / WorkerServer(mesh_devices=)) of at
    # least `fragment_fusion_min_devices` chips.  Modes (round 18,
    # plan/fusion_cost.py): `auto` (default) prices every mesh-local
    # exchange edge CUT vs FUSED with the calibrated exchange roofline
    # + a per-plan-shape decision memo fed by observed execute walls;
    # `force` restores round 12's fuse-every-eligible-edge policy
    # byte-identically (legacy boolean True maps here); `off` keeps the
    # per-fragment HTTP path (False maps here; env kill
    # PRESTO_TPU_FRAGMENT_FUSION=off).  Any fused-attempt failure
    # retries on the HTTP path.  `fragment_fusion_kinds` (csv)
    # restricts which edge kinds fuse, for A/B runs and partial-fusion
    # coverage; `fusion_profile` points at a calibration JSON written
    # by `tools/roofline.py --calibrate` (else PRESTO_TPU_FUSION_PROFILE
    # env, else baked per-platform defaults); `fragment_fusion_memo`
    # (default on) is the runtime-feedback kill switch — off = pure
    # model, nothing recorded.
    "fragment_fusion": "auto",
    "fragment_fusion_min_devices": 2,
    "fragment_fusion_kinds": "",
    "fragment_fusion_memo": True,
    "fusion_profile": "",
    # cross-host collective fusion (round 21): workers that joined one
    # `jax.distributed` multi-process mesh (cluster worker
    # --distributed-coordinator / PRESTO_TPU_MULTIHOST) form a GANG the
    # classifier may fuse cross-host exchange edges onto — repartition
    # lowers to all_to_all and broadcast/gather to all_gather over the
    # DCN fabric, priced by the profile's dcn_edge_ms/dcn_ms_per_mb
    # lane.  Off = mesh members are plain HTTP workers; any gang
    # failure (member death, collective fault) already degrades to the
    # HTTP exchange path on its own.
    "multihost_fusion": True,
    # cluster scheduling policy (reference: PhasedExecutionSchedule vs
    # AllAtOnceExecutionPolicy, execution-policy session property):
    # phased gates probe-side stage startup on build-side completion,
    # bounding worker buffer memory on deep join DAGs
    "phased_execution": False,
    # cluster robustness knobs (parallel/retry.py, docs/ROBUSTNESS.md):
    # one query-level deadline every RPC timeout derives from (None =
    # unbounded; env PRESTO_TPU_QUERY_DEADLINE overrides the default),
    # the straggler-hedging policy, and the health circuit breaker
    "cluster_query_deadline_s": None,
    "cluster_hedging": True,
    "cluster_hedge_quantile": 0.5,  # hedge when this wave share FINISHED
    "cluster_hedge_factor": 3.0,    # ... and a task exceeds q*factor
    "cluster_hedge_min_s": 0.25,    # ... with at least this headroom
    "cluster_health_trip_after": 3,   # consecutive failures to quarantine
    "cluster_health_probation_s": 5.0,  # re-probe a quarantined worker
    # task-granular restart (parallel/cluster.py, fault-tolerant
    # execution): when ONE task dies mid-wave the coordinator re-runs
    # just that slot on a healthy survivor inside the SAME attempt
    # (hedge-style slot repoint; completed siblings' durable pages are
    # untouched) — up to this many restarts per slot before escalating
    # to the whole-attempt retry.  0 disables (whole-attempt retry
    # only, the pre-round-20 behavior the attempt-level chaos tests
    # pin).
    "cluster_task_restarts": 2,
    # query journal (parallel/journal.py): fleet-visible resumable
    # state per in-flight distributed query, so the ring successor
    # adopts a dead coordinator's queries (docs/ROBUSTNESS.md).
    # "auto" (default) journals exactly when a fleet is attached;
    # on/off force it.  query_journal_path overrides the journal dir
    # ("" = <spill base>/journal — coordinators sharing a spill base
    # share the journal).
    "query_journal": "auto",
    "query_journal_path": "",
    # compilation economics (exec/compile_cache.py): persistent XLA
    # executable cache directory ("" = env PRESTO_TPU_COMPILE_CACHE /
    # legacy PRESTO_TPU_XLA_CACHE / the /tmp default; "0" or "off"
    # disables persistence) and the background compile-ahead that
    # AOT-compiles chunked fragments 2..N while fragment 1 executes
    # (kill switch; env PRESTO_TPU_COMPILE_AHEAD=off|on overrides
    # process-wide, and the unforced default is on only with >1 usable
    # core — on a single core a "background" compile can only steal the
    # query's cycles.  Never changes results, only when programs
    # compile).
    "compile_cache_dir": "",
    "compile_ahead": True,
    # dynamic filtering (plan/runtime_filters.py + exec/kernels.py rf_*):
    # selective-join build sides publish runtime key summaries (min/max
    # domain + exact or bloom membership) that probe-side scans consume
    # to skip rows / chunks / splits before the join.  Never changes
    # results (kill switch: env PRESTO_TPU_DYNAMIC_FILTERS=off).
    "dynamic_filtering": True,
    # cluster mode: how long a probe-side task waits for a not-yet-
    # delivered filter summary before scanning filter-free (ms).  0 =
    # never wait — a slow or crashed build worker can then never stall
    # the probe; unreceived filters degrade to today's behaviour.
    "dynamic_filtering_wait_ms": 0,
    # transitive semi-join pushdown (plan/optimizer); chunked planning
    # turns it off — the inferred probe-side semi never compacts at
    # chunk capacities
    # serving tier (server/serving.py, docs/SERVING.md): prepared
    # statements bind through the typed aval-abstracted path (one plan +
    # executable per parameter-type signature; kill switch falls every
    # EXECUTE back to text substitution), admission waits bound by the
    # queue timeout, and the protocol server's result cache serving
    # identical re-submitted SELECTs without execution (keyed by text x
    # catalog token+version x properties; any engine write invalidates)
    "prepared_typed_binding": True,
    # query coalescing (server/serving.QueryCoalescer + exec/executor.
    # run_compiled_batched): concurrent EXECUTEs of the SAME prepared
    # signature arriving within the micro-batch window stack their
    # bound parameters into a leading axis and share ONE vmap-batched
    # XLA launch.  query_coalescing: auto (default — a window opens
    # only when another same-signature query is in flight) | on | off
    # (env kill switch PRESTO_TPU_QUERY_COALESCING=off); the window is
    # coalesce_window_ms and batches cap at coalesce_max_batch (stacked
    # sizes quantize to pow2 below the cap so near-identical batch
    # sizes share executables).  Never changes results: anything that
    # cannot batch exits the group and runs solo.
    "query_coalescing": "auto",
    "coalesce_window_ms": 2.0,
    "coalesce_max_batch": 16,
    "admission_queue_timeout_s": 60.0,
    # coordinator fleet (server/fleet.py; docs/SERVING.md "Multi-
    # coordinator topology"): coordinator_count is the serving-fleet
    # size (1 = classic single coordinator; bench.py --serve
    # --coordinators N overrides per run); fleet_affinity is the front
    # door's routing mode for statements owned by a ring peer — proxy
    # (default: forward and re-home URIs, dumb clients keep one
    # endpoint) | redirect (307 to the owner; clients that follow it
    # skip the proxy hop) | off (execute wherever the statement lands;
    # coalescing batches then fragment 1/N); fleet_invalidate gates the
    # best-effort version-stamped invalidation broadcast on engine
    # writes (the catalog token+version baked into every cache key is
    # the correctness backstop — a dropped broadcast degrades to a key
    # miss, never a stale hit)
    "coordinator_count": 1,
    "fleet_affinity": "proxy",
    "fleet_invalidate": True,
    "result_cache_enabled": True,
    "result_cache_max_entries": 256,
    "result_cache_max_bytes": 64 << 20,
    "result_cache_max_rows": 10_000,
    "transitive_semijoin_inference": True,
    "iterative_optimizer_enabled": True,
    "reorder_joins": True,  # Selinger-DP ReorderJoins in the Memo
    "max_reorder_joins": 8,  # Memo/Rule fixpoint pass
    "spill_path": "",  # "" = <tmp>/presto_tpu_spill
    "localfile_root": "",  # "" = <tmp>/presto_tpu_tables (file connectors)
    # write subsystem (exec/writer.py, docs/WRITES.md): rows per
    # streamed write chunk (chunked-mode CTAS/INSERT appends one sink
    # page per chunk — the bounded-host-memory knob), and the writer
    # worker count for distributed writes (0 = auto: one thread per
    # core up to 8; each worker writes its OWN staged files, the
    # coordinator runs the single finish/commit)
    "write_page_rows": 1 << 20,
    "write_parallelism": 0,
    "spill_partition_count": 8,  # Grace hash fan-out (GenericPartitioningSpiller)
    "max_spill_bytes": 64 << 30,
    # force grouped execution above this input row count regardless of the
    # memory probe (0 = memory-driven only); the deterministic test knob,
    # like the reference's tiny operator-memory configs in spill tests
    "spill_trigger_rows": 0,
    # spill-tiered degradation (exec/spill_exec.py, docs/SPILL.md):
    # force hybrid spilling when an operator's estimated state exceeds
    # this many bytes (0 = memory-context-driven), force a specific tier
    # deterministically ("partial" | "recursive"; env
    # PRESTO_TPU_FORCE_SPILL outranks), bound the recursive
    # re-partitioning depth (past it the query fails LOUDLY with
    # SpillRecursionError), and optionally read each spill frame back
    # right after writing so write-path corruption heals by a
    # transparent re-spill instead of failing the query at unspill
    "spill_threshold_bytes": 0,
    "force_spill": "",
    "spill_max_recursion_depth": 3,
    "spill_verify_writes": False,
}


@dataclasses.dataclass
class QueryResult:
    """Host-side materialized result (reference: MaterializedResult)."""

    columns: list  # [(name, Type)]
    rows: list  # list of python tuples
    stats: Any = None  # this query's QueryStats (observe.stats)

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)

    def column(self, i: int) -> list:
        return [r[i] for r in self.rows]

    def to_dict(self) -> Dict[str, list]:
        return {name: self.column(i) for i, (name, _) in enumerate(self.columns)}


class Session:
    def __init__(self, catalog=None, properties: Optional[Dict[str, Any]] = None,
                 user: str = "user", source: str = "embedded"):
        import collections

        from presto_tpu.catalog import Catalog
        from presto_tpu.security import ALLOW_ALL, SessionPropertyManager
        from presto_tpu.transaction import TransactionManager

        self.catalog = catalog if catalog is not None else Catalog()
        self.user = user
        self.source = source
        self.access_control = ALLOW_ALL  # security.FileBasedAccessControl to restrict
        self.txn = TransactionManager(self)
        self.property_manager: Optional[SessionPropertyManager] = None
        self.properties = dict(DEFAULT_SESSION_PROPERTIES)
        self._explicit_props: set = set()
        if properties:
            self.properties.update(properties)
            self._explicit_props.update(properties)
        # query introspection + event pipeline (reference: QueryTracker
        # bounded history + eventlistener/EventListenerManager); the lock
        # covers concurrent server threads appending while others iterate
        import threading

        self.history = collections.deque(maxlen=1000)
        self.history_lock = threading.Lock()
        self.event_listeners: list = []
        # system/information_schema virtual tables over this session
        # (reference: SystemConnector + information_schema connector)
        from presto_tpu.connectors.system import register_system_tables

        register_system_tables(self)

    def set(self, name: str, value) -> None:
        if name not in self.properties:
            raise KeyError(f"unknown session property: {name}")
        self.properties[name] = value
        # explicit settings outrank property-manager rule defaults
        self._explicit_props.add(name)

    def add_event_listener(self, listener) -> None:
        self.event_listeners.append(listener)

    @property
    def last_stats(self):
        """QueryStats of the most recently begun query (reference:
        /v1/query).  Under concurrent queries prefer QueryResult.stats."""
        with self.history_lock:
            return self.history[-1] if self.history else None

    def history_snapshot(self) -> list:
        with self.history_lock:
            return list(self.history)

    def apply_property_manager(self) -> None:
        """Apply rule-matched session property DEFAULTS (reference:
        SessionPropertyConfigurationManager) — explicit SET SESSION /
        constructor values outrank rules, matching the reference's
        precedence."""
        if self.property_manager is not None:
            for k, v in self.property_manager.overrides(
                    self.user, self.source).items():
                if k in self.properties and k not in self._explicit_props:
                    self.properties[k] = v

    def sql(self, text: str) -> QueryResult:
        from presto_tpu.exec.executor import execute_query

        return execute_query(self, text)

    def explain(self, text: str, analyze: bool = False) -> str:
        from presto_tpu.exec.executor import explain_query

        return explain_query(self, text, analyze=analyze)


def connect(catalog=None, **properties) -> Session:
    return Session(catalog, properties or None)
