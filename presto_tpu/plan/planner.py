"""Analyzer + logical planner: AST -> typed logical plan.

Reference parity: sql/analyzer/StatementAnalyzer.java +
ExpressionAnalyzer.java (scopes, name resolution, type checking, coercions)
and sql/planner/{LogicalPlanner,RelationPlanner,QueryPlanner,SubqueryPlanner}.
Collapsed into one pass that emits typed IR directly (the reference's
separate Analysis object buys incremental re-analysis we don't need).

Subquery handling (reference: SubqueryPlanner + TransformCorrelated* rules):
- EXISTS / IN-subquery conjuncts  -> SEMI/ANTI join (+ residual filter)
- correlated scalar-aggregate subquery -> grouped aggregate joined on the
  correlation keys
- uncorrelated scalar subquery -> separately-planned subplan referenced by
  a ScalarSub IR leaf (evaluated first, like a gather-exchange stage)
"""

from __future__ import annotations

import itertools
import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from presto_tpu import types as T
from presto_tpu.functions import aggregate as agg_fns
from presto_tpu.functions import scalar as scalar_fns
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P
from presto_tpu.sql import ast


class SemanticError(Exception):
    pass


@dataclass
class Field_:
    qualifier: Optional[str]
    name: Optional[str]
    symbol: str
    type: T.Type


@dataclass
class Scope:
    fields: List[Field_] = field(default_factory=list)
    parent: Optional["Scope"] = None  # outer query scope (correlation)

    def resolve(self, parts: Tuple[str, ...]) -> Tuple[Field_, bool]:
        """Returns (field, is_outer)."""
        matches = self._match(parts)
        if len(matches) == 1:
            return matches[0], False
        if len(matches) > 1:
            raise SemanticError(f"Column '{'.'.join(parts)}' is ambiguous")
        if self.parent is not None:
            f, _ = self.parent.resolve(parts)
            return f, True
        raise SemanticError(f"Column '{'.'.join(parts)}' cannot be resolved")

    def _match(self, parts):
        if len(parts) == 1:
            return [f for f in self.fields if f.name == parts[0]]
        if len(parts) >= 2:
            q, n = parts[-2], parts[-1]
            return [f for f in self.fields if f.name == n and f.qualifier == q]
        return []

    def visible(self):
        return [f for f in self.fields if f.name is not None]


class SymbolAllocator:
    def __init__(self):
        self.counter = itertools.count()

    def new(self, hint: str) -> str:
        return f"{hint}${next(self.counter)}"


class Planner:
    def __init__(self, session):
        self.session = session
        self.catalog = session.catalog
        self.symbols = SymbolAllocator()
        self.subplans: Dict[int, P.PlanNode] = {}
        self.subplan_ids = itertools.count()
        self.cte_stack: List[Dict[str, tuple]] = []
        # id(ast.ScalarSubquery) -> decorrelated column Ref (see
        # _try_subquery_conjunct's general correlated form)
        self._scalar_sub_overrides: Dict[int, ir.RowExpr] = {}
        self._mark_overrides: Dict[int, str] = {}  # Exists/In -> mark sym

    # ------------------------------------------------------------------
    def plan_statement(self, stmt: ast.Statement) -> P.QueryPlan:
        if isinstance(stmt, ast.QueryStatement):
            node, scope, names = self.plan_query(stmt.query)
            out = P.Output(node, names, [f.symbol for f in scope.fields])
            return P.QueryPlan(out, self.subplans)
        raise SemanticError(f"unsupported statement: {type(stmt).__name__}")

    # ------------------------------------------------------------------
    @staticmethod
    def wrap_write(inner: P.QueryPlan, target: str, connector: str,
                   columns, write_props) -> P.QueryPlan:
        """Wrap an (already optimized) query plan as a write plan:
        Output <- TableFinish <- TableWriter <- inner (reference:
        LogicalPlanner.createTableWriterPlan).  The write metadata is
        plain data on the nodes; the runtime sink state lives in the
        executor's WriteContext (exec/writer.py)."""
        tw = P.TableWriter(source=inner.root, target=target,
                           connector=connector, columns=list(columns),
                           write_props=write_props)
        tf = P.TableFinish(source=tw)
        out = P.Output(source=tf, names=["rows"],
                       symbols=[tw.rows_symbol])
        return P.QueryPlan(root=out, subplans=inner.subplans)

    # ------------------------------------------------------------------
    def plan_query(self, q: ast.Query, outer: Optional[Scope] = None):
        """Returns (plan, scope, output names)."""
        if q.ctes:
            self.cte_stack.append({name.lower(): (query, cols) for name, query, cols in q.ctes})
        try:
            node, scope, names = self._plan_body(q.body, outer)
            if q.order_by:
                node, scope = self._plan_order_limit(node, scope, names, q.order_by, q.limit, outer)
            elif q.limit is not None:
                node = P.Limit(node, q.limit)
            return node, scope, names
        finally:
            if q.ctes:
                self.cte_stack.pop()

    def _plan_body(self, body, outer):
        if isinstance(body, ast.QuerySpec):
            return self.plan_query_spec(body, outer)
        if isinstance(body, ast.SetOp):
            return self._plan_set_op(body, outer)
        raise SemanticError(f"unsupported query body {type(body).__name__}")

    def _plan_set_op(self, op: ast.SetOp, outer):
        lnode, lscope, lnames = self._plan_body(op.left, outer)
        rnode, rscope, rnames = self._plan_body(op.right, outer)
        lf, rf = lscope.fields, rscope.fields
        if len(lf) != len(rf):
            raise SemanticError("set operation column count mismatch")
        if op.op == "UNION":
            out_syms, mappings_l, mappings_r = [], {}, {}
            out_fields = []
            for a, b in zip(lf, rf):
                ct = T.common_super_type(a.type, b.type)
                if ct is None:
                    raise SemanticError(f"UNION type mismatch {a.type} vs {b.type}")
                s = self.symbols.new(a.name or "col")
                out_syms.append(s)
                mappings_l[s] = a.symbol
                mappings_r[s] = b.symbol
                out_fields.append(Field_(None, a.name, s, ct))
            node = P.Union([lnode, rnode], out_syms, [mappings_l, mappings_r])
            scope = Scope(out_fields)
            if not op.all:
                node = P.Aggregate(node, out_syms, {}, "SINGLE")
            return node, scope, lnames
        # INTERSECT/EXCEPT via SEMI/ANTI join on all columns (distinct first)
        join_type = "SEMI" if op.op == "INTERSECT" else "ANTI"
        lnode = P.Aggregate(lnode, [f.symbol for f in lf], {}, "SINGLE")
        criteria = [(a.symbol, b.symbol) for a, b in zip(lf, rf)]
        node = P.Join(lnode, rnode, join_type, criteria)
        return node, lscope, lnames

    # ------------------------------------------------------------------
    def _expand_grouping_sets(self, spec: ast.QuerySpec):
        """GROUPING SETS/ROLLUP/CUBE -> UNION ALL of per-set aggregations
        (reference: GroupIdNode + GroupIdOperator, expressed as a set
        union instead of a group-id column).  Select items that are
        grouping keys excluded from a set become typed NULLs (UNION
        coercion settles the type)."""
        all_keys = set()
        for s in spec.grouping_sets:
            for e in s:
                all_keys.add(_ast_key(e))

        def name_of(item):
            if item.alias:
                return item.alias
            if isinstance(item.expr, ast.Identifier):
                return item.expr.parts[-1]
            return None

        def null_out(expr, excluded):
            """Replace references to rolled-up keys with NULL literals
            inside arbitrary select expressions (e.g. the lochierarchy
            CASE of TPC-DS q86 referencing a rolled-up column), and
            resolve grouping(e1..en) to its per-branch literal bitmask
            (reference: GroupingOperationRewriter — grouping() is a
            constant once the grouping set is fixed)."""
            if isinstance(expr, ast.FunctionCall) \
                    and expr.name.lower() == "grouping":
                bits = 0
                for a in expr.args:
                    bits = bits * 2 + (1 if _ast_key(a) in excluded else 0)
                return ast.Literal(bits)
            if isinstance(expr, ast.Expr) and _ast_key(expr) in excluded:
                return ast.Literal(None)
            if isinstance(expr, ast.FunctionCall) \
                    and agg_fns.is_aggregate(expr.name):
                return expr  # aggregate args see underlying rows, not NULLs
            if not isinstance(expr, ast.Node):
                return expr
            def sub(v):
                if isinstance(v, ast.Node):
                    return null_out(v, excluded)
                if isinstance(v, (list, tuple)):  # e.g. CASE whens pairs
                    return type(v)(sub(x) for x in v)
                return v

            changed = {}
            for f in dataclasses.fields(expr):
                v = getattr(expr, f.name)
                nv = sub(v)
                if nv is not v and nv != v:
                    changed[f.name] = nv
            return dataclasses.replace(expr, **changed) if changed else expr

        branches = []
        for s in spec.grouping_sets:
            in_set = {_ast_key(e) for e in s}
            excluded = all_keys - in_set
            items = []
            for item in spec.select:
                k = _ast_key(item.expr)
                if k in all_keys and k not in in_set:
                    items.append(ast.SelectItem(ast.Literal(None),
                                                name_of(item)))
                elif k not in all_keys:
                    # null_out with an empty exclusion set still resolves
                    # grouping() (all bits 0 in the finest branch)
                    items.append(ast.SelectItem(
                        null_out(item.expr, excluded), name_of(item)))
                else:
                    items.append(item)
            branches.append(ast.QuerySpec(
                items, spec.distinct, spec.from_, spec.where, list(s),
                spec.having))
        body = branches[0]
        for b in branches[1:]:
            body = ast.SetOp("UNION", True, body, b)
        return body

    def plan_query_spec(self, spec: ast.QuerySpec, outer):
        if getattr(spec, "grouping_sets", None):
            return self._plan_body(self._expand_grouping_sets(spec), outer)
        # FROM
        if spec.from_ is not None:
            node, scope = self.plan_relation(spec.from_, outer)
        else:
            sym = self.symbols.new("dual")
            node = P.Values([sym], [T.BIGINT], [[0]])
            scope = Scope([])
        scope.parent = outer

        # WHERE (with subquery conjuncts)
        if spec.where is not None:
            node = self._plan_where(node, scope, spec.where)

        # aggregation analysis
        agg_calls: List[Tuple[ast.FunctionCall, str]] = []  # (ast node, out symbol)
        # GROUP BY ordinals resolve to select-list expressions (reference:
        # StatementAnalyzer.analyzeGroupBy ordinal handling)
        group_by = []
        for ge in (spec.group_by or []):
            if isinstance(ge, ast.Literal) and isinstance(ge.value, int) \
                    and not isinstance(ge.value, bool):
                k = ge.value
                if not (1 <= k <= len(spec.select)) \
                        or isinstance(spec.select[k - 1].expr, ast.Star):
                    raise SemanticError(
                        f"GROUP BY position {k} is not in select list")
                group_by.append(spec.select[k - 1].expr)
            else:
                group_by.append(ge)
        has_group = bool(group_by)
        exprs_to_scan = [it.expr for it in spec.select if not isinstance(it.expr, ast.Star)]
        if spec.having is not None:
            exprs_to_scan.append(spec.having)
        for e in exprs_to_scan:
            self._collect_aggs(e, agg_calls)
        has_agg = bool(agg_calls) or has_group

        select_scope = scope
        if has_agg:
            node, select_scope, agg_map, group_map = self._plan_aggregation(
                node, scope, group_by, agg_calls, outer)
        else:
            agg_map, group_map = {}, {}

        # HAVING
        if spec.having is not None:
            node = self._plan_where(node, select_scope, spec.having,
                                    agg_map=agg_map, group_map=group_map)

        # window functions: plan one Window node per distinct
        # (partition, order, frame) spec, evaluated after aggregation
        # (reference: sql/planner/QueryPlanner.window + WindowNode)
        win_calls: List[ast.FunctionCall] = []
        for e in exprs_to_scan:
            self._collect_windows(e, win_calls)
        if win_calls:
            node, win_map = self._plan_windows(
                node, select_scope, win_calls, agg_map, group_map)
            agg_map = {**(agg_map or {}), **win_map}

        # SELECT projections
        assignments: Dict[str, ir.RowExpr] = {}
        out_fields: List[Field_] = []
        names: List[str] = []
        for item in spec.select:
            if isinstance(item.expr, ast.Star):
                for f in (select_scope.visible() if item.expr.qualifier is None else
                          [f for f in select_scope.fields if f.qualifier == item.expr.qualifier]):
                    s = self.symbols.new(f.name or "col")
                    assignments[s] = ir.Ref(f.symbol, f.type)
                    out_fields.append(Field_(None, f.name, s, f.type))
                    names.append(f.name or "_col")
                continue
            e = self.analyze(item.expr, select_scope, agg_map=agg_map, group_map=group_map)
            name = item.alias or self._derive_name(item.expr)
            s = self.symbols.new(name or "expr")
            assignments[s] = e
            out_fields.append(Field_(None, name, s, e.type))
            names.append(name or "_col")
        node = P.Project(node, assignments)
        scope_out = Scope(out_fields)

        if spec.distinct:
            node = P.Aggregate(node, [f.symbol for f in out_fields], {}, "SINGLE")

        # stash for ORDER BY resolution: keep pre-projection scope available
        scope_out.pre_projection = (select_scope, agg_map, group_map)  # type: ignore
        return node, scope_out, names

    def _derive_name(self, e: ast.Expr) -> Optional[str]:
        if isinstance(e, ast.Identifier):
            return e.name
        if isinstance(e, ast.FunctionCall):
            return e.name
        return None

    # ------------------------------------------------------------------
    def _plan_order_limit(self, node, scope, names, order_by, limit, outer):
        """Sort may reference select aliases, ordinals, or (for non-agg
        queries) underlying columns; extra sort keys are projected then
        trimmed (reference: QueryPlanner.planOrderBy)."""
        keys = []
        extra_assignments = {}
        pre = getattr(scope, "pre_projection", None)
        for si in order_by:
            e = si.expr
            sym = None
            if isinstance(e, ast.Literal) and isinstance(e.value, int):
                idx = e.value - 1
                if not (0 <= idx < len(scope.fields)):
                    raise SemanticError(f"ORDER BY position {e.value} out of range")
                sym = scope.fields[idx].symbol
            elif isinstance(e, ast.Identifier) and len(e.parts) == 1:
                matches = [f for f in scope.fields if f.name == e.name]
                if matches:
                    sym = matches[0].symbol
            if sym is None:
                if pre is not None:
                    sel_scope, agg_map, group_map = pre
                    rex = self.analyze(e, sel_scope, agg_map=agg_map, group_map=group_map)
                else:
                    rex = self.analyze(e, scope)
                s = self.symbols.new("sortkey")
                extra_assignments[s] = rex
                sym = s
            keys.append((sym, si.ascending, si.nulls_first))
        if extra_assignments:
            if isinstance(node, P.Project):
                node = P.Project(node.source,
                                 {**node.assignments, **extra_assignments})
            else:
                # non-projection source (e.g. the UNION of grouping-set
                # branches under a computed ORDER BY key, q36/q70):
                # wrap in an identity projection carrying the sort keys
                assigns = {f.symbol: ir.Ref(f.symbol, f.type)
                           for f in scope.fields}
                node = P.Project(node, {**assigns, **extra_assignments})
        if limit is not None:
            node = P.TopN(node, keys, limit)
        else:
            node = P.Sort(node, keys)
        if extra_assignments:
            # trim the extra sort keys after sorting
            keep = {f.symbol: ir.Ref(f.symbol, f.type) for f in scope.fields}
            node = P.Project(node, keep)
        return node, scope

    # ------------------------------------------------------------------
    # relations
    # ------------------------------------------------------------------
    def plan_relation(self, rel: ast.Relation, outer) -> Tuple[P.PlanNode, Scope]:
        if isinstance(rel, ast.Table):
            return self._plan_table(rel, outer)
        if isinstance(rel, ast.SubqueryRelation):
            node, scope, names = self.plan_query(rel.query, outer)
            q = rel.alias
            fields = []
            for i, f in enumerate(scope.fields):
                nm = (rel.column_aliases[i] if rel.column_aliases and i < len(rel.column_aliases)
                      else f.name)
                fields.append(Field_(q, nm, f.symbol, f.type))
            return node, Scope(fields)
        if isinstance(rel, ast.Join):
            return self._plan_join(rel, outer)
        if isinstance(rel, ast.ValuesRelation):
            return self._plan_values(rel)
        if isinstance(rel, ast.Unnest):
            # standalone FROM UNNEST(...): explode over a one-row source
            sym = self.symbols.new("dual")
            dual = P.Values([sym], [T.BIGINT], [[0]])
            return self._plan_unnest(dual, Scope([]), rel)
        raise SemanticError(f"unsupported relation {type(rel).__name__}")

    def _plan_unnest(self, lnode, lscope, rel: ast.Unnest):
        """Lateral UNNEST: the array expression may reference the left
        relation's columns (reference: UnnestNode planned from a lateral
        Join in RelationPlanner.visitUnnest)."""
        if len(rel.exprs) != 1:
            raise SemanticError("UNNEST of multiple arrays not supported yet")
        rex = self.analyze(rel.exprs[0], lscope)
        if rex.type.name != "ARRAY":
            raise SemanticError(f"UNNEST argument must be an ARRAY, got {rex.type}")
        elem = rex.type.params[0] if rex.type.params else T.UNKNOWN
        out_sym = self.symbols.new("unnest")
        ord_sym = self.symbols.new("ordinality") if rel.with_ordinality else None
        node = P.Unnest(lnode, rex, out_sym, elem, ord_sym)
        q = rel.alias
        aliases = getattr(rel, "column_aliases", None) or []
        fields = list(lscope.fields)
        fields.append(Field_(q, aliases[0] if aliases else (q or "col"),
                             out_sym, elem))
        if ord_sym:
            fields.append(Field_(q, aliases[1] if len(aliases) > 1
                                 else "ordinality", ord_sym, T.BIGINT))
        return node, Scope(fields)

    def _plan_table(self, rel: ast.Table, outer):
        name = rel.name.lower()
        for ctes in reversed(self.cte_stack):
            if name in ctes:
                query, col_aliases = ctes[name]
                node, scope, names = self.plan_query(query, None)
                q = rel.alias or rel.name
                fields = []
                for i, f in enumerate(scope.fields):
                    nm = (col_aliases[i] if col_aliases and i < len(col_aliases) else f.name)
                    fields.append(Field_(q, nm, f.symbol, f.type))
                return node, Scope(fields)
        table = self.catalog.get(name)
        assignments, types, fields = {}, {}, []
        # implicit qualifier is the bare table name (reference: a qualified
        # name's last part is the relation alias)
        q = rel.alias or rel.name.split(".")[-1]
        for i, (col, typ) in enumerate(table.schema.items()):
            nm = (rel.column_aliases[i] if rel.column_aliases and i < len(rel.column_aliases)
                  else col)
            s = self.symbols.new(col)
            assignments[s] = col
            types[s] = typ
            fields.append(Field_(q, nm, s, typ))
        node = P.TableScan(name, assignments, types)
        if getattr(rel, "sample", None):
            # TABLESAMPLE BERNOULLI(p): keep each row with probability
            # p% (reference: SampleNode; SYSTEM trims to the same
            # row-level bernoulli — this engine has no split-local
            # storage granularity worth sampling by)
            _method, pct = rel.sample
            pred = ir.Call(
                "lt", (ir.Call("random", (), T.DOUBLE),
                       ir.Lit(pct / 100.0, T.DOUBLE)), T.BOOLEAN)
            node = P.Filter(node, pred)
        return node, Scope(fields)

    def _plan_values(self, rel: ast.ValuesRelation):
        rows = []
        col_types: List[T.Type] = []
        for row in rel.rows:
            vals = []
            for j, e in enumerate(row):
                rex = self.analyze(e, Scope([]))
                # fold CAST(NULL AS t) — the idiomatic way to type a
                # NULL column in VALUES (reference VALUES accepts
                # arbitrary constant expressions)
                if isinstance(rex, ir.CastExpr) and \
                        isinstance(rex.arg, ir.Lit) and rex.arg.value is None:
                    rex = ir.Lit(None, rex.type)
                if not isinstance(rex, ir.Lit):
                    # constant expressions (ARRAY[..] / MAP(..) ctors,
                    # arithmetic over literals) fold at plan time —
                    # the reference's VALUES accepts any constant expr
                    folded = _fold_constant_expr(rex)
                    if folded is None:
                        raise SemanticError(
                            "VALUES requires constant expressions")
                    rex = folded
                vals.append(rex.value)
                if j >= len(col_types):
                    col_types.append(rex.type)
                else:
                    ct = T.common_super_type(col_types[j], rex.type)
                    if ct is None:
                        raise SemanticError("VALUES type mismatch")
                    col_types[j] = ct
            rows.append(vals)
        syms = [self.symbols.new(f"col{j}") for j in range(len(col_types))]
        aliases = rel.column_aliases or [f"_col{j}" for j in range(len(col_types))]
        fields = [Field_(rel.alias, aliases[j] if j < len(aliases) else f"_col{j}",
                         syms[j], col_types[j]) for j in range(len(col_types))]
        return P.Values(syms, col_types, rows), Scope(fields)

    def _plan_join(self, rel: ast.Join, outer):
        lnode, lscope = self.plan_relation(rel.left, outer)
        if isinstance(rel.right, ast.Unnest):
            if rel.join_type != "CROSS":
                raise SemanticError("UNNEST joins must be CROSS JOIN / comma")
            return self._plan_unnest(lnode, lscope, rel.right)
        rnode, rscope = self.plan_relation(rel.right, outer)
        combined = Scope(lscope.fields + rscope.fields)
        jt = rel.join_type
        if jt == "CROSS":
            return P.Join(lnode, rnode, "CROSS"), combined
        criteria: List[Tuple[str, str]] = []
        residual: List[ir.RowExpr] = []
        left_only: List[ir.RowExpr] = []
        right_only: List[ir.RowExpr] = []
        lsyms = {f.symbol for f in lscope.fields}
        rsyms = {f.symbol for f in rscope.fields}
        conjs: List[ast.Expr] = []
        if rel.using:
            for col in rel.using:
                conjs.append(ast.BinaryOp("=", ast.Identifier((col,)), ast.Identifier((col,))))
                # resolve each side explicitly below
        else:
            conjs = _ast_conjuncts(rel.on)
        for c in conjs:
            if rel.using and isinstance(c, ast.BinaryOp) and c.op == "=":
                colname = c.left.name  # type: ignore
                lf = [f for f in lscope.fields if f.name == colname]
                rf = [f for f in rscope.fields if f.name == colname]
                if not lf or not rf:
                    raise SemanticError(f"USING column {colname} missing")
                criteria.append((lf[0].symbol, rf[0].symbol))
                continue
            rex = self.analyze(c, combined)
            refs = rex.refs()
            if isinstance(rex, ir.Call) and rex.fn == "eq":
                a, b = rex.args
                ar, br = a.refs(), b.refs()
                if ar and br:
                    if ar <= lsyms and br <= rsyms:
                        criteria.append((self._as_symbol(a, "lk"), self._as_symbol(b, "rk")))
                        lnode, rnode = self._attach_key(lnode, a), self._attach_key(rnode, b)
                        continue
                    if ar <= rsyms and br <= lsyms:
                        criteria.append((self._as_symbol(b, "lk"), self._as_symbol(a, "rk")))
                        lnode, rnode = self._attach_key(lnode, b), self._attach_key(rnode, a)
                        continue
            if refs and refs <= lsyms:
                left_only.append(rex)
            elif refs and refs <= rsyms:
                right_only.append(rex)
            else:
                residual.append(rex)
        # push single-side conjuncts (semantics-preserving placement by join type)
        if jt == "INNER":
            if left_only:
                lnode = P.Filter(lnode, ir.combine_conjuncts(left_only))
            if right_only:
                rnode = P.Filter(rnode, ir.combine_conjuncts(right_only))
        else:
            if jt == "LEFT" and right_only:
                rnode = P.Filter(rnode, ir.combine_conjuncts(right_only))
            elif jt == "RIGHT" and left_only:
                lnode = P.Filter(lnode, ir.combine_conjuncts(left_only))
            else:
                residual.extend(left_only + right_only)
        node = P.Join(lnode, rnode, jt, criteria, ir.combine_conjuncts(residual))
        return node, combined

    def _as_symbol(self, e: ir.RowExpr, hint: str) -> str:
        if isinstance(e, ir.Ref):
            return e.name
        s = self.symbols.new(hint)
        # RowExprs are frozen dataclasses: attach the planning-only
        # symbol without tripping __setattr__ (a literal or computed
        # join key lands here, e.g. ON l.x = u.k after `1 AS x` inlines)
        object.__setattr__(e, "_planned_symbol", s)
        return s

    def _attach_key(self, node: P.PlanNode, e: ir.RowExpr) -> P.PlanNode:
        """If a join key is a computed expression, project it onto the input."""
        if isinstance(e, ir.Ref):
            return node
        sym = getattr(e, "_planned_symbol")
        assigns = {s: ir.Ref(s, t) for s, t in node.outputs()}
        assigns[sym] = e
        return P.Project(node, assigns)

    # ------------------------------------------------------------------
    # WHERE / HAVING with subquery conjunct handling
    # ------------------------------------------------------------------
    def _plan_where(self, node, scope, pred: ast.Expr, agg_map=None, group_map=None):
        plain: List[ir.RowExpr] = []
        for conj in _ast_conjuncts(pred):
            node, handled = self._try_subquery_conjunct(node, scope, conj, agg_map, group_map)
            if handled:
                continue
            plain.append(self.analyze(conj, scope, agg_map=agg_map, group_map=group_map))
        if plain:
            node = P.Filter(node, ir.combine_conjuncts(plain))
        return node

    def _try_subquery_conjunct(self, node, scope, conj, agg_map, group_map):
        neg = False
        inner = conj
        while isinstance(inner, ast.UnaryOp) and inner.op == "NOT":
            neg = not neg
            inner = inner.operand
        if isinstance(inner, ast.Exists):
            sub = inner.query
            negated = neg != inner.negated
            return self._plan_exists(node, scope, sub, negated), True
        if isinstance(inner, ast.InSubquery):
            negated = neg != inner.negated
            return self._plan_in_subquery(node, scope, inner.value, inner.query, negated,
                                          agg_map, group_map), True
        if isinstance(inner, ast.BinaryOp) and inner.op in ("=", "<>", "<", "<=", ">", ">=") and not neg:
            lhs, rhs = inner.left, inner.right
            if isinstance(rhs, ast.ScalarSubquery) or isinstance(lhs, ast.ScalarSubquery):
                if isinstance(lhs, ast.ScalarSubquery):
                    lhs, rhs = rhs, lhs
                    opmap = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
                    inner = ast.BinaryOp(opmap.get(inner.op, inner.op), lhs, rhs)
                return self._plan_scalar_compare(node, scope, inner.op, lhs,
                                                 rhs.query, agg_map, group_map), True
        # EXISTS/IN under a boolean combination (q10/q35's
        # `EXISTS(...) OR EXISTS(...)`): plan each subquery as a MARK
        # join adding a boolean match column, then evaluate the
        # original expression over the marks (reference: SemiJoinNode's
        # semiJoinOutput consumed by a FilterNode)
        marked = self._try_mark_joins(node, scope, conj, agg_map, group_map)
        if marked is not None:
            return marked, True
        # general form: ONE correlated scalar subquery anywhere in the
        # conjunct (e.g. `price > 1.2 * (SELECT avg(...) WHERE corr)`) —
        # decorrelate to a joined column, substitute, analyze as usual
        subs: List[ast.ScalarSubquery] = []
        _collect_scalar_subqueries(conj, subs)
        if len(subs) == 1:
            sq = subs[0].query
            if isinstance(sq.body, ast.QuerySpec) and sq.body.from_ is not None \
                    and self._find_correlation(sq.body, scope):
                new_node, sref = self._decorrelate_scalar_to_column(
                    node, scope, sq.body)
                self._scalar_sub_overrides[id(subs[0])] = sref
                try:
                    rex = self.analyze(conj, scope, agg_map, group_map)
                finally:
                    self._scalar_sub_overrides.pop(id(subs[0]), None)
                return P.Filter(new_node, rex), True
        return node, False

    def _try_mark_joins(self, node, scope, conj, agg_map, group_map):
        """Plan a conjunct whose boolean expression CONTAINS subquery
        predicates (not as top-level conjuncts): each EXISTS/IN becomes
        a MARK join; the expression then filters on the mark columns.
        Returns the new plan node, or None if the shape doesn't apply."""
        subqs: List[ast.Expr] = []
        _collect_subquery_preds(conj, subqs)
        if not subqs:
            return None
        planned = []
        try:
            for sq in subqs:
                mark = self.symbols.new("mark")
                if isinstance(sq, ast.Exists):
                    spec = sq.query.body
                    if not isinstance(spec, ast.QuerySpec) or spec.group_by \
                            or spec.having:
                        return None
                    inner_node, inner_scope = self.plan_relation(spec.from_,
                                                                 None)
                    node = self._correlated_semi_join(
                        node, scope, inner_node, inner_scope, spec.where,
                        negated=False, mark=mark)
                else:  # InSubquery
                    val = self.analyze(sq.value, scope, agg_map=agg_map,
                                       group_map=group_map)
                    inner_node, inner_scope, _ = self.plan_query(sq.query,
                                                                 scope)
                    if len(inner_scope.fields) != 1:
                        return None
                    lsym = self._as_symbol(val, "inval")
                    if not isinstance(val, ir.Ref):
                        node = self._attach_key(node, val)
                    node = P.Join(node, inner_node, "MARK",
                                  [(lsym, inner_scope.fields[0].symbol)],
                                  mark=mark)
                # negation is applied where the expression references the
                # mark (analyze's Exists/InSubquery override)
                planned.append((id(sq), mark))
                self._mark_overrides[id(sq)] = mark
            rex = self.analyze(conj, scope, agg_map=agg_map,
                               group_map=group_map)
        except SemanticError:
            return None
        finally:
            for k, _m in planned:
                self._mark_overrides.pop(k, None)
        return P.Filter(node, rex)

    def _plan_exists(self, node, scope, sub: ast.Query, negated: bool):
        if not isinstance(sub.body, ast.QuerySpec) or sub.body.group_by or sub.body.having:
            raise SemanticError("EXISTS subquery too complex")
        spec = sub.body
        inner_node, inner_scope = self.plan_relation(spec.from_, None)
        return self._correlated_semi_join(
            node, scope, inner_node, inner_scope, spec.where, negated)

    def _correlated_semi_join(self, node, scope, inner_node, inner_scope,
                              where: Optional[ast.Expr], negated: bool,
                              extra_criteria: Optional[list] = None,
                              mark: Optional[str] = None):
        inner_syms = {f.symbol for f in inner_scope.fields}
        joint = Scope(inner_scope.fields, parent=scope)
        criteria: List[Tuple[str, str]] = list(extra_criteria or [])
        inner_only: List[ir.RowExpr] = []
        residual: List[ir.RowExpr] = []
        for c in _ast_conjuncts(where):
            rex = self.analyze(c, joint)
            refs = rex.refs()
            if refs <= inner_syms:
                inner_only.append(rex)
                continue
            if isinstance(rex, ir.Call) and rex.fn == "eq":
                a, b = rex.args
                if a.refs() <= inner_syms and isinstance(b, ir.Ref):
                    criteria.append((b.name, self._as_symbol(a, "ck")))
                    inner_node = self._attach_key(inner_node, a)
                    continue
                if b.refs() <= inner_syms and isinstance(a, ir.Ref):
                    criteria.append((a.name, self._as_symbol(b, "ck")))
                    inner_node = self._attach_key(inner_node, b)
                    continue
            residual.append(rex)
        if inner_only:
            inner_node = P.Filter(inner_node, ir.combine_conjuncts(inner_only))
        if not criteria and residual:
            raise SemanticError("unsupported correlated predicate (no equality)")
        if mark is not None:
            if residual:
                # the MARK executor path is filter-free; residual
                # correlation falls back to the caller's error path
                raise SemanticError("MARK join with residual predicate")
            return P.Join(node, inner_node, "MARK", criteria, mark=mark)
        jt = "ANTI" if negated else "SEMI"
        return P.Join(node, inner_node, jt, criteria, ir.combine_conjuncts(residual))

    def _plan_in_subquery(self, node, scope, value: ast.Expr, sub: ast.Query,
                          negated: bool, agg_map, group_map):
        val = self.analyze(value, scope, agg_map=agg_map, group_map=group_map)
        inner_node, inner_scope, _ = self.plan_query(sub, scope)
        if len(inner_scope.fields) != 1:
            raise SemanticError("IN subquery must return one column")
        inner_sym = inner_scope.fields[0].symbol
        lsym = self._as_symbol(val, "inval")
        if not isinstance(val, ir.Ref):
            node = self._attach_key(node, val)
        if negated:
            # null-aware NOT IN: with no match the predicate is NULL
            # (row filtered) when x is NULL or the build side contains
            # NULLs.  A plain ANTI join has EXISTS semantics and keeps
            # exactly those rows; the MARK join's 3-valued mark carries
            # the distinction (reference: SemiJoinNode semiJoinOutput
            # consumed by FilterNode(NOT mark))
            mark = self.symbols.new("mark")
            j = P.Join(node, inner_node, "MARK", [(lsym, inner_sym)],
                       mark=mark)
            return P.Filter(j, ir.Call("not", (ir.Ref(mark, T.BOOLEAN),),
                                       T.BOOLEAN))
        return P.Join(node, inner_node, "SEMI", [(lsym, inner_sym)])

    def _plan_scalar_compare(self, node, scope, op: str, lhs: ast.Expr,
                             sub: ast.Query, agg_map, group_map):
        """lhs OP (scalar subquery): correlated-agg decorrelation or
        uncorrelated subplan."""
        opn = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}[op]
        lval = self.analyze(lhs, scope, agg_map=agg_map, group_map=group_map)
        # attempt correlated-aggregate decorrelation
        if isinstance(sub.body, ast.QuerySpec) and sub.body.from_ is not None:
            spec = sub.body
            correlated = self._find_correlation(spec, scope)
            if correlated:
                return self._decorrelate_scalar_agg(node, scope, opn, lval, spec)
        # uncorrelated: separate subplan
        sub_node, sub_scope, _ = self.plan_query(sub, None)
        if len(sub_scope.fields) != 1:
            raise SemanticError("scalar subquery must return one column")
        pid = next(self.subplan_ids)
        self.subplans[pid] = sub_node
        sref = ir.ScalarSub(pid, sub_scope.fields[0].type)
        a, b = self._coerce_pair(lval, sref)
        return P.Filter(node, ir.Call(opn, (a, b), T.BOOLEAN))

    def _find_correlation(self, spec: ast.QuerySpec, outer_scope: Scope) -> bool:
        """Cheap correlation test: try planning the FROM + analyzing WHERE
        with no outer scope; resolution error mentioning outer columns =>
        correlated."""
        saved_symbols = self.symbols
        saved_subplans = dict(self.subplans)
        try:
            inner_node, inner_scope = self.plan_relation(spec.from_, None)
            for c in _ast_conjuncts(spec.where):
                self.analyze(c, inner_scope)
            return False
        except SemanticError:
            return True
        finally:
            self.subplans.clear()
            self.subplans.update(saved_subplans)

    def _decorrelate_scalar_agg(self, node, scope, opn, lval, spec: ast.QuerySpec):
        join, sref = self._decorrelate_scalar_to_column(node, scope, spec)
        a, b = self._coerce_pair(lval, sref)
        return P.Filter(join, ir.Call(opn, (a, b), T.BOOLEAN))

    def _decorrelate_scalar_to_column(self, node, scope, spec: ast.QuerySpec):
        """`(SELECT f(aggs) FROM inner WHERE eqs AND rest)` correlated on
        eqs -> Aggregate(inner, group=correlation keys) JOIN outer ON eqs;
        returns (join node, Ref to the scalar column).
        (Reference: TransformCorrelatedScalarAggregationToJoin rule.)"""
        if len(spec.select) != 1 or spec.group_by or spec.having:
            raise SemanticError("unsupported correlated scalar subquery shape")
        inner_node, inner_scope = self.plan_relation(spec.from_, None)
        inner_syms = {f.symbol for f in inner_scope.fields}
        joint = Scope(inner_scope.fields, parent=scope)
        criteria: List[Tuple[str, str]] = []
        inner_only: List[ir.RowExpr] = []
        for c in _ast_conjuncts(spec.where):
            rex = self.analyze(c, joint)
            if rex.refs() <= inner_syms:
                inner_only.append(rex)
                continue
            if isinstance(rex, ir.Call) and rex.fn == "eq":
                a, b = rex.args
                if a.refs() <= inner_syms and isinstance(b, ir.Ref):
                    criteria.append((b.name, self._as_symbol(a, "ck")))
                    inner_node = self._attach_key(inner_node, a)
                    continue
                if b.refs() <= inner_syms and isinstance(a, ir.Ref):
                    criteria.append((a.name, self._as_symbol(b, "ck")))
                    inner_node = self._attach_key(inner_node, b)
                    continue
            raise SemanticError("unsupported correlated predicate in scalar subquery")
        if not criteria:
            raise SemanticError("correlated scalar subquery without equality correlation")
        if inner_only:
            inner_node = P.Filter(inner_node, ir.combine_conjuncts(inner_only))
        # aggregate over correlation keys
        agg_calls: List[Tuple[ast.FunctionCall, str]] = []
        self._collect_aggs(spec.select[0].expr, agg_calls)
        if not agg_calls:
            raise SemanticError("correlated scalar subquery must aggregate")
        group_keys = [rk for _, rk in criteria]
        pre_assigns = {s: ir.Ref(s, t) for s, t in inner_node.outputs()}
        aggs: Dict[str, ir.AggCall] = {}
        agg_map: Dict[int, Tuple[str, T.Type]] = {}
        for fc, _ in agg_calls:
            arg_exprs = tuple(self.analyze(a, inner_scope) for a in fc.args)
            arg_syms = []
            for ae in arg_exprs:
                if isinstance(ae, ir.Ref):
                    arg_syms.append(ae)
                else:
                    s2 = self.symbols.new("aggarg")
                    pre_assigns[s2] = ae
                    arg_syms.append(ir.Ref(s2, ae.type))
            rt = agg_fns.resolve(fc.name, [a.type for a in arg_syms], fc.distinct)
            s = self.symbols.new(fc.name)
            aggs[s] = ir.AggCall(fc.name.lower(), tuple(arg_syms), rt, fc.distinct)
            agg_map[id(fc)] = (s, rt)
        inner_node = P.Project(inner_node, pre_assigns)
        agg_node = P.Aggregate(inner_node, group_keys, aggs, "SINGLE")
        # the subquery's select expression over agg outputs
        agg_scope = Scope([Field_(None, None, s, t) for s, t in agg_node.outputs()])
        sel_expr = self.analyze(spec.select[0].expr, agg_scope, agg_map=agg_map,
                                group_map={})
        ssym = self.symbols.new("scalar")
        proj = {s: ir.Ref(s, t) for s, t in agg_node.outputs()}
        proj[ssym] = sel_expr
        sub_node = P.Project(agg_node, proj)
        # join outer to the grouped aggregate: LEFT, so outer rows with no
        # matching group survive with a NULL scalar (reference:
        # TransformCorrelatedScalarAggregationToJoin uses a left join —
        # matters under OR / coalesce / count(*)=0 shapes)
        jcriteria = [(lk, rk) for (lk, rk) in criteria]
        join = P.Join(node, sub_node, "LEFT", jcriteria)
        return join, ir.Ref(ssym, sel_expr.type)

    # ------------------------------------------------------------------
    # aggregation planning
    # ------------------------------------------------------------------
    def _collect_aggs(self, e: ast.Expr, out: List[Tuple[ast.FunctionCall, str]]):
        if isinstance(e, ast.FunctionCall) and agg_fns.is_aggregate(e.name) and e.window is None:
            out.append((e, ""))
            return  # no nested aggregates
        for child in e.children():
            if isinstance(child, (ast.Query, ast.QuerySpec)):
                continue  # subquery boundaries
            self._collect_aggs(child, out)

    def _collect_windows(self, e: ast.Expr, out: List[ast.FunctionCall]):
        if isinstance(e, ast.FunctionCall) and e.window is not None:
            out.append(e)
            return  # window functions cannot nest
        for child in e.children():
            if isinstance(child, (ast.Query, ast.QuerySpec)):
                continue
            self._collect_windows(child, out)

    def _plan_windows(self, node, scope, win_calls, agg_map, group_map):
        """Attach partition/order/arg columns below, then one P.Window per
        distinct spec; returns (node, {id(ast call) -> (symbol, type)})."""
        pre = {s: ir.Ref(s, t) for s, t in node.outputs()}

        def to_sym(e_ast):
            rex = self.analyze(e_ast, scope, agg_map=agg_map, group_map=group_map)
            if isinstance(rex, ir.Ref) and rex.name in pre:
                return rex.name, rex.type
            s = self.symbols.new("winkey")
            pre[s] = rex
            return s, rex.type

        planned = []
        for fc in win_calls:
            w = fc.window
            part = tuple(to_sym(p)[0] for p in w.partition_by)
            order = tuple((to_sym(si.expr)[0], si.ascending, si.nulls_first)
                          for si in w.order_by)
            args = []
            for a_ast in fc.args:
                rex = self.analyze(a_ast, scope, agg_map=agg_map, group_map=group_map)
                if isinstance(rex, ir.Lit):
                    args.append(rex)
                elif isinstance(rex, ir.Ref) and rex.name in pre:
                    args.append(rex)
                else:
                    s2 = self.symbols.new("winarg")
                    pre[s2] = rex
                    args.append(ir.Ref(s2, rex.type))
            planned.append((fc, part, order, w.frame, tuple(args)))

        node = P.Project(node, pre)
        win_map: Dict[int, Tuple[str, T.Type]] = {}
        groups: Dict[tuple, list] = {}
        for fc, part, order, frame, args in planned:
            groups.setdefault((part, order, frame), []).append((fc, args))
        for (part, order, frame), calls in groups.items():
            fns: Dict[str, ir.AggCall] = {}
            for fc, args in calls:
                if fc.distinct:
                    raise SemanticError(
                        f"DISTINCT not supported in window function {fc.name}")
                if fc.filter is not None:
                    raise SemanticError(
                        f"FILTER not supported in window function {fc.name}")
                try:
                    rt = agg_fns.resolve_window(fc.name, [a.type for a in args])
                except KeyError as e:
                    raise SemanticError(str(e.args[0])) from None
                ign = fc.null_treatment == "IGNORE"
                if ign and fc.name.lower() not in (
                        "lag", "lead", "first_value", "last_value",
                        "nth_value"):
                    raise SemanticError(
                        "IGNORE NULLS applies only to the window value "
                        "functions (lag/lead/first_value/last_value/"
                        "nth_value)")
                s = self.symbols.new(fc.name)
                fns[s] = ir.AggCall(fc.name.lower(), args, rt, fc.distinct,
                                    None, ignore_nulls=ign)
                win_map[id(fc)] = (s, rt)
            node = P.Window(node, list(part), list(order), fns, frame)
        return node, win_map

    def _plan_aggregation(self, node, scope, group_by, agg_calls, outer):
        pre_assigns = {s: ir.Ref(s, t) for s, t in node.outputs()}
        group_keys: List[str] = []
        group_map: Dict[str, str] = {}  # ast repr of group expr -> symbol
        group_fields: List[Field_] = []
        for ge in group_by:
            rex = self.analyze(ge, scope)
            if isinstance(rex, ir.Ref):
                sym = rex.name
            else:
                sym = self.symbols.new("groupkey")
                pre_assigns[sym] = rex
            group_keys.append(sym)
            group_map[_ast_key(ge)] = sym
            f = next((f for f in scope.fields if f.symbol == sym), None)
            group_fields.append(Field_(f.qualifier if f else None,
                                       f.name if f else None, sym, rex.type))
        aggs: Dict[str, ir.AggCall] = {}
        agg_map: Dict[int, Tuple[str, T.Type]] = {}
        def _agg_lambda(l, ptypes, name):
            """Type a lambda aggregate argument (reduce_agg) against the
            enclosing scope — same shape as the scalar-HOF `lam` helper."""
            if not isinstance(l, ast.Lambda):
                raise SemanticError(f"{name} expects a lambda argument")
            if len(l.params) != len(ptypes):
                raise SemanticError(
                    f"{name} lambda must take {len(ptypes)} argument(s)")
            syms = [self.symbols.new(f"lam_{p}") for p in l.params]
            inner = Scope([Field_(None, p, sy, t) for p, sy, t
                           in zip(l.params, syms, ptypes)], parent=scope)
            body = self.analyze(l.body, inner)
            return ir.LambdaExpr(tuple(syms), tuple(ptypes), body,
                                 T.function_type(body.type))

        for fc, _ in agg_calls:
            if fc.name.lower() == "reduce_agg":
                # reduce_agg(value, init, (s,v)->s, (s,s)->s) — the
                # lambdas ride the AggCall unevaluated (reference:
                # ReduceAggregationFunction)
                if len(fc.args) != 4:
                    raise SemanticError(
                        "reduce_agg(input, init, input_fn, combine_fn) "
                        "expected")
                arg_refs = []
                for a in fc.args[:2]:
                    ae = self.analyze(a, scope)
                    if isinstance(ae, ir.Ref):
                        arg_refs.append(ae)
                    else:
                        s2 = self.symbols.new("aggarg")
                        pre_assigns[s2] = ae
                        arg_refs.append(ir.Ref(s2, ae.type))
                st = arg_refs[1].type
                in_lam = _agg_lambda(fc.args[2], (st, arg_refs[0].type),
                                     "reduce_agg")
                if in_lam.body.type != st:
                    in_lam = ir.LambdaExpr(
                        in_lam.params, in_lam.param_types,
                        ir.CastExpr(in_lam.body, st), T.function_type(st))
                comb_lam = _agg_lambda(fc.args[3], (st, st), "reduce_agg")
                if comb_lam.body.type != st:
                    comb_lam = ir.LambdaExpr(
                        comb_lam.params, comb_lam.param_types,
                        ir.CastExpr(comb_lam.body, st),
                        T.function_type(st))
                s = self.symbols.new(fc.name)
                aggs[s] = ir.AggCall(
                    "reduce_agg",
                    (arg_refs[0], arg_refs[1], in_lam, comb_lam), st,
                    fc.distinct, None)
                agg_map[id(fc)] = (s, st)
                continue
            arg_refs = []
            for i, a in enumerate(fc.args):
                ae = self.analyze(a, scope)
                if isinstance(ae, ir.Ref) or (i > 0 and isinstance(ae, ir.Lit)):
                    # parameter-position literals (percentile fraction,
                    # approx_distinct max error, min_by n) stay literal:
                    # the distributed partial/final split needs their
                    # VALUES at plan time (sketch register/summary widths
                    # are static shapes), and a projected aggarg column
                    # would not survive to the FINAL aggregate's input
                    arg_refs.append(ae)
                else:
                    s2 = self.symbols.new("aggarg")
                    pre_assigns[s2] = ae
                    arg_refs.append(ir.Ref(s2, ae.type))
            filt = None
            if fc.filter is not None:
                fe = self.analyze(fc.filter, scope)
                filt = fe
            rt = agg_fns.resolve(fc.name, [a.type for a in arg_refs], fc.distinct)
            s = self.symbols.new(fc.name)
            aggs[s] = ir.AggCall(fc.name.lower(), tuple(arg_refs), rt, fc.distinct, filt)
            agg_map[id(fc)] = (s, rt)
        node = P.Project(node, pre_assigns)
        node = P.Aggregate(node, group_keys, aggs, "SINGLE")
        post_fields = group_fields + [Field_(None, None, s, a.type) for s, a in aggs.items()]
        post_scope = Scope(post_fields, parent=outer)
        return node, post_scope, agg_map, group_map

    # ------------------------------------------------------------------
    # expression analysis -> typed IR
    # ------------------------------------------------------------------
    def analyze(self, e: ast.Expr, scope: Scope, agg_map=None, group_map=None) -> ir.RowExpr:
        a = lambda x: self.analyze(x, scope, agg_map, group_map)
        if agg_map and isinstance(e, ast.FunctionCall) and id(e) in agg_map:
            sym, t = agg_map[id(e)]
            return ir.Ref(sym, t)
        if group_map and _ast_key(e) in (group_map or {}):
            sym = group_map[_ast_key(e)]
            # type from scope
            f = next((f for f in scope.fields if f.symbol == sym), None)
            if f is not None:
                return ir.Ref(sym, f.type)
        if isinstance(e, ast.Literal):
            return _literal_to_ir(e)
        if isinstance(e, ast.Parameter):
            # the serving tier (server/serving.py) binds `type_` from the
            # EXECUTE parameter values before planning; an unbound `?`
            # outside a prepared statement is a semantic error, like the
            # reference's "Incorrect number of parameters"
            if e.type_ is None:
                raise SemanticError(
                    "query parameter ? is only valid in a prepared "
                    "statement (PREPARE ... / EXECUTE ... USING)")
            return ir.Param(e.position, e.type_)
        if isinstance(e, ast.IntervalLiteral):
            # INTERVAL DAY TO SECOND carries MICROSECONDS (reference
            # stores millis, spi/type/IntervalDayTimeType; micros match
            # the TIMESTAMP lane)
            us = {"DAY": 86_400_000_000, "WEEK": 7 * 86_400_000_000,
                  "HOUR": 3_600_000_000, "MINUTE": 60_000_000,
                  "SECOND": 1_000_000}.get(e.unit)
            if us is not None:
                return ir.Lit(e.value * us, T.INTERVAL_DAY_TIME)
            if e.unit in ("MONTH", "YEAR"):
                return ir.Lit(e.value * (12 if e.unit == "YEAR" else 1), T.INTERVAL_YEAR_MONTH)
            raise SemanticError(f"unsupported interval unit {e.unit}")
        if isinstance(e, ast.Identifier):
            try:
                f, is_outer = scope.resolve(e.parts)
                return ir.Ref(f.symbol, f.type)
            except SemanticError:
                # r.field / t.r.field where r is a ROW-typed column
                # (reference: ExpressionAnalyzer DereferenceExpression
                # disambiguation between qualified names and row fields)
                if len(e.parts) >= 2:
                    try:
                        f, _ = scope.resolve(e.parts[:-1])
                    except SemanticError:
                        f = None
                    if f is not None and f.type.name == "ROW":
                        return self._row_field(ir.Ref(f.symbol, f.type),
                                               e.parts[-1])
                raise
        if isinstance(e, ast.BinaryOp):
            opn = {"+": "add", "-": "sub", "*": "mul", "/": "div", "%": "mod",
                   "=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt",
                   ">=": "ge", "AND": "and", "OR": "or", "||": "concat"}[e.op]
            l, r = a(e.left), a(e.right)
            if opn in ("eq", "ne", "lt", "le", "gt", "ge", "add", "sub", "mul",
                       "div", "mod"):
                l, r = self._coerce_pair(l, r)
            return self._call(opn, [l, r])
        if isinstance(e, ast.UnaryOp):
            if e.op == "-":
                return self._call("neg", [a(e.operand)])
            return self._call("not", [a(e.operand)])
        if isinstance(e, ast.Between):
            v, lo, hi = a(e.value), a(e.low), a(e.high)
            v1, lo1 = self._coerce_pair(v, lo)
            v2, hi1 = self._coerce_pair(v, hi)
            rex = self._call("and", [self._call("ge", [v1, lo1]),
                                     self._call("le", [v2, hi1])])
            return self._call("not", [rex]) if e.negated else rex
        if isinstance(e, ast.InList):
            v = a(e.value)
            terms = []
            for item in e.items:
                it = a(item)
                x, y = self._coerce_pair(v, it)
                terms.append(self._call("eq", [x, y]))
            rex = terms[0]
            for t_ in terms[1:]:
                rex = self._call("or", [rex, t_])
            return self._call("not", [rex]) if e.negated else rex
        if isinstance(e, ast.Like):
            args = [a(e.value), a(e.pattern)] + ([a(e.escape)] if e.escape else [])
            rex = self._call("like", args)
            return self._call("not", [rex]) if e.negated else rex
        if isinstance(e, ast.IsNull):
            rex = self._call("is_null", [a(e.value)])
            return self._call("not", [rex]) if e.negated else rex
        if isinstance(e, ast.Case):
            args: List[ir.RowExpr] = []
            whens = e.whens
            if e.operand is not None:
                op_ir = a(e.operand)
                for c, v in whens:
                    cc = a(c)
                    x, y = self._coerce_pair(op_ir, cc)
                    args.append(self._call("eq", [x, y]))
                    args.append(a(v))
            else:
                for c, v in whens:
                    args.append(a(c))
                    args.append(a(v))
            if e.default is not None:
                args.append(a(e.default))
            # coerce all value arms to common type
            vals = [args[i] for i in range(1, len(args), 2)]
            if e.default is not None:
                vals.append(args[-1])
            ct = vals[0].type
            for v in vals[1:]:
                ct2 = T.common_super_type(ct, v.type)
                if ct2 is not None:
                    ct = ct2
            for i in range(1, len(args), 2):
                args[i] = self._coerce(args[i], ct)
            if e.default is not None:
                args[-1] = self._coerce(args[-1], ct)
            return self._call("case", args)
        if isinstance(e, ast.Cast):
            v = a(e.value)
            to = T.parse_type(e.type_name)
            return ir.CastExpr(v, to, e.safe)
        if isinstance(e, ast.Extract):
            return self._call(f"extract_{e.fld.lower()}", [a(e.value)])
        if isinstance(e, ast.FunctionCall):
            if agg_fns.is_aggregate(e.name) and e.window is None:
                raise SemanticError(f"aggregate {e.name} not allowed here")
            if e.null_treatment is not None and e.window is None:
                raise SemanticError(
                    "IGNORE/RESPECT NULLS requires an OVER clause")
            if any(isinstance(x, ast.Lambda) for x in e.args):
                return self._analyze_lambda_call(e, scope, agg_map, group_map)
            if e.name == "$dereference":
                base = a(e.args[0])
                if base.type.name != "ROW":
                    raise SemanticError(
                        f"cannot dereference a {base.type} value")
                return self._row_field(base, e.args[1].value)
            if e.name == "subscript" and e.args and \
                    isinstance(e.args[1], ast.Literal) and \
                    isinstance(e.args[1].value, int):
                base = a(e.args[0])
                if base.type.name == "ROW":  # r[i], 1-based
                    idx = int(e.args[1].value) - 1
                    if not (0 <= idx < len(base.type.params)):
                        raise SemanticError(f"ROW index {idx + 1} out of range")
                    ft = base.type.params[idx][1]
                    return ir.Call("row_field",
                                   (base, ir.Lit(idx, T.INTEGER)), ft)
                args = [base, a(e.args[1])]
                return self._call("subscript", args)
            args = [a(x) for x in e.args]
            return self._call(e.name.lower(), args)
        if isinstance(e, ast.Lambda):
            raise SemanticError("lambda is only valid as a function argument")
        if isinstance(e, ast.ScalarSubquery):
            override = self._scalar_sub_overrides.get(id(e))
            if override is not None:
                return override
            sub_node, sub_scope, _ = self.plan_query(e.query, None)
            if len(sub_scope.fields) != 1:
                raise SemanticError("scalar subquery must return one column")
            pid = next(self.subplan_ids)
            self.subplans[pid] = sub_node
            return ir.ScalarSub(pid, sub_scope.fields[0].type)
        if isinstance(e, (ast.Exists, ast.InSubquery)):
            mark = self._mark_overrides.get(id(e))
            if mark is not None:
                ref = ir.Ref(mark, T.BOOLEAN)
                if getattr(e, "negated", False):
                    return ir.Call("not", (ref,), T.BOOLEAN)
                return ref
            raise SemanticError(
                f"{type(e).__name__} only supported as a top-level WHERE/HAVING conjunct")
        raise SemanticError(f"unsupported expression {type(e).__name__}")

    def _analyze_lambda_call(self, e: ast.FunctionCall, scope, agg_map,
                             group_map) -> ir.RowExpr:
        """Higher-order functions (reference: analyzer lambda handling in
        ExpressionAnalyzer.visitLambdaExpression + function resolution of
        FunctionType arguments).  Lambda parameter types are driven by the
        array arguments, so each function shape is typed explicitly here."""
        name = e.name.lower()
        a = lambda x: self.analyze(x, scope, agg_map, group_map)

        def lam(l, ptypes):
            if not isinstance(l, ast.Lambda):
                raise SemanticError(f"{name} expects a lambda argument")
            if len(l.params) != len(ptypes):
                raise SemanticError(
                    f"{name} lambda must take {len(ptypes)} argument(s)")
            syms = [self.symbols.new(f"lam_{p}") for p in l.params]
            inner = Scope([Field_(None, p, s, t) for p, s, t
                           in zip(l.params, syms, ptypes)], parent=scope)
            body = self.analyze(l.body, inner, agg_map, group_map)
            return ir.LambdaExpr(tuple(syms), tuple(ptypes), body,
                                 T.function_type(body.type))

        def elem_of(v):
            if v.type.name != "ARRAY":
                raise SemanticError(
                    f"{name} expects an array argument, got {v.type}")
            return v.type.params[0]

        if name in ("transform", "filter", "any_match", "all_match",
                    "none_match"):
            if len(e.args) != 2:
                raise SemanticError(f"{name}(array, lambda) expected")
            arr = a(e.args[0])
            le = lam(e.args[1], (elem_of(arr),))
            if name != "transform" and le.body.type not in (T.BOOLEAN,
                                                            T.UNKNOWN):
                raise SemanticError(f"{name} lambda must return BOOLEAN")
            return self._call(name, [arr, le])
        if name in ("map_filter", "transform_values", "transform_keys"):
            if len(e.args) != 2:
                raise SemanticError(f"{name}(map, lambda) expected")
            m = a(e.args[0])
            if m.type.name != "MAP":
                raise SemanticError(f"{name} expects a MAP argument")
            kt, vt = m.type.params
            le = lam(e.args[1], (kt, vt))
            if name == "map_filter" and le.body.type not in (T.BOOLEAN,
                                                             T.UNKNOWN):
                raise SemanticError(f"{name} lambda must return BOOLEAN")
            return self._call(name, [m, le])
        if name in ("all_keys_match", "any_keys_match", "no_keys_match",
                    "any_values_match", "no_values_match"):
            if len(e.args) != 2:
                raise SemanticError(f"{name}(map, lambda) expected")
            m = a(e.args[0])
            if m.type.name != "MAP":
                raise SemanticError(f"{name} expects a MAP argument")
            kt, vt = m.type.params
            le = lam(e.args[1], (kt if "keys" in name else vt,))
            if le.body.type not in (T.BOOLEAN, T.UNKNOWN):
                raise SemanticError(f"{name} lambda must return BOOLEAN")
            return self._call(name, [m, le])
        if name == "array_sort" and len(e.args) == 2:
            arr = a(e.args[0])
            et = elem_of(arr)
            le = lam(e.args[1], (et, et))
            return self._call(name, [arr, le])
        if name == "regexp_replace" and len(e.args) == 3 \
                and isinstance(e.args[2], ast.Lambda):
            s_, p_ = a(e.args[0]), a(e.args[1])
            le = lam(e.args[2], (T.array_of(T.VARCHAR),))
            return self._call(name, [s_, p_, le])
        if name == "map_zip_with":
            if len(e.args) != 3:
                raise SemanticError(
                    "map_zip_with(map, map, lambda) expected")
            m1, m2 = a(e.args[0]), a(e.args[1])
            if m1.type.name != "MAP" or m2.type.name != "MAP":
                raise SemanticError("map_zip_with expects two MAP arguments")
            kt = T.common_super_type(m1.type.params[0], m2.type.params[0])
            if kt is None:
                raise SemanticError("map_zip_with key types are incompatible")
            le = lam(e.args[2], (kt, m1.type.params[1], m2.type.params[1]))
            return self._call(name, [m1, m2, le])
        if name == "zip_with":
            if len(e.args) != 3:
                raise SemanticError("zip_with(array, array, lambda) expected")
            arr1, arr2 = a(e.args[0]), a(e.args[1])
            le = lam(e.args[2], (elem_of(arr1), elem_of(arr2)))
            return self._call(name, [arr1, arr2, le])
        if name == "reduce":
            if len(e.args) not in (3, 4):
                raise SemanticError(
                    "reduce(array, init, merge_lambda[, output_lambda]) expected")
            arr, init = a(e.args[0]), a(e.args[1])
            merge = lam(e.args[2], (init.type, elem_of(arr)))
            if merge.body.type != init.type:
                # widen the state to cover the merge result (e.g. init 0 with
                # DOUBLE elements), re-typing the merge under the wider state
                ct = T.common_super_type(init.type, merge.body.type)
                if ct is not None and ct != init.type:
                    init = self._coerce(init, ct)
                    merge = lam(e.args[2], (ct, elem_of(arr)))
                if merge.body.type != init.type:
                    merge = ir.LambdaExpr(
                        merge.params, merge.param_types,
                        ir.CastExpr(merge.body, init.type),
                        T.function_type(init.type))
            if len(e.args) > 3:
                out = lam(e.args[3], (init.type,))
            else:
                s = self.symbols.new("lam_s")
                out = ir.LambdaExpr((s,), (init.type,),
                                    ir.Ref(s, init.type),
                                    T.function_type(init.type))
            return self._call("reduce", [arr, init, merge, out])
        raise SemanticError(f"function {name} does not take lambda arguments")

    def _row_field(self, base: ir.RowExpr, name: str) -> ir.RowExpr:
        idx = T.row_field_index(base.type, name)
        if idx is None:
            raise SemanticError(f"ROW has no field named '{name}'")
        ft = base.type.params[idx][1]
        return ir.Call("row_field", (base, ir.Lit(idx, T.INTEGER)), ft)

    def _call(self, name: str, args: List[ir.RowExpr]) -> ir.RowExpr:
        fn = scalar_fns.REGISTRY.get(name)
        if fn is None:
            raise SemanticError(f"unknown function {name}")
        rt = fn.resolve([x.type for x in args])
        if rt is None:
            raise SemanticError(
                f"no signature {name}({', '.join(str(x.type) for x in args)})")
        return ir.Call(name, tuple(args), rt)

    def _coerce(self, e: ir.RowExpr, to: T.Type) -> ir.RowExpr:
        if e.type == to:
            return e
        if isinstance(e, ir.Lit) and e.type == T.UNKNOWN:
            return ir.Lit(None, to)
        return ir.CastExpr(e, to)

    def _coerce_pair(self, l: ir.RowExpr, r: ir.RowExpr):
        if l.type == r.type:
            return l, r
        # TIMESTAMP_TZ vs plain temporal: lift the plain side onto the
        # instant lane via the session zone (reference coerces
        # TIMESTAMP -> TIMESTAMP WITH TIME ZONE the same way) — must
        # run BEFORE the keep-native branch below or the UTC lane would
        # compare raw against a wall-clock/days lane
        if {l.type.name, r.type.name} <= {"TIMESTAMP_TZ", "TIMESTAMP",
                                          "DATE"} \
                and "TIMESTAMP_TZ" in (l.type.name, r.type.name) \
                and l.type.name != r.type.name:
            tz = T.timestamp_tz()
            return (l if l.type.name == "TIMESTAMP_TZ"
                    else self._coerce(l, tz),
                    r if r.type.name == "TIMESTAMP_TZ"
                    else self._coerce(r, tz))
        if {l.type.name, r.type.name} == {"TIME", "TIME_TZ"}:
            tz = T.time_tz()
            return (l if l.type.name == "TIME_TZ" else self._coerce(l, tz),
                    r if r.type.name == "TIME_TZ" else self._coerce(r, tz))
        # temporal/interval arithmetic keeps native types
        if l.type.name in ("DATE", "TIMESTAMP", "INTERVAL_DAY_TIME", "INTERVAL_YEAR_MONTH") or \
           r.type.name in ("DATE", "TIMESTAMP", "INTERVAL_DAY_TIME", "INTERVAL_YEAR_MONTH"):
            return l, r
        ct = T.common_super_type(l.type, r.type)
        if ct is None:
            return l, r
        return self._coerce(l, ct), self._coerce(r, ct)


def _fold_constant_expr(rex: ir.RowExpr):
    """Evaluate a ref-free scalar expression at plan time to a typed
    literal (VALUES with ARRAY/MAP constructors; reference: VALUES rows
    are arbitrary constant expressions evaluated by the analyzer).
    Returns None when the expression isn't foldable."""
    if rex.refs():
        return None
    try:
        import jax.numpy as jnp

        from presto_tpu.batch import Batch
        from presto_tpu.exec.compiler import EvalContext, eval_expr
        from presto_tpu.functions.scalar import _pylist_from_colval

        cv = eval_expr(rex, Batch({}, jnp.ones((1,), bool)),
                       EvalContext())
        v = _pylist_from_colval(cv, 1)[0]
        return ir.Lit(v, cv.type if cv.type is not None else rex.type)
    except Exception:
        return None


def _literal_to_ir(e: ast.Literal) -> ir.Lit:
    import numpy as np

    if e.value is None:
        return ir.Lit(None, T.UNKNOWN)
    if e.type_hint == "date":
        days = int((np.datetime64(e.value, "D") - np.datetime64("1970-01-01", "D"))
                   / np.timedelta64(1, "D"))
        return ir.Lit(days, T.DATE)
    if e.type_hint == "timestamp":
        text = str(e.value).strip()
        import re as _re

        m = _re.match(
            r"^(\d{4}-\d{2}-\d{2})"
            r"(?:[ T](\d{2}:\d{2}(?::\d{2}(?:\.\d{1,6})?)?))?"
            r"(?:\s+(\S.*))?$", text)
        if m is None:
            raise SemanticError(f"invalid TIMESTAMP literal {text!r}")
        civil = m.group(1) + ("T" + m.group(2) if m.group(2) else "")
        local_us = int((np.datetime64(civil)
                        - np.datetime64("1970-01-01T00:00:00"))
                       / np.timedelta64(1, "us"))
        zone = m.group(3)
        if zone is None:
            return ir.Lit(local_us, T.TIMESTAMP)
        # `TIMESTAMP '2020-01-01 00:00:00 America/New_York'` -> WITH
        # TIME ZONE, wall clock resolved via the zone's rules (DST
        # ambiguity picks the earlier offset, like java.time)
        from presto_tpu.functions import tzdb

        try:
            r = tzdb.rules(zone)
        except ValueError:
            raise SemanticError(
                f"invalid TIMESTAMP literal {text!r}: unknown zone")
        return ir.Lit(r.local_to_utc_scalar(local_us), T.timestamp_tz(zone))
    if e.type_hint == "time":
        text = str(e.value).strip()
        import re as _re

        m = _re.match(
            r"^(\d{2}):(\d{2})(?::(\d{2})(?:\.(\d{1,6}))?)?"
            r"(?:\s*([+-]\d{2}:?\d{2}))?$", text)
        if m is None:
            raise SemanticError(f"invalid TIME literal {text!r}")
        frac = (m.group(4) or "").ljust(6, "0")
        us = ((int(m.group(1)) * 3600 + int(m.group(2)) * 60
               + int(m.group(3) or 0)) * 1_000_000 + int(frac or 0))
        if m.group(5) is None:
            return ir.Lit(us, T.TIME)
        off = m.group(5).replace(":", "")
        mins = int(off[1:3]) * 60 + int(off[3:5])
        if off[0] == "-":
            mins = -mins
        return ir.Lit(us, T.time_tz(mins))
    if e.type_hint == "decimal":
        # DECIMAL 'x.y' typed literal: precision/scale from the text
        # (reference DecimalParseResult / Decimals.parse)
        from decimal import Decimal, InvalidOperation

        import decimal as _dec

        try:
            d = Decimal(str(e.value).strip())
        except InvalidOperation:
            raise SemanticError(f"invalid DECIMAL literal {e.value!r}")
        if not d.is_finite():  # Decimal('NaN')/'Infinity' parse fine
            raise SemanticError(f"invalid DECIMAL literal {e.value!r}")
        exp = d.as_tuple().exponent
        scale = max(0, -exp)
        with _dec.localcontext() as ctx:
            ctx.prec = 80  # default 28 would round >28-digit literals
            unscaled = int(d.scaleb(scale))
        precision = max(len(str(abs(unscaled))), scale, 1)
        if precision > 38:
            raise SemanticError(
                f"DECIMAL literal {e.value!r} exceeds precision 38")
        return ir.Lit(unscaled, T.decimal(precision, scale))
    if isinstance(e.value, bool):
        return ir.Lit(e.value, T.BOOLEAN)
    if isinstance(e.value, int):
        return ir.Lit(e.value, T.BIGINT if abs(e.value) > 2**31 - 1 else T.INTEGER)
    if isinstance(e.value, float):
        return ir.Lit(e.value, T.DOUBLE)
    if isinstance(e.value, str):
        return ir.Lit(e.value, T.VARCHAR)
    raise SemanticError(f"bad literal {e.value!r}")


def _collect_scalar_subqueries(e: ast.Expr, out: list) -> None:
    if isinstance(e, ast.ScalarSubquery):
        out.append(e)
        return
    for child in e.children():
        if isinstance(child, (ast.Query, ast.QuerySpec)):
            continue
        _collect_scalar_subqueries(child, out)


def _collect_subquery_preds(e: ast.Expr, out: list) -> None:
    """EXISTS/IN-subquery predicate nodes inside a boolean expression
    (without descending into the subqueries themselves)."""
    if isinstance(e, (ast.Exists, ast.InSubquery)):
        out.append(e)
        return
    if isinstance(e, ast.ScalarSubquery):
        return
    for child in e.children():
        if isinstance(child, (ast.Query, ast.QuerySpec)):
            continue
        _collect_subquery_preds(child, out)


def _ast_conjuncts(e: Optional[ast.Expr]) -> List[ast.Expr]:
    if e is None:
        return []
    if isinstance(e, ast.BinaryOp) and e.op == "AND":
        return _ast_conjuncts(e.left) + _ast_conjuncts(e.right)
    return [e]


def _ast_key(e: ast.Expr) -> str:
    """Structural key for GROUP BY expression matching in SELECT/HAVING."""
    return repr(e)
