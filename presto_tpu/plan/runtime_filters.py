"""Dynamic filtering: plan-time wiring of build-side runtime filters
into probe-side scans.

Reference parity: Presto's dynamic filtering (DynamicFilterService +
PredicatePushDown's dynamic-filter assignments): the build side of a
selective equi-join produces a runtime summary of its keys (min/max
domain + membership set) and probe-side scans consume it to skip rows,
chunks, and splits BEFORE the join ever sees them.  This pass only
WIRES producers to consumers; the summaries themselves are built and
probed by the kernel family in exec/kernels.py (rf_build / rf_probe),
applied by the executor, the chunked runner, and the cluster tasks.

Annotations (plain dicts/strings — they ride plan serde and fragment
cutting untouched, so cluster tasks agree on filter ids):

  Join.rf_produce   = [{"fid", "build_sym", "probe_sym"}]
  TableScan.rf_consume = [{"fid", "sym", "column"}]

Soundness: a filter on probe symbol `s` at join J removes only rows
whose key value is missing from J's build key set — for an INNER/SEMI
join those rows produce no J output, so removing them ANYWHERE below J
is result-identical as long as (a) the symbol's VALUE is unchanged from
the scan to J (we walk only through Filter / identity-Project /
probe-preserving Join edges) and (b) every consumer of the scan's
output lies on that walk (we refuse shared DAG subtrees).  Bloom
summaries may keep extra rows (false positives) but never drop a
matching row; results are therefore identical with filtering on or off.

Everything is best-effort and sits behind the `dynamic_filtering`
session property (default on) and the PRESTO_TPU_DYNAMIC_FILTERS env
kill switch.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P

ENV_KILL = "PRESTO_TPU_DYNAMIC_FILTERS"
#: build sides estimated above this row count produce no filter (the
#: summary itself would rival the probe work it saves)
DEFAULT_MAX_BUILD_ROWS = 8_000_000
#: probe sides estimated below this produce no filter either: the
#: membership mask costs one probe-length pass + trace ops per query,
#: which a small probe can never pay back (at SF>=1 every real fact
#: probe clears this; env PRESTO_TPU_DF_MIN_PROBE overrides)
DEFAULT_MIN_PROBE_ROWS = 50_000

#: key types whose stored representation is an integer the kernels can
#: summarize exactly (strings would need cross-dictionary translation,
#: floats a lossless orderable mapping on BOTH host paths — excluded)
_FILTERABLE = ("TINYINT", "SMALLINT", "INTEGER", "BIGINT", "DATE",
               "TIMESTAMP", "BOOLEAN")


def enabled(session) -> bool:
    """The ONE gate every layer consults: env kill switch outranks the
    session property."""
    env = os.environ.get(ENV_KILL, "").lower()
    if env in ("0", "off", "false"):
        return False
    return bool(session.properties.get("dynamic_filtering", True))


def max_build_rows() -> int:
    return int(os.environ.get("PRESTO_TPU_DF_MAX_BUILD",
                              DEFAULT_MAX_BUILD_ROWS))


def min_probe_rows() -> int:
    return int(os.environ.get("PRESTO_TPU_DF_MIN_PROBE",
                              DEFAULT_MIN_PROBE_ROWS))


def annotate(plan: P.QueryPlan, session) -> None:
    """Attach producer/consumer runtime-filter annotations to every
    eligible INNER/SEMI equi-join whose build side is estimated small
    and whose probe key traces cleanly to a scan column.  Filter ids
    are unique within the plan (df0, df1, ...) and survive fragment
    serde, so every cluster task names the same filter the same way."""
    if not enabled(session):
        return
    if getattr(session, "catalog", None) is None:
        return
    counter = [0]
    seen: set = set()

    def visit(node: P.PlanNode) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        for s in node.sources:
            visit(s)
        if not isinstance(node, P.Join) or not node.criteria \
                or node.join_type not in ("INNER", "SEMI"):
            return
        # estimates come from annotate_static_hints (which already ran
        # a memoized stats derivation over this exact plan) — this pass
        # adds NO stats work of its own; no hints, no filter
        rs_est = getattr(node, "right_est_hint", None)
        ls_est = getattr(node, "left_est_hint", None)
        if rs_est is None or ls_est is None:
            return
        # small/selective build gate: the probe must clearly outweigh
        # the build (4x) AND be worth filtering at all — a near-equal
        # build costs a probe-sized membership pass to prune little,
        # and a small probe can't repay the pass no matter what
        if rs_est > max_build_rows() or ls_est < 4 * rs_est \
                or ls_est < min_probe_rows():
            return
        ltypes = node.left.output_types()
        rtypes = node.right.output_types()
        for lk, rk in node.criteria:
            lt, rt = ltypes.get(lk), rtypes.get(rk)
            if lt is None or rt is None or lt.name not in _FILTERABLE \
                    or rt.name not in _FILTERABLE:
                continue
            hit = resolve_probe_scan(node.left, lk)
            if hit is None:
                continue
            scan, scan_sym = hit
            fid = f"df{counter[0]}"
            counter[0] += 1
            prod = list(getattr(node, "rf_produce", None) or [])
            prod.append({"fid": fid, "build_sym": rk, "probe_sym": lk})
            node.rf_produce = prod
            cons = list(getattr(scan, "rf_consume", None) or [])
            cons.append({"fid": fid, "sym": scan_sym,
                         "column": scan.assignments.get(scan_sym)})
            scan.rf_consume = cons
            break  # one filter per join: the leading resolvable key

    visit(plan.root)
    for sub in plan.subplans.values():
        visit(sub)


def resolve_probe_scan(node: P.PlanNode, sym: str
                       ) -> Optional[Tuple[P.TableScan, str]]:
    """Walk the probe subtree down to the TableScan producing `sym`,
    through row-VALUE-preserving edges only: Filter (masks), identity
    Project (renames), and join edges that keep probe rows' key values
    intact.  Returns (scan, scan_symbol) or None when the origin is not
    a clean scan column (expression, aggregate, union, exchange buffer,
    or a shared DAG subtree another consumer also reads)."""
    while True:
        if getattr(node, "shared_subtree", False):
            # plan DAG (transitive semi-join inference): pruning here
            # would starve the OTHER consumer of the shared result
            return None
        if isinstance(node, P.TableScan):
            if node.table.startswith("__exch_") \
                    or sym not in node.assignments:
                return None
            return node, sym
        if isinstance(node, P.Filter):
            node = node.source
        elif isinstance(node, P.Project):
            e = node.assignments.get(sym)
            if not isinstance(e, ir.Ref):
                return None
            sym = e.name
            node = node.source
        elif isinstance(node, P.Join):
            # removing a row below an intermediate join removes only
            # output rows carrying that row's key value — which the
            # producer join up top drops anyway (INNER/SEMI semantics)
            if node.join_type in ("INNER", "LEFT", "SEMI", "ANTI",
                                  "MARK") \
                    and sym in {s for s, _ in node.left.outputs()}:
                node = node.left
            elif node.join_type == "INNER" \
                    and sym in {s for s, _ in node.right.outputs()}:
                node = node.right
            else:
                return None
        else:
            return None  # Aggregate/Union/Window/...: values re-derived
