"""Distribution planning: insert Exchange nodes + split aggregations.

Reference parity: sql/planner/optimizations/AddExchanges.java (chooses
SINGLE/FIXED_HASH/FIXED_BROADCAST distributions and inserts remote
exchanges), DetermineJoinDistributionType (partitioned-vs-broadcast by
build-side size), and the partial->final aggregation split
(AddExchanges.java:239-265).  The output plan still executes single-pass —
a DistExecutor traces it inside ONE shard_map where each Exchange becomes
a collective (parallel/exchange.py).

Distribution lattice per node:
  any        — rows sharded arbitrarily over the mesh axis (SOURCE dist)
  hashed(K)  — sharded; all rows with equal values of K on one shard
  replicated — every shard holds every row (post-gather / broadcast)
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Dict, List, Optional, Tuple

from presto_tpu.plan import agg_strategy as AS
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P
from presto_tpu import types as T


@dataclasses.dataclass(frozen=True)
class Dist:
    kind: str  # 'any' | 'hashed' | 'replicated'
    keys: Tuple[str, ...] = ()


ANY = Dist("any")
REPLICATED = Dist("replicated")


class Undistributable(Exception):
    """Plan shape the distributed planner can't place; caller runs the
    single-device path instead."""


def distribute(plan: P.QueryPlan, session, ndev: int,
               bucketed=None) -> P.QueryPlan:
    """Rewrite an optimized single-device plan into a distributed one.
    Subplans (uncorrelated scalars) stay single-device — they are evaluated
    host-side before the superstep, like the reference's pre-requisite
    stages feeding a gather exchange.

    `bucketed` ({table: bucket column}) switches the planner into
    chunked/grouped-execution mode (reference: connector bucketing +
    grouped execution, BucketNodeMap + Lifespan): scans of bucketed
    tables are hashed on the bucket column (all rows of one bucket land
    in one chunk — range-bucketing colocates equi-joins the same way
    hash-bucketing does), every other scan is replicated (resident whole
    in HBM, visible to every chunk)."""
    d = Distributer(session, ndev, bucketed=bucketed)
    # subplans run in the SAME trace (not host-side) so float reduction
    # order — and therefore sums compared against the main plan, e.g.
    # TPC-H Q15's total_revenue = (select max(...)) — is bit-identical
    subplans = {}
    for pid, sub in sorted(plan.subplans.items()):
        snode, sdist = d.visit(sub)
        if sdist.kind != "replicated":
            snode = P.Exchange(snode, "gather")
        subplans[pid] = snode
    root, dist = d.visit(plan.root.source)
    if dist.kind != "replicated":
        root = P.Exchange(root, "gather")
    # post-exchange iterative rules (the reference runs e.g.
    # PushPartialAggregationThroughExchange AFTER AddExchanges,
    # PlanOptimizers.java:230-424)
    from presto_tpu.plan.iterative import (
        IterativeOptimizer, PushPartialAggregationThroughExchange)

    root = IterativeOptimizer(
        [PushPartialAggregationThroughExchange(session)]).optimize(root)
    out = P.Output(root, plan.root.names, plan.root.symbols)
    dplan = P.QueryPlan(out, subplans)
    # fragment-fusion economics (plan/fusion_cost.py): stamp every
    # Exchange node with stats-derived est_rows/est_bytes hints so the
    # coordinator's per-edge fuse-vs-cut pricing (and anything reading
    # the serde'd fragments) knows what each edge moves
    from presto_tpu.plan import fusion_cost as FC

    FC.annotate_exchange_bytes(dplan, session)
    return dplan


# aggregate fns that have a (partial fns -> final merge fn) decomposition
_MERGEABLE = {"count", "count_if", "sum", "min", "max", "avg",
              "bool_and", "every", "bool_or", "arbitrary", "any_value",
              "stddev", "stddev_samp", "stddev_pop",
              "variance", "var_samp", "var_pop",
              "min_by", "max_by", "checksum"}


def _sketch_mergeable(a: ir.AggCall) -> bool:
    """True when this sketch-family aggregate decomposes into a
    fixed-width device state (plan/agg_strategy.SKETCH_FNS).  The
    array-of-percentiles / weighted approx_percentile overloads have no
    fixed-shape state and keep the single-phase repartition route."""
    if a.distinct:
        return False
    if a.fn == "approx_percentile":
        return len(a.args) == 2 and a.type.name != "ARRAY"
    return a.fn in ("approx_distinct", "approx_count", "approx_sum")


class Distributer:
    def __init__(self, session, ndev: int = 1, bucketed=None):
        self.session = session
        self.ndev = ndev
        self.bucketed = bucketed or {}  # table -> bucket column (chunk mode)
        self.broadcast_rows = int(session.properties.get(
            "broadcast_join_threshold_rows", 1_000_000))
        if self.bucketed:
            # chunk mode: a "broadcast" build side is ONE resident
            # on-chip buffer shared by the sequential chunk loop, not a
            # per-shard copy — the economic threshold is HBM headroom,
            # not replication cost (q64's cs_ui at SF100 is ~1.8M rows
            # and must stay resident or the repartition path buffers
            # the 10x bigger store join output instead)
            self.broadcast_rows = int(session.properties.get(
                "chunk_broadcast_rows", 8_000_000))
        self.dist_sort_threshold = int(session.properties.get(
            "distributed_sort_threshold_rows", 100_000))
        self.partial_agg_groups = int(session.properties.get(
            "partial_aggregation_max_groups", 8192))
        self._ctr = 0
        # symbol equivalence classes from equi-join criteria and identity
        # projections (reference: AddExchanges' partitioning properties
        # carry symbol equivalences, so hashed(l_orderkey) satisfies a
        # requirement for hashed(o_orderkey) after l_orderkey=o_orderkey)
        self._equiv: dict = {}

    def fresh(self, base: str) -> str:
        self._ctr += 1
        return f"{base}$d{self._ctr}"

    def _find(self, s: str) -> str:
        root = s
        while self._equiv.get(root, root) != root:
            root = self._equiv[root]
        while self._equiv.get(s, s) != root:  # path compression
            self._equiv[s], s = root, self._equiv[s]
        return root

    def _union(self, a: str, b: str) -> None:
        ra, rb = self._find(a), self._find(b)
        if ra != rb:
            self._equiv[ra] = rb

    def _same_keys(self, keys_a, keys_b) -> bool:
        return [self._find(k) for k in keys_a] == \
            [self._find(k) for k in keys_b]

    def _keys_subset(self, keys, of) -> bool:
        reps = {self._find(k) for k in of}
        return all(self._find(k) in reps for k in keys)

    def _colocated(self, ldist, rdist, criteria) -> bool:
        """Both sides hashed on keys that some pairing of the equi-join
        criteria makes equal — regardless of criteria ORDER (hashed(K)
        colocates any join whose criteria CONTAIN K=K': q64 writes
        `ss_item_sk = sr_item_sk AND ss_ticket_number = sr_ticket_number`
        and both sides are bucketed on the ticket, the second
        criterion).  Reference: AddExchanges' partitioning-properties
        satisfaction is set-based the same way."""
        if not (ldist.kind == "hashed" and rdist.kind == "hashed"
                and len(ldist.keys) == len(rdist.keys)):
            return False
        pair = {}
        for lk, rk in criteria:
            pair.setdefault(self._find(lk), self._find(rk))
        want = [pair.get(self._find(lk)) for lk in ldist.keys]
        return (None not in want
                and want == [self._find(rk) for rk in rdist.keys])

    # ------------------------------------------------------------------
    def visit(self, node: P.PlanNode) -> Tuple[P.PlanNode, Dist]:
        m = getattr(self, f"_visit_{type(node).__name__.lower()}", None)
        if m is None:
            raise Undistributable(type(node).__name__)
        return m(node)

    def _visit_tablescan(self, node: P.TableScan):
        if self.bucketed:
            bcol = self.bucketed.get(node.table)
            if bcol is None:
                return node, REPLICATED  # resident table: whole per chunk
            syms = [s for s, c in node.assignments.items() if c == bcol]
            if syms:
                return node, Dist("hashed", (syms[0],))
            return node, ANY
        return node, ANY

    def _visit_values(self, node: P.Values):
        return node, REPLICATED

    def _visit_filter(self, node: P.Filter):
        src, dist = self.visit(node.source)
        node.source = src
        return node, dist

    def _visit_project(self, node: P.Project):
        src, dist = self.visit(node.source)
        node.source = src
        if dist.kind == "hashed":
            # hashed keys survive only through identity projections
            rename = {}
            for sym, e in node.assignments.items():
                if isinstance(e, ir.Ref):
                    rename.setdefault(e.name, sym)
            if all(k in rename for k in dist.keys):
                for old, new in rename.items():
                    self._union(old, new)  # identity: same values
                dist = Dist("hashed", tuple(rename[k] for k in dist.keys))
            else:
                dist = ANY
        return node, dist

    # ---- aggregation --------------------------------------------------
    def _visit_aggregate(self, node: P.Aggregate):
        src, dist = self.visit(node.source)
        node.source = src
        if dist.kind == "replicated":
            return node, REPLICATED
        if dist.kind == "hashed" and node.group_keys and \
                self._keys_subset(dist.keys, node.group_keys):
            # co-located: every group entirely on one shard
            return node, Dist("hashed", dist.keys)
        has_distinct = any(a.distinct for a in node.aggs.values())
        mergeable = all((a.fn in _MERGEABLE or _sketch_mergeable(a))
                        and not a.distinct
                        for a in node.aggs.values())
        # sketch aggregates (HLL registers / KLL summaries / seeded
        # samples): the partial state is fixed-width per group no matter
        # the input cardinality, so a hash repartition is NEVER cut for
        # them — the partial/final split's gather edge merges states
        # with one elementwise fold (lax.pmax on the fused mesh lane)
        has_sketch = any(_sketch_mergeable(a) for a in node.aggs.values())
        cap = getattr(node, "capacity_hint", None)
        small = cap is not None and cap <= self.partial_agg_groups
        # aggregation strategy (plan/agg_strategy.py): a final_only
        # aggregate routes rows to their group's shard and aggregates
        # ONCE — the global-table route, no partial stage planned at
        # all.  Chunked distribution (self.bucketed) is exempt: a
        # repartition exchange between chunk fragments buffers at input
        # scale, where the per-chunk partial state is tiny — there the
        # partial stays planned and the RUNTIME bypass adapts instead.
        strategy = getattr(node, "agg_strategy", None) \
            if AS.enabled(self.session) else None
        # final_only (repartition + single pass, the global-table route)
        # is consumed only where it can actually win:
        # - skew floor: with fewer distinct keys than ~4x the shard
        #   count, the hash repartition lands everything on a few
        #   shards (q1's four group combos over 8 devices overflow the
        #   in-trace all_to_all capacity);
        # - exchange-volume guard: the repartition moves EVERY input
        #   row, while two-phase exchanges ~ndev x groups partial rows —
        #   a strongly-reducing input (a 5-group GROUP BY over 15k rows)
        #   stays on the tiny-partial split; final_only wins exactly
        #   when the partial would NOT have reduced the exchange much.
        est = getattr(node, "input_est_hint", None)
        final_only = (node.group_keys and mergeable and not has_distinct
                      and not self.bucketed and strategy == AS.FINAL_ONLY
                      and cap is not None and cap >= 4 * self.ndev
                      and est is not None
                      and est <= cap * self.ndev * 4)
        # chunked (virtual-time-axis) distribution: a repartition
        # exchange between chunk fragments buffers at input scale
        # either way, so a high-estimated-NDV GROUP BY keeps the
        # partial/final split WITH THE RUNTIME BYPASS ARMED — the
        # partial probes its own reduction ratio and flips to
        # pass-through when it isn't paying (the adaptive plan is never
        # much worse than single-phase and wins whenever the estimate
        # was wrong the other way)
        adaptive_chunked = (self.bucketed and node.group_keys and mergeable
                            and not has_distinct
                            and strategy in (AS.TWO_PHASE, AS.ONE_PASS))
        if node.group_keys and (has_distinct or not mergeable
                                or (not small and not adaptive_chunked
                                    and not has_sketch)
                                or final_only):
            # repartition rows so each group lands wholly on one shard,
            # then aggregate locally in a single phase (handles DISTINCT
            # and non-decomposable aggregates for free; also the
            # final_only strategy's single global grouping pass)
            node.source = P.Exchange(src, "repartition", list(node.group_keys))
            return node, Dist("hashed", tuple(node.group_keys))
        if not mergeable:
            raise Undistributable(
                f"global aggregate with non-mergeable fns "
                f"{[a.fn for a in node.aggs.values()]}")
        return self._split_partial_final(node, src)

    def decompose_aggs(self, aggs):
        """(partial_aggs, final_aggs) for a mergeable aggregate map, or
        (None, None) when some aggregate has no partial/final
        decomposition (shared by _split_partial_final and the
        PushPartialAggregationThroughExchange rule)."""
        try:
            return self._decompose_aggs(aggs)
        except Undistributable:
            return None, None

    def _split_partial_final(self, node: P.Aggregate, src: P.PlanNode):
        """partial agg per shard -> gather -> final merge (the reference's
        PARTIAL/FINAL AggregationNode pair around a repartition,
        AddExchanges.java:239; here the combine is a gather because the
        partial output is tiny — <= partial_aggregation_max_groups rows)."""
        partial_aggs, final_aggs = self._decompose_aggs(node.aggs)
        partial = P.Aggregate(src, list(node.group_keys), partial_aggs, "PARTIAL")
        partial.capacity_hint = getattr(node, "capacity_hint", None)
        partial.key_stats = getattr(node, "key_stats", {})
        if AS.enabled(self.session):
            # the split plans two phases: the partial carries the
            # strategy (one_pass keeps the per-shard run-boundary
            # grouping; anything else is two_phase with the runtime
            # bypass armed) so executors count what actually ran and
            # the flip monitor knows its node.  Ordering hints move to
            # the partial with it — the partial's source IS the node's
            # source, so the claims (still guard-verified) transfer.
            s = getattr(node, "agg_strategy", None)
            partial.agg_strategy = s if s in (AS.ONE_PASS, AS.SKETCH) \
                else AS.TWO_PHASE
            for h in ("ordering_hint", "ordering_pack_order",
                      "ordering_hint_safe", "input_est_hint"):
                if hasattr(node, h):
                    setattr(partial, h, getattr(node, h))
        gathered = P.Exchange(partial, "gather")
        if any(_sketch_mergeable(a) for a in node.aggs.values()):
            # sketch-state edge: fixed-width mergeable rows.  Stamped so
            # fusion_cost prices it on the near-zero sketch lane and
            # cluster fragment cutting knows no repartition was needed.
            gathered.sketch_only = True
            if not node.group_keys and all(
                    a.fn == "$hll_partial" for a in partial_aggs.values()):
                # global HLL merge IS elementwise max over aligned
                # register rows: the fused mesh lane lowers this gather
                # to ONE lax.pmax collective (grouped states shard their
                # group slots data-dependently, so anything grouped —
                # and KLL's sort-merge — stays on all_gather + re-group)
                gathered.sketch_merge = "pmax"
        final = P.Aggregate(gathered, list(node.group_keys), final_aggs, "FINAL")
        final.capacity_hint = getattr(node, "capacity_hint", None)
        final.key_stats = getattr(node, "key_stats", {})
        return final, REPLICATED

    def _decompose_aggs(self, aggs):
        partial_aggs = {}
        final_aggs = {}
        for sym, a in aggs.items():
            fn = a.fn
            if fn in ("count", "count_if"):
                p = self.fresh(sym)
                partial_aggs[p] = a
                final_aggs[sym] = ir.AggCall("merge_count", (ir.Ref(p, T.BIGINT),),
                                             a.type)
            elif fn == "sum":
                p = self.fresh(sym)
                partial_aggs[p] = a
                final_aggs[sym] = ir.AggCall("sum", (ir.Ref(p, a.type),), a.type)
            elif fn in ("min", "max", "bool_and", "every", "bool_or",
                        "arbitrary", "any_value"):
                p = self.fresh(sym)
                partial_aggs[p] = a
                final_aggs[sym] = ir.AggCall(a.fn, (ir.Ref(p, a.type),), a.type)
            elif fn == "avg":
                ps = self.fresh(sym + "_s")
                pc = self.fresh(sym + "_c")
                partial_aggs[ps] = ir.AggCall("partial_sum_double", a.args,
                                              T.DOUBLE, False, a.filter)
                partial_aggs[pc] = ir.AggCall("count", a.args, T.BIGINT,
                                              False, a.filter)
                final_aggs[sym] = ir.AggCall(
                    "merge_avg", (ir.Ref(ps, T.DOUBLE), ir.Ref(pc, T.BIGINT)),
                    T.DOUBLE)
            elif fn in ("min_by", "max_by"):
                # partial keeps (winning value, winning key); final
                # re-runs the same argmin/argmax over the partials
                pv = self.fresh(sym + "_v")
                pk = self.fresh(sym + "_k")
                key_t = a.args[1].type if hasattr(a.args[1], "type") else a.type
                partial_aggs[pv] = a
                partial_aggs[pk] = ir.AggCall(
                    "min" if fn == "min_by" else "max", (a.args[1],),
                    key_t, False, a.filter)
                final_aggs[sym] = ir.AggCall(
                    fn, (ir.Ref(pv, a.type), ir.Ref(pk, key_t)), a.type)
            elif fn == "checksum":
                # wrapping sum is associative/commutative: sum the partials
                p = self.fresh(sym)
                partial_aggs[p] = a
                final_aggs[sym] = ir.AggCall("sum", (ir.Ref(p, T.BIGINT),),
                                             T.BIGINT)
            elif fn == "approx_distinct":
                # partial = (n_groups, m) HLL register rows; final folds
                # rows with elementwise max and estimates (exec/kernels
                # hll_partial / hll_merge_estimate) — estimates match
                # the single-pass kernel bit-for-bit at equal m
                from presto_tpu.exec.kernels import hll_m_for_error

                m = 1024
                if len(a.args) >= 2 and isinstance(a.args[1], ir.Lit) \
                        and a.args[1].value is not None:
                    m = hll_m_for_error(float(a.args[1].value))
                st = T.hll_state(m)
                p = self.fresh(sym)
                partial_aggs[p] = ir.AggCall("$hll_partial", (a.args[0],),
                                             st, False, a.filter)
                final_aggs[sym] = ir.AggCall("$hll_est",
                                             (ir.Ref(p, st),), T.BIGINT)
            elif fn == "approx_percentile" and _sketch_mergeable(a):
                # partial = (n_groups, 2K) quantile summary rows; the
                # percentile fraction literal rides the FINAL call.  K
                # sizes rank error ~1/K per merge level (session knob
                # approx_percentile_accuracy, default 0.01 -> K=200)
                acc = float(self.session.properties.get(
                    "approx_percentile_accuracy", 0.01))
                kk = max(16, int(math.ceil(2.0 / max(acc, 1e-6))))
                st = T.kll_state(2 * kk)
                p = self.fresh(sym)
                partial_aggs[p] = ir.AggCall("$kll_partial", (a.args[0],),
                                             st, False, a.filter)
                final_aggs[sym] = ir.AggCall(
                    "$kll_pct", (ir.Ref(p, st), a.args[1]), a.type)
            elif fn in ("approx_count", "approx_sum"):
                # the seeded sample is value-hash-determined, so the fn
                # is its own partial and the final just sums partials
                p = self.fresh(sym)
                partial_aggs[p] = a
                final_aggs[sym] = ir.AggCall(
                    "merge_count" if fn == "approx_count" else "sum",
                    (ir.Ref(p, a.type),), a.type)
            elif fn in ("approx_percentile", "geometric_mean", "corr",
                        "covar_samp", "covar_pop"):
                # array/weighted percentile forms and moment aggregates:
                # no fixed-shape partial state -> single-device
                # execution stays correct
                raise Undistributable(f"aggregate {fn}")
            elif fn in ("stddev", "stddev_samp", "stddev_pop", "variance",
                        "var_samp", "var_pop"):
                s1 = self.fresh(sym + "_s1")
                s2 = self.fresh(sym + "_s2")
                pc = self.fresh(sym + "_c")
                partial_aggs[s1] = ir.AggCall("partial_sum_double", a.args,
                                              T.DOUBLE, False, a.filter)
                partial_aggs[s2] = ir.AggCall("partial_sum_sq_double", a.args,
                                              T.DOUBLE, False, a.filter)
                partial_aggs[pc] = ir.AggCall("count", a.args, T.BIGINT,
                                              False, a.filter)
                final_aggs[sym] = ir.AggCall(
                    f"merge_{fn}",
                    (ir.Ref(s1, T.DOUBLE), ir.Ref(s2, T.DOUBLE),
                     ir.Ref(pc, T.BIGINT)), T.DOUBLE)
            else:
                raise Undistributable(f"aggregate {fn}")
        return partial_aggs, final_aggs

    # ---- joins --------------------------------------------------------
    def _visit_join(self, node: P.Join):
        left, ldist = self.visit(node.left)
        right, rdist = self.visit(node.right)
        node.left, node.right = left, right
        jt = node.join_type

        if ldist.kind == "replicated" and rdist.kind == "replicated":
            return node, REPLICATED

        if jt in ("RIGHT", "FULL") and node.criteria:
            # partitioned outer joins (reference: LookupOuterOperator +
            # AddExchanges): hash-repartition BOTH sides on the join keys
            # so matched pairs AND unmatched rows of either side are
            # decidable shard-locally.  Broadcast is never legal here —
            # a replicated side would emit its unmatched rows once per
            # shard.
            lkeys0 = [lk for lk, _ in node.criteria]
            rkeys0 = [rk for _, rk in node.criteria]
            if not self._colocated(ldist, rdist, node.criteria):
                # a replicated side must be scattered before the
                # repartition or every shard contributes a duplicate
                # copy of each row to the exchange (same rule as the
                # INNER repartition path below; exposed by q51's FULL
                # join over a gathered CTE)
                lsrc = P.Exchange(left, "scatter") \
                    if ldist.kind == "replicated" else left
                rsrc = P.Exchange(right, "scatter") \
                    if rdist.kind == "replicated" else right
                node.left = P.Exchange(lsrc, "repartition", lkeys0)
                node.right = P.Exchange(rsrc, "repartition", rkeys0)
            # output is NOT hashed on the keys: NULL-extended rows land
            # on shards by the OTHER side's hash, so the NULL key group
            # is scattered — downstream consumers must re-exchange
            return node, ANY
        if jt in ("RIGHT", "FULL"):
            node.left = self._to_replicated(left, ldist)
            node.right = self._to_replicated(right, rdist)
            return node, REPLICATED

        if jt == "CROSS":
            if rdist.kind != "replicated":
                node.right = P.Exchange(right, "broadcast")
            if ldist.kind == "replicated":
                return node, REPLICATED
            return node, ANY

        lkeys = [lk for lk, _ in node.criteria]
        rkeys = [rk for _, rk in node.criteria]

        if jt == "INNER":
            # equi-criteria make the key symbols equivalent in the output
            # (INNER only: outer joins NULL-extend one side)
            for lk, rk in node.criteria:
                self._union(lk, rk)

        # probe replicated + build sharded: each probe row would match on
        # every shard; make the build side whole instead (small by stats)
        if ldist.kind == "replicated":
            node.right = self._to_replicated(right, rdist)
            return node, REPLICATED

        build_rows = self._estimated_rows(node.right)
        broadcast_ok = (rdist.kind == "replicated"
                        or (build_rows is not None
                            and build_rows <= self.broadcast_rows))
        if self._colocated(ldist, rdist, node.criteria):
            out_dist = Dist("hashed", ldist.keys)
            return node, out_dist
        if broadcast_ok and node.distribution != "PARTITIONED":
            if rdist.kind != "replicated":
                node.right = P.Exchange(right, "broadcast")
            # probe side keeps its distribution
            return node, ldist
        # P1: repartition both sides on the join keys
        node.left = P.Exchange(left, "repartition", lkeys)
        node.right = P.Exchange(right, "repartition", rkeys)
        if rdist.kind == "replicated":
            # replicated build must be scattered first or every shard
            # contributes a duplicate copy of each row to the exchange
            node.right = P.Exchange(P.Exchange(right, "scatter"),
                                    "repartition", rkeys)
        return node, Dist("hashed", tuple(lkeys))

    def _to_replicated(self, node: P.PlanNode, dist: Dist) -> P.PlanNode:
        return node if dist.kind == "replicated" else P.Exchange(node, "gather")

    def _estimated_rows(self, node: P.PlanNode) -> Optional[int]:
        try:
            from presto_tpu.plan import stats as S

            return S.derive(node, self.session.catalog).rows
        except Exception:
            return None

    # ---- order/limit/misc --------------------------------------------
    def _visit_sort(self, node: P.Sort):
        src, dist = self.visit(node.source)
        rows = self._estimated_rows(src)
        small = rows is not None and rows <= self.dist_sort_threshold
        if dist.kind != "replicated" and not small:
            # P11 distributed sample-sort: range all_to_all on the primary
            # key, local full sort per shard, ordered gather — shard i's
            # rows all precede shard i+1's, so the concatenation IS the
            # merge (reference: partial sort + MergeOperator,
            # admin/dist-sort.rst)
            ex = P.Exchange(src, "range")
            ex.sort_keys = list(node.keys)
            local = P.Sort(ex, list(node.keys))
            return P.Exchange(local, "gather"), REPLICATED
        node.source = self._to_replicated(src, dist)
        return node, REPLICATED

    def _visit_topn(self, node: P.TopN):
        src, dist = self.visit(node.source)
        if dist.kind == "replicated":
            node.source = src
            return node, REPLICATED
        # local top-N per shard, then gather + final top-N: the
        # distributed-sort pattern (partial sort + MergeOperator,
        # SURVEY.md P11) with N small enough to replicate
        local = P.TopN(src, list(node.keys), node.count)
        node.source = P.Exchange(local, "gather")
        return node, REPLICATED

    def _visit_limit(self, node: P.Limit):
        src, dist = self.visit(node.source)
        if dist.kind == "replicated":
            node.source = src
            return node, REPLICATED
        local = P.Limit(src, node.count)
        node.source = P.Exchange(local, "gather")
        return node, REPLICATED

    def _visit_union(self, node: P.Union):
        new_sources = []
        for s in node.sources_:
            src, dist = self.visit(s)
            if dist.kind == "replicated":
                src = P.Exchange(src, "scatter")
            new_sources.append(src)
        node.sources_ = new_sources
        if node.distinct:
            raise Undistributable("UNION DISTINCT")  # planner lowers it to agg
        return node, ANY

    def _visit_unnest(self, node):
        # row-local expansion: each row explodes on its own shard, so the
        # source distribution passes through (hashed keys survive since
        # source columns are preserved in the output)
        src, dist = self.visit(node.source)
        node.source = src
        return node, dist

    def _visit_window(self, node: P.Window):
        src, dist = self.visit(node.source)
        if node.partition_by:
            # hash-partitioned window execution: all rows of a window
            # partition land on one shard, local sorted-scan windows per
            # shard (reference: WindowOperator + AddExchanges inserting a
            # partitioned exchange on the partition keys)
            if dist.kind == "replicated" or (
                    dist.kind == "hashed"
                    and self._keys_subset(dist.keys, node.partition_by)):
                node.source = src
                out = dist if dist.kind == "replicated" \
                    else Dist("hashed", dist.keys)
                return node, out
            node.source = P.Exchange(src, "repartition",
                                     list(node.partition_by))
            return node, Dist("hashed", tuple(node.partition_by))
        node.source = self._to_replicated(src, dist)
        return node, REPLICATED

    def _visit_exchange(self, node: P.Exchange):
        src, _ = self.visit(node.source)
        node.source = src
        return node, REPLICATED if node.kind in ("gather", "broadcast") else ANY


# ---------------------------------------------------------------------------
# fragment fusion (ROADMAP open item 1): splice mesh-local exchange edges
# back into ONE traced program
# ---------------------------------------------------------------------------
#
# The cluster path (parallel/cluster.py) cuts the distributed plan at its
# Exchange nodes and moves pages over HTTP between fragments.  When the
# producer and consumer of an exchange edge are placed on chips of the
# SAME ICI mesh, that host round-trip (pack -> POST -> poll -> GET ->
# unpack, per page) is pure overhead: the identical exchange lowers to a
# collective (`lax.all_to_all` for hash repartition, `all_gather` for
# broadcast/gather — parallel/exchange.py) inside the shard_map program
# the mesh executes anyway.  `fuse_fragments` contracts those edges: the
# consumer absorbs the producer's plan with the original Exchange node
# restored INLINE, so a scan -> repartition -> join -> aggregate pipeline
# compiles as one XLA program with zero host hops between stages.  The
# per-fragment HTTP path remains the fallback for cross-host edges,
# capacity-overflow guard trips, and fault recovery (any fused-attempt
# failure retries with fusion disabled — parallel/cluster.py).

#: exchange kinds the mesh collective kernels implement in-trace
#: (parallel/exchange.py + DistExecutor._exec_exchange) — all of them;
#: `fragment_fusion_kinds` can restrict for A/B runs
FUSIBLE_KINDS = frozenset(
    {"repartition", "broadcast", "gather", "scatter", "range"})


def fusion_mode(session) -> str:
    """Fragment-fusion policy: session property `fragment_fusion` —
    `auto` (default: the plan/fusion_cost.py per-edge cost model +
    decision memo pick fuse-vs-cut per exchange edge), `force` (round
    12's fuse-every-eligible-edge policy, byte-identical), `off` (the
    per-fragment HTTP path).  Legacy booleans map True -> force /
    False -> off so pre-round-18 callers keep their exact behavior.
    The PRESTO_TPU_FRAGMENT_FUSION env kill switch (off|0|false)
    disables process-wide."""
    env = os.environ.get("PRESTO_TPU_FRAGMENT_FUSION", "").lower()
    if env in ("off", "0", "false"):
        return "off"
    v = session.properties.get("fragment_fusion", "auto")
    if v is True:
        return "force"
    if v is False or v is None:
        return "off"
    v = str(v).strip().lower()
    if v in ("force", "on", "true", "1"):
        return "force"
    if v in ("off", "false", "0", ""):
        return "off"
    return "auto"


def fusion_enabled(session) -> bool:
    """Fragment-fusion master switch (any mode but `off`)."""
    return fusion_mode(session) != "off"


def fusion_kinds(session) -> frozenset:
    """Edge kinds eligible for fusion (session property
    `fragment_fusion_kinds`, csv)."""
    raw = session.properties.get("fragment_fusion_kinds", "")
    if not raw:
        return FUSIBLE_KINDS
    return frozenset(k.strip() for k in str(raw).split(",")
                     if k.strip()) & FUSIBLE_KINDS


def _rewrite_exch_scans(root, on_scan):
    """Generic rebuild of a fragment plan tree: `on_scan(eid, node)`
    returns a replacement for each `__exch_{eid}` scan (or the node
    itself).  Mirrors cut_fragments' rewrite, including the carry of
    optimizer instance attrs that are not dataclass fields."""

    def rewrite(n):
        if isinstance(n, P.TableScan):
            if n.table.startswith("__exch_"):
                return on_scan(int(n.table[len("__exch_"):]), n)
            return n
        changed = {}
        for f in dataclasses.fields(n):
            v = getattr(n, f.name)
            if isinstance(v, P.PlanNode):
                nv = rewrite(v)
                if nv is not v:
                    changed[f.name] = nv
            elif isinstance(v, list) and v \
                    and all(isinstance(x, P.PlanNode) for x in v):
                nv = [rewrite(x) for x in v]
                if any(a is not b for a, b in zip(nv, v)):
                    changed[f.name] = nv
        if not changed:
            return n
        nn = dataclasses.replace(n, **changed)
        fields = {f.name for f in dataclasses.fields(n)}
        for k, v in n.__dict__.items():
            if k not in fields and k not in nn.__dict__:
                setattr(nn, k, v)
        return nn

    return rewrite(root)


def fuse_fragments(fragments: list, verdict) -> Tuple[list, int]:
    """The fusion pass.  `fragments` is cut_fragments' output (duck-typed
    parallel/cluster.Fragment dataclasses, topological — producers
    first); `verdict(consumer_frag, exchange_input) -> bool` is the
    PER-EDGE fuse decision (the caller folds placement, kind filters,
    and the plan/fusion_cost.py cost model in: an edge only fuses when
    producer and consumer land on the same mesh AND the edge priced as
    a net win — or `fragment_fusion=force` said fuse everything).

    Every fused edge splices the producer fragment's plan into the
    consumer with the Exchange node restored inline, so the consumer
    becomes a SUPER-fragment whose inline exchanges lower to collectives
    (parallel/dist_executor.run_fused_fragment).  A producer's surviving
    (non-fused) inputs migrate to the consumer.  Non-fused repartition /
    range inputs that feed a super-fragment are wrapped in an in-trace
    re-exchange, restoring the hashed/range distribution contract the
    consumer plan was built against (the single fused task pulls ALL
    buckets of such an edge, so the wire partitioning is lost).

    Returns (new fragment list — renumbered, producers-first — and the
    number of fragments absorbed).  Fused fragments carry `fused=True`
    and `fused_fids` (the original fids they absorbed)."""
    if len(fragments) <= 1:
        return fragments, 0
    spliced: Dict[int, object] = {}    # old fid -> rewritten root
    ext_inputs: Dict[int, list] = {}   # old fid -> surviving inputs
    has_scan: Dict[int, bool] = {}
    absorbed_into: Dict[int, List[int]] = {}  # old fid -> absorbed fids
    absorbed: set = set()
    # range ExchangeInputs carry plain keys; the sort tuples live on the
    # producer fragment's out_keys — needed to rebuild the inline node
    okeys_of = {}
    for f in fragments:
        for inp in f.inputs:
            okeys_of[inp.eid] = fragments[inp.producer].out_keys

    for frag in fragments:
        by_eid = {i.eid: i for i in frag.inputs}
        kept: list = []
        taken: List[int] = []
        hscan = [frag.has_scan]

        def on_scan(eid, node):
            inp = by_eid.get(eid)
            if inp is None:  # an absorbed producer's migrated input
                return node
            if verdict(frag, inp):
                ex = P.Exchange(spliced[inp.producer], inp.kind,
                                list(inp.keys))
                if inp.kind == "range":
                    ex.sort_keys = list(okeys_of[eid])
                if getattr(inp, "sketch", False):
                    # restore the sketch-edge stamps cut_fragments
                    # carried: the inline gather keeps its pmax lowering
                    ex.sketch_only = True
                    if getattr(inp, "sketch_merge", ""):
                        ex.sketch_merge = inp.sketch_merge
                absorbed.add(inp.producer)
                taken.extend([inp.producer]
                             + absorbed_into.get(inp.producer, []))
                kept.extend(ext_inputs.pop(inp.producer, []))
                hscan[0] = hscan[0] or has_scan[inp.producer]
                return ex
            kept.append(inp)
            return node

        root = _rewrite_exch_scans(frag.root, on_scan)
        if taken:
            # super-fragment: restore the distribution contract of the
            # remaining EXTERNAL repartition/range inputs in-trace
            wrap_of = {i.eid: i for i in kept
                       if i.kind in ("repartition", "range")}

            def wrap(eid, node):
                inp = wrap_of.get(eid)
                if inp is None:
                    return node
                ex = P.Exchange(node, inp.kind, list(inp.keys))
                if inp.kind == "range":
                    ex.sort_keys = list(okeys_of[eid])
                return ex

            root = _rewrite_exch_scans(root, wrap)
        spliced[frag.fid] = root
        ext_inputs[frag.fid] = kept
        has_scan[frag.fid] = hscan[0]
        absorbed_into[frag.fid] = taken

    survivors = [f for f in fragments if f.fid not in absorbed]
    renum = {f.fid: i for i, f in enumerate(survivors)}
    out = []
    for f in survivors:
        inputs = [dataclasses.replace(i, producer=renum[i.producer])
                  for i in ext_inputs[f.fid]]
        nf = dataclasses.replace(f, fid=renum[f.fid],
                                 root=spliced[f.fid], inputs=inputs,
                                 has_scan=has_scan[f.fid])
        if absorbed_into[f.fid]:
            nf.fused = True
            nf.fused_fids = list(absorbed_into[f.fid])
        out.append(nf)
    return out, len(absorbed)


def fused_root_replicated(root, exch_kinds: Dict[int, str]) -> bool:
    """Is a fused super-fragment's output REPLICATED across the mesh
    (every shard holds the full result — emit one shard's copy) or
    per-shard (concatenate shards)?  Mirrors the coarse replicated/
    sharded projection of the Dist lattice distribute() used to build
    the plan; `exch_kinds` maps external `__exch_{eid}` inputs to their
    edge kind."""

    def walk(n) -> bool:
        if isinstance(n, P.Exchange):
            return n.kind in ("gather", "broadcast")
        if isinstance(n, P.TableScan):
            if n.table.startswith("__exch_"):
                eid = int(n.table[len("__exch_"):])
                return exch_kinds.get(eid) in ("gather", "broadcast")
            return False  # sharded_scan slices rows per shard
        if isinstance(n, P.Values):
            return True
        if isinstance(n, P.Union):
            return False  # distribute() scatters replicated sources
        srcs = n.sources
        if not srcs:
            return False
        if len(srcs) > 1:  # joins: replicated iff every side is
            return all(walk(s) for s in srcs)
        return walk(srcs[0])

    return walk(root)
