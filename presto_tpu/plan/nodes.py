"""Logical plan nodes.

Reference parity: sql/planner/plan/ (41 node classes) trimmed to the set
the engine executes; symbols are unique strings (reference: Symbol +
SymbolAllocator), every node knows its output symbols and types.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from presto_tpu.plan.ir import AggCall, RowExpr
from presto_tpu.types import Type


class PlanNode:
    id_counter = itertools.count()

    def outputs(self) -> List[Tuple[str, Type]]:
        raise NotImplementedError

    @property
    def sources(self) -> list:
        return []

    def output_names(self):
        return [n for n, _ in self.outputs()]

    def output_types(self) -> Dict[str, Type]:
        return dict(self.outputs())


@dataclass
class TableScan(PlanNode):
    table: str
    # symbol -> source column name (projection pushdown unit)
    assignments: Dict[str, str] = field(default_factory=dict)
    types: Dict[str, Type] = field(default_factory=dict)

    def outputs(self):
        return [(s, self.types[s]) for s in self.assignments]


@dataclass
class Values(PlanNode):
    symbols: List[str] = field(default_factory=list)
    types_: List[Type] = field(default_factory=list)
    rows: List[list] = field(default_factory=list)  # python literal values

    def outputs(self):
        return list(zip(self.symbols, self.types_))


@dataclass
class Filter(PlanNode):
    source: PlanNode
    predicate: RowExpr

    def outputs(self):
        return self.source.outputs()

    @property
    def sources(self):
        return [self.source]


@dataclass
class Project(PlanNode):
    source: PlanNode
    assignments: Dict[str, RowExpr] = field(default_factory=dict)

    def outputs(self):
        return [(s, e.type) for s, e in self.assignments.items()]

    @property
    def sources(self):
        return [self.source]


@dataclass
class Aggregate(PlanNode):
    source: PlanNode
    group_keys: List[str] = field(default_factory=list)
    aggs: Dict[str, AggCall] = field(default_factory=dict)
    # step: SINGLE | PARTIAL | FINAL (reference: AggregationNode.Step)
    step: str = "SINGLE"

    def outputs(self):
        src_types = self.source.output_types()
        out = [(k, src_types[k]) for k in self.group_keys]
        out += [(s, a.type) for s, a in self.aggs.items()]
        return out

    @property
    def sources(self):
        return [self.source]


@dataclass
class SpatialJoin(PlanNode):
    """Grid-indexed spatial inner join (reference: SpatialJoinOperator +
    PagesRTreeIndex).  TPU-native redesign: instead of a pointer-chasing
    R-tree, the build side bins into a uniform grid sized so each
    geometry bbox spans O(1) cells; probes hash to their cell, candidate
    pairs expand vectorized, and the exact predicate (even-odd ray cast
    / distance) evaluates on device over padded edge arrays."""

    left: PlanNode  # probe side: point coordinates
    right: PlanNode  # build side: geometries (or points for distance)
    kind: str  # "contains" | "distance"
    probe_x: str = ""
    probe_y: str = ""
    build_geom: str = ""  # contains: right WKT/GEOMETRY symbol
    build_x: str = ""  # distance: right point coords
    build_y: str = ""
    radius: float = 0.0  # distance joins: st_distance(..) <= radius
    strict: bool = False  # True: < radius, False: <= radius
    filter: Optional[RowExpr] = None  # residual conjuncts

    def outputs(self):
        return list(self.left.outputs()) + list(self.right.outputs())

    @property
    def sources(self):
        return [self.left, self.right]


@dataclass
class Join(PlanNode):
    """INNER/LEFT/RIGHT/FULL/CROSS equi-join (+ residual filter), or
    SEMI/ANTI (left row kept iff [no] right match passes the filter —
    reference: SemiJoinNode, with the filtered-EXISTS generalization),
    or MARK (every left row kept, match-ness exposed as a BOOLEAN
    column `mark` — reference: SemiJoinNode's semiJoinOutput symbol,
    what EXISTS compiles to when it is NOT a top-level conjunct)."""

    left: PlanNode
    right: PlanNode
    join_type: str  # INNER LEFT RIGHT FULL CROSS SEMI ANTI MARK
    criteria: List[Tuple[str, str]] = field(default_factory=list)  # (lsym, rsym)
    filter: Optional[RowExpr] = None
    # execution hints filled by the optimizer
    distribution: str = "AUTOMATIC"  # PARTITIONED | BROADCAST | AUTOMATIC
    mark: Optional[str] = None  # MARK only: output symbol for match-ness
    reordered: bool = False  # ReorderJoins already explored this tree

    def outputs(self):
        if self.join_type == "MARK":
            from presto_tpu import types as _T

            return self.left.outputs() + [(self.mark, _T.BOOLEAN)]
        if self.join_type in ("SEMI", "ANTI"):
            return self.left.outputs()
        lout = self.left.outputs()
        rout = self.right.outputs()
        if self.join_type in ("LEFT", "FULL"):
            rout = [(s, t) for s, t in rout]
        return lout + rout

    @property
    def sources(self):
        return [self.left, self.right]


@dataclass
class Sort(PlanNode):
    source: PlanNode
    keys: List[Tuple[str, bool, Optional[bool]]] = field(default_factory=list)
    # (symbol, ascending, nulls_first)

    def outputs(self):
        return self.source.outputs()

    @property
    def sources(self):
        return [self.source]


@dataclass
class Limit(PlanNode):
    source: PlanNode
    count: int = 0

    def outputs(self):
        return self.source.outputs()

    @property
    def sources(self):
        return [self.source]


@dataclass
class TopN(PlanNode):
    source: PlanNode
    keys: List[Tuple[str, bool, Optional[bool]]] = field(default_factory=list)
    count: int = 0

    def outputs(self):
        return self.source.outputs()

    @property
    def sources(self):
        return [self.source]


@dataclass
class Union(PlanNode):
    sources_: List[PlanNode] = field(default_factory=list)
    symbols: List[str] = field(default_factory=list)
    # per-source mapping: output symbol -> source symbol
    mappings: List[Dict[str, str]] = field(default_factory=list)
    distinct: bool = False

    def outputs(self):
        t0 = self.sources_[0].output_types()
        return [(s, t0[self.mappings[0][s]]) for s in self.symbols]

    @property
    def sources(self):
        return list(self.sources_)


@dataclass
class Window(PlanNode):
    source: PlanNode
    partition_by: List[str] = field(default_factory=list)
    order_by: List[Tuple[str, bool, Optional[bool]]] = field(default_factory=list)
    functions: Dict[str, AggCall] = field(default_factory=dict)  # symbol -> call
    frame: Optional[Tuple[str, str, str]] = None

    def outputs(self):
        return self.source.outputs() + [(s, c.type) for s, c in self.functions.items()]

    @property
    def sources(self):
        return [self.source]


@dataclass
class Unnest(PlanNode):
    """Lateral array explode (reference: UnnestNode + operator/unnest/):
    each source row fans out to one row per element of its array value."""

    source: PlanNode
    array_expr: object  # RowExpr yielding an ARRAY column
    out_sym: str = ""
    elem_type: Type = None
    ordinality_sym: Optional[str] = None

    def outputs(self):
        out = list(self.source.outputs())
        out.append((self.out_sym, self.elem_type))
        if self.ordinality_sym:
            from presto_tpu.types import BIGINT

            out.append((self.ordinality_sym, BIGINT))
        return out

    @property
    def sources(self):
        return [self.source]


@dataclass
class Exchange(PlanNode):
    """Data-movement boundary between distributions (reference:
    sql/planner/plan/ExchangeNode.java — REPARTITION/REPLICATE/GATHER
    over REMOTE_STREAMING scope).  On TPU these lower to collectives
    inside one shard_mapped program instead of HTTP shuffles:
    repartition -> lax.all_to_all on row-hash buckets (P1),
    broadcast   -> lax.all_gather (P2),
    gather      -> lax.all_gather to full replication (P5),
    scatter     -> replicated input masked to one shard (inverse of P2,
                   used to feed replicated rows into a sharded union)."""

    source: PlanNode
    kind: str = "gather"  # repartition | broadcast | gather | scatter
    keys: List[str] = field(default_factory=list)  # hash keys (repartition)

    def outputs(self):
        return self.source.outputs()

    @property
    def sources(self):
        return [self.source]


@dataclass
class TableWriter(PlanNode):
    """Streams the source relation into a connector PageSink
    (reference: sql/planner/plan/TableWriterNode + TableWriterOperator).
    The sink handle itself is runtime state carried by the executor's
    WriteContext (exec/writer.py) — the node holds only the write's
    metadata so plans stay data-only and EXPLAIN can render the target.
    Output: one row with the appended row count."""

    source: PlanNode
    target: str = ""            # table name being written
    connector: str = ""         # memory | localfile | parquet | orc | ...
    columns: List[str] = field(default_factory=list)  # target column order
    write_props: Optional[dict] = None  # bucketed_by/sorted_by/... summary
    rows_symbol: str = "rows$w"

    def outputs(self):
        from presto_tpu import types as _T

        return [(self.rows_symbol, _T.BIGINT)]

    @property
    def sources(self):
        return [self.source]


@dataclass
class TableFinish(PlanNode):
    """Commit point of a write plan (reference: TableFinishNode +
    TableFinishOperator): runs ONCE on the coordinator after every
    TableWriter page landed, publishing the staged output atomically
    (manifest rewrite / catalog registration) and emitting the final
    row count."""

    source: PlanNode  # the TableWriter

    def outputs(self):
        return self.source.outputs()

    @property
    def sources(self):
        return [self.source]


@dataclass
class Output(PlanNode):
    source: PlanNode
    names: List[str] = field(default_factory=list)  # user-visible column names
    symbols: List[str] = field(default_factory=list)

    def outputs(self):
        t = self.source.output_types()
        return [(s, t[s]) for s in self.symbols]

    @property
    def sources(self):
        return [self.source]


# ---------------------------------------------------------------------------


@dataclass
class QueryPlan:
    """Root plan + uncorrelated scalar subplans it references.
    Subplans are evaluated first (reference: uncorrelated Apply lowered to
    an exchange from a separate stage)."""

    root: Output
    subplans: Dict[int, PlanNode] = field(default_factory=dict)


def plan_tree_str(node: PlanNode, indent: int = 0, annotate=None) -> str:
    """EXPLAIN-style textual plan (reference: textLogicalPlan in
    sql/planner/planPrinter/PlanPrinter.java); annotate(node) -> suffix
    string appends runtime stats for EXPLAIN ANALYZE."""
    pad = "  " * indent
    name = type(node).__name__
    detail = ""
    if isinstance(node, TableScan):
        detail = f" {node.table} {list(node.assignments.values())}"
    elif isinstance(node, Filter):
        detail = f" [{node.predicate}]"
    elif isinstance(node, Project):
        detail = " {" + ", ".join(f"{s} := {e}" for s, e in node.assignments.items()) + "}"
    elif isinstance(node, Aggregate):
        detail = (f" {node.step} keys={node.group_keys} "
                  + "{" + ", ".join(f"{s} := {a}" for s, a in node.aggs.items()) + "}")
    elif isinstance(node, Join):
        detail = f" {node.join_type} {node.criteria}" + (
            f" filter=[{node.filter}]" if node.filter is not None else "") + (
            " INDEX" if getattr(node, "index_lookup", None) else "")
    elif isinstance(node, SpatialJoin):
        pred = (f"ST_Contains({node.build_geom}, "
                f"point({node.probe_x}, {node.probe_y}))"
                if node.kind == "contains" else
                f"ST_Distance(({node.probe_x}, {node.probe_y}), "
                f"({node.build_x}, {node.build_y})) "
                f"{'<' if node.strict else '<='} {node.radius}")
        detail = f" GRID-INDEXED [{pred}]" + (
            f" filter=[{node.filter}]" if node.filter is not None else "")
    elif isinstance(node, (Sort, TopN)):
        detail = f" {node.keys}" + (
            f" limit={node.count}" if isinstance(node, TopN) else "")
    elif isinstance(node, Limit):
        detail = f" {node.count}"
    elif isinstance(node, Output):
        detail = f" {list(zip(node.names, node.symbols))}"
    elif isinstance(node, Values):
        detail = f" {len(node.rows)} rows"
    elif isinstance(node, Window):
        detail = f" partition={node.partition_by} order={node.order_by}"
    elif isinstance(node, Exchange):
        detail = f" {node.kind}" + (f" keys={node.keys}" if node.keys else "")
    elif isinstance(node, TableWriter):
        props = {k: v for k, v in (node.write_props or {}).items() if v}
        detail = f" {node.target} [{node.connector}]" + (
            f" {props}" if props else "")
    lines = [pad + name + detail + (annotate(node) if annotate else "")]
    for s in node.sources:
        lines.append(plan_tree_str(s, indent + 1, annotate))
    return "\n".join(lines)
