"""Iterative rule framework: Pattern + Rule + Memo + IterativeOptimizer.

Reference parity: sql/planner/iterative/{IterativeOptimizer, Memo, Rule}
driven by the presto-matching Pattern DSL (presto-matching/.../matching/).
The reference runs 87 rules to fixpoint over a Memo whose groups replace
node children; this is the same machinery at the scale the engine needs:
groups, group references, fixpoint iteration with a budget, and a small
set of always-safe normalization rules.  The heavyweight passes
(predicate pushdown/join reassembly, column pruning, exchange planning)
remain whole-plan passes, as PlanOptimizers.java also keeps its legacy
passes alongside the iterative ones.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from presto_tpu import types as T
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P

_log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# pattern DSL (presto-matching analog)
# ---------------------------------------------------------------------------


@dataclass
class Pattern:
    """Match a node by type + predicates + (optionally) source patterns.
    Source patterns look through GroupRefs, like the reference's
    `Patterns.source().matching(...)` with Lookup.resolve."""

    node_type: type
    predicates: List[Callable] = field(default_factory=list)
    source_patterns: List[Optional["Pattern"]] = field(default_factory=list)

    def matching(self, pred: Callable) -> "Pattern":
        return Pattern(self.node_type, self.predicates + [pred],
                       self.source_patterns)

    def with_source(self, *pats: Optional["Pattern"]) -> "Pattern":
        return Pattern(self.node_type, self.predicates, list(pats))

    def matches(self, node, lookup) -> bool:
        if not isinstance(node, self.node_type):
            return False
        if any(not p(node) for p in self.predicates):
            return False
        if self.source_patterns:
            srcs = node.sources
            if len(srcs) < len(self.source_patterns):
                return False
            for pat, src in zip(self.source_patterns, srcs):
                if pat is None:
                    continue
                if not pat.matches(lookup(src), lookup):
                    return False
        return True


def pattern(node_type: type) -> Pattern:
    return Pattern(node_type)


class Rule:
    """Subclass with `pattern` and `apply(node, ctx)` returning a
    replacement node or None (reference: iterative/Rule.java)."""

    pattern: Pattern = Pattern(P.PlanNode)

    def apply(self, node, ctx: "RuleContext"):
        raise NotImplementedError


@dataclass
class RuleContext:
    memo: "Memo"

    def resolve(self, node):
        """Look through a GroupRef to the group's current node
        (reference: Lookup.resolve)."""
        return self.memo.resolve(node)


# ---------------------------------------------------------------------------
# memo (reference: iterative/Memo.java)
# ---------------------------------------------------------------------------


@dataclass
class GroupRef(P.PlanNode):
    """Placeholder child pointing at a memo group."""

    memo: "Memo"
    gid: int

    def outputs(self):
        return self.memo.node(self.gid).outputs()

    @property
    def sources(self):
        return []

    def __repr__(self):
        return f"GroupRef({self.gid})"


def _carry_attrs(src: P.PlanNode, dst: P.PlanNode) -> P.PlanNode:
    """Preserve optimizer hint instance-attrs (capacity_hint, key_stats,
    build_unique, fanout_bound — not dataclass fields) across
    dataclasses.replace round-trips through the memo."""
    fields = {f.name for f in dataclasses.fields(src)}
    for k, v in src.__dict__.items():
        if k not in fields and k not in dst.__dict__:
            setattr(dst, k, v)
    return dst


class Memo:
    """Plan stored as groups; children of every stored node are
    GroupRefs.  `replace` rewires a group to a new representative
    (equivalence is by construction: rules only produce semantically
    equal plans)."""

    def __init__(self, root: P.PlanNode):
        self._nodes: Dict[int, P.PlanNode] = {}
        self._ids = itertools.count()
        self.root_gid = self._insert(root)

    # -- structure ----------------------------------------------------
    def _insert(self, node: P.PlanNode) -> int:
        gid = next(self._ids)
        self._nodes[gid] = self._with_group_children(node)
        return gid

    def _with_group_children(self, node: P.PlanNode) -> P.PlanNode:
        if isinstance(node, GroupRef):
            return node
        changed = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, GroupRef):
                continue
            if isinstance(v, P.PlanNode):
                changed[f.name] = GroupRef(self, self._insert(v))
            elif isinstance(v, list) and v and \
                    all(isinstance(x, P.PlanNode) for x in v):
                changed[f.name] = [
                    x if isinstance(x, GroupRef)
                    else GroupRef(self, self._insert(x)) for x in v]
        return _carry_attrs(node, dataclasses.replace(node, **changed)) \
            if changed else node

    def node(self, gid: int) -> P.PlanNode:
        return self._nodes[gid]

    def resolve(self, node: P.PlanNode) -> P.PlanNode:
        while isinstance(node, GroupRef):
            node = self._nodes[node.gid]
        return node

    def group_ids(self) -> List[int]:
        """Reachable groups, children before parents."""
        out: List[int] = []
        seen = set()

        def visit(gid):
            if gid in seen:
                return
            seen.add(gid)
            for f in dataclasses.fields(self._nodes[gid]):
                v = getattr(self._nodes[gid], f.name)
                for x in (v if isinstance(v, list) else [v]):
                    if isinstance(x, GroupRef):
                        visit(x.gid)
            out.append(gid)

        visit(self.root_gid)
        return out

    def replace(self, gid: int, node: P.PlanNode) -> None:
        self._nodes[gid] = self._with_group_children(node)

    def extract(self, gid: Optional[int] = None) -> P.PlanNode:
        """Materialize the plan back out of the memo."""
        return self.extract_node(
            self._nodes[self.root_gid if gid is None else gid])

    def extract_node(self, node: P.PlanNode) -> P.PlanNode:
        """Materialize a node whose children may be GroupRefs."""
        if isinstance(node, GroupRef):
            return self.extract(node.gid)
        changed = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, GroupRef):
                changed[f.name] = self.extract(v.gid)
            elif isinstance(v, list) and v and \
                    any(isinstance(x, GroupRef) for x in v):
                changed[f.name] = [self.extract(x.gid)
                                   if isinstance(x, GroupRef) else x
                                   for x in v]
        return _carry_attrs(node, dataclasses.replace(node, **changed)) \
            if changed else node


class IterativeOptimizer:
    """Run rules over memo groups until no rule fires (reference:
    iterative/IterativeOptimizer.exploreGroup), bounded by a budget so a
    bad rule can't loop forever."""

    def __init__(self, rules: List[Rule], max_applications: int = 10_000):
        self.rules = rules
        self.max_applications = max_applications

    def optimize(self, root: P.PlanNode) -> P.PlanNode:
        memo = Memo(root)
        ctx = RuleContext(memo)
        budget = self.max_applications
        progress = True
        while progress and budget > 0:
            progress = False
            for gid in memo.group_ids():
                node = memo.node(gid)
                for rule in self.rules:
                    if not rule.pattern.matches(node, memo.resolve):
                        continue
                    out = rule.apply(node, ctx)
                    if out is not None and out is not node:
                        memo.replace(gid, out)
                        progress = True
                        budget -= 1
                        break  # re-match this group next sweep
        return memo.extract()


# ---------------------------------------------------------------------------
# normalization rules (always-safe subset of the reference's 87)
# ---------------------------------------------------------------------------


class MergeFilters(Rule):
    """Filter(Filter(x)) -> Filter(x, a AND b)
    (reference: rule/MergeFilters.java)."""

    pattern = pattern(P.Filter).with_source(pattern(P.Filter))

    def apply(self, node: P.Filter, ctx):
        child = ctx.resolve(node.source)
        combined = ir.combine_conjuncts(
            ir.conjuncts(child.predicate) + ir.conjuncts(node.predicate))
        return P.Filter(child.source, combined)


class RemoveTrivialFilter(Rule):
    """Filter(TRUE) -> source (reference: RemoveTrivialFilters)."""

    pattern = pattern(P.Filter).matching(
        lambda n: isinstance(n.predicate, ir.Lit)
        and n.predicate.value is True)

    def apply(self, node: P.Filter, ctx):
        return ctx.resolve(node.source)


class MergeLimits(Rule):
    """Limit(a, Limit(b, x)) -> Limit(min(a,b), x)
    (reference: rule/MergeLimits.java)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Limit))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        return P.Limit(child.source, min(node.count, child.count))


class MergeLimitWithSort(Rule):
    """Limit(k, Sort(x)) -> TopN(k, x)
    (reference: rule/MergeLimitWithSort.java — the TopN rewrite)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Sort))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        return P.TopN(child.source, child.keys, node.count)


class PushLimitThroughProject(Rule):
    """Limit(Project(x)) -> Project(Limit(x))
    (reference: rule/PushLimitThroughProject.java)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Project))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        return P.Project(P.Limit(child.source, node.count),
                         dict(child.assignments))


class InlineIdentityProject(Rule):
    """Project that re-emits exactly its input symbols -> source
    (reference: RemoveRedundantIdentityProjections)."""

    pattern = pattern(P.Project)

    def apply(self, node: P.Project, ctx):
        child = ctx.resolve(node.source)
        child_outs = [s for s, _ in child.outputs()]
        if list(node.assignments) != child_outs:
            return None
        for s, e in node.assignments.items():
            if not (isinstance(e, ir.Ref) and e.name == s):
                return None
        return child


class MergeAdjacentProjects(Rule):
    """Project(Project(x)) -> one Project with inlined expressions when
    the inner assignments are pure Refs (reference: InlineProjections)."""

    pattern = pattern(P.Project).with_source(pattern(P.Project))

    def apply(self, node: P.Project, ctx):
        child = ctx.resolve(node.source)
        if not all(isinstance(e, ir.Ref) for e in child.assignments.values()):
            return None
        mapping = dict(child.assignments)
        new_assigns = {s: ir.substitute(e, mapping)
                       for s, e in node.assignments.items()}
        return P.Project(child.source, new_assigns)


# ---------------------------------------------------------------------------
# constant-folding / empty-relation rules (reference: rule/
# EvaluateZeroLimit, RemoveTrivialFilters' FALSE arm, the
# Evaluate*Over{EmptyRelation} family)
# ---------------------------------------------------------------------------


def _empty_values(node: P.PlanNode) -> P.Values:
    outs = node.outputs()
    return P.Values([s for s, _ in outs], [t for _, t in outs], [])


def _is_empty_pattern() -> Pattern:
    return pattern(P.Values).matching(lambda n: not n.rows)


class EvaluateZeroLimit(Rule):
    """Limit(0, x) -> empty Values (rule/EvaluateZeroLimit.java)."""

    pattern = pattern(P.Limit).matching(lambda n: n.count == 0)

    def apply(self, node: P.Limit, ctx):
        return _empty_values(node)


class EvaluateZeroTopN(Rule):
    """TopN(0, x) -> empty Values (part of the reference's zero-limit
    family)."""

    pattern = pattern(P.TopN).matching(lambda n: n.count == 0)

    def apply(self, node: P.TopN, ctx):
        return _empty_values(node)


class RemoveFalseFilter(Rule):
    """Filter(FALSE | NULL) -> empty Values (RemoveTrivialFilters)."""

    pattern = pattern(P.Filter).matching(
        lambda n: isinstance(n.predicate, ir.Lit)
        and (n.predicate.value is False or n.predicate.value is None))

    def apply(self, node: P.Filter, ctx):
        return _empty_values(node)


class FoldValuesLimit(Rule):
    """Limit(k, Values) -> Values[:k] (constant fold)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Values))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        if len(child.rows) <= node.count:
            return child
        return P.Values(child.symbols, child.types_,
                        child.rows[:node.count])


class PropagateEmptySource(Rule):
    """Row-wise / order-wise nodes over an empty relation are empty
    (reference: the EvaluateXOverEmptyRelation rule family)."""

    pattern = Pattern((P.Filter, P.Project, P.Sort, P.TopN, P.Limit,
                       P.Window, P.Unnest)).with_source(_is_empty_pattern())

    def apply(self, node, ctx):
        return _empty_values(node)


class EvaluateEmptyAggregate(Rule):
    """Grouped aggregate over an empty relation -> no groups, empty
    (global aggregates still emit their single row and are excluded)."""

    pattern = pattern(P.Aggregate).matching(
        lambda n: bool(n.group_keys)).with_source(_is_empty_pattern())

    def apply(self, node: P.Aggregate, ctx):
        return _empty_values(node)


class EliminateEmptyJoin(Rule):
    """Joins with a statically-empty side fold away (reference:
    rule/RemoveRedundant*Join*): INNER/CROSS/SEMI with either-empty
    probe or relevant side -> empty; ANTI with empty build -> probe
    passthrough; MARK with empty build -> probe + mark := FALSE."""

    pattern = pattern(P.Join)

    def apply(self, node: P.Join, ctx):
        from presto_tpu import types as T

        left = ctx.resolve(node.left)
        right = ctx.resolve(node.right)
        lempty = isinstance(left, P.Values) and not left.rows
        rempty = isinstance(right, P.Values) and not right.rows
        if not lempty and not rempty:
            return None
        jt = node.join_type
        if lempty:
            # RIGHT/FULL null-extend the RIGHT side's rows even with an
            # empty probe; folding them would drop rows
            if jt in ("INNER", "CROSS", "SEMI", "ANTI", "MARK", "LEFT"):
                return _empty_values(node)
            return None
        if jt in ("INNER", "CROSS", "SEMI"):
            return _empty_values(node)
        if jt == "ANTI":  # nothing to reject: left passes through
            return ctx.memo.extract_node(left)
        if jt == "MARK":  # no build rows: every mark is FALSE
            assigns = {s: ir.Ref(s, t) for s, t in left.outputs()}
            assigns[node.mark] = ir.Lit(False, T.BOOLEAN)
            return P.Project(ctx.memo.extract_node(left), assigns)
        return None  # LEFT/RIGHT/FULL need null-extension; leave as-is


class PruneEmptyUnionBranches(Rule):
    """UNION ALL drops statically-empty branches; all-empty -> empty,
    one branch -> remapping Project (reference: set-operation pruning
    rules)."""

    pattern = pattern(P.Union).matching(lambda n: not n.distinct)

    def apply(self, node: P.Union, ctx):
        kept = [(src, m) for src, m in zip(node.sources_, node.mappings)
                if not (isinstance(ctx.resolve(src), P.Values)
                        and not ctx.resolve(src).rows)]
        if len(kept) == len(node.sources_):
            return None
        if not kept:
            return _empty_values(node)
        types = dict(node.outputs())
        if len(kept) == 1:
            src, m = kept[0]
            return P.Project(ctx.memo.extract_node(ctx.resolve(src)),
                             {s: ir.Ref(m[s], types[s])
                              for s in node.symbols})
        return P.Union([ctx.memo.extract_node(ctx.resolve(s))
                        for s, _ in kept],
                       list(node.symbols), [m for _, m in kept], False)


# ---------------------------------------------------------------------------
# pushdown rules (reference: rule/PushLimitThrough*, PushTopNThrough*,
# the post-AddExchanges Filter pushes)
# ---------------------------------------------------------------------------


class MergeLimitWithTopN(Rule):
    """Limit(k, TopN(n, x)) -> TopN(min(k, n), x)
    (rule/MergeLimitWithTopN.java)."""

    pattern = pattern(P.Limit).with_source(pattern(P.TopN))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        return P.TopN(child.source, list(child.keys),
                      min(node.count, child.count))


class PushLimitThroughUnion(Rule):
    """Limit(k, Union ALL) -> Limit(k, Union(Limit(k, s)...)): each
    branch needs at most k rows (rule/PushLimitThroughUnion.java)."""

    pattern = pattern(P.Limit).with_source(
        pattern(P.Union).matching(lambda n: not n.distinct))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        k = node.count
        srcs = [ctx.resolve(s) for s in child.sources_]
        if all(isinstance(s, P.Limit) and s.count <= k for s in srcs):
            return None  # already pushed
        new_srcs = [s if isinstance(ctx.resolve(s), P.Limit)
                    and ctx.resolve(s).count <= k else P.Limit(s, k)
                    for s in child.sources_]
        return P.Limit(P.Union(new_srcs, list(child.symbols),
                               [dict(m) for m in child.mappings], False), k)


class PushLimitThroughOuterJoin(Rule):
    """Limit(k, LEFT join) -> Limit(k, join(Limit(k, probe), build)):
    a LEFT join emits at least one row per probe row, so k output rows
    need at most k probe rows (rule/PushLimitThroughOuterJoin.java)."""

    pattern = pattern(P.Limit).with_source(
        pattern(P.Join).matching(lambda n: n.join_type == "LEFT"))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        probe = ctx.resolve(child.left)
        if isinstance(probe, P.Limit) and probe.count <= node.count:
            return None  # already pushed
        new_join = dataclasses.replace(child,
                                       left=P.Limit(child.left, node.count))
        _carry_attrs(child, new_join)
        return P.Limit(new_join, node.count)


class PushLimitThroughMarkJoin(Rule):
    """Limit(k, MARK join) -> same push as the outer-join rule: MARK
    emits exactly one row per probe row (reference:
    PushLimitThroughSemiJoin operating on SemiJoinNode)."""

    pattern = pattern(P.Limit).with_source(
        pattern(P.Join).matching(lambda n: n.join_type == "MARK"))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        probe = ctx.resolve(child.left)
        if isinstance(probe, P.Limit) and probe.count <= node.count:
            return None
        new_join = dataclasses.replace(child,
                                       left=P.Limit(child.left, node.count))
        _carry_attrs(child, new_join)
        return P.Limit(new_join, node.count)


class PushTopNThroughProject(Rule):
    """TopN(Project(x)) -> Project(TopN(x)) when every sort key maps
    through an identity Ref — the projection then computes on at most
    N rows (rule/PushTopNThroughProject.java)."""

    pattern = pattern(P.TopN).with_source(pattern(P.Project))

    def apply(self, node: P.TopN, ctx):
        child = ctx.resolve(node.source)
        new_keys = []
        for sym, asc, nf in node.keys:
            e = child.assignments.get(sym)
            if not isinstance(e, ir.Ref):
                return None
            new_keys.append((e.name, asc, nf))
        return P.Project(P.TopN(child.source, new_keys, node.count),
                         dict(child.assignments))


class PushFilterThroughProject(Rule):
    """Filter(Project(x)) -> Project(Filter(x)) with the predicate
    rewritten through the assignments (reference: the
    PushDownFilterThroughProject shape inside PredicatePushDown)."""

    pattern = pattern(P.Filter).with_source(pattern(P.Project))

    def apply(self, node: P.Filter, ctx):
        child = ctx.resolve(node.source)
        refs = node.predicate.refs()
        if not refs <= set(child.assignments):
            return None
        rewritten = ir.substitute(node.predicate, dict(child.assignments))
        return P.Project(P.Filter(child.source, rewritten),
                         dict(child.assignments))


class PushFilterThroughUnion(Rule):
    """Filter(Union) -> Union(Filter(s)...) with per-branch symbol
    remapping (reference: PredicatePushDown's union arm)."""

    pattern = pattern(P.Filter).with_source(pattern(P.Union))

    def apply(self, node: P.Filter, ctx):
        child = ctx.resolve(node.source)
        types = dict(node.outputs())
        if not node.predicate.refs() <= set(child.symbols):
            return None
        new_srcs = []
        for src, m in zip(child.sources_, child.mappings):
            sub = {s: ir.Ref(m[s], types[s]) for s in child.symbols}
            new_srcs.append(P.Filter(src, ir.substitute(node.predicate,
                                                        sub)))
        return P.Union(new_srcs, list(child.symbols),
                       [dict(m) for m in child.mappings], child.distinct)


class SimplifyCountOverConstant(Rule):
    """count(<non-null literal>) -> count(*)
    (rule/SimplifyCountOverConstant.java)."""

    pattern = pattern(P.Aggregate)

    def apply(self, node: P.Aggregate, ctx):
        changed = {}
        for sym, a in node.aggs.items():
            if a.fn == "count" and not a.distinct and len(a.args) == 1 \
                    and isinstance(a.args[0], ir.Lit) \
                    and a.args[0].value is not None:
                changed[sym] = dataclasses.replace(a, args=())
        if not changed:
            return None
        aggs = dict(node.aggs)
        aggs.update(changed)
        out = P.Aggregate(node.source, list(node.group_keys), aggs,
                          node.step)
        return _carry_attrs(node, out)


class MergeUnions(Rule):
    """Union(Union ALL(a, b), c) -> Union(a, b, c): compose mappings
    through the inner ALL union (reference: MergeUnion /
    SetOperationMerge)."""

    pattern = pattern(P.Union)

    def apply(self, node: P.Union, ctx):
        new_srcs, new_maps = [], []
        changed = False
        for src, m in zip(node.sources_, node.mappings):
            inner = ctx.resolve(src)
            if isinstance(inner, P.Union) and not inner.distinct:
                for isrc, im in zip(inner.sources_, inner.mappings):
                    new_srcs.append(isrc)
                    new_maps.append({s: im[m[s]] for s in node.symbols})
                changed = True
            else:
                new_srcs.append(src)
                new_maps.append(dict(m))
        if not changed:
            return None
        return P.Union([ctx.memo.extract_node(ctx.resolve(s))
                        for s in new_srcs],
                       list(node.symbols), new_maps, node.distinct)


class RemoveRedundantSortOverValues(Rule):
    """Sort / TopN(n>=1) over a <=1-row relation is a no-op
    (reference: the RemoveRedundantSort rule on maxCardinality<=1)."""

    pattern = Pattern((P.Sort, P.TopN)).with_source(
        pattern(P.Values).matching(lambda n: len(n.rows) <= 1))

    def apply(self, node, ctx):
        if isinstance(node, P.TopN) and node.count < 1:
            return None  # zero-TopN folds via EvaluateZeroTopN
        return ctx.memo.extract_node(ctx.resolve(node.source))


class PushFilterThroughAggregation(Rule):
    """Filter conjuncts that reference ONLY group keys move below the
    Aggregate (HAVING on keys filters the same groups either way —
    reference: PredicatePushDown visiting AggregationNode)."""

    pattern = pattern(P.Filter).with_source(pattern(P.Aggregate))

    def apply(self, node: P.Filter, ctx):
        child = ctx.resolve(node.source)
        if not child.group_keys:
            return None
        keys = set(child.group_keys)
        below, keep = [], []
        for c in ir.conjuncts(node.predicate):
            (below if c.refs() <= keys else keep).append(c)
        if not below:
            return None
        new_agg = dataclasses.replace(
            child, source=P.Filter(child.source,
                                   ir.combine_conjuncts(below)))
        _carry_attrs(child, new_agg)
        if keep:
            return P.Filter(new_agg, ir.combine_conjuncts(keep))
        return new_agg


class PushFilterThroughSort(Rule):
    """Filter(Sort(x)) -> Sort(Filter(x)) — filter fewer rows first
    (reference: PredicatePushDown through SortNode)."""

    pattern = pattern(P.Filter).with_source(pattern(P.Sort))

    def apply(self, node: P.Filter, ctx):
        child = ctx.resolve(node.source)
        return P.Sort(P.Filter(child.source, node.predicate), child.keys)


class PushFilterThroughProbePreservingJoin(Rule):
    """Filter conjuncts over ONLY the probe (left) outputs move below
    SEMI/ANTI/MARK/LEFT joins — these joins never CHANGE a probe row,
    they only remove it (SEMI/ANTI) or extend it with build columns /
    a mark that the pushed conjuncts cannot reference (probe outputs
    exclude both).  Reference: PredicatePushDown visiting SemiJoinNode
    and outer joins."""

    pattern = pattern(P.Filter).with_source(pattern(P.Join).matching(
        lambda n: n.join_type in ("SEMI", "ANTI", "MARK", "LEFT")))

    def apply(self, node: P.Filter, ctx):
        child = ctx.resolve(node.source)
        probe = ctx.resolve(child.left)
        probe_syms = {s for s, _ in probe.outputs()}
        below, keep = [], []
        for c in ir.conjuncts(node.predicate):
            (below if c.refs() <= probe_syms else keep).append(c)
        if not below:
            return None
        new_join = dataclasses.replace(
            child, left=P.Filter(child.left,
                                 ir.combine_conjuncts(below)))
        _carry_attrs(child, new_join)
        if keep:
            return P.Filter(new_join, ir.combine_conjuncts(keep))
        return new_join


def _bounded_below(ctx, src, count: int) -> bool:
    """Already a TopN/Limit <= count under `src`, looking through
    Projects (other push rules re-home the bound inside a projection;
    without the deep look this guard misses it and the fixpoint wraps a
    fresh TopN every iteration — unbounded plan growth)."""
    r = ctx.resolve(src)
    for _ in range(8):
        if isinstance(r, (P.TopN, P.Limit)):
            return r.count <= count
        if isinstance(r, P.Project):
            r = ctx.resolve(r.source)
            continue
        return False
    return False


class PushTopNThroughOuterJoin(Rule):
    """TopN over a LEFT join whose sort keys are all left-side symbols:
    copy the TopN onto the probe input (each left row yields >= 1
    output row, so rows outside the left top-N can never reach the
    overall top-N — reference: rule/PushTopNThroughOuterJoin.java)."""

    pattern = pattern(P.TopN).with_source(pattern(P.Join).matching(
        lambda n: n.join_type == "LEFT"))

    def apply(self, node: P.TopN, ctx):
        child = ctx.resolve(node.source)
        probe = ctx.resolve(child.left)
        probe_syms = {s for s, _ in probe.outputs()}
        if not all(k in probe_syms for k, _a, _nf in node.keys):
            return None
        if _bounded_below(ctx, child.left, node.count):
            return None  # already pushed
        new_join = dataclasses.replace(
            child, left=P.TopN(child.left, list(node.keys), node.count))
        _carry_attrs(child, new_join)
        return P.TopN(new_join, list(node.keys), node.count)


class PushTopNThroughUnion(Rule):
    """TopN over UNION ALL -> per-branch TopN feeding the outer TopN
    (reference: rule/PushTopNThroughUnion.java)."""

    pattern = pattern(P.TopN).with_source(pattern(P.Union))

    def apply(self, node: P.TopN, ctx):
        child = ctx.resolve(node.source)
        if getattr(child, "distinct", False):
            return None
        new_sources = []
        changed = False
        for src, mapping in zip(child.sources_, child.mappings):
            if _bounded_below(ctx, src, node.count):
                new_sources.append(src)
                continue
            keys = [(mapping[k], a, nf) for k, a, nf in node.keys
                    if k in mapping]
            if len(keys) != len(node.keys):
                return None
            new_sources.append(P.TopN(src, keys, node.count))
            changed = True
        if not changed:
            return None
        new_union = dataclasses.replace(child, sources_=new_sources)
        _carry_attrs(child, new_union)
        return P.TopN(new_union, list(node.keys), node.count)


class RemoveRedundantDistinct(Rule):
    """A pure-DISTINCT Aggregate whose keys cover an inner Aggregate's
    group keys is a no-op: the inner output is already unique on them
    (reference: RemoveRedundantDistinct /
    PruneDistinctAggregation)."""

    pattern = pattern(P.Aggregate).matching(
        lambda n: not n.aggs and n.group_keys)

    def apply(self, node: P.Aggregate, ctx):
        child = ctx.resolve(node.source)
        if isinstance(child, P.Project):
            # identity-Ref projections preserve uniqueness
            inner = ctx.resolve(child.source)
            renames = {}
            for s, e in child.assignments.items():
                if isinstance(e, ir.Ref):
                    renames[s] = e.name
            if not isinstance(inner, P.Aggregate) or not inner.group_keys:
                return None
            mapped = {renames.get(k) for k in node.group_keys}
            if set(inner.group_keys) <= mapped:
                return _project_keys(node, child)
            return None
        if isinstance(child, P.Aggregate) and child.group_keys \
                and set(child.group_keys) <= set(node.group_keys):
            return _project_keys(node, child)
        return None


def _project_keys(distinct: P.Aggregate, source: P.PlanNode) -> P.PlanNode:
    types = dict(source.outputs())
    return P.Project(source, {k: ir.Ref(k, types[k])
                              for k in distinct.group_keys})


class RemoveLimitOverScalarAggregate(Rule):
    """Limit(n>=1) over a global Aggregate (exactly one row) is a no-op
    (reference: RemoveRedundantLimit's cardinality reasoning)."""

    pattern = pattern(P.Limit).matching(lambda n: n.count >= 1)

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        if isinstance(child, P.Aggregate) and not child.group_keys:
            return child
        return None


_FOLD_CMP = {"eq": lambda a, b: a == b, "lt": lambda a, b: a < b,
             "le": lambda a, b: a <= b, "gt": lambda a, b: a > b,
             "ge": lambda a, b: a >= b}


class FoldConstantComparisons(Rule):
    """Filter conjuncts comparing two literals fold to TRUE/FALSE
    (reference: SimplifyExpressions' constant folding, trimmed to the
    comparison shapes macro-generated queries produce)."""

    pattern = pattern(P.Filter)

    def apply(self, node: P.Filter, ctx):
        changed = False
        out = []
        for c in ir.conjuncts(node.predicate):
            if isinstance(c, ir.Call) and c.fn in _FOLD_CMP \
                    and len(c.args) == 2 \
                    and all(isinstance(a, ir.Lit)
                            and a.value is not None
                            and isinstance(a.value, (int, float, str,
                                                     bool))
                            for a in c.args) \
                    and len({type(a.value) is str for a in c.args}) == 1:
                v = _FOLD_CMP[c.fn](c.args[0].value, c.args[1].value)
                changed = True
                if v:
                    continue  # TRUE conjunct drops
                return P.Filter(node.source, ir.Lit(False, T.BOOLEAN))
            out.append(c)
        if not changed:
            return None
        if not out:
            return ctx.resolve(node.source)
        return P.Filter(node.source, ir.combine_conjuncts(out))


class MergeSorts(Rule):
    """Sort(Sort(x)) -> outer Sort only (the inner order is clobbered;
    reference: RemoveRedundantSort class of cleanups)."""

    pattern = pattern(P.Sort).with_source(pattern(P.Sort))

    def apply(self, node: P.Sort, ctx):
        child = ctx.resolve(node.source)
        return P.Sort(child.source, node.keys)


class PushProjectionThroughUnion(Rule):
    """Project(Union ALL) -> Union(per-branch Projects): expressions
    evaluate once per branch at branch width (reference:
    rule/PushProjectionThroughUnion.java)."""

    pattern = pattern(P.Project).with_source(pattern(P.Union).matching(
        lambda n: not n.distinct))

    def apply(self, node: P.Project, ctx):
        child = ctx.resolve(node.source)
        # identity projects die via InlineIdentityProject; pushing them
        # would churn the memo without progress
        if all(isinstance(e, ir.Ref) and e.name == s
               for s, e in node.assignments.items()):
            return None
        new_sources, new_mappings = [], []
        for src, mapping in zip(child.sources_, child.mappings):
            types = ctx.resolve(src).output_types()
            ref_map = {u: ir.Ref(m, types[m]) for u, m in mapping.items()}
            assigns = {s: ir.substitute(e, ref_map)
                       for s, e in node.assignments.items()}
            new_sources.append(P.Project(src, assigns))
            new_mappings.append({s: s for s in node.assignments})
        new_union = dataclasses.replace(
            child, sources_=new_sources,
            symbols=list(node.assignments), mappings=new_mappings)
        return _carry_attrs(child, new_union)


class SingleDistinctAggregationToGroupBy(Rule):
    """All aggregates DISTINCT over one shared argument list -> dedup
    with an inner GROUP BY, then aggregate plainly (reference:
    rule/SingleDistinctAggregationToGroupBy.java).  The rewrite turns
    per-group distinct tracking into the engine's sort-based grouping,
    which is the fast path on device."""

    pattern = pattern(P.Aggregate).matching(
        lambda n: n.aggs and n.step == "SINGLE"
        and all(a.distinct for a in n.aggs.values()))

    def apply(self, node: P.Aggregate, ctx):
        calls = list(node.aggs.values())
        if any(a.filter is not None or not a.args
               or any(not isinstance(r, ir.Ref) for r in a.args)
               for a in calls):
            return None
        if any(a.fn not in ("count", "sum", "avg", "min", "max")
               for a in calls):
            return None
        arg_lists = {tuple(r.name for r in a.args) for a in calls}
        if len(arg_lists) != 1:
            return None
        arg_syms = next(iter(arg_lists))
        inner_keys = list(node.group_keys) + [
            s for s in arg_syms if s not in node.group_keys]
        inner = P.Aggregate(node.source, inner_keys, {}, "SINGLE")
        new_aggs = {sym: dataclasses.replace(a, distinct=False)
                    for sym, a in node.aggs.items()}
        out = dataclasses.replace(node, source=inner, aggs=new_aggs)
        return _carry_attrs(node, out)


class PushAggregationThroughOuterJoin(Rule):
    """Aggregate over a LEFT equi-join where every aggregate input
    comes from the build side: pre-aggregate the build side per join
    key, join the (much smaller) partials, and merge above (reference:
    rule/PushAggregationThroughOuterJoin.java; the merge-above shape
    keeps the rewrite correct for duplicate probe keys, where the
    reference instead requires distinct probe rows).

    count merges as sum(coalesce(partial, 0)) — an unmatched probe row
    contributes 0, exactly the count over its null-extended row."""

    pattern = pattern(P.Aggregate).matching(
        lambda n: n.group_keys and n.aggs and n.step == "SINGLE"
    ).with_source(pattern(P.Join).matching(
        lambda n: n.join_type == "LEFT" and not n.filter
        and len(n.criteria) == 1))

    MERGEABLE = {"count": "sum", "sum": "sum", "min": "min", "max": "max"}

    def apply(self, node: P.Aggregate, ctx):
        join = ctx.resolve(node.source)
        build = ctx.resolve(join.right)
        if isinstance(build, P.Aggregate):
            return None  # already pushed
        lk, rk = join.criteria[0]
        probe_syms = {s for s, _ in ctx.resolve(join.left).outputs()}
        build_syms = {s for s, _ in build.outputs()}
        if not all(k in probe_syms for k in node.group_keys):
            return None
        calls = list(node.aggs.items())
        if any(a.distinct or a.filter is not None or not a.args
               or a.fn not in self.MERGEABLE
               or not all(isinstance(r, ir.Ref)
                          and r.name in build_syms for r in a.args)
               for _s, a in calls):
            return None
        # build-side partials, grouped by the join key
        partial_aggs = {}
        partial_sym = {}
        for s, a in calls:
            ps = f"{s}$part"
            partial_sym[s] = ps
            partial_aggs[ps] = ir.AggCall(a.fn, a.args,
                                          a.type, False, None)
        inner = P.Aggregate(join.right, [rk], partial_aggs, "SINGLE")
        new_join = dataclasses.replace(join, right=inner)
        _carry_attrs(join, new_join)
        # coalesce count partials to 0 for null-extended probe rows
        types = dict(new_join.outputs())
        assigns = {k: ir.Ref(k, types[k]) for k in node.group_keys}
        for s, a in calls:
            ps = partial_sym[s]
            ref = ir.Ref(ps, a.type)
            if a.fn == "count":
                assigns[ps] = ir.Call(
                    "coalesce", (ref, ir.Lit(0, a.type)), a.type)
            else:
                assigns[ps] = ref
        proj = P.Project(new_join, assigns)
        merged = {s: ir.AggCall(self.MERGEABLE[a.fn],
                                (ir.Ref(partial_sym[s], a.type),),
                                a.type, False, None)
                  for s, a in calls}
        out = dataclasses.replace(node, source=proj, aggs=merged)
        return _carry_attrs(node, out)


class PushFilterThroughWindow(Rule):
    """Filter conjuncts over ONLY the partition keys move below a
    Window: they drop whole partitions, never rows within one, so
    every window value is unchanged (reference:
    rule/PushdownFilterIntoWindow.java's partition-key case)."""

    pattern = pattern(P.Filter).with_source(pattern(P.Window))

    def apply(self, node: P.Filter, ctx):
        child = ctx.resolve(node.source)
        keys = set(child.partition_by)
        if not keys:
            return None
        below, keep = [], []
        for c in ir.conjuncts(node.predicate):
            (below if c.refs() <= keys else keep).append(c)
        if not below:
            return None
        new_win = dataclasses.replace(
            child, source=P.Filter(child.source,
                                   ir.combine_conjuncts(below)))
        _carry_attrs(child, new_win)
        if keep:
            return P.Filter(new_win, ir.combine_conjuncts(keep))
        return new_win


class RemoveSortOverScalar(Rule):
    """Sort over a global Aggregate (exactly one row) is a no-op
    (reference: RemoveRedundantSort's cardinality reasoning)."""

    pattern = pattern(P.Sort)

    def apply(self, node: P.Sort, ctx):
        child = ctx.resolve(node.source)
        if isinstance(child, P.Aggregate) and not child.group_keys \
                and child.step == "SINGLE":
            return child
        return None


DEFAULT_RULES: List[Rule] = [
    MergeFilters(), RemoveTrivialFilter(), MergeLimits(),
    MergeLimitWithSort(), PushLimitThroughProject(),
    InlineIdentityProject(), MergeAdjacentProjects(),
    EvaluateZeroLimit(), EvaluateZeroTopN(), RemoveFalseFilter(),
    FoldValuesLimit(), PropagateEmptySource(), EvaluateEmptyAggregate(),
    EliminateEmptyJoin(), PruneEmptyUnionBranches(),
    MergeLimitWithTopN(), PushLimitThroughUnion(),
    PushLimitThroughOuterJoin(), PushLimitThroughMarkJoin(),
    PushTopNThroughProject(), PushFilterThroughProject(),
    PushFilterThroughUnion(), SimplifyCountOverConstant(),
    MergeUnions(), RemoveRedundantSortOverValues(),
    # round-5 breadth (VERDICT item 9)
    PushFilterThroughAggregation(), PushFilterThroughSort(),
    PushFilterThroughProbePreservingJoin(), PushTopNThroughOuterJoin(),
    PushTopNThroughUnion(), RemoveRedundantDistinct(),
    RemoveLimitOverScalarAggregate(), FoldConstantComparisons(),
    MergeSorts(),
    # round-5 batch 2
    PushProjectionThroughUnion(), SingleDistinctAggregationToGroupBy(),
    PushAggregationThroughOuterJoin(), PushFilterThroughWindow(),
    RemoveSortOverScalar(),
]


# ---------------------------------------------------------------------------
# cost-based rules (reference: rule/ReorderJoins.java — the CBO join
# enumeration INSIDE the iterative framework, replacing the greedy
# whole-plan pass for bounded join sets)
# ---------------------------------------------------------------------------


class ReorderJoins(Rule):
    """Memoized cost-based join reordering: flatten a tree of INNER
    equi-joins (through GroupRefs), run a Selinger-style DP over
    connected subsets, and keep the cheapest tree.  Bounded to
    `max_reorder_joins` relations like the reference's JoinEnumerator
    (ReorderJoins.java limits to 9); larger sets keep the greedy order
    from the reassembly pass.

    The cost model mirrors THIS engine's executor, not a generic
    row-count heuristic (the reference couples enumeration to its real
    cost model the same way: ReorderJoins.java + CostComparator +
    CostCalculatorUsingExchanges):

    - a join is ONE composite sort of the combined padded relation
      (exec/kernels.build_probe sorts |L|+|R| rows), so cost carries
      an (|L|+|R|)·log term over the STATIC row bounds — filters do
      not shrink padded shapes, so `est` alone is blind to the real
      work;
    - the output materializes at its STATIC bound: |L| when the build
      (right) side is unique on the join keys, |L|·fanout when the
      connector bounds the fanout, and a large dynamic-fallback
      penalty when nothing bounds it (the static executor raises
      StaticFallback there and the whole query drops to per-op
      dynamic dispatch).  This is what makes orientation matter: both
      split orientations are enumerated, and a plan that puts the
      fact table on the build side is priced at its true blow-up;
    - the CBO estimate enters with a small weight as the tie-breaker
      (live rows drive dynamic-mode expansions and exchange volume).
    """

    SORT_WEIGHT = 1.0
    OUT_WEIGHT = 2.0
    EST_WEIGHT = 0.5
    # no uniqueness, no fanout bound, no ndv: the static executor falls
    # back to dynamic per-op execution — price it like a huge expansion
    DYN_FALLBACK_FANOUT = 32

    def __init__(self, session):
        self.session = session
        self.max_rels = int(session.properties.get("max_reorder_joins", 8))
        self.pattern = pattern(P.Join).matching(
            lambda n: n.join_type == "INNER" and n.criteria
            and not n.reordered and n.filter is None)

    def _note_stat_failure(self, what, err):
        """CBO degradation is visible, not silent (round-3 VERDICT weak
        #6): count on the session (observable by tests/EXPLAIN readers)
        and log."""
        self.session.cbo_stat_failures = \
            getattr(self.session, "cbo_stat_failures", 0) + 1
        _log.debug("ReorderJoins stats failure on %s: %r", what, err)

    def _flatten(self, node, ctx, sources, criteria):
        node = ctx.resolve(node)
        if isinstance(node, P.Join) and node.join_type == "INNER" \
                and node.criteria and node.filter is None:
            self._flatten(node.left, ctx, sources, criteria)
            self._flatten(node.right, ctx, sources, criteria)
            criteria.extend(node.criteria)
            return
        sources.append(ctx.memo.extract_node(node))

    def _join_cost(self, ls, rs, criteria, st) -> float:
        """Cost of executing Join(L, R) given child stats, per the
        model in the class docstring."""
        import math

        from presto_tpu.plan import stats as S

        n = float(ls.rows + rs.rows)
        sort_cost = n * math.log2(max(n, 2.0))
        rkeys = frozenset(rk for _, rk in criteria)
        penalty = 0.0
        if any(u <= rkeys for u in rs.unique):
            out_bound = float(ls.rows)
        else:
            best_key = S._best_fanout_key(rs, rkeys)
            bound = rs.fanout.get(best_key) if best_key else None
            if bound is None:
                # the same speculative bound annotate_static_hints will
                # hand the executor, so the cost prices the real shape
                bound = S.speculative_fanout_bound(rs, criteria)
            if bound is None:
                # nothing bounds the fanout: the static executor raises
                # StaticFallback and the WHOLE query re-runs per-op
                # dynamic — a fixed catastrophic penalty, not one
                # proportional to the (possibly tiny) probe side
                out_bound = float(ls.rows) * self.DYN_FALLBACK_FANOUT
                penalty = 1e12
            else:
                out_bound = float(ls.rows) * bound
        return (self.SORT_WEIGHT * sort_cost
                + self.OUT_WEIGHT * out_bound
                + self.EST_WEIGHT * st.est_rows + penalty)

    def apply(self, node: P.Join, ctx):
        from presto_tpu.plan import stats as S

        catalog = getattr(self.session, "catalog", None)
        if catalog is None:
            return None
        sources: List[P.PlanNode] = []
        criteria: List[tuple] = []
        self._flatten(node, ctx, sources, criteria)
        n = len(sources)
        if n < 3 or n > self.max_rels:
            return self._mark(node)
        sym_of = {}  # symbol -> relation index
        for i, s in enumerate(sources):
            for sym, _t in s.outputs():
                sym_of[sym] = i
        edges = []  # (i, j, lsym@i, rsym@j)
        for lk, rk in criteria:
            i, j = sym_of.get(lk), sym_of.get(rk)
            if i is None or j is None or i == j:
                return self._mark(node)
            edges.append((i, j, lk, rk))

        smemo: Dict[int, object] = {}  # id-keyed stats memo shared by
        # every candidate (children are shared objects, so each new
        # join node derives in O(1) — no per-candidate tree walks)

        def stats_of(tree):
            try:
                return S.derive(tree, catalog, smemo)
            except Exception as e:
                self._note_stat_failure(type(tree).__name__, e)
                return None

        # DP over connected subsets: best[mask] = (cost, tree, stats)
        best: Dict[int, tuple] = {}
        for i, s in enumerate(sources):
            st = stats_of(s)
            if st is None:
                return self._mark(node)
            best[1 << i] = (0.0, s, st)
        full = (1 << n) - 1
        for mask in range(3, full + 1):
            if mask & (mask - 1) == 0:
                continue
            cand = None
            # every proper submask, so BOTH orientations of each split
            # are priced (probe-vs-build side assignment is the
            # decision the cost model exists for)
            sub = (mask - 1) & mask
            while sub:
                rest = mask ^ sub
                bl, br = best.get(sub), best.get(rest)
                if bl and br:
                    crit = [(lk, rk) for (i, j, lk, rk) in edges
                            if (sub >> i) & 1 and (rest >> j) & 1]
                    crit += [(rk, lk) for (i, j, lk, rk) in edges
                             if (rest >> i) & 1 and (sub >> j) & 1]
                    if crit:
                        tree = P.Join(bl[1], br[1], "INNER", crit,
                                      reordered=True)
                        st = stats_of(tree)
                        if st is not None:
                            cost = bl[0] + br[0] + \
                                self._join_cost(bl[2], br[2], crit, st)
                            if cand is None or cost < cand[0]:
                                cand = (cost, tree, st)
                sub = (sub - 1) & mask
            if cand is not None:
                best[mask] = cand
        if full not in best:
            return self._mark(node)
        cost, tree, _st = best[full]
        cur_cost = self._tree_cost(ctx.memo.extract_node(node), catalog,
                                   smemo)
        if cur_cost is not None and cost >= cur_cost:
            return self._mark(node)
        return tree

    def _tree_cost(self, tree, catalog, smemo):
        """Cost of the CURRENT (extracted) tree under the same model."""
        from presto_tpu.plan import stats as S

        if not (isinstance(tree, P.Join) and tree.join_type == "INNER"
                and tree.criteria and tree.filter is None):
            return 0.0
        try:
            ls = S.derive(tree.left, catalog, smemo)
            rs = S.derive(tree.right, catalog, smemo)
            st = S.derive(tree, catalog, smemo)
        except Exception as e:
            self._note_stat_failure("current tree", e)
            return None
        lc = self._tree_cost(tree.left, catalog, smemo)
        rc = self._tree_cost(tree.right, catalog, smemo)
        if lc is None or rc is None:
            return None
        return lc + rc + self._join_cost(ls, rs, tree.criteria, st)

    @staticmethod
    def _mark(node):
        return dataclasses.replace(node, reordered=True)


class PushPartialAggregationThroughExchange(Rule):
    """Aggregate(SINGLE, Exchange(repartition, keys == group keys)) ->
    FinalAgg(Exchange(repartition, PartialAgg(src))) when every
    aggregate decomposes into a partial/final pair and the stats say
    shards hold duplicate keys (reference:
    rule/PushPartialAggregationThroughExchange.java, run after
    AddExchanges; here run by distribute() on the distributed plan)."""

    def __init__(self, session):
        self.session = session
        self.pattern = pattern(P.Aggregate).matching(
            lambda n: n.step == "SINGLE" and n.group_keys)

    def apply(self, node: P.Aggregate, ctx):
        from presto_tpu.plan.distribute import Distributer, _MERGEABLE

        if not bool(self.session.properties.get(
                "push_partial_aggregation_through_exchange", True)):
            return None
        ex = ctx.resolve(node.source)
        if not (isinstance(ex, P.Exchange) and ex.kind == "repartition"
                and list(ex.keys) == list(node.group_keys)):
            return None
        if any(a.distinct or a.fn not in _MERGEABLE
               for a in node.aggs.values()):
            return None
        from presto_tpu.plan import agg_strategy as AS

        if AS.enabled(self.session) \
                and getattr(node, "agg_strategy", None) == AS.FINAL_ONLY:
            # final_only strategy: the single aggregation over the
            # repartition IS the global-table route — pushing a partial
            # through the exchange would re-plan the stage this
            # strategy exists to avoid
            return None
        src = ex.source
        d = Distributer(self.session)
        partial_aggs, final_aggs = d.decompose_aggs(node.aggs)
        if partial_aggs is None:
            return None
        partial = P.Aggregate(ctx.memo.extract_node(ctx.resolve(src)),
                              list(node.group_keys), partial_aggs,
                              "PARTIAL")
        partial.capacity_hint = getattr(node, "capacity_hint", None)
        partial.key_stats = getattr(node, "key_stats", {})
        if AS.enabled(self.session):
            partial.agg_strategy = AS.TWO_PHASE  # runtime bypass armed
        new_ex = P.Exchange(partial, "repartition", list(ex.keys))
        final = P.Aggregate(new_ex, list(node.group_keys), final_aggs,
                            "FINAL")
        final.capacity_hint = getattr(node, "capacity_hint", None)
        final.key_stats = getattr(node, "key_stats", {})
        return final
