"""Iterative rule framework: Pattern + Rule + Memo + IterativeOptimizer.

Reference parity: sql/planner/iterative/{IterativeOptimizer, Memo, Rule}
driven by the presto-matching Pattern DSL (presto-matching/.../matching/).
The reference runs 87 rules to fixpoint over a Memo whose groups replace
node children; this is the same machinery at the scale the engine needs:
groups, group references, fixpoint iteration with a budget, and a small
set of always-safe normalization rules.  The heavyweight passes
(predicate pushdown/join reassembly, column pruning, exchange planning)
remain whole-plan passes, as PlanOptimizers.java also keeps its legacy
passes alongside the iterative ones.
"""

from __future__ import annotations

import dataclasses
import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P


# ---------------------------------------------------------------------------
# pattern DSL (presto-matching analog)
# ---------------------------------------------------------------------------


@dataclass
class Pattern:
    """Match a node by type + predicates + (optionally) source patterns.
    Source patterns look through GroupRefs, like the reference's
    `Patterns.source().matching(...)` with Lookup.resolve."""

    node_type: type
    predicates: List[Callable] = field(default_factory=list)
    source_patterns: List[Optional["Pattern"]] = field(default_factory=list)

    def matching(self, pred: Callable) -> "Pattern":
        return Pattern(self.node_type, self.predicates + [pred],
                       self.source_patterns)

    def with_source(self, *pats: Optional["Pattern"]) -> "Pattern":
        return Pattern(self.node_type, self.predicates, list(pats))

    def matches(self, node, lookup) -> bool:
        if not isinstance(node, self.node_type):
            return False
        if any(not p(node) for p in self.predicates):
            return False
        if self.source_patterns:
            srcs = node.sources
            if len(srcs) < len(self.source_patterns):
                return False
            for pat, src in zip(self.source_patterns, srcs):
                if pat is None:
                    continue
                if not pat.matches(lookup(src), lookup):
                    return False
        return True


def pattern(node_type: type) -> Pattern:
    return Pattern(node_type)


class Rule:
    """Subclass with `pattern` and `apply(node, ctx)` returning a
    replacement node or None (reference: iterative/Rule.java)."""

    pattern: Pattern = Pattern(P.PlanNode)

    def apply(self, node, ctx: "RuleContext"):
        raise NotImplementedError


@dataclass
class RuleContext:
    memo: "Memo"

    def resolve(self, node):
        """Look through a GroupRef to the group's current node
        (reference: Lookup.resolve)."""
        return self.memo.resolve(node)


# ---------------------------------------------------------------------------
# memo (reference: iterative/Memo.java)
# ---------------------------------------------------------------------------


@dataclass
class GroupRef(P.PlanNode):
    """Placeholder child pointing at a memo group."""

    memo: "Memo"
    gid: int

    def outputs(self):
        return self.memo.node(self.gid).outputs()

    @property
    def sources(self):
        return []

    def __repr__(self):
        return f"GroupRef({self.gid})"


class Memo:
    """Plan stored as groups; children of every stored node are
    GroupRefs.  `replace` rewires a group to a new representative
    (equivalence is by construction: rules only produce semantically
    equal plans)."""

    def __init__(self, root: P.PlanNode):
        self._nodes: Dict[int, P.PlanNode] = {}
        self._ids = itertools.count()
        self.root_gid = self._insert(root)

    # -- structure ----------------------------------------------------
    def _insert(self, node: P.PlanNode) -> int:
        gid = next(self._ids)
        self._nodes[gid] = self._with_group_children(node)
        return gid

    def _with_group_children(self, node: P.PlanNode) -> P.PlanNode:
        if isinstance(node, GroupRef):
            return node
        changed = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, GroupRef):
                continue
            if isinstance(v, P.PlanNode):
                changed[f.name] = GroupRef(self, self._insert(v))
            elif isinstance(v, list) and v and \
                    all(isinstance(x, P.PlanNode) for x in v):
                changed[f.name] = [
                    x if isinstance(x, GroupRef)
                    else GroupRef(self, self._insert(x)) for x in v]
        return dataclasses.replace(node, **changed) if changed else node

    def node(self, gid: int) -> P.PlanNode:
        return self._nodes[gid]

    def resolve(self, node: P.PlanNode) -> P.PlanNode:
        while isinstance(node, GroupRef):
            node = self._nodes[node.gid]
        return node

    def group_ids(self) -> List[int]:
        """Reachable groups, children before parents."""
        out: List[int] = []
        seen = set()

        def visit(gid):
            if gid in seen:
                return
            seen.add(gid)
            for f in dataclasses.fields(self._nodes[gid]):
                v = getattr(self._nodes[gid], f.name)
                for x in (v if isinstance(v, list) else [v]):
                    if isinstance(x, GroupRef):
                        visit(x.gid)
            out.append(gid)

        visit(self.root_gid)
        return out

    def replace(self, gid: int, node: P.PlanNode) -> None:
        self._nodes[gid] = self._with_group_children(node)

    def extract(self, gid: Optional[int] = None) -> P.PlanNode:
        """Materialize the plan back out of the memo."""
        node = self._nodes[self.root_gid if gid is None else gid]
        changed = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, GroupRef):
                changed[f.name] = self.extract(v.gid)
            elif isinstance(v, list) and v and \
                    any(isinstance(x, GroupRef) for x in v):
                changed[f.name] = [self.extract(x.gid)
                                   if isinstance(x, GroupRef) else x
                                   for x in v]
        return dataclasses.replace(node, **changed) if changed else node


class IterativeOptimizer:
    """Run rules over memo groups until no rule fires (reference:
    iterative/IterativeOptimizer.exploreGroup), bounded by a budget so a
    bad rule can't loop forever."""

    def __init__(self, rules: List[Rule], max_applications: int = 10_000):
        self.rules = rules
        self.max_applications = max_applications

    def optimize(self, root: P.PlanNode) -> P.PlanNode:
        memo = Memo(root)
        ctx = RuleContext(memo)
        budget = self.max_applications
        progress = True
        while progress and budget > 0:
            progress = False
            for gid in memo.group_ids():
                node = memo.node(gid)
                for rule in self.rules:
                    if not rule.pattern.matches(node, memo.resolve):
                        continue
                    out = rule.apply(node, ctx)
                    if out is not None and out is not node:
                        memo.replace(gid, out)
                        progress = True
                        budget -= 1
                        break  # re-match this group next sweep
        return memo.extract()


# ---------------------------------------------------------------------------
# normalization rules (always-safe subset of the reference's 87)
# ---------------------------------------------------------------------------


class MergeFilters(Rule):
    """Filter(Filter(x)) -> Filter(x, a AND b)
    (reference: rule/MergeFilters.java)."""

    pattern = pattern(P.Filter).with_source(pattern(P.Filter))

    def apply(self, node: P.Filter, ctx):
        child = ctx.resolve(node.source)
        combined = ir.combine_conjuncts(
            ir.conjuncts(child.predicate) + ir.conjuncts(node.predicate))
        return P.Filter(child.source, combined)


class RemoveTrivialFilter(Rule):
    """Filter(TRUE) -> source (reference: RemoveTrivialFilters)."""

    pattern = pattern(P.Filter).matching(
        lambda n: isinstance(n.predicate, ir.Lit)
        and n.predicate.value is True)

    def apply(self, node: P.Filter, ctx):
        return ctx.resolve(node.source)


class MergeLimits(Rule):
    """Limit(a, Limit(b, x)) -> Limit(min(a,b), x)
    (reference: rule/MergeLimits.java)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Limit))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        return P.Limit(child.source, min(node.count, child.count))


class MergeLimitWithSort(Rule):
    """Limit(k, Sort(x)) -> TopN(k, x)
    (reference: rule/MergeLimitWithSort.java — the TopN rewrite)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Sort))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        return P.TopN(child.source, child.keys, node.count)


class PushLimitThroughProject(Rule):
    """Limit(Project(x)) -> Project(Limit(x))
    (reference: rule/PushLimitThroughProject.java)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Project))

    def apply(self, node: P.Limit, ctx):
        child = ctx.resolve(node.source)
        return P.Project(P.Limit(child.source, node.count),
                         dict(child.assignments))


class InlineIdentityProject(Rule):
    """Project that re-emits exactly its input symbols -> source
    (reference: RemoveRedundantIdentityProjections)."""

    pattern = pattern(P.Project)

    def apply(self, node: P.Project, ctx):
        child = ctx.resolve(node.source)
        child_outs = [s for s, _ in child.outputs()]
        if list(node.assignments) != child_outs:
            return None
        for s, e in node.assignments.items():
            if not (isinstance(e, ir.Ref) and e.name == s):
                return None
        return child


class MergeAdjacentProjects(Rule):
    """Project(Project(x)) -> one Project with inlined expressions when
    the inner assignments are pure Refs (reference: InlineProjections)."""

    pattern = pattern(P.Project).with_source(pattern(P.Project))

    def apply(self, node: P.Project, ctx):
        child = ctx.resolve(node.source)
        if not all(isinstance(e, ir.Ref) for e in child.assignments.values()):
            return None
        mapping = dict(child.assignments)
        new_assigns = {s: ir.substitute(e, mapping)
                       for s, e in node.assignments.items()}
        return P.Project(child.source, new_assigns)


DEFAULT_RULES: List[Rule] = [
    MergeFilters(), RemoveTrivialFilter(), MergeLimits(),
    MergeLimitWithSort(), PushLimitThroughProject(),
    InlineIdentityProject(), MergeAdjacentProjects(),
]
