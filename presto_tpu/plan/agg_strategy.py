"""Adaptive aggregation economics (ROADMAP item 2, docs/PERF.md round 17).

Two papers point at the same gap in a static two-phase GROUP BY
pipeline.  *Partial Partial Aggregates*: partial aggregation should
disable itself per-partition when it is not reducing rows — a
high-cardinality GROUP BY (q67-class) pays a full per-chunk group-build
whose output is the size of its input.  *Global Hash Tables Strike
Back!*: a single global table beats partitioned two-phase aggregation
far more often than folklore says — a low-NDV unsorted input wants ONE
grouping pass, not a partial stage plus a merge.

This module is the one place that decides HOW a GROUP BY aggregates:

1. **Planner strategy** (``annotate``): every grouped SINGLE Aggregate
   is stamped with ``agg_strategy``:

   - ``one_pass``   — the input is presorted on a safe leading group key
     (plan/properties.py ``ordering_hint_safe``): the PR-3 run-boundary
     scan groups in one pass with no sort, so no partial stage is ever
     worth planning;
   - ``final_only`` — the NDV estimate (``capacity_hint`` from
     annotate_static_hints) is small and the input visibly reduces:
     distribution routes rows to their group's shard and aggregates
     ONCE (the global-table route) — no partial stage planned at all;
   - ``two_phase``  — high/unknown NDV keeps the partial→final split,
     with the runtime bypass below armed.

   The annotation is a plain string attribute, so it rides plan serde
   and fragment cutting to cluster workers unchanged.

2. **Runtime bypass** (``FlipState`` + the pass-through transform):
   during chunked and cluster execution the partial stage's reduction
   ratio (live rows in / groups out) is monitored; when it stays below
   ``partial_agg_min_reduction`` the partial stage flips to
   PASS-THROUGH — each input row is projected straight into the
   partial-output schema (count→0/1, sum→x, avg→(x,1), …) and streams
   to the final stage, skipping the per-chunk group-build entirely.
   The flip is per-fragment, hysteresis-guarded (``FLIP_STRIKES``
   consecutive bad windows to flip, ``REENABLE_FACTOR`` headroom to
   flip back), revisitable (a periodic probe chunk re-measures the
   ratio), and checksum-neutral — the final stage re-groups whatever
   mix of grouped partials and raw rows arrives.

Kill switches: session property ``adaptive_partial_agg`` (default on)
and env ``PRESTO_TPU_ADAPTIVE_AGG=off``.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Dict, Optional

from presto_tpu import types as T
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P

_KILL_ENV = "PRESTO_TPU_ADAPTIVE_AGG"

# strategy names (the QueryStats.agg_strategy counter keys)
ONE_PASS = "one_pass"
FINAL_ONLY = "final_only"
TWO_PHASE = "two_phase"
SKETCH = "sketch"

# sketch aggregate family: fixed-width mergeable device states (HLL
# registers / KLL summaries / deterministic samples).  Their partials
# NEVER overflow — the state is O(1) per group regardless of input
# cardinality — so the bypass/hysteresis economics above do not apply:
# a sketch partial is ALWAYS worth keeping, and distribution never cuts
# a hash-repartition edge for a sketch-only aggregate (the merge is one
# elementwise collective over registers, see plan/distribute.py).
SKETCH_FNS = frozenset({"approx_distinct", "approx_percentile",
                        "approx_count", "approx_sum"})


def sketch_fns(node: P.Aggregate) -> frozenset:
    """The sketch-family fns this Aggregate uses (empty when none)."""
    return frozenset(a.fn for a in node.aggs.values()) & SKETCH_FNS

# hysteresis constants (module-level, not session knobs: the knob that
# matters — the reduction threshold — is partial_agg_min_reduction;
# these only shape how fast decisions move)
FLIP_STRIKES = 2        # consecutive bad windows before flipping
REENABLE_FACTOR = 2.0   # re-enable needs min_reduction * this headroom
RATIO_WINDOW = 4        # chunks per ratio observation window
RECHECK_EVERY = 16      # while bypassed, probe the grouped lane every N


def enabled(session) -> bool:
    """Master switch for BOTH the planner strategy choice and the
    runtime bypass (property default on, env kill outranks)."""
    if os.environ.get(_KILL_ENV, "").lower() in ("off", "0", "false"):
        return False
    return bool(session.properties.get("adaptive_partial_agg", True))


def min_reduction(session) -> float:
    """Rows-in / groups-out below this and the partial stage is not
    paying for itself (default measured by the tools/roofline.py `agg`
    sweep: the two-phase-vs-final-only crossover sits near 1.3x on CPU
    and well under 2x on chip — see docs/PERF.md round 17)."""
    return float(session.properties.get("partial_agg_min_reduction", 1.3))


def final_only_max_groups(session) -> int:
    """NDV-estimate ceiling for the planner's final_only (global table)
    route — above it the estimate is too coarse to bet the exchange
    volume on, and two_phase + runtime bypass adapts instead."""
    return int(session.properties.get("agg_final_only_max_groups", 4096))


# ---------------------------------------------------------------------------
# planner strategy choice
# ---------------------------------------------------------------------------

def choose(node: P.Aggregate, session) -> str:
    """Pick the aggregation strategy for one grouped Aggregate from the
    plan/properties.py ordering facts and the annotate_static_hints NDV
    estimates.  Presorted wins unconditionally; a confidently-small NDV
    with real reduction routes final-only; everything else keeps
    two-phase with the runtime bypass armed."""
    if sketch_fns(node):
        # fixed-width mergeable states: the partial stage never
        # overflows and never loses, regardless of NDV — keep it
        # unconditionally and keep the capacity check out of the way
        # (a FINAL_ONLY stamp would route the hash-repartition edge the
        # sketch exists to delete)
        return SKETCH
    if getattr(node, "ordering_hint", None) is not None \
            and getattr(node, "ordering_hint_safe", False):
        # run-boundary one-pass grouping: no sort, no partial stage
        return ONE_PASS
    cap = getattr(node, "capacity_hint", None)
    if cap and cap <= final_only_max_groups(session):
        # confidently small group table: one global grouping pass
        # (distribution adds a skew floor — see distribute.py — so a
        # near-degenerate key set still rides the tiny-partial split)
        return FINAL_ONLY
    return TWO_PHASE


def annotate(plan: P.QueryPlan, session) -> None:
    """Stamp ``agg_strategy`` on every grouped SINGLE Aggregate.  Runs
    after plan/properties.annotate (needs ordering_hint) and
    annotate_static_hints (needs capacity/input estimates)."""
    if not enabled(session):
        return
    seen: set = set()

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for s in node.sources:
            walk(s)
        if isinstance(node, P.Aggregate) and node.group_keys \
                and node.step == "SINGLE":
            node.agg_strategy = choose(node, session)

    walk(plan.root)
    for sub in plan.subplans.values():
        walk(sub)


# ---------------------------------------------------------------------------
# pass-through transform: a PARTIAL Aggregate as a per-row Project
# ---------------------------------------------------------------------------

def _row_expr(a: ir.AggCall) -> Optional[ir.RowExpr]:
    """The per-row expression whose FINAL-stage fold equals the original
    aggregate over raw rows, or None when the partial has no row form
    (the fragment is then not bypassable).  FILTER/DISTINCT partials
    are excluded — DISTINCT never reaches a PARTIAL split, and a FILTER
    needs a null-injecting conditional we do not emit today."""
    if a.distinct or a.filter is not None:
        return None
    fn = a.fn
    if fn == "count" and not a.args:
        return ir.Lit(1, a.type)  # count(*): every live row counts one
    if fn in ("count", "count_if") and a.args:
        arg = a.args[0]
        one, zero = ir.Lit(1, a.type), ir.Lit(0, a.type)
        if fn == "count_if":
            return ir.Call("if", (arg, one, zero), a.type)
        # count(x): non-null rows count one (final merge_count sums)
        return ir.Call(
            "if", (ir.Call("is_null", (arg,), T.BOOLEAN), zero, one),
            a.type)
    if fn in ("sum", "min", "max", "bool_and", "every", "bool_or",
              "arbitrary", "any_value", "min_by", "max_by"):
        arg = a.args[0]
        at = getattr(arg, "type", None)
        if at is not None and at != a.type:
            return ir.CastExpr(arg, a.type)
        return arg  # nulls stay null; the final fold skips them
    if fn == "partial_sum_double":
        return ir.CastExpr(a.args[0], T.DOUBLE)
    if fn == "partial_sum_sq_double":
        x = ir.CastExpr(a.args[0], T.DOUBLE)
        return ir.Call("mul", (x, x), T.DOUBLE)
    return None


def passthrough_project(node: P.Aggregate) -> Optional[P.Project]:
    """The pass-through lane for a PARTIAL Aggregate: a Project over the
    SAME source emitting the partial-output schema per row.  Returns
    None when any aggregate has no row form."""
    if node.step != "PARTIAL" or not node.group_keys:
        return None
    src_types = dict(node.source.outputs())
    assigns: Dict[str, ir.RowExpr] = {}
    for k in node.group_keys:
        t = src_types.get(k)
        if t is None:
            return None
        assigns[k] = ir.Ref(k, t)
    for sym, a in node.aggs.items():
        e = _row_expr(a)
        if e is None:
            return None
        assigns[sym] = e
    return P.Project(node.source, assigns)


def bypassable(node) -> bool:
    return isinstance(node, P.Aggregate) \
        and passthrough_project(node) is not None


def find_partial_agg(root) -> Optional[P.Aggregate]:
    """The PARTIAL Aggregate on a fragment's root chain (through
    Output/Project/Filter wrappers), or None.  Aggregates buried below
    joins are not monitored — their output does not feed the consumer
    exchange directly, so bypassing them would not shrink anything the
    monitor can see."""
    node = root
    while isinstance(node, (P.Output, P.Project, P.Filter)):
        node = node.source
    if isinstance(node, P.Aggregate) and node.step == "PARTIAL" \
            and node.group_keys:
        return node
    return None


def bypass_root(root):
    """A copy of the fragment root chain with the PARTIAL Aggregate
    swapped for its pass-through Project; the subtree BELOW the
    aggregate is shared (scan node identities survive, which the
    chunked runner's scan_inputs keying relies on).  None when the
    chain has no bypassable partial."""
    agg = find_partial_agg(root)
    if agg is None:
        return None
    proj = passthrough_project(agg)
    if proj is None:
        return None

    def rebuild(node):
        if node is agg:
            return proj
        clone = copy.copy(node)  # keeps optimizer hint instance-attrs
        clone.source = rebuild(node.source)
        return clone

    return rebuild(root) if root is not agg else proj


# ---------------------------------------------------------------------------
# runtime flip state (per partial-aggregate, hysteresis-guarded)
# ---------------------------------------------------------------------------

class FlipState:
    """Hysteresis-guarded bypass decision for ONE partial aggregate.

    observe() feeds one reduction-ratio measurement (rows in / groups
    out); FLIP_STRIKES consecutive measurements under the threshold
    flip the stage to pass-through, and a recovered ratio (threshold x
    REENABLE_FACTOR, measured by periodic grouped probes) flips it
    back.  Events are returned so callers count flips into QueryStats
    (partial_aggs_bypassed / partial_aggs_reenabled)."""

    __slots__ = ("bypassed", "strikes", "served", "last_ratio")

    def __init__(self):
        self.bypassed = False
        self.strikes = 0
        self.served = 0  # bypassed serves since the last grouped probe
        self.last_ratio = 0.0

    def probe_due(self) -> bool:
        """While bypassed: route this execution/chunk through the
        grouped lane to re-measure the ratio?"""
        return self.bypassed and self.served >= RECHECK_EVERY

    def note_bypassed(self) -> None:
        self.served += 1

    def observe(self, ratio: float, threshold: float) -> str:
        """Feed one grouped-lane measurement; returns "" | "flipped" |
        "reenabled"."""
        self.last_ratio = float(ratio)
        if self.bypassed:
            self.served = 0  # this was the periodic probe
            if ratio >= threshold * REENABLE_FACTOR:
                self.bypassed = False
                self.strikes = 0
                return "reenabled"
            return ""
        if ratio < threshold:
            self.strikes += 1
            if self.strikes >= FLIP_STRIKES:
                self.bypassed = True
                self.strikes = 0
                self.served = 0
                return "flipped"
        else:
            self.strikes = 0
        return ""


def node_fingerprint(node: P.Aggregate) -> str:
    """Stable identity of a partial aggregate across executors, runs and
    (decoded) cluster task fragments: group keys + aggregate signatures.
    Deliberately NOT cached on the node — a cached attribute would ride
    plan serde and perturb fragment fingerprints depending on whether
    the flip state was consulted before or after fragment cutting."""
    aggs = sorted((sym, a.fn, len(a.args),
                   str(getattr(a.args[0], "type", "")) if a.args else "")
                  for sym, a in node.aggs.items())
    return json.dumps([list(node.group_keys), aggs], sort_keys=True)


def flip_state(session, node: P.Aggregate) -> Optional[FlipState]:
    """The session-scoped FlipState for a bypassable PARTIAL aggregate
    (None when not bypassable).  Cluster workers hold their own session
    per process, so the state — and the ratio it tracks — is per-task
    by construction; the decision's counters ride task status back to
    the coordinator."""
    if not bypassable(node):
        return None
    states = getattr(session, "_agg_flip_states", None)
    if states is None:
        states = session._agg_flip_states = {}
    fp = node_fingerprint(node)
    st = states.get(fp)
    if st is None:
        st = states[fp] = FlipState()
    return st
