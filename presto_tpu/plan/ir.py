"""Typed row-expression IR.

Reference parity: presto-spi/.../spi/relation/ (RowExpression: CallExpression,
ConstantExpression, InputReferenceExpression, SpecialFormExpression) plus the
translator sql/relational/SqlToRowExpressionTranslator.java.  The analyzer
emits this IR; the executor traces it straight into jaxprs (the role the
reference fills with JVM bytecode generation, sql/gen/ExpressionCompiler).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

from presto_tpu.types import Type


class RowExpr:
    type: Type

    def walk(self):
        yield self
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, RowExpr):
                yield from v.walk()
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, RowExpr):
                        yield from x.walk()

    def refs(self) -> set:
        """Free column references (lambda-bound params excluded)."""
        out = set()

        def visit(e):
            if isinstance(e, Ref):
                out.add(e.name)
                return
            if isinstance(e, LambdaExpr):
                out.update(e.body.refs() - set(e.params))
                return
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, RowExpr):
                    visit(v)
                elif isinstance(v, tuple):
                    for x in v:
                        if isinstance(x, RowExpr):
                            visit(x)

        visit(self)
        return out


@dataclass(frozen=True)
class Ref(RowExpr):
    name: str  # symbol name in the containing plan node's input
    type: Type

    def __str__(self):
        return self.name


@dataclass(frozen=True)
class Lit(RowExpr):
    value: object
    type: Type

    def __str__(self):
        return repr(self.value)


@dataclass(frozen=True)
class Call(RowExpr):
    fn: str  # function registry key, e.g. 'add', 'eq', 'like', 'substring'
    args: Tuple[RowExpr, ...]
    type: Type

    def __str__(self):
        return f"{self.fn}({', '.join(str(a) for a in self.args)})"


@dataclass(frozen=True)
class CastExpr(RowExpr):
    arg: RowExpr
    type: Type
    safe: bool = False

    def __str__(self):
        return f"CAST({self.arg} AS {self.type})"


@dataclass(frozen=True)
class Param(RowExpr):
    """A bound prepared-statement parameter (reference: spi/relation has
    no analog — the reference rewrites parameters to constants at
    analysis; we keep them SYMBOLIC so the plan and its compiled
    executable are value-free).  Evaluation reads
    `EvalContext.params[position]`: a host scalar in dynamic mode, a
    traced 0-d device scalar in compiled mode (the same channel
    ScalarSub uses for distributed subquery values) — so parameter
    binding is a dict lookup plus device transfer, never a retrace."""

    position: int
    type: Type

    def __str__(self):
        return f"$param_{self.position}"


@dataclass(frozen=True)
class ScalarSub(RowExpr):
    """Uncorrelated scalar subquery, referencing a pre-evaluated subplan.
    (Reference: EnforceSingleRowNode + uncorrelated Apply — here the
    executor evaluates subplan DAG nodes before the fragments that use
    them, which is exactly a REMOTE gather exchange in the reference.)"""

    plan_id: int
    type: Type

    def __str__(self):
        return f"$subquery_{self.plan_id}"


@dataclass(frozen=True)
class LambdaExpr(RowExpr):
    """A typed lambda passed to a higher-order function (reference:
    spi/relation LambdaDefinitionExpression).  `params` are fresh symbols
    bound over `body`; free refs beyond them are captures of the enclosing
    row."""

    params: Tuple[str, ...]
    param_types: Tuple[Type, ...]
    body: RowExpr
    type: Type  # FUNCTION(body.type)

    def __str__(self):
        return f"({', '.join(self.params)}) -> {self.body}"


@dataclass(frozen=True)
class AggCall:
    fn: str
    args: Tuple[RowExpr, ...]
    type: Type
    distinct: bool = False
    filter: Optional[RowExpr] = None
    # window value functions only (lag/lead/first/last/nth_value):
    # IGNORE NULLS (reference: sql/tree/FunctionCall nullTreatment)
    ignore_nulls: bool = False

    def __str__(self):
        d = "DISTINCT " if self.distinct else ""
        return f"{self.fn}({d}{', '.join(str(a) for a in self.args)})"


def substitute(expr: RowExpr, mapping: dict) -> RowExpr:
    """Replace Refs by name -> RowExpr."""
    if isinstance(expr, Ref):
        return mapping.get(expr.name, expr)
    if isinstance(expr, Call):
        return Call(expr.fn, tuple(substitute(a, mapping) for a in expr.args), expr.type)
    if isinstance(expr, CastExpr):
        return CastExpr(substitute(expr.arg, mapping), expr.type, expr.safe)
    if isinstance(expr, LambdaExpr):
        # params are allocator-fresh symbols, so they can't collide with keys
        return LambdaExpr(expr.params, expr.param_types,
                          substitute(expr.body, mapping), expr.type)
    return expr


def conjuncts(expr: Optional[RowExpr]) -> list:
    """Flatten nested ANDs."""
    if expr is None:
        return []
    if isinstance(expr, Call) and expr.fn == "and":
        out = []
        for a in expr.args:
            out.extend(conjuncts(a))
        return out
    return [expr]


def combine_conjuncts(exprs) -> Optional[RowExpr]:
    from presto_tpu.types import BOOLEAN

    exprs = [e for e in exprs if e is not None]
    if not exprs:
        return None
    out = exprs[0]
    for e in exprs[1:]:
        out = Call("and", (out, e), BOOLEAN)
    return out
