"""Plan-fragment and value serialization for the cluster wire.

Reference: the reference ships plan fragments between coordinator and
workers as JSON via Jackson (server/remotetask/HttpRemoteTask.java:591,
PlanFragment's @JsonCreator constructors) — executing a task never
involves deserializing arbitrary code.  This module gives the engine the
same property: a tagged JSON encoding whose decoder instantiates ONLY
whitelisted plan/IR dataclasses, replacing the pickled fragments the
round-4 review flagged (pickle.loads of network bytes == remote code
execution gated only by the HMAC secret).

Encoding:
  scalars      -> native JSON (int/float/str/bool/None)
  bytes        -> {"$b": base64}
  Decimal      -> {"$d": str}
  tuple        -> {"$t": [...]}
  set/frozenset-> {"$s"/"$fs": [...]}
  dict         -> {"$m": [[k, v], ...]}  (keys keep their types)
  nan/inf      -> {"$f": "nan"|"inf"|"-inf"}
  dataclass    -> {"$n": "ClassName", "f": {attr: value, ...}}
                  (the full __dict__, so optimizer annotations like
                  scan_domains / index_lookup / key_stats survive)

Decoding uses cls.__new__ + __dict__.update — no constructors run, no
callables are ever encoded, unknown class names are an error.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import math
from decimal import Decimal

import numpy as np


def _registry():
    from presto_tpu import types as T
    from presto_tpu.plan import ir
    from presto_tpu.plan import nodes as P
    from presto_tpu.plan import stats as S
    from presto_tpu.storage.shard import Domain

    classes = [T.Type, S.ColStats, Domain,
               ir.Ref, ir.Lit, ir.Call, ir.CastExpr, ir.ScalarSub,
               ir.Param, ir.LambdaExpr, ir.AggCall]
    for name in dir(P):
        obj = getattr(P, name)
        if isinstance(obj, type) and dataclasses.is_dataclass(obj):
            classes.append(obj)
    return {c.__name__: c for c in classes}


_REGISTRY = None


def _classes():
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = _registry()
    return _REGISTRY


def register_class(cls) -> None:
    """Whitelist an additional dataclass (e.g. the cluster TaskSpec)."""
    assert dataclasses.is_dataclass(cls)
    _classes()[cls.__name__] = cls


def encode(v):
    if v is None or isinstance(v, (bool, str)):
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        v = float(v)
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        if math.isnan(v):
            return {"$f": "nan"}
        if math.isinf(v):
            return {"$f": "inf" if v > 0 else "-inf"}
        return v
    if isinstance(v, (bytes, bytearray, np.void)):
        return {"$b": base64.b64encode(bytes(v)).decode("ascii")}
    if isinstance(v, Decimal):
        return {"$d": str(v)}
    if isinstance(v, tuple):
        return {"$t": [encode(x) for x in v]}
    if isinstance(v, list):
        return [encode(x) for x in v]
    if isinstance(v, frozenset):
        return {"$fs": [encode(x) for x in sorted(v, key=repr)]}
    if isinstance(v, set):
        return {"$s": [encode(x) for x in sorted(v, key=repr)]}
    if isinstance(v, dict):
        return {"$m": [[encode(k), encode(x)] for k, x in v.items()]}
    if isinstance(v, np.ndarray):  # e.g. Values rows ingested from numpy
        return {"$t": [encode(x) for x in v.tolist()]}
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        name = type(v).__name__
        if name not in _classes():
            raise TypeError(f"cannot serialize plan object {name}")
        return {"$n": name,
                "f": {k: encode(x) for k, x in vars(v).items()}}
    if isinstance(v, np.generic):
        return encode(v.item())
    raise TypeError(f"cannot serialize {type(v).__name__} on the wire")


def decode(j):
    if j is None or isinstance(j, (bool, int, float, str)):
        return j
    if isinstance(j, list):
        return [decode(x) for x in j]
    if isinstance(j, dict):
        if "$f" in j:
            return {"nan": math.nan, "inf": math.inf,
                    "-inf": -math.inf}[j["$f"]]
        if "$b" in j:
            return base64.b64decode(j["$b"])
        if "$d" in j:
            return Decimal(j["$d"])
        if "$t" in j:
            return tuple(decode(x) for x in j["$t"])
        if "$s" in j:
            return set(decode(x) for x in j["$s"])
        if "$fs" in j:
            return frozenset(decode(x) for x in j["$fs"])
        if "$m" in j:
            return {decode(k): decode(x) for k, x in j["$m"]}
        if "$n" in j:
            cls = _classes().get(j["$n"])
            if cls is None:
                raise ValueError(f"unknown plan class {j['$n']!r}")
            fields = j.get("f")
            if not isinstance(fields, dict):  # hostile/malformed body
                raise ValueError(f"bad fields for {j['$n']!r}")
            obj = cls.__new__(cls)
            obj.__dict__.update(
                {k: decode(x) for k, x in fields.items()})
            return obj
    raise ValueError(f"bad wire value {type(j).__name__}")


def dumps(obj) -> bytes:
    return json.dumps(encode(obj), separators=(",", ":"),
                      allow_nan=False).encode("utf-8")


def loads(buf: bytes):
    return decode(json.loads(buf.decode("utf-8")))
