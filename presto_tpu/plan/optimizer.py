"""Logical plan optimizer.

Reference parity: sql/planner/PlanOptimizers.java (~40 passes, 87 iterative
rules).  Round-1 set, the ones correctness/feasibility actually require:

- predicate pushdown + cross-join elimination (reference: PredicatePushDown
  + EliminateCrossJoins): implicit-join queries arrive as CROSS-join trees
  under a Filter; we collect the join graph and greedily re-assemble
  equi-joins from equality conjuncts (a cross join of TPC-H lineitem x
  orders would otherwise materialize ~10^13 rows).
- column pruning (reference: PruneUnreferencedOutputs): scans read only
  referenced columns.
- projection inlining of trivial Ref-only projects.
"""

from __future__ import annotations

from typing import List, Set

from presto_tpu import types as T
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P


def optimize(plan: P.QueryPlan, session) -> P.QueryPlan:
    root = plan.root
    if session.properties.get("prefer_approx_distinct", False):
        # opt-in approximation: count(DISTINCT x) -> approx_distinct(x)
        # (~3.25% std error at 1024 registers) trades exactness for the
        # sketch lane — no hash repartition, fixed-width mergeable
        # state.  Must run BEFORE _optimize_node lowers DISTINCT
        # aggregates into a pre-group.  Counted into
        # QueryStats.approx_rewrites through the compile-accounting
        # sink (planning runs inside CC.recording).
        n = _approx_distinct_rewrites(root)
        for sub in plan.subplans.values():
            n += _approx_distinct_rewrites(sub)
        if n:
            from presto_tpu.exec import compile_cache as CC

            CC._note("approx_rewrites", n)
    subplans = {k: _optimize_node(v, session) for k, v in plan.subplans.items()}
    new_root = _optimize_node(root, session)
    out = P.QueryPlan(new_root, subplans)
    annotate_static_hints(out, session)
    if session.properties.get("prune_fd_group_keys", False):
        # OFF by default: measured on chip (SF1 Q3 517->607ms, Q18
        # 647->687ms), each arbitrary() representative costs a
        # full-capacity reduction pass that outweighs the narrower
        # grouping sort in this executor.  The rewrite itself is
        # correct and tested; revisit if representatives ever ride the
        # grouping sort directly.
        # Needs build_unique from the annotation pass; re-annotate after
        # the rewrite so aggregate capacity hints match the new keys
        changed = _prune_fd_group_keys(out.root, set())
        for sub in out.subplans.values():
            changed |= _prune_fd_group_keys(sub, set())
        if changed:
            annotate_static_hints(out, session)
    if session.properties.get("ordering_aware_execution", True):
        # ordering-properties hints (plan/properties.py): advisory,
        # guard-verified at every exploitation site.  Runs LAST so the
        # hints see the final key lists (fd-pruning may drop keys).
        from presto_tpu.plan import properties as OP

        OP.annotate(out, session)
    # dynamic filtering (plan/runtime_filters.py): wire build-side
    # runtime-filter producers to probe-side scan consumers.  After the
    # structural passes so the join tree and scan assignments are final;
    # the annotations are advisory and survive fragment serde.
    from presto_tpu.plan import runtime_filters as RF

    RF.annotate(out, session)
    # aggregation strategy (plan/agg_strategy.py): one_pass / final_only
    # / two_phase per grouped Aggregate, from the ordering facts and NDV
    # estimates the passes above just attached.  distribute() and the
    # executor consume it; the string annotation rides fragment serde.
    from presto_tpu.plan import agg_strategy as AS

    AS.annotate(out, session)
    return out


def _approx_distinct_rewrites(node: P.PlanNode) -> int:
    """Replace count(DISTINCT x) aggregates with approx_distinct(x),
    returning how many calls were rewritten.  Only hashable scalar
    types rewrite (hll_hash64's domain); everything else keeps the
    exact dedup path."""
    n = 0
    if isinstance(node, P.Aggregate):
        for s, a in list(node.aggs.items()):
            if a.fn == "count" and a.distinct and len(a.args) == 1:
                t = a.args[0].type
                if t.is_numeric or t.is_string or t.name in (
                        "DATE", "TIMESTAMP", "BOOLEAN"):
                    node.aggs[s] = ir.AggCall(
                        "approx_distinct", a.args, T.BIGINT, False,
                        a.filter)
                    n += 1
    for src in node.sources:
        n += _approx_distinct_rewrites(src)
    return n


def _prune_fd_group_keys(node: P.PlanNode, seen: set) -> bool:
    """Group keys functionally determined through a unique-build join
    collapse to arbitrary() aggregates: grouping by (l_orderkey,
    o_orderdate, o_shippriority) over lineitem JOIN orders-unique-on-
    orderkey sorts ONE key instead of three and gathers representatives
    at the group bound (reference: the unique-constraint-driven
    grouping-key pruning in newer optimizers; correctness is the FD
    through AggregationNode semantics — within a group of the join key
    the unique build row, and so every build column, is constant;
    LEFT-join groups are uniformly matched or uniformly null-extended).
    Mutates Aggregates in place; returns whether anything changed."""
    if id(node) in seen:
        return False
    seen.add(id(node))
    changed = False
    for s in node.sources:
        changed |= _prune_fd_group_keys(s, seen)
    if not isinstance(node, P.Aggregate) or node.step != "SINGLE" \
            or len(node.group_keys) < 2:
        return changed
    # walk identity projections down to the join, tracking renames
    maps = []
    cur = node.source
    while isinstance(cur, P.Project):
        maps.append({s: (e.name if isinstance(e, ir.Ref) else None)
                     for s, e in cur.assignments.items()})
        cur = cur.source
    if not isinstance(cur, P.Join) \
            or cur.join_type not in ("INNER", "LEFT") \
            or len(cur.criteria) != 1 or cur.filter is not None \
            or not getattr(cur, "build_unique", False):
        return changed
    lk, rk = cur.criteria[0]
    build_syms = {s for s, _ in cur.right.outputs()}

    def base(sym):
        s = sym
        for m in maps:
            s = m.get(s)
            if s is None:
                return None
        return s

    keys_base = {k: base(k) for k in node.group_keys}
    anchors = [k for k, b in keys_base.items()
               if b == lk or (cur.join_type == "INNER" and b == rk)]
    if not anchors:
        return changed
    anchor = anchors[0]
    fd = [k for k in node.group_keys
          if k != anchor and keys_base.get(k) in build_syms]
    if not fd:
        return changed
    types = dict(node.source.outputs())
    node.group_keys = [k for k in node.group_keys if k not in fd]
    for k in fd:
        node.aggs[k] = ir.AggCall("arbitrary", (ir.Ref(k, types[k]),),
                                  types[k])
    return True


def annotate_static_hints(plan: P.QueryPlan, session) -> None:
    """Attach stats-derived static-shape hints used by the compiled
    executor: group capacities, key ranges, join build-uniqueness and
    fanout bounds (plan/stats.py docstring explains why)."""
    from presto_tpu.plan import stats as S

    catalog = getattr(session, "catalog", None)
    if catalog is None:
        return
    memo = {}

    def annotate(node):
        for s in node.sources:
            annotate(s)
        try:
            if isinstance(node, P.Aggregate):
                src = S.derive(node.source, catalog, memo)
                node.capacity_hint = S.capacity_for_groups(node, src)
                node.key_stats = {k: src.cols.get(k) for k in node.group_keys}
                # selectivity ESTIMATE of the input (not the sound upper
                # bound): drives the guarded pre-aggregation compaction
                # in the static executor
                node.input_est_hint = int(src.est_rows)
            elif isinstance(node, P.Join) and node.join_type not in ("CROSS",):
                ls = S.derive(node.left, catalog, memo)
                rs = S.derive(node.right, catalog, memo)
                # estimate hints for guarded join-input compaction
                node.left_est_hint = int(ls.est_rows)
                node.right_est_hint = int(rs.est_rows)
                rkeys = frozenset(rk for _, rk in node.criteria)
                node.build_unique = any(u <= rkeys for u in rs.unique)
                best = S._best_fanout_key(rs, rkeys)
                node.fanout_bound = rs.fanout.get(best) if best else None
                if node.fanout_bound is None:
                    node.fanout_bound = \
                        S.speculative_fanout_bound(rs, node.criteria)
                node.key_stats = {}
                for lk, rk in node.criteria:
                    node.key_stats[lk] = ls.cols.get(lk)
                    node.key_stats[rk] = rs.cols.get(rk)
                node.index_lookup = _index_lookup_info(node, catalog)
        except Exception:
            pass  # hints are optional; executor falls back to dynamic mode

    annotate(plan.root)
    for sub in plan.subplans.values():
        annotate(sub)


def _index_lookup_info(node: P.Join, catalog):
    """P10 index joins, TPU-native: when the build (right) side is a
    resident table whose single join key is a DENSE unique integer key
    (surrogate keys: tpch nation/part/customer, tpcds date_dim/item...),
    the probe lowers to ONE gather — position = key - key_min — instead
    of the three sorts of build_probe.  Reference:
    sql/planner/optimizations/IndexJoinOptimizer.java planning
    IndexJoinNode probes against a connector index (operator/index/
    IndexLoader); here the "index" is the identity layout of a dense
    surrogate key, the natural connector index on TPU.

    Returns {"min", "rows"} or None.  Sound preconditions: the build
    subtree is Filter/Project-over-TableScan ONLY (row positions reach
    the join unchanged — filters mask sel, never compact), the key is an
    identity Ref of the scan's dense unique column, and the executor
    additionally verifies gathered key == probe key in-trace, so stale
    stats degrade to no-match on rows a sort join would also not match.
    """
    if len(node.criteria) != 1:
        return None
    if node.join_type not in ("INNER", "LEFT", "SEMI", "ANTI", "MARK"):
        return None
    if node.filter is not None and node.join_type not in ("INNER", "LEFT"):
        return None  # filtered SEMI/ANTI take the expanding path
    sym = node.criteria[0][1]
    cur = node.right
    while True:
        if isinstance(cur, P.Filter):
            cur = cur.source
        elif isinstance(cur, P.Project):
            e = cur.assignments.get(sym)
            if not isinstance(e, ir.Ref):
                return None
            sym = e.name
            cur = cur.source
        elif isinstance(cur, P.Join) and sym in {
                s for s, _ in cur.left.outputs()} and (
                cur.join_type in ("SEMI", "ANTI", "MARK")
                or (cur.join_type in ("INNER", "LEFT")
                    and getattr(cur, "index_lookup", None) is not None)):
            # probe-layout-preserving joins (this executor masks the
            # probe in place for SEMI/ANTI/MARK and for index joins):
            # the key column still sits at its natural scan positions.
            # Runtime layout verification in the executor guards the
            # cases where the inner join takes a re-ordering fallback.
            cur = cur.left
        else:
            break
    if not isinstance(cur, P.TableScan):
        return None
    col = cur.assignments.get(sym)
    if col is None:
        return None
    try:
        t = catalog.get(cur.table)
    except KeyError:
        return None
    if not hasattr(t, "unique_keys") or (col,) not in \
            [tuple(k) for k in t.unique_keys()]:
        return None
    typ = cur.types.get(sym)
    if typ is None or not typ.is_integer:
        return None
    cs = t.column_stats(col) if hasattr(t, "column_stats") else None
    rows = t.row_count()
    if rows == 0:
        return None
    if cs is not None and cs.min is not None and cs.max is not None \
            and cs.ndv == rows and int(cs.max) - int(cs.min) + 1 == rows:
        # dense surrogate key: identity layout
        return {"min": int(cs.min), "rows": int(rows),
                "block_keys": 1, "block_rows": 1}
    # sparse-but-invertible generator layouts (dbgen orderkey: 8 keys
    # per 32-key block) — the connector declares the closed form
    layout = t.key_layout(col) if hasattr(t, "key_layout") else None
    if layout is not None:
        base, bk, br = layout
        return {"min": int(base), "rows": int(rows),
                "block_keys": int(bk), "block_rows": int(br)}
    return None


def _optimize_node(node: P.PlanNode, session) -> P.PlanNode:
    node = _rewrite(node, session)
    node = prune_columns(node, set(n for n, _ in node.outputs()))
    if session.properties.get("iterative_optimizer_enabled", True):
        from presto_tpu.plan.iterative import (DEFAULT_RULES,
                                               IterativeOptimizer,
                                               ReorderJoins)

        rules = list(DEFAULT_RULES)
        if session.properties.get("reorder_joins", True):
            # cost-based join enumeration inside the memo (reference:
            # rule/ReorderJoins.java replacing the greedy order)
            rules.append(ReorderJoins(session))
        node = IterativeOptimizer(rules).optimize(node)
    node = _pushdown_connector_predicates(node, session)
    node = _extract_spatial_joins(node)
    # re-prune: a pushed-down predicate leaves its original string column
    # unreferenced in the scan — dropping it is the whole point (the
    # column never materializes)
    node = prune_columns(node, set(n for n, _ in node.outputs()))
    # AFTER pruning: the inferred semi join shares its subquery subtree
    # with the original (a DAG prune_columns would split back into two).
    # Chunked execution plans with this OFF: per-chunk capacities dwarf
    # whole-table estimates, so the extra probe-side semi never enables
    # compaction there and is pure added work per chunk program.
    if session.properties.get("transitive_semijoin_inference", True):
        node = infer_transitive_semijoins(node)
    return node


def _pushdown_connector_predicates(node: P.PlanNode, session) -> P.PlanNode:
    """Rewrite connector-evaluable predicates into virtual scan columns
    (reference: predicate pushdown into the connector via TupleDomain /
    PickTableLayout + ConnectorMetadata).  A conjunct like
    `p_name LIKE '%green%'` over a generator connector becomes a BOOLEAN
    column the connector computes natively on device — the string column
    itself never materializes."""
    catalog = getattr(session, "catalog", None)
    if catalog is None:
        return node
    for attr in ("source", "left", "right"):
        if hasattr(node, attr):
            setattr(node, attr, _pushdown_connector_predicates(
                getattr(node, attr), session))
    if isinstance(node, P.Union):
        node.sources_ = [_pushdown_connector_predicates(s, session)
                         for s in node.sources_]
    if not (isinstance(node, P.Filter)
            and isinstance(node.source, P.TableScan)):
        return node
    scan = node.source
    try:
        table = catalog.get(scan.table)
    except KeyError:
        return node
    if getattr(table, "supports_domain_pushdown", False):
        # TupleDomain-style stats pruning: attach per-column domains to
        # the scan for the reader to prune stripes/row groups (advisory
        # — the Filter stays; reference: PickTableLayout pushing the
        # TupleDomain into the connector's table layout)
        from presto_tpu.plan.domains import (
            domains_from_conjuncts,
            domains_pickle_safe,
        )

        doms = domains_from_conjuncts(
            ir.conjuncts(node.predicate), scan.assignments)
        if doms:
            scan.scan_domains = domains_pickle_safe(doms)
    hook = getattr(table, "pushdown_like", None)
    if hook is None:
        return node
    conjs = list(ir.conjuncts(node.predicate))
    changed = False
    for i, c in enumerate(conjs):
        if not (isinstance(c, ir.Call) and c.fn == "like"
                and len(c.args) == 2 and isinstance(c.args[0], ir.Ref)
                and isinstance(c.args[1], ir.Lit)):
            continue
        col = scan.assignments.get(c.args[0].name)
        if col is None:
            continue
        vcol = hook(col, str(c.args[1].value))
        if vcol is None:
            continue
        vsym = f"{c.args[0].name}$pushed{i}"
        scan.assignments[vsym] = vcol
        scan.types[vsym] = T.BOOLEAN
        conjs[i] = ir.Ref(vsym, T.BOOLEAN)
        changed = True
    if changed:
        return P.Filter(scan, ir.combine_conjuncts(conjs))
    return node


def _rewrite(node: P.PlanNode, session) -> P.PlanNode:
    # bottom-up
    if isinstance(node, P.Filter):
        src = _rewrite(node.source, session)
        return push_filter(src, ir.conjuncts(node.predicate), session)
    for attr in ("source", "left", "right"):
        if hasattr(node, attr):
            setattr(node, attr, _rewrite(getattr(node, attr), session))
    if isinstance(node, P.Union):
        node.sources_ = [_rewrite(s, session) for s in node.sources_]
    if isinstance(node, P.Join) and node.join_type == "CROSS":
        # cross join with no predicates above — leave as-is
        pass
    return node


def _extract_common_or_conjuncts(conjs: List[ir.RowExpr]) -> List[ir.RowExpr]:
    """`(A and X) or (A and Y)` -> `A and (X or Y)` per conjunct (reference:
    ExtractCommonPredicatesExpressionRewriter).  This is what surfaces the
    join equality in TPC-H Q19's three-armed OR predicate."""
    out: List[ir.RowExpr] = []
    for c in conjs:
        if not (isinstance(c, ir.Call) and c.fn == "or"):
            out.append(c)
            continue
        branches: List[List[ir.RowExpr]] = []

        def collect_or(e):
            if isinstance(e, ir.Call) and e.fn == "or":
                collect_or(e.args[0])
                collect_or(e.args[1])
            else:
                branches.append(ir.conjuncts(e))

        collect_or(c)
        common = [x for x in branches[0]
                  if all(any(x == y for y in b) for b in branches[1:])]
        if not common:
            out.append(c)
            continue
        out.extend(common)
        rest_branches = []
        for b in branches:
            rest = [x for x in b if not any(x == y for y in common)]
            rest_branches.append(ir.combine_conjuncts(rest))
        if any(r is None for r in rest_branches):
            continue  # one branch was exactly the common set -> OR is true given common
        from presto_tpu.types import BOOLEAN

        disj = rest_branches[0]
        for r in rest_branches[1:]:
            disj = ir.Call("or", (disj, r), BOOLEAN)
        out.append(disj)
    return out


def push_filter(node: P.PlanNode, conjs: List[ir.RowExpr], session) -> P.PlanNode:
    """Push filter conjuncts down; turn cross joins + equalities into
    equi-joins (join-graph reassembly)."""
    conjs = _extract_common_or_conjuncts(conjs)
    if not conjs:
        return node
    if isinstance(node, P.Filter):
        return push_filter(node.source, conjs + ir.conjuncts(node.predicate), session)
    if isinstance(node, P.Project):
        if all(isinstance(e, ir.Ref) for e in node.assignments.values()):
            mapping = {s: e for s, e in node.assignments.items()}
            rewritten = [ir.substitute(c, mapping) for c in conjs]
            return P.Project(push_filter(node.source, rewritten, session),
                             node.assignments)
        pushable, kept = [], []
        mapping = {s: e for s, e in node.assignments.items() if isinstance(e, ir.Ref)}
        for c in conjs:
            if c.refs() <= set(mapping):
                pushable.append(ir.substitute(c, mapping))
            else:
                kept.append(c)
        src = push_filter(node.source, pushable, session) if pushable else node.source
        out: P.PlanNode = P.Project(src, node.assignments)
        if kept:
            out = P.Filter(out, ir.combine_conjuncts(kept))
        return out
    if isinstance(node, P.Join) and node.join_type in ("CROSS", "INNER"):
        return _reassemble_join(node, conjs, session)
    if isinstance(node, P.Join) and node.join_type in ("SEMI", "ANTI",
                                                       "LEFT", "MARK"):
        # left rows pass through 1:1 (MARK adds only its bool column),
        # so left-only conjuncts commute with the join
        lsyms = {s for s, _ in node.left.outputs()}
        pushable = [c for c in conjs if c.refs() <= lsyms]
        kept = [c for c in conjs if not (c.refs() <= lsyms)]
        if pushable:
            node.left = push_filter(node.left, pushable, session)
        if kept:
            return P.Filter(node, ir.combine_conjuncts(kept))
        return node
    if isinstance(node, P.Aggregate):
        # push conjuncts that only reference group keys below the agg
        keys = set(node.group_keys)
        pushable = [c for c in conjs if c.refs() <= keys]
        kept = [c for c in conjs if not (c.refs() <= keys)]
        if pushable:
            node.source = push_filter(node.source, pushable, session)
        if kept:
            return P.Filter(node, ir.combine_conjuncts(kept))
        return node
    return P.Filter(node, ir.combine_conjuncts(conjs))


def _flatten_inner_join_tree(node: P.PlanNode, sources: List[P.PlanNode],
                             conjs: List[ir.RowExpr]):
    if isinstance(node, P.Join) and node.join_type in ("CROSS", "INNER") and not node.filter:
        for lk, rk in node.criteria:
            lt = dict(node.left.outputs()).get(lk) or dict(node.right.outputs()).get(lk)
            conjs.append(ir.Call("eq", (ir.Ref(lk, lt), ir.Ref(rk, lt)), None))
        _flatten_inner_join_tree(node.left, sources, conjs)
        _flatten_inner_join_tree(node.right, sources, conjs)
    else:
        sources.append(node)


def _reassemble_join(root: P.Join, conjs: List[ir.RowExpr], session) -> P.PlanNode:
    """Collect the flat source set + all conjuncts, then greedily build a
    left-deep equi-join tree, joining a connected relation each step
    (reference: EliminateCrossJoins; CBO join reordering comes later)."""
    sources: List[P.PlanNode] = []
    all_conjs: List[ir.RowExpr] = list(conjs)
    _flatten_inner_join_tree(root, sources, all_conjs)
    # fix up eq conjuncts created from criteria (type filled from outputs)
    fixed: List[ir.RowExpr] = []
    for c in all_conjs:
        if isinstance(c, ir.Call) and c.type is None:
            from presto_tpu.types import BOOLEAN

            fixed.append(ir.Call(c.fn, c.args, BOOLEAN))
        else:
            fixed.append(c)
    all_conjs = fixed

    src_syms: List[Set[str]] = [{s for s, _ in n.outputs()} for n in sources]

    # push single-source conjuncts into their source
    remaining: List[ir.RowExpr] = []
    for c in all_conjs:
        refs = c.refs()
        placed = False
        for i, syms in enumerate(src_syms):
            if refs <= syms:
                sources[i] = P.Filter(sources[i], c)
                placed = True
                break
        if not placed:
            remaining.append(c)

    # cost-based greedy join order (reference: ReorderJoins — ours is the
    # greedy variant over the selectivity-aware estimates in plan/stats.py):
    # start from the largest-estimate source (the fact table becomes the
    # probe side so hash builds stay small), then repeatedly join the
    # connected source minimizing the estimated output cardinality,
    # tie-breaking toward unique-key builds (FK joins lower to pure
    # gathers on TPU) and then smaller build sides.
    from presto_tpu.plan import stats as S

    catalog = getattr(session, "catalog", None)

    def src_stats(i):
        try:
            return S.derive(sources[i], catalog)
        except Exception:
            return None

    stats_list = [src_stats(i) for i in range(len(sources))]
    rows = [s.rows if s else 1 << 30 for s in stats_list]
    ests = [s.est_rows if s else float(1 << 30) for s in stats_list]
    start = max(range(len(sources)), key=lambda i: ests[i])

    current = sources[start]
    cur_stats = stats_list[start]
    cur_syms = set(src_syms[start])
    todo = [i for i in range(len(sources)) if i != start]
    while todo:
        candidates = []
        for i in todo:
            crits = []
            for c in remaining:
                pair = _equi_pair(c, cur_syms, src_syms[i])
                if pair is not None:
                    crits.append((c, pair))
            if crits:
                rkeys = frozenset(pair[1] for _, pair in crits)
                st = stats_list[i]
                unique_build = bool(st and any(u <= rkeys for u in st.unique))
                if cur_stats is not None and st is not None:
                    out_est = S.join_cardinality(
                        cur_stats, st, [pair for _, pair in crits])
                else:
                    out_est = float(1 << 30)
                candidates.append((out_est, not unique_build, rows[i], i, crits))
        if not candidates:
            i = todo[0]
            current = P.Join(current, sources[i], "CROSS")
            cur_syms |= src_syms[i]
            cur_stats = None
            todo.remove(i)
            continue
        candidates.sort(key=lambda t: (t[0], t[1], t[2]))
        _, _, _, i, crits = candidates[0]
        criteria = [pair for _, pair in crits]
        used = {id(c) for c, _ in crits}
        remaining = [c for c in remaining if id(c) not in used]
        current = P.Join(current, sources[i], "INNER", criteria)
        cur_syms |= src_syms[i]
        todo.remove(i)
        # attach any now-evaluable residual conjuncts as filters right away
        now, remaining = _split(remaining, cur_syms)
        if now:
            current = P.Filter(current, ir.combine_conjuncts(now))
        try:
            cur_stats = S.derive(current, catalog)
        except Exception:
            cur_stats = None
    if remaining:
        current = P.Filter(current, ir.combine_conjuncts(remaining))
    return current


def _split(conjs, syms):
    now = [c for c in conjs if c.refs() <= syms]
    later = [c for c in conjs if not (c.refs() <= syms)]
    return now, later


def _equi_pair(c: ir.RowExpr, lsyms: Set[str], rsyms: Set[str]):
    if not (isinstance(c, ir.Call) and c.fn == "eq"):
        return None
    a, b = c.args
    if not (isinstance(a, ir.Ref) and isinstance(b, ir.Ref)):
        return None
    if a.name in lsyms and b.name in rsyms:
        return (a.name, b.name)
    if b.name in lsyms and a.name in rsyms:
        return (b.name, a.name)
    return None


# ---------------------------------------------------------------------------
# column pruning
# ---------------------------------------------------------------------------


def prune_columns(node: P.PlanNode, required: Set[str]) -> P.PlanNode:
    if isinstance(node, P.TableScan):
        keep = {s: c for s, c in node.assignments.items() if s in required}
        if not keep:  # keep at least one column for row counting
            first = next(iter(node.assignments))
            keep = {first: node.assignments[first]}
        out = P.TableScan(node.table, keep,
                          {s: node.types[s] for s in keep})
        for extra in ("scan_domains", "index_lookup", "build_unique"):
            if hasattr(node, extra):  # dynamic pushdown annotations
                setattr(out, extra, getattr(node, extra))
        return out
    if isinstance(node, P.Values):
        return node
    if isinstance(node, P.Filter):
        need = required | node.predicate.refs()
        return P.Filter(prune_columns(node.source, need), node.predicate)
    if isinstance(node, P.Project):
        keep = {s: e for s, e in node.assignments.items() if s in required}
        if not keep and node.assignments:
            s0 = next(iter(node.assignments))
            keep = {s0: node.assignments[s0]}
        need = set()
        for e in keep.values():
            need |= e.refs()
        return P.Project(prune_columns(node.source, need), keep)
    if isinstance(node, P.Aggregate):
        keep_aggs = {s: a for s, a in node.aggs.items() if s in required}
        need = set(node.group_keys)
        for a in keep_aggs.values():
            for arg in a.args:
                need |= arg.refs()
            if a.filter is not None:
                need |= a.filter.refs()
        return P.Aggregate(prune_columns(node.source, need), node.group_keys,
                           keep_aggs, node.step)
    if isinstance(node, P.Join):
        need_l = set()
        need_r = set()
        lsyms = {s for s, _ in node.left.outputs()}
        rsyms = {s for s, _ in node.right.outputs()}
        for lk, rk in node.criteria:
            need_l.add(lk)
            need_r.add(rk)
        if node.filter is not None:
            for r in node.filter.refs():
                (need_l if r in lsyms else need_r).add(r)
        for r in required:
            if r in lsyms:
                need_l.add(r)
            elif r in rsyms:
                need_r.add(r)
        left = prune_columns(node.left, need_l)
        right = prune_columns(node.right, need_r)
        return P.Join(left, right, node.join_type, node.criteria, node.filter,
                      node.distribution, node.mark)
    if isinstance(node, P.SpatialJoin):
        lsyms = {s for s, _ in node.left.outputs()}
        rsyms = {s for s, _ in node.right.outputs()}
        need_l = {node.probe_x, node.probe_y} & lsyms
        need_r = ({node.build_geom, node.build_x, node.build_y}
                  - {""}) & rsyms
        extra = set(required)
        if node.filter is not None:
            extra |= node.filter.refs()
        for r in extra:
            (need_l if r in lsyms else need_r if r in rsyms
             else set()).add(r)
        import dataclasses as _dc

        # fresh node, like every sibling branch (in-place child swaps
        # would narrow plans shared with a retained pre-prune tree)
        return _dc.replace(node,
                           left=prune_columns(node.left, need_l),
                           right=prune_columns(node.right, need_r))
    if isinstance(node, (P.Sort, P.TopN)):
        need = required | {k for k, _, _ in node.keys}
        src = prune_columns(node.source, need)
        if isinstance(node, P.Sort):
            return P.Sort(src, node.keys)
        return P.TopN(src, node.keys, node.count)
    if isinstance(node, P.Limit):
        return P.Limit(prune_columns(node.source, required), node.count)
    if isinstance(node, P.Union):
        new_sources = []
        keep_syms = [s for s in node.symbols if s in required] or node.symbols[:1]
        new_mappings = []
        for src, mapping in zip(node.sources_, node.mappings):
            need = {mapping[s] for s in keep_syms}
            new_sources.append(prune_columns(src, need))
            new_mappings.append({s: mapping[s] for s in keep_syms})
        return P.Union(new_sources, keep_syms, new_mappings, node.distinct)
    if isinstance(node, P.Window):
        need = required | set(node.partition_by) | {k for k, _, _ in node.order_by}
        for c in node.functions.values():
            for arg in c.args:
                need |= arg.refs()
        return P.Window(prune_columns(node.source, need), node.partition_by,
                        node.order_by, node.functions, node.frame)
    if isinstance(node, P.Output):
        return P.Output(prune_columns(node.source, set(node.symbols)),
                        node.names, node.symbols)
    return node


# ---------------------------------------------------------------------------
# spatial join extraction (reference: ExtractSpatialJoins +
# SpatialJoinOperator/PagesRTreeIndex in presto-main; here the runtime
# index is a uniform grid — see P.SpatialJoin)
# ---------------------------------------------------------------------------


def _point_refs(e):
    """st_point(Ref x, Ref y) -> (x, y) symbol names, else None."""
    if isinstance(e, ir.Call) and e.fn == "st_point" \
            and len(e.args) == 2 \
            and all(isinstance(a, ir.Ref) for a in e.args):
        return e.args[0].name, e.args[1].name
    return None


def _match_spatial_conjunct(c, lsyms, rsyms):
    """One conjunct -> SpatialJoin fields, or None.  Shapes:
    st_contains(g, p) / st_within(p, g) with g a Ref and p an
    st_point over Refs; st_distance(p1, p2) < lit / <= lit."""
    if not isinstance(c, ir.Call):
        return None
    if c.fn in ("st_contains", "st_within", "st_intersects") \
            and len(c.args) == 2:
        # a point probe makes st_intersects == st_contains (interior
        # test; boundary points follow the same ray-cast tolerance)
        if c.fn == "st_intersects" and _point_refs(c.args[0]) is not None:
            g, p = c.args[1], c.args[0]
        elif c.fn == "st_within":
            g, p = c.args[1], c.args[0]
        else:
            g, p = c.args
        if isinstance(g, ir.Call) and g.fn == "st_geometryfromtext" \
                and len(g.args) == 1 and isinstance(g.args[0], ir.Ref):
            g = g.args[0]  # WKT column: the executor parses per entry
        pt = _point_refs(p)
        if not isinstance(g, ir.Ref) or pt is None:
            return None
        if g.name in rsyms and pt[0] in lsyms and pt[1] in lsyms:
            return {"kind": "contains", "probe_x": pt[0],
                    "probe_y": pt[1], "build_geom": g.name}
        if g.name in lsyms and pt[0] in rsyms and pt[1] in rsyms:
            return {"kind": "contains", "probe_x": pt[0],
                    "probe_y": pt[1], "build_geom": g.name,
                    "swap": True}
        return None
    if c.fn in ("lt", "le") and len(c.args) == 2 \
            and isinstance(c.args[0], ir.Call) \
            and c.args[0].fn == "st_distance" \
            and isinstance(c.args[1], ir.Lit) \
            and isinstance(c.args[1].value, (int, float)):
        p1 = _point_refs(c.args[0].args[0])
        p2 = _point_refs(c.args[0].args[1])
        if p1 is None or p2 is None:
            return None
        r = float(c.args[1].value)
        # either argument order: the PROBE is whichever point reads the
        # left child's symbols, so the join sides never swap here
        for probe, build in ((p1, p2), (p2, p1)):
            if probe[0] in lsyms and probe[1] in lsyms \
                    and build[0] in rsyms and build[1] in rsyms:
                return {"kind": "distance", "probe_x": probe[0],
                        "probe_y": probe[1], "build_x": build[0],
                        "build_y": build[1], "radius": r,
                        "strict": c.fn == "lt"}
    return None


def _extract_spatial_joins(node: P.PlanNode) -> P.PlanNode:
    for attr in ("source", "left", "right"):
        if hasattr(node, attr):
            setattr(node, attr, _extract_spatial_joins(getattr(node, attr)))
    if isinstance(node, P.Union):
        node.sources_ = [_extract_spatial_joins(s) for s in node.sources_]
    # pattern A: Filter over a filter-free CROSS join
    # pattern B: the CROSS join carries the predicate itself
    filt_node = None
    join = node
    if isinstance(node, P.Filter) and isinstance(node.source, P.Join):
        filt_node, join = node, node.source
    if not (isinstance(join, P.Join) and join.join_type == "CROSS"
            and not join.criteria):
        return node
    pred = filt_node.predicate if filt_node is not None else join.filter
    if filt_node is not None and join.filter is not None:
        pred = ir.combine_conjuncts(
            list(ir.conjuncts(pred)) + list(ir.conjuncts(join.filter)))
    if pred is None:
        return node
    lsyms = {s for s, _ in join.left.outputs()}
    rsyms = {s for s, _ in join.right.outputs()}
    conjs = list(ir.conjuncts(pred))
    for i, c in enumerate(conjs):
        m = _match_spatial_conjunct(c, lsyms, rsyms)
        if m is None:
            continue
        swap = m.pop("swap", False)
        left, right = (join.right, join.left) if swap \
            else (join.left, join.right)
        rest = conjs[:i] + conjs[i + 1:]
        sj = P.SpatialJoin(left=left, right=right,
                           filter=ir.combine_conjuncts(rest)
                           if rest else None, **m)
        return sj
    return node


# ---------------------------------------------------------------------------
# transitive semi-join inference (reference: PredicatePushDown's
# equality inference deriving `l.k IN S` from `l.k = r.k AND r.k IN S`;
# also the static analog of dynamic filtering)
# ---------------------------------------------------------------------------


def infer_transitive_semijoins(node: P.PlanNode) -> P.PlanNode:
    """INNER join whose build side is SEMI-filtered on the join key gets
    the same SEMI filter on the probe side, sharing the filter subquery
    SUBTREE (the executor memoizes shared nodes, so it runs once).  On
    the mask-not-compact executor this is the difference between probing
    6M rows and probing the handful the subquery admits (TPC-H Q18)."""
    for attr in ("source", "left", "right"):
        if hasattr(node, attr):
            setattr(node, attr, infer_transitive_semijoins(
                getattr(node, attr)))
    if isinstance(node, P.Union):
        node.sources_ = [infer_transitive_semijoins(s)
                         for s in node.sources_]
    if not (isinstance(node, P.Join) and node.join_type == "SEMI"
            and len(node.criteria) == 1 and node.filter is None
            and isinstance(node.left, P.Join)
            and node.left.join_type == "INNER" and node.left.criteria):
        return node
    k, sk = node.criteria[0]
    j = node.left
    for lk, rk in j.criteria:
        if k not in (lk, rk):
            continue
        sub = node.right  # SHARED subtree, not a copy
        setattr(sub, "shared_subtree", True)
        # recurse: each pushed SEMI may sit over another inner join in a
        # chain, so the filter keeps descending toward the scans
        lsemi = infer_transitive_semijoins(
            P.Join(j.left, sub, "SEMI", [(lk, sk)], None))
        rsemi = infer_transitive_semijoins(
            P.Join(j.right, sub, "SEMI", [(rk, sk)], None))
        # both inner-join inputs filter on the (equal) key, so the top
        # SEMI is subsumed and the expensive sides compact early
        return P.Join(lsemi, rsemi, "INNER", j.criteria, j.filter,
                      j.distribution, j.mark)
    return node
