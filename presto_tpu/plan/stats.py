"""Statistics: column ranges, cardinalities, uniqueness, fanout bounds.

Reference parity: the CBO stats layer (presto-main/.../cost/, 44 files:
StatsCalculator + per-node rules producing PlanNodeStatsEstimate).  Here
stats serve a second, TPU-specific master: they make shapes STATIC —
group-by capacities, key-pack layouts, and join expansion bounds become
compile-time constants so whole plans jit with zero host syncs (the
difference between a fused XLA program and per-op tunnel round-trips).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, FrozenSet, List, Optional, Tuple

from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P


@dataclasses.dataclass
class ColStats:
    min: Optional[float] = None  # range of the PHYSICAL representation
    max: Optional[float] = None
    ndv: Optional[int] = None  # distinct values


@dataclasses.dataclass
class NodeStats:
    rows: int  # row-count UPPER BOUND (static shape sizing must trust it)
    cols: Dict[str, ColStats]
    unique: List[FrozenSet[str]]  # symbol sets known unique per row
    # max rows matching any single value of these key sets (join fanout bound)
    fanout: Dict[FrozenSet[str], int]
    # CBO cardinality ESTIMATE (selectivity-aware, may undershoot; used for
    # join ordering + distribution choice, never for static sizing).
    # None -> fall back to rows.  Reference: PlanNodeStatsEstimate
    # outputRowCount vs our additional static-shape contract.
    est: Optional[float] = None

    @property
    def est_rows(self) -> float:
        return self.rows if self.est is None else self.est


def derive(node: P.PlanNode, catalog, memo=None) -> NodeStats:
    """Bottom-up stats derivation (reference: ComposableStatsCalculator
    visiting per-node rules).  The memo stores (node, stats) and checks
    identity on lookup: entries hold a strong ref so a memo that
    outlives temporaries (e.g. the ReorderJoins DP deriving stats for
    rejected candidate trees) can never serve stale stats through a
    recycled id()."""
    if memo is None:
        memo = {}
    hit = memo.get(id(node))
    if hit is not None and hit[0] is node:
        return hit[1]
    s = _derive(node, catalog, memo)
    memo[id(node)] = (node, s)
    return s


def _derive(node, catalog, memo) -> NodeStats:
    d = lambda n: derive(n, catalog, memo)
    if isinstance(node, P.TableScan):
        t = catalog.get(node.table)
        rows = t.row_count()
        cols = {}
        for sym, col in node.assignments.items():
            cs = t.column_stats(col) if hasattr(t, "column_stats") else None
            cols[sym] = cs or ColStats()
        col_to_sym = {}
        for sym, col in node.assignments.items():
            col_to_sym.setdefault(col, sym)
        unique = []
        fanout = {}
        if hasattr(t, "unique_keys"):
            for keyset in t.unique_keys():
                if all(c in col_to_sym for c in keyset):
                    fs = frozenset(col_to_sym[c] for c in keyset)
                    unique.append(fs)
                    fanout[fs] = 1
        if hasattr(t, "max_rows_per_key"):
            for keyset, bound in t.max_rows_per_key().items():
                if all(c in col_to_sym for c in keyset):
                    fanout[frozenset(col_to_sym[c] for c in keyset)] = bound
        return NodeStats(rows, cols, unique, fanout)
    if isinstance(node, P.Values):
        return NodeStats(len(node.rows), {s: ColStats() for s in node.symbols},
                         [], {})
    if isinstance(node, P.Filter):
        s = d(node.source)
        sel, cols = filter_selectivity(s, node.predicate)
        src = node.source
        while isinstance(src, P.Project):
            src = src.source
        if isinstance(src, P.Aggregate) and src.group_keys \
                and _refs_agg_output(node.predicate, src):
            # HAVING-style comparison against an aggregate output:
            # range selectivity is unknowable from column stats, and
            # such filters are characteristically sharp (Q18's
            # sum(l_quantity) > 300 keeps ~0.4% of groups).  The
            # reference uses an unknown-filter coefficient here too
            # (FilterStatsCalculator.UNKNOWN_FILTER_COEFFICIENT);
            # downstream consumers of est guard against underestimates
            # (pre-aggregation compaction aborts to dynamic).
            sel = min(sel, 0.05)
        est = max(1.0, s.est_rows * sel)
        return NodeStats(s.rows, cols, s.unique, s.fanout, est)
    if isinstance(node, P.Project):
        s = d(node.source)
        cols = {}
        rename: Dict[str, str] = {}
        for sym, e in node.assignments.items():
            if isinstance(e, ir.Ref):
                cols[sym] = s.cols.get(e.name, ColStats())
                rename.setdefault(e.name, sym)
            else:
                cols[sym] = ColStats()
        unique = []
        for u in s.unique:
            if all(x in rename for x in u):
                unique.append(frozenset(rename[x] for x in u))
        fanout = {}
        for k, b in s.fanout.items():
            if all(x in rename for x in k):
                fanout[frozenset(rename[x] for x in k)] = b
        return NodeStats(s.rows, cols, unique, fanout, s.est)
    if isinstance(node, P.Aggregate):
        s = d(node.source)
        cap = capacity_for_groups(node, s)
        cols = {k: s.cols.get(k, ColStats()) for k in node.group_keys}
        for sym, a in node.aggs.items():
            cols[sym] = ColStats()
        keyset = frozenset(node.group_keys)
        return NodeStats(cap, cols, [keyset] if node.group_keys else [],
                         {keyset: 1} if node.group_keys else {},
                         min(float(cap), s.est_rows))
    if isinstance(node, P.Join):
        ls, rs = d(node.left), d(node.right)
        if node.join_type in ("SEMI", "ANTI"):
            # matching fraction ~= |distinct build keys| / ndv(probe key)
            # (containment assumption, reference SemiJoinStatsCalculator);
            # 0.5 when ndv is unknown
            frac = 0.5
            if node.criteria:
                lk, rk = node.criteria[0]
                lcs = ls.cols.get(lk)
                rcs = rs.cols.get(rk)
                # DISTINCT build keys, not build rows (duplicates do not
                # admit more probe rows)
                build_keys = rs.est_rows
                if rcs and rcs.ndv:
                    build_keys = min(build_keys, float(rcs.ndv))
                if lcs and lcs.ndv:
                    frac = min(1.0, build_keys / max(float(lcs.ndv), 1.0))
            if node.join_type == "ANTI":
                frac = 1.0 - frac
            est = max(ls.est_rows * frac, 1.0)
            return NodeStats(ls.rows, ls.cols, ls.unique, ls.fanout, est)
        if node.join_type == "MARK":
            # every left row survives, one extra boolean column
            cols = dict(ls.cols)
            cols[node.mark] = ColStats(ndv=2)
            return NodeStats(ls.rows, cols, ls.unique, ls.fanout,
                             ls.est_rows)
        cols = {**ls.cols, **rs.cols}
        rkeys = frozenset(rk for _, rk in node.criteria)
        build_unique = any(u <= rkeys for u in rs.unique)
        if node.join_type == "CROSS":
            rows = ls.rows * rs.rows
            return NodeStats(rows, cols, [], {},
                             ls.est_rows * rs.est_rows)
        est = join_cardinality(ls, rs, node.criteria)
        bound = rs.fanout.get(_best_fanout_key(rs, rkeys), None)
        if build_unique:
            rows = ls.rows
            unique = list(ls.unique)
            fanout = dict(ls.fanout)
        else:
            if bound is None:
                # a plain small constant here UNDERSHOOTS (rows is a
                # bound the planner must be able to trust)
                bound = speculative_fanout_bound(rs, node.criteria)
            rows = ls.rows * (bound if bound is not None else 4)
            unique, fanout = [], {}
        if node.join_type in ("LEFT", "FULL"):
            est = max(est, ls.est_rows)  # outer side survives
        return NodeStats(rows, cols, unique, fanout, min(est, float(rows)))
    if isinstance(node, (P.Sort, P.Limit, P.TopN)):
        s = d(node.source)
        rows = s.rows
        est = s.est_rows
        if isinstance(node, (P.Limit, P.TopN)):
            rows = min(rows, node.count)
            est = min(est, float(node.count))
        return NodeStats(rows, s.cols, s.unique, s.fanout, est)
    if isinstance(node, P.Union):
        subs = [d(x) for x in node.sources_]
        rows = sum(x.rows for x in subs)
        cols = {sym: ColStats() for sym in node.symbols}
        return NodeStats(rows, cols, [], {}, sum(x.est_rows for x in subs))
    if isinstance(node, P.Window):
        s = d(node.source)
        cols = dict(s.cols)
        for sym in node.functions:
            cols[sym] = ColStats()
        return NodeStats(s.rows, cols, s.unique, s.fanout, s.est)
    if isinstance(node, P.Unnest):
        s = d(node.source)
        cols = dict(s.cols)
        cols[node.out_sym] = ColStats()
        # ragged fanout unknown; 3x is the planning guess (not a bound:
        # UNNEST is dynamic-mode only, so nothing sizes statically off it)
        return NodeStats(s.rows * 3, cols, [], {}, s.est_rows * 3)
    if isinstance(node, P.Exchange):
        # exchanges move rows, they don't change global cardinality
        return d(node.source)
    if isinstance(node, P.Output):
        s = d(node.source)
        return NodeStats(s.rows, s.cols, s.unique, s.fanout, s.est)
    raise TypeError(f"no stats rule for {type(node).__name__}")


# ---------------------------------------------------------------------------
# CBO estimation rules (reference: cost/FilterStatsCalculator.java,
# cost/JoinStatsRule.java)
# ---------------------------------------------------------------------------

UNKNOWN_FILTER_COEFFICIENT = 0.9   # reference: FilterStatsCalculator default
COMPARISON_UNKNOWN = 1.0 / 3.0     # range predicate with unknown bounds
EQ_UNKNOWN = 0.1
LIKE_COEFFICIENT = 0.25


def _lit_value(e) -> Optional[float]:
    if isinstance(e, ir.Lit) and isinstance(e.value, (int, float, bool)):
        return float(e.value)
    return None


def _refs_agg_output(pred, agg) -> bool:
    """Does the predicate reference any AGGREGATE symbol (vs group key)?"""
    agg_syms = set(agg.aggs)
    return bool(pred.refs() & agg_syms)


def filter_selectivity(src: NodeStats, pred: ir.RowExpr
                       ) -> Tuple[float, Dict[str, ColStats]]:
    """Estimated fraction of rows surviving `pred`, plus narrowed column
    stats for range predicates (containment assumption, like the
    reference's FilterStatsCalculator)."""
    cols = dict(src.cols)
    sel = 1.0
    for c in ir.conjuncts(pred):
        sel *= _conjunct_selectivity(c, cols)
    return max(min(sel, 1.0), 1e-9), cols


def _conjunct_selectivity(c: ir.RowExpr, cols: Dict[str, ColStats]) -> float:
    if not isinstance(c, ir.Call):
        return UNKNOWN_FILTER_COEFFICIENT
    fn = c.fn
    if fn == "and":
        return (_conjunct_selectivity(c.args[0], cols)
                * _conjunct_selectivity(c.args[1], cols))
    if fn == "or":
        a = _conjunct_selectivity(c.args[0], dict(cols))
        b = _conjunct_selectivity(c.args[1], dict(cols))
        return min(1.0, a + b - a * b)
    if fn == "not":
        return max(0.0, 1.0 - _conjunct_selectivity(c.args[0], dict(cols)))
    if fn == "is_null":
        return 0.1
    if fn == "like":
        return LIKE_COEFFICIENT
    if fn == "in":
        # lowered as OR of eq upstream; if present directly, treat as eq*k
        return min(1.0, EQ_UNKNOWN * max(1, len(c.args) - 1))
    if fn in ("eq", "ne", "lt", "le", "gt", "ge") and len(c.args) == 2:
        a, b = c.args
        if isinstance(b, ir.Ref) and not isinstance(a, ir.Ref):
            a, b = b, a
            fn = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(fn, fn)
        if not isinstance(a, ir.Ref):
            return UNKNOWN_FILTER_COEFFICIENT
        if isinstance(b, ir.Ref):
            # column-to-column comparison (non-join residual)
            return 0.5 if fn != "eq" else EQ_UNKNOWN
        v = _lit_value(b)
        cs = cols.get(a.name)
        if fn == "eq":
            if cs is not None and cs.ndv:
                return 1.0 / cs.ndv
            return EQ_UNKNOWN
        if fn == "ne":
            if cs is not None and cs.ndv:
                return 1.0 - 1.0 / cs.ndv
            return 1.0 - EQ_UNKNOWN
        if v is None or cs is None or cs.min is None or cs.max is None \
                or cs.max <= cs.min:
            return COMPARISON_UNKNOWN
        span = cs.max - cs.min
        if fn in ("lt", "le"):
            frac = (v - cs.min) / span
            new = ColStats(cs.min, min(cs.max, v), cs.ndv)
        else:
            frac = (cs.max - v) / span
            new = ColStats(max(cs.min, v), cs.max, cs.ndv)
        frac = max(0.0, min(1.0, frac))
        if frac > 0:
            # narrow only the RANGE (a guaranteed bound on surviving
            # rows); ndv * frac is an estimate, not a bound, and these
            # ColStats feed static group-capacity sizing which must
            # never undershoot (join_cardinality caps ndv by est_rows
            # itself, so estimates still benefit)
            cols[a.name] = ColStats(new.min, new.max, cs.ndv)
        return frac
    return UNKNOWN_FILTER_COEFFICIENT


def join_cardinality(ls: NodeStats, rs: NodeStats, criteria) -> float:
    """|L join R| ~= |L|*|R| / prod(max(ndv_l, ndv_r)) over the equi-keys,
    ndv capped by the side's estimated rows (containment assumption) —
    reference: JoinStatsRule's formula."""
    est = ls.est_rows * rs.est_rows
    if not criteria:
        return est
    for lk, rk in criteria:
        lcs, rcs = ls.cols.get(lk), rs.cols.get(rk)
        ndv_l = min(lcs.ndv, max(ls.est_rows, 1)) if lcs and lcs.ndv else None
        ndv_r = min(rcs.ndv, max(rs.est_rows, 1)) if rcs and rcs.ndv else None
        if ndv_l and ndv_r:
            denom = max(ndv_l, ndv_r)
        elif ndv_l or ndv_r:
            denom = ndv_l or ndv_r
        else:
            denom = max(ls.est_rows, rs.est_rows, 1.0) * EQ_UNKNOWN
        est /= max(denom, 1.0)
    return max(est, 1.0)


def speculative_fanout_bound(rs: NodeStats, criteria) -> Optional[int]:
    """Build-side fanout bound from ndv when no connector bound exists:
    ~4x the average rows-per-key, min over every criterion key (a
    composite-key match is at most any single key's fanout).  The ONE
    definition shared by the stats join rule, annotate_static_hints and
    the ReorderJoins cost model — the executor guards the actual counts
    and re-runs dynamically on overflow, so 4x average is safe to
    speculate."""
    bound = None
    for _lk, rk in criteria:
        cs = rs.cols.get(rk)
        if cs is not None and cs.ndv:
            b = max(4, math.ceil(rs.rows / cs.ndv) * 4)
            bound = b if bound is None else min(bound, b)
    return bound


def _best_fanout_key(stats: NodeStats, keys: FrozenSet[str]):
    best = None
    for k in stats.fanout:
        if k <= keys and (best is None or stats.fanout[k] < stats.fanout[best]):
            best = k
    return best


def capacity_for_groups(node: P.Aggregate, src: NodeStats) -> int:
    """Static group capacity = product of key cardinalities, clamped to
    input rows; power-of-two padded."""
    cap = 1
    for k in node.group_keys:
        cs = src.cols.get(k)
        if cs is not None and cs.ndv:
            card = cs.ndv + 1
        elif cs is not None and cs.min is not None and cs.max is not None:
            card = int(cs.max - cs.min) + 2
        else:
            card = src.rows
        cap = min(cap * card, src.rows)
        if cap >= src.rows:
            return src.rows
    return max(int(2 ** math.ceil(math.log2(max(cap, 1)))), 1)
