"""Statistics: column ranges, cardinalities, uniqueness, fanout bounds.

Reference parity: the CBO stats layer (presto-main/.../cost/, 44 files:
StatsCalculator + per-node rules producing PlanNodeStatsEstimate).  Here
stats serve a second, TPU-specific master: they make shapes STATIC —
group-by capacities, key-pack layouts, and join expansion bounds become
compile-time constants so whole plans jit with zero host syncs (the
difference between a fused XLA program and per-op tunnel round-trips).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, FrozenSet, List, Optional, Tuple

from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P


@dataclasses.dataclass
class ColStats:
    min: Optional[float] = None  # range of the PHYSICAL representation
    max: Optional[float] = None
    ndv: Optional[int] = None  # distinct values


@dataclasses.dataclass
class NodeStats:
    rows: int  # row-count estimate (upper bound for static sizing)
    cols: Dict[str, ColStats]
    unique: List[FrozenSet[str]]  # symbol sets known unique per row
    # max rows matching any single value of these key sets (join fanout bound)
    fanout: Dict[FrozenSet[str], int]


def derive(node: P.PlanNode, catalog, memo=None) -> NodeStats:
    """Bottom-up stats derivation (reference: ComposableStatsCalculator
    visiting per-node rules)."""
    if memo is None:
        memo = {}
    if id(node) in memo:
        return memo[id(node)]
    s = _derive(node, catalog, memo)
    memo[id(node)] = s
    return s


def _derive(node, catalog, memo) -> NodeStats:
    d = lambda n: derive(n, catalog, memo)
    if isinstance(node, P.TableScan):
        t = catalog.get(node.table)
        rows = t.row_count()
        cols = {}
        for sym, col in node.assignments.items():
            cs = t.column_stats(col) if hasattr(t, "column_stats") else None
            cols[sym] = cs or ColStats()
        col_to_sym = {}
        for sym, col in node.assignments.items():
            col_to_sym.setdefault(col, sym)
        unique = []
        fanout = {}
        if hasattr(t, "unique_keys"):
            for keyset in t.unique_keys():
                if all(c in col_to_sym for c in keyset):
                    fs = frozenset(col_to_sym[c] for c in keyset)
                    unique.append(fs)
                    fanout[fs] = 1
        if hasattr(t, "max_rows_per_key"):
            for keyset, bound in t.max_rows_per_key().items():
                if all(c in col_to_sym for c in keyset):
                    fanout[frozenset(col_to_sym[c] for c in keyset)] = bound
        return NodeStats(rows, cols, unique, fanout)
    if isinstance(node, P.Values):
        return NodeStats(len(node.rows), {s: ColStats() for s in node.symbols},
                         [], {})
    if isinstance(node, P.Filter):
        s = d(node.source)
        return NodeStats(s.rows, s.cols, s.unique, s.fanout)
    if isinstance(node, P.Project):
        s = d(node.source)
        cols = {}
        rename: Dict[str, str] = {}
        for sym, e in node.assignments.items():
            if isinstance(e, ir.Ref):
                cols[sym] = s.cols.get(e.name, ColStats())
                rename.setdefault(e.name, sym)
            else:
                cols[sym] = ColStats()
        unique = []
        for u in s.unique:
            if all(x in rename for x in u):
                unique.append(frozenset(rename[x] for x in u))
        fanout = {}
        for k, b in s.fanout.items():
            if all(x in rename for x in k):
                fanout[frozenset(rename[x] for x in k)] = b
        return NodeStats(s.rows, cols, unique, fanout)
    if isinstance(node, P.Aggregate):
        s = d(node.source)
        cap = capacity_for_groups(node, s)
        cols = {k: s.cols.get(k, ColStats()) for k in node.group_keys}
        for sym, a in node.aggs.items():
            cols[sym] = ColStats()
        keyset = frozenset(node.group_keys)
        return NodeStats(cap, cols, [keyset] if node.group_keys else [],
                         {keyset: 1} if node.group_keys else {})
    if isinstance(node, P.Join):
        ls, rs = d(node.left), d(node.right)
        if node.join_type in ("SEMI", "ANTI"):
            return NodeStats(ls.rows, ls.cols, ls.unique, ls.fanout)
        cols = {**ls.cols, **rs.cols}
        rkeys = frozenset(rk for _, rk in node.criteria)
        build_unique = any(u <= rkeys for u in rs.unique)
        if node.join_type == "CROSS":
            rows = ls.rows * rs.rows
            return NodeStats(rows, cols, [], {})
        bound = rs.fanout.get(_best_fanout_key(rs, rkeys), None)
        if build_unique:
            rows = ls.rows
            unique = list(ls.unique)
            fanout = dict(ls.fanout)
        elif bound is not None:
            rows = ls.rows * bound
            unique, fanout = [], {}
        else:
            rows = ls.rows * 4  # heuristic expansion guess (eager fallback)
            unique, fanout = [], {}
        return NodeStats(rows, cols, unique, fanout)
    if isinstance(node, (P.Sort, P.Limit, P.TopN)):
        s = d(node.source)
        rows = s.rows
        if isinstance(node, (P.Limit, P.TopN)):
            rows = min(rows, node.count)
        return NodeStats(rows, s.cols, s.unique, s.fanout)
    if isinstance(node, P.Union):
        subs = [d(x) for x in node.sources_]
        rows = sum(x.rows for x in subs)
        cols = {sym: ColStats() for sym in node.symbols}
        return NodeStats(rows, cols, [], {})
    if isinstance(node, P.Window):
        s = d(node.source)
        cols = dict(s.cols)
        for sym in node.functions:
            cols[sym] = ColStats()
        return NodeStats(s.rows, cols, s.unique, s.fanout)
    if isinstance(node, P.Exchange):
        # exchanges move rows, they don't change global cardinality
        return d(node.source)
    if isinstance(node, P.Output):
        s = d(node.source)
        return NodeStats(s.rows, s.cols, s.unique, s.fanout)
    raise TypeError(f"no stats rule for {type(node).__name__}")


def _best_fanout_key(stats: NodeStats, keys: FrozenSet[str]):
    best = None
    for k in stats.fanout:
        if k <= keys and (best is None or stats.fanout[k] < stats.fanout[best]):
            best = k
    return best


def capacity_for_groups(node: P.Aggregate, src: NodeStats) -> int:
    """Static group capacity = product of key cardinalities, clamped to
    input rows; power-of-two padded."""
    cap = 1
    for k in node.group_keys:
        cs = src.cols.get(k)
        if cs is not None and cs.ndv:
            card = cs.ndv + 1
        elif cs is not None and cs.min is not None and cs.max is not None:
            card = int(cs.max - cs.min) + 2
        else:
            card = src.rows
        cap = min(cap * card, src.rows)
        if cap >= src.rows:
            return src.rows
    return max(int(2 ** math.ceil(math.log2(max(cap, 1)))), 1)
