"""Fragment-fusion economics: per-edge fuse-vs-cut pricing with a
calibrated exchange roofline and a runtime decision memo.

Round 12 fused EVERY mesh-local exchange edge into one shard_map
program.  The committed MULTICHIP record shows that policy is wrong in
both directions: q3 fused wins (host hops deleted, dispatch amortized)
while q18 fused LOSES (2056ms vs 747ms cut warm on the 8-virtual-dev
CPU mesh) — collapsing ten independently-schedulable fragments into one
program serializes work the cut path overlaps, and the in-trace
collectives move 12MB through a slower lane than the loopback host
path.  "Accelerating Presto with GPUs" (PAPERS.md) reaches the same
conclusion for GPU offload: per-operator cost gating beats blanket
offload.

This module prices each mesh-local exchange edge BOTH ways:

    CUT(e)   = host_edge_ms + bytes/host_bw + dispatch_ms
               (PTPG pack -> host hop -> unpack, plus the per-fragment
               task dispatch / compile-amortization overhead the cut
               path pays to keep the producer a separate fragment)
    FUSED(e) = coll_edge_ms(ndev) + bytes/ici_bw(ndev) + serial(e)
               (the in-trace collective, plus the marginal
               fusion-induced serialization cost of growing the fused
               group past `serial_free` independently-schedulable
               fragments — the q18 failure mode)

and greedily contracts only net-win edges (producers-first, the same
topological order `fuse_fragments` walks).  Constants come from a
per-platform profile calibrated by `tools/roofline.py --calibrate`
(the existing `exchange` sweep, least-squares intercept+slope per
ndev), loaded from PRESTO_TPU_FUSION_PROFILE / the `fusion_profile`
session property, with baked defaults measured on the CI CPU host.

A runtime feedback loop closes the model-vs-truth gap: the coordinator
records the observed execute wall of every multi-fragment cluster
query (fused-group and cut-fragment walls, measured with the PR-8
trace clock) into a bounded per-plan-fingerprint decision memo.  When
both legs of a shape have been observed, a mispredicted edge set flips
on the NEXT execution of the same shape — hysteresis-guarded (margin +
consecutive-strike requirement), never mid-query.  `fragment_fusion=
force` reproduces the round-12 fuse-everything policy byte-identically;
`off` keeps the per-fragment HTTP path; `auto` (the default) runs this
model.

The test_lint AST rule confines profile reads and the bandwidth /
serialization constants to THIS module — distribute.py and cluster.py
consume verdicts, never prices.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

#: env var naming a calibration-profile JSON (tools/roofline.py
#: --calibrate writes it); the `fusion_profile` session property is the
#: per-session override.  Reads are confined to this module (test_lint).
PROFILE_ENV = "PRESTO_TPU_FUSION_PROFILE"
PROFILE_PROPERTY = "fusion_profile"

#: bytes assumed for an edge with no est_bytes annotation (a gathered
#: partial-aggregate output is typically this order of magnitude)
DEFAULT_EDGE_BYTES = 1 << 16

#: decision-memo hysteresis: a leg must beat the other by this factor
#: to count as a winner at all, and overturning an EXISTING override
#: takes FLIP_STRIKES consecutive winner-disagreeing observations —
#: noisy walls near parity never ping-pong the decision.
FLIP_MARGIN = 1.15
FLIP_STRIKES = 2
MEMO_MAX_ENTRIES = 256

#: baked per-platform calibration defaults.  The cpu numbers come from
#: `tools/roofline.py --calibrate` on the CI host (least-squares fit of
#: the exchange sweep: host loopback HTTP trip vs in-trace all_to_all
#: over the virtual mesh) — on CPU the "ICI" collective is a memcpy
#: through one core and LOSES to the host path per byte, which is
#: exactly why q18's 12MB of edges should cut there.  The tpu defaults
#: are order-of-magnitude priors (real ICI ~100x host bandwidth, ~ms
#: dispatch) pending an on-chip --calibrate run.
DEFAULT_PROFILES: Dict[str, dict] = {
    "cpu": {
        "platform": "cpu",
        "host_edge_ms": 3.1,
        "host_ms_per_mb": 11.9,
        "coll_edge_ms": {2: 0.1, 4: 0.1, 8: 0.1},
        "coll_ms_per_mb": {2: 31.2, 4: 29.8, 8: 25.3},
        "dispatch_ms": 9.0,
        "serial_ms": 160.0,
        "serial_free": 5,
        # sketch-state edges (plan/distribute stamps Exchange.sketch
        # _only): a fixed-width register fold (lax.pmax / tiny
        # all_gather), priced near-zero so the cost model fuses it by
        # default — the whole point of the sketch is deleting the
        # repartition; the tools/roofline.py `sketch` sweep anchors the
        # per-MB rate (the state is <= m bytes/group regardless of rows)
        "sketch_edge_ms": 0.05,
        "sketch_ms_per_mb": 0.5,
    },
    # the CROSS-HOST lane measured on the CI box (tools/roofline.py
    # --calibrate --multiproc: 2- and 4-process gloo loopback meshes,
    # committed record tools/fusion_profile_cpu-multiproc.json): the
    # dcn tables are keyed by PROCESS count and price the in-trace
    # collective when the fusion target spans processes.  The fori_loop
    # sweep amortises launch, so the intercept fits to ~0 (pinned to
    # the same 0.1ms floor as the coll lane); the slope is ~2.5x the
    # host memcpy rate, so on CPU-gloo SMALL cross-host edges fuse
    # (they dodge host_edge_ms + dispatch) and big ones cut — the memo
    # then refines that crossover per plan shape.
    "cpu-multiproc": {
        "platform": "cpu",
        "host_edge_ms": 3.1,
        "host_ms_per_mb": 11.9,
        "coll_edge_ms": {2: 0.1, 4: 0.1, 8: 0.1},
        "coll_ms_per_mb": {2: 31.2, 4: 29.8, 8: 25.3},
        "dcn_edge_ms": {2: 0.1, 4: 0.1},
        "dcn_ms_per_mb": {2: 31.9, 4: 29.6},
        "dispatch_ms": 9.0,
        "serial_ms": 160.0,
        "serial_free": 5,
        "sketch_edge_ms": 0.05,
        "sketch_ms_per_mb": 0.5,
    },
    "tpu": {
        "platform": "tpu",
        "host_edge_ms": 4.0,
        "host_ms_per_mb": 25.0,     # PTPG pack + DCN hop + unpack
        "coll_edge_ms": {2: 0.05, 4: 0.05, 8: 0.08},
        "coll_ms_per_mb": {2: 0.03, 4: 0.03, 8: 0.03},  # ~40GB/s ICI
        # documented PRIORS pending an on-pod --calibrate --multiproc
        # run: per-host DCN is ~2.5GB/s with ~1ms launch overhead, so
        # cross-host collectives beat the HTTP path (~25ms/MB pack+hop)
        # by ~60x per byte — the DrJAX composition this round targets
        "dcn_edge_ms": {2: 1.0, 4: 1.2, 8: 1.5},
        "dcn_ms_per_mb": {2: 0.4, 4: 0.4, 8: 0.45},
        "dispatch_ms": 6.0,
        "serial_ms": 2.0,           # XLA overlaps collectives on-chip
        "serial_free": 8,
        # on chip the register fold rides the same ~40GB/s ICI as the
        # coll lane but skips the variable-shape exchange machinery
        "sketch_edge_ms": 0.03,
        "sketch_ms_per_mb": 0.03,
    },
}


@dataclasses.dataclass(frozen=True)
class FusionProfile:
    """Calibrated exchange-roofline constants for one platform."""

    platform: str = "cpu"
    host_edge_ms: float = 3.1        # fixed pack+hop+unpack floor
    host_ms_per_mb: float = 11.9     # marginal host-path cost per MB
    coll_edge_ms: Dict[int, float] = dataclasses.field(
        default_factory=dict)      # per-ndev collective launch overhead
    coll_ms_per_mb: Dict[int, float] = dataclasses.field(
        default_factory=dict)      # per-ndev collective cost per MB
    dcn_edge_ms: Dict[int, float] = dataclasses.field(
        default_factory=dict)      # per-NPROC cross-host launch overhead
    dcn_ms_per_mb: Dict[int, float] = dataclasses.field(
        default_factory=dict)      # per-NPROC cross-host cost per MB
    dispatch_ms: float = 9.0         # per-fragment task overhead (cut)
    serial_ms: float = 160.0         # per extra group member past free
    serial_free: int = 5
    sketch_edge_ms: float = 0.05     # fixed-width sketch-fold launch
    sketch_ms_per_mb: float = 0.5    # marginal sketch-state cost per MB

    def _nd(self, table: Dict[int, float], ndev: int,
            default: float) -> float:
        if not table:
            return default
        keys = sorted(table)
        best = keys[0]
        for k in keys:
            if k <= ndev:
                best = k
        return float(table[best])

    def cut_ms(self, nbytes: int) -> float:
        """Price of keeping an edge on the per-fragment HTTP path."""
        return (self.host_edge_ms + self.dispatch_ms
                + nbytes / 1e6 * self.host_ms_per_mb)

    def fused_base_ms(self, nbytes: int, ndev: int,
                      nproc: int = 1) -> float:
        """Price of the edge as an in-trace collective, BEFORE the
        marginal serialization penalty of growing the fused group.
        When the fusion target spans `nproc` > 1 processes the edge
        crosses the DCN fabric — the dcn tables (keyed by process
        count) price that lane; the slower hop dominates the mesh-local
        ICI leg, so the model charges it alone."""
        if nproc > 1 and (self.dcn_edge_ms or self.dcn_ms_per_mb):
            return (self._nd(self.dcn_edge_ms, nproc, 2.0)
                    + nbytes / 1e6
                    * self._nd(self.dcn_ms_per_mb, nproc, 40.0))
        return (self._nd(self.coll_edge_ms, ndev, 1.0)
                + nbytes / 1e6 * self._nd(self.coll_ms_per_mb, ndev, 8.0))

    def sketch_ms(self, nbytes: int) -> float:
        """Price of a sketch-state edge fused: the fixed-width register
        fold (one elementwise collective / tiny gather).  Near-zero and
        independent of the input cardinality that produced the state —
        the lane exists so the model fuses sketch edges by default
        instead of pricing them like a variable-shape exchange."""
        return self.sketch_edge_ms + nbytes / 1e6 * self.sketch_ms_per_mb

    def serial_penalty_ms(self, group: int) -> float:
        """Group-size serialization potential: a fused program of
        `group` fragments pays serial_ms for every member past
        serial_free (the q18 failure mode — independently-schedulable
        fragments collapsed into one sequential trace)."""
        return self.serial_ms * max(0, group - self.serial_free)


def _profile_from_dict(d: dict) -> FusionProfile:
    def _int_keys(m):
        return {int(k): float(v) for k, v in (m or {}).items()}

    return FusionProfile(
        platform=str(d.get("platform", "cpu")),
        host_edge_ms=float(d.get("host_edge_ms", 3.1)),
        host_ms_per_mb=float(d.get("host_ms_per_mb", 11.9)),
        coll_edge_ms=_int_keys(d.get("coll_edge_ms")),
        coll_ms_per_mb=_int_keys(d.get("coll_ms_per_mb")),
        dcn_edge_ms=_int_keys(d.get("dcn_edge_ms")),
        dcn_ms_per_mb=_int_keys(d.get("dcn_ms_per_mb")),
        dispatch_ms=float(d.get("dispatch_ms", 9.0)),
        serial_ms=float(d.get("serial_ms", 160.0)),
        serial_free=int(d.get("serial_free", 5)),
        sketch_edge_ms=float(d.get("sketch_edge_ms", 0.05)),
        sketch_ms_per_mb=float(d.get("sketch_ms_per_mb", 0.5)),
    )


def load_profile(session=None, multihost: bool = False) -> FusionProfile:
    """Session `fusion_profile` (a JSON path) > PRESTO_TPU_FUSION_PROFILE
    env > baked per-platform default.  A missing/bad file degrades to
    the default — calibration is an optimization, never a failure.
    `multihost=True` (the fusion target spans processes) prefers the
    baked `<platform>-multiproc` entry, whose dcn tables carry the
    measured cross-process collective lane."""
    path = None
    if session is not None:
        try:
            path = session.properties.get(PROFILE_PROPERTY) or None
        except Exception:  # noqa: BLE001 — duck-typed sessions in tests
            path = None
    if path is None:
        path = os.environ.get(PROFILE_ENV) or None
    if path:
        try:
            with open(path, encoding="utf-8") as f:
                return _profile_from_dict(json.load(f))
        except (OSError, ValueError):
            pass
    from presto_tpu.observe import profile as OP

    plat = OP.platform()
    if multihost and f"{plat}-multiproc" in DEFAULT_PROFILES:
        return _profile_from_dict(DEFAULT_PROFILES[f"{plat}-multiproc"])
    return _profile_from_dict(
        DEFAULT_PROFILES.get(plat, DEFAULT_PROFILES["cpu"]))


def profile_from_exchange_sweep(sweep: dict, platform: str) -> dict:
    """Fit a calibration profile from the roofline `exchange` sweep
    ({"r64k": {"bytes": B, "host_nd2_ms": .., "coll_nd2_ms": ..}, ...}):
    least-squares intercept+slope of wall vs MB for the host path
    (pooled over ndev — the loopback trip doesn't scale with the mesh)
    and per-ndev for the collective path.  Returns the JSON-able dict
    `load_profile` reads."""

    def fit(points: List[Tuple[float, float]]) -> Tuple[float, float]:
        # (mb, ms) least squares; degenerate inputs fall back sanely
        n = len(points)
        if n == 0:
            return 0.0, 0.0
        if n == 1:
            mb, ms = points[0]
            return 0.0, ms / mb if mb else 0.0
        sx = sum(p[0] for p in points)
        sy = sum(p[1] for p in points)
        sxx = sum(p[0] * p[0] for p in points)
        sxy = sum(p[0] * p[1] for p in points)
        den = n * sxx - sx * sx
        if den <= 0:
            return 0.0, 0.0
        slope = (n * sxy - sx * sy) / den
        intercept = (sy - slope * sx) / n
        return max(intercept, 0.0), max(slope, 0.0)

    host_pts: List[Tuple[float, float]] = []
    coll_pts: Dict[int, List[Tuple[float, float]]] = {}
    dcn_pts: Dict[int, List[Tuple[float, float]]] = {}
    for cell in sweep.values():
        if not isinstance(cell, dict) or "bytes" not in cell:
            continue
        mb = float(cell["bytes"]) / 1e6
        for k, v in cell.items():
            if v is None:
                continue
            if k.startswith("host_nd") and k.endswith("_ms"):
                host_pts.append((mb, float(v)))
            elif k.startswith("coll_nd") and k.endswith("_ms"):
                nd = int(k[len("coll_nd"):-len("_ms")])
                coll_pts.setdefault(nd, []).append((mb, float(v)))
            elif k.startswith("dcn_np") and k.endswith("_ms"):
                np_ = int(k[len("dcn_np"):-len("_ms")])
                dcn_pts.setdefault(np_, []).append((mb, float(v)))
    h_edge, h_mb = fit(host_pts)
    base = DEFAULT_PROFILES.get(platform, DEFAULT_PROFILES["cpu"])
    prof = dict(base)
    prof["platform"] = platform
    if host_pts:
        prof["host_edge_ms"] = round(h_edge, 3)
        prof["host_ms_per_mb"] = round(h_mb, 3)
    if coll_pts:
        prof["coll_edge_ms"] = {}
        prof["coll_ms_per_mb"] = {}
        for nd, pts in sorted(coll_pts.items()):
            c_edge, c_mb = fit(pts)
            prof["coll_edge_ms"][nd] = round(c_edge, 3)
            prof["coll_ms_per_mb"][nd] = round(c_mb, 3)
    if dcn_pts:
        prof["dcn_edge_ms"] = {}
        prof["dcn_ms_per_mb"] = {}
        for np_, pts in sorted(dcn_pts.items()):
            d_edge, d_mb = fit(pts)
            prof["dcn_edge_ms"][np_] = round(d_edge, 3)
            prof["dcn_ms_per_mb"][np_] = round(d_mb, 3)
    return prof


# ---------------------------------------------------------------------------
# edge byte estimates (annotate_static_hints row estimates x row width)
# ---------------------------------------------------------------------------


def _row_bytes(outputs) -> int:
    """Estimated wire bytes per row of an exchange edge: 8-byte device
    columns (+1 validity) for numerics/dates, dictionary code + pooled
    string estimate for varchars, two limbs for long decimals."""
    w = 0
    for _sym, t in outputs:
        name = getattr(t, "name", "")
        if name == "HLL_STATE":
            w += int(t.params[0]) + 1  # m uint8 registers per group row
        elif name == "KLL_STATE":
            w += int(t.params[0]) * 8 + 1  # 2K float64s per group row
        elif getattr(t, "is_string", False):
            w += 4 + 16 + 1  # i32 code + amortized dictionary entry
        elif getattr(t, "is_long_decimal", False):
            w += 16 + 1  # two Int128 limbs
        else:
            w += 8 + 1
    return max(w, 1)


def annotate_exchange_bytes(plan, session) -> None:
    """Attach `est_rows_hint` / `est_bytes_hint` to every Exchange node
    of a distributed plan (called by plan/distribute.distribute after
    the exchange insertion pass).  The hints are plain ints riding the
    node __dict__, so plan serde carries them through fragment cutting
    to the coordinator's fusion decision AND to workers (the serde
    round-trip the tests assert).  Stats failures leave nodes bare —
    the model then prices DEFAULT_EDGE_BYTES."""
    from presto_tpu.plan import nodes as P
    from presto_tpu.plan import stats as S

    catalog = getattr(session, "catalog", None)
    if catalog is None:
        return
    memo: dict = {}

    def walk(node):
        for s in node.sources:
            walk(s)
        if isinstance(node, P.Exchange):
            try:
                st = S.derive(node.source, catalog, memo)
                rows = int(max(st.est_rows, 1.0))
                node.est_rows_hint = rows
                node.est_bytes_hint = rows * _row_bytes(node.outputs())
            except Exception:  # noqa: BLE001 — hints are best-effort
                pass

    try:
        walk(plan.root)
        for sub in plan.subplans.values():
            walk(sub)
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# per-edge pricing + greedy contraction
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class EdgeDecision:
    """One exchange edge priced both ways.  `fuse` is the verdict;
    `reason` explains a cut ("" when fused): kind (edge kind excluded
    by fragment_fusion_kinds), cost (model: CUT cheaper), memo (the
    decision memo overrode the model), cross_host (no declared mesh —
    filled in by the caller, which owns placement)."""

    eid: int
    kind: str
    consumer: int
    producer: int
    est_bytes: int
    cut_est_ms: float
    fused_est_ms: Optional[float]
    fuse: bool
    reason: str = ""
    #: which collective fabric a FUSE verdict lowers onto: "ici" for a
    #: mesh-local edge, "dcn" when the fusion target spans processes —
    #: the cross_host_collective verdict (repartition -> all_to_all over
    #: DCN, broadcast/gather -> all_gather)
    lane: str = "ici"


def price_edges(fragments, ndev: int, profile: FusionProfile,
                kinds, nproc: int = 1) -> List[EdgeDecision]:
    """Model-only pricing pass: walk edges producers-first (the order
    `fuse_fragments` contracts them), price CUT vs FUSED with the
    marginal serialization penalty of the contraction, and greedily
    fuse net-win edges.  Union-find tracks fused-group sizes so each
    contraction is charged for the parallelism it destroys.  `nproc` >
    1 means the fusion target is a multi-process gang: the collective
    leg prices on the DCN lane."""
    parent = {f.fid: f.fid for f in fragments}
    gsize = {f.fid: 1 for f in fragments}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    lane = "dcn" if nproc > 1 else "ici"
    out: List[EdgeDecision] = []
    for frag in fragments:
        for inp in frag.inputs:
            nb = int(getattr(inp, "est_bytes", None)
                     or DEFAULT_EDGE_BYTES)
            cut = profile.cut_ms(nb)
            if inp.kind not in kinds:
                out.append(EdgeDecision(
                    inp.eid, inp.kind, frag.fid, inp.producer, nb,
                    round(cut, 3), None, False, "kind"))
                continue
            rc, rp = find(frag.fid), find(inp.producer)
            merged = gsize[rc] + gsize[rp]
            pen = (profile.serial_penalty_ms(merged)
                   - profile.serial_penalty_ms(gsize[rc])
                   - profile.serial_penalty_ms(gsize[rp]))
            if getattr(inp, "sketch", False):
                # sketch-state edge: a fixed-width register fold, priced
                # on the near-zero sketch lane so it fuses by default
                fused = profile.sketch_ms(nb) + pen
                elane = "sketch"
            else:
                fused = profile.fused_base_ms(nb, ndev, nproc) + pen
                elane = lane
            if fused < cut:
                parent[rp] = rc
                gsize[rc] = merged
                out.append(EdgeDecision(
                    inp.eid, inp.kind, frag.fid, inp.producer, nb,
                    round(cut, 3), round(fused, 3), True, "", elane))
            else:
                out.append(EdgeDecision(
                    inp.eid, inp.kind, frag.fid, inp.producer, nb,
                    round(cut, 3), round(fused, 3), False, "cost", elane))
    return out


def fingerprint(fragments) -> str:
    """Plan-shape fingerprint the decision memo keys on: the serde
    bytes of every fragment root (cut BEFORE fusion, so forced-fused,
    forced-cut, and auto legs of the same query share one key)."""
    from presto_tpu.plan import serde as plan_serde

    h = hashlib.sha1()
    for f in fragments:
        h.update(plan_serde.dumps(f.root))
        h.update(b"|")
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# decision memo: runtime feedback, hysteresis-guarded
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MemoEntry:
    best_fused_ms: Optional[float] = None   # best observed WARM wall
    best_cut_ms: Optional[float] = None
    fused_runs: int = 0
    cut_runs: int = 0
    override: Optional[str] = None          # "fuse" | "cut" | None
    strikes: int = 0
    flips: int = 0
    runs: int = 0


class DecisionMemo:
    """Bounded per-plan-fingerprint memory of observed execute walls.
    `observe` records each execution's wall under the mode that ran
    (fused / cut); once BOTH legs of a shape have been seen, the better
    one (by FLIP_MARGIN) becomes the override consulted on the next
    auto execution — a misprediction flips the edge set next run, never
    mid-query.  Overturning an existing override takes FLIP_STRIKES
    consecutive disagreeing observations (hysteresis), so walls jittering
    around parity never ping-pong the plan."""

    def __init__(self, max_entries: int = MEMO_MAX_ENTRIES):
        self._entries: "OrderedDict[str, MemoEntry]" = OrderedDict()
        self._max = max_entries
        self._lock = threading.Lock()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def entry(self, fp: str) -> Optional[MemoEntry]:
        with self._lock:
            return self._entries.get(fp)

    def verdict(self, fp: str) -> Optional[str]:
        with self._lock:
            e = self._entries.get(fp)
            return e.override if e is not None else None

    def observe(self, fp: str, mode: str, wall_ms: float) -> None:
        """Record one execution's wall.  `mode` is "fused" when the
        attempt ran any fused super-fragment, "cut" otherwise."""
        if wall_ms <= 0.0:
            return
        with self._lock:
            e = self._entries.get(fp)
            if e is None:
                e = self._entries[fp] = MemoEntry()
                while len(self._entries) > self._max:
                    self._entries.popitem(last=False)
            else:
                self._entries.move_to_end(fp)
            e.runs += 1
            # each mode's FIRST observation is cold — dominated by
            # one-time XLA compiles (a cut leg's per-fragment compile
            # bill dwarfs its steady-state wall) — so it never enters
            # the comparison; the best WARM wall is what fuse-vs-cut
            # economics are about
            if mode == "fused":
                e.fused_runs += 1
                if e.fused_runs > 1:
                    e.best_fused_ms = wall_ms if e.best_fused_ms is None \
                        else min(e.best_fused_ms, wall_ms)
            else:
                e.cut_runs += 1
                if e.cut_runs > 1:
                    e.best_cut_ms = wall_ms if e.best_cut_ms is None \
                        else min(e.best_cut_ms, wall_ms)
            f, c = e.best_fused_ms, e.best_cut_ms
            if f is None or c is None:
                return
            if f * FLIP_MARGIN < c:
                winner = "fuse"
            elif c * FLIP_MARGIN < f:
                winner = "cut"
            else:
                e.strikes = 0
                return
            if e.override is None:
                e.override = winner
                e.strikes = 0
            elif e.override != winner:
                e.strikes += 1
                if e.strikes >= FLIP_STRIKES:
                    e.override = winner
                    e.strikes = 0
                    e.flips += 1
            else:
                e.strikes = 0


#: process-wide memo, like the compile-cache executable memo: decisions
#: learned by one session serve every session executing the same shape
MEMO = DecisionMemo()


def memo_enabled(session) -> bool:
    """The feedback loop's kill switch (`fragment_fusion_memo`, default
    on): off = model-only decisions, nothing recorded."""
    try:
        return bool(session.properties.get("fragment_fusion_memo", True))
    except Exception:  # noqa: BLE001
        return True


def decide_edges(fragments, ndev: int, session, mode: str,
                 kinds, fp: str = "", nproc: int = 1) -> Tuple[
                     Dict[int, bool], Dict[str, int], int,
                     str, List[EdgeDecision]]:
    """The coordinator's one entry point: price every exchange edge and
    return (verdict {eid: fuse?}, skip-reason counts, mispredicted-edge
    count, plan fingerprint, per-edge decisions).  `fp` is the caller's
    precomputed plan fingerprint (computed here when omitted and the
    memo is on).  `nproc` is the process span of the fusion target the
    caller chose (1 = mesh-local; > 1 prices the DCN lane and stamps
    FUSE verdicts lane="dcn").

    mode "force" reproduces round 12: every kind-eligible edge fuses,
    the model prices nothing.  mode "auto" runs the greedy model, then
    applies the decision memo's override (if this shape has observed
    walls contradicting the model, the edges flip — each flipped edge
    counts as mispredicted)."""
    profile = load_profile(session, multihost=nproc > 1)
    if not fp and memo_enabled(session):
        fp = fingerprint(fragments)
    lane = "dcn" if nproc > 1 else "ici"
    if mode == "force":
        decisions = []
        for frag in fragments:
            for inp in frag.inputs:
                ok = inp.kind in kinds
                decisions.append(EdgeDecision(
                    inp.eid, inp.kind, frag.fid, inp.producer,
                    int(getattr(inp, "est_bytes", None)
                        or DEFAULT_EDGE_BYTES),
                    0.0, None, ok, "" if ok else "kind", lane))
        mispredicted = 0
    else:
        decisions = price_edges(fragments, ndev, profile, kinds, nproc)
        override = MEMO.verdict(fp) if fp else None
        mispredicted = 0
        if override is not None:
            for d in decisions:
                if d.reason == "kind":
                    continue
                if override == "cut" and d.fuse:
                    d.fuse, d.reason = False, "memo"
                    mispredicted += 1
                elif override == "fuse" and not d.fuse:
                    d.fuse, d.reason = True, ""
                    mispredicted += 1
    verdict = {d.eid: d.fuse for d in decisions}
    skips: Dict[str, int] = {}
    for d in decisions:
        if not d.fuse:
            skips[d.reason] = skips.get(d.reason, 0) + 1
    return verdict, skips, mispredicted, fp, decisions
