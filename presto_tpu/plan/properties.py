"""Ordering properties: derive, propagate, and exploit sortedness.

Reference parity: LocalProperties + StreamPropertyDerivations feeding
AddLocalExchanges (sql/planner/optimizations/), which elide redundant
local sorts/repartitions when an ordering or grouping is already
satisfied.  The TPU engine's version serves the sort economics of the
kernel layer: every heavyweight operator bottoms out in a full-length
`lax.sort` (~170ms per 6M rows measured), and the connectors' device
generators emit their tables ALREADY ordered by primary key — so
knowing (and re-deriving through the plan) what is sorted lets the
executor route to sort-free kernel variants (exec/kernels.py
group_ids_presorted / build_probe with an identity order).

Derived per node:

- ``sorted_on``: a tuple of (symbol, ascending) — the output rows are
  lexicographically nondecreasing on this key prefix over LIVE rows
  (masked rows may sit anywhere; the mask-not-compact executor never
  moves rows, it only hides them).
- ``grouped_on``: a tuple of symbols whose equal-value rows are
  contiguous among live rows (sortedness implies groupedness; grouping
  survives some transforms that break global order).

Claims seeded from connector metadata (``ConnectorTable.ordering()``)
are CLAIMS, not facts: every consumption site verifies them with a
traced monotonicity guard over the actual packed key (the same pattern
as ``layout_range_guard``), so a wrong declaration degrades to the
dynamic sort path and can never corrupt results.  Operator-produced
orderings (a sort-based group-by emits rows ascending on its packed
group key) are exact by construction but still flow through the same
guarded routing — certainty lives in the executor's runtime channel,
not here.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P


@dataclasses.dataclass(frozen=True)
class OrderingProps:
    """Per-node ordering claims (see module docstring).

    ``all_live_or_tail``: structurally, masked rows can only form a
    SUFFIX of this node's output (scans emit all-live; a static
    aggregate's exists mask is a prefix of live groups) — required by
    consumers that need the FULL array nondecreasing (a presorted join
    build, where masked-row sentinels must sort last by position).
    Filters and joins mask interior rows and clear it.

    ``fd_leading``: symbols functionally determined by the leading
    sorted symbol (constant within each of its equal-value runs) —
    derived from unique keys and unique-build joins.  What makes a
    multi-key GROUP BY packed key provably monotone when only the
    leading key is sorted (TPC-H q3: o_orderdate/o_shippriority ride
    the unique orders join, so they are constant per l_orderkey)."""

    sorted_on: Tuple[Tuple[str, bool], ...] = ()
    grouped_on: Tuple[str, ...] = ()
    all_live_or_tail: bool = False
    fd_leading: frozenset = frozenset()

    @property
    def leading(self) -> Optional[str]:
        return self.sorted_on[0][0] if self.sorted_on else None


EMPTY = OrderingProps()


def _scan_props(node: P.TableScan, catalog) -> OrderingProps:
    """Seed from connector metadata: the longest prefix of the table's
    declared ordering whose columns the scan projects.  A missing
    prefix column breaks the claim there (sortedness of (k1, k2) says
    nothing about k2 alone)."""
    if catalog is None:
        return EMPTY
    try:
        table = catalog.get(node.table)
    except KeyError:
        return EMPTY
    decl = []
    if hasattr(table, "ordering"):
        try:
            decl = list(table.ordering() or [])
        except Exception:
            decl = []
    if not decl:
        return EMPTY
    col_to_sym: Dict[str, str] = {}
    for sym, col in node.assignments.items():
        col_to_sym.setdefault(col, sym)
    out = []
    for col, asc in decl:
        sym = col_to_sym.get(col)
        if sym is None:
            break
        out.append((sym, bool(asc)))
    sorted_on = tuple(out)
    if not sorted_on:
        return EMPTY
    # leading column unique => every row's value is distinct => every
    # projected symbol is trivially constant within its (1-row) runs
    fd = {sorted_on[0][0]}
    try:
        uniq = [tuple(k) for k in table.unique_keys()] \
            if hasattr(table, "unique_keys") else []
    except Exception:
        uniq = []
    lead_col = decl[0][0]
    if (lead_col,) in uniq:
        fd |= set(node.assignments)
    return OrderingProps(sorted_on, tuple(s for s, _ in sorted_on),
                         all_live_or_tail=True, fd_leading=frozenset(fd))


def _project_props(node: P.Project, src: OrderingProps) -> OrderingProps:
    """Row-wise: order passes through identity (Ref) assignments under
    their new names; the prefix cuts at the first key that is not
    re-exposed as a plain Ref.  An output is FD-of-leading when every
    input it reads is (a pure row-wise function of constants is
    constant)."""
    out_of: Dict[str, str] = {}
    for sym, e in node.assignments.items():
        if isinstance(e, ir.Ref):
            out_of.setdefault(e.name, sym)
    sorted_on = []
    for sym, asc in src.sorted_on:
        mapped = out_of.get(sym)
        if mapped is None:
            break
        sorted_on.append((mapped, asc))
    if not sorted_on:
        return OrderingProps(all_live_or_tail=src.all_live_or_tail)
    grouped = []
    for sym in src.grouped_on:
        mapped = out_of.get(sym)
        if mapped is None:
            break
        grouped.append(mapped)
    fd = set()
    for sym, e in node.assignments.items():
        try:
            if e.refs() <= src.fd_leading:
                fd.add(sym)
        except Exception:
            pass
    fd.add(sorted_on[0][0])
    return OrderingProps(tuple(sorted_on), tuple(grouped),
                         all_live_or_tail=src.all_live_or_tail,
                         fd_leading=frozenset(fd))


def _aggregate_props(node: P.Aggregate) -> OrderingProps:
    """Sort-based grouping emits one row per group in ascending packed-
    key order, and kernels pack with the FIRST key most significant —
    so the output is sorted on the group keys in pack order.  Exact
    packing only: the 62-bit hash fallback is order-destroying, which
    is one of the reasons consumers must guard.  all_live_or_tail stays
    False: the small-layout direct path (packed key as slot id) leaves
    dead slots INTERSPERSED; the executor's runtime channel knows which
    path actually ran and upgrades certainty there."""
    if not node.group_keys:
        return EMPTY  # single global row: trivially sorted, nothing usable
    keys = list(getattr(node, "ordering_pack_order", None)
                or node.group_keys)
    fd = {keys[0]}
    if len(keys) == 1:
        # unique on the single key: every output symbol constant per row
        fd |= {keys[0]} | set(node.aggs)
    return OrderingProps(tuple((k, True) for k in keys), tuple(keys),
                         all_live_or_tail=False, fd_leading=frozenset(fd))


def _join_props(node: P.Join, left: OrderingProps,
                right: OrderingProps) -> OrderingProps:
    """Probe (left) order survives every probe-layout-preserving join in
    this executor: SEMI/ANTI/MARK mask the probe in place; unique-build
    INNER/LEFT and index joins gather the build at probe positions; the
    expanding join emits probe rows in nondecreasing probe-row order
    (lidx = repeat(arange)).  Sort-order materialization re-permutes an
    expansion ONLY when every consumer is order-insensitive, and the
    executor turns that off below ordering-exploiting aggregates — the
    claim and the exploitation are kept consistent there.  FULL appends
    unmatched build rows (order destroyed); CROSS repeats the probe
    rows in order (preserved).

    FD transfer: a single-criterion unique-build INNER/LEFT join whose
    probe key is FD-of-leading makes EVERY build output constant within
    a leading run (the unique build row per key value — the FD that
    lets q3 group by (l_orderkey, o_orderdate, o_shippriority) with
    only l_orderkey sorted)."""
    if node.join_type == "FULL":
        return EMPTY
    if node.join_type == "RIGHT":
        # executed as the mirrored LEFT: build (left operand) rows
        # gathered at probe positions — the RIGHT side's order survives
        base = right
    else:
        base = left
    if not base.sorted_on:
        return EMPTY
    fd = set(base.fd_leading)
    if node.join_type in ("INNER", "LEFT") and len(node.criteria) == 1 \
            and getattr(node, "build_unique", False):
        lk, _rk = node.criteria[0]
        if lk in fd:
            fd |= {s for s, _ in node.right.outputs()}
    # INNER/SEMI/ANTI/expanding joins mask or repeat interior rows
    return OrderingProps(base.sorted_on, base.grouped_on,
                         all_live_or_tail=False,
                         fd_leading=frozenset(fd))


def _window_props() -> OrderingProps:
    # execute_window sorts by (partition, order) and leaves the batch
    # there; claiming that ordering needs partition-key prefix
    # semantics we don't exploit yet — stay conservative
    return EMPTY


def derive(node: P.PlanNode, catalog, memo=None) -> OrderingProps:
    """Bottom-up ordering derivation (identity-checked memo, same shape
    as plan/stats.derive)."""
    if memo is None:
        memo = {}
    hit = memo.get(id(node))
    if hit is not None and hit[0] is node:
        return hit[1]
    p = _derive(node, catalog, memo)
    memo[id(node)] = (node, p)
    return p


def _derive(node, catalog, memo) -> OrderingProps:
    d = lambda n: derive(n, catalog, memo)
    if isinstance(node, P.TableScan):
        return _scan_props(node, catalog)
    if isinstance(node, P.Filter):
        # masking never moves rows, but it punches interior holes
        return dataclasses.replace(d(node.source), all_live_or_tail=False)
    if isinstance(node, P.Limit):
        # rank-cut: live rows keep their positions; newly-masked rows
        # extend whatever tail the input already had
        return d(node.source)
    if isinstance(node, P.Project):
        return _project_props(node, d(node.source))
    if isinstance(node, P.Output):
        return d(node.source)
    if isinstance(node, P.Aggregate):
        d(node.source)  # populate memo for annotate()
        return _aggregate_props(node)
    if isinstance(node, (P.Sort, P.TopN)):
        d(node.source)
        sorted_on = tuple((s, asc) for s, asc, _nf in node.keys)
        # sort_perm sends masked rows last => suffix masking
        return OrderingProps(sorted_on, tuple(s for s, _ in sorted_on),
                             all_live_or_tail=True,
                             fd_leading=frozenset({sorted_on[0][0]})
                             if sorted_on else frozenset())
    if isinstance(node, P.Join):
        return _join_props(node, d(node.left), d(node.right))
    if isinstance(node, P.SpatialJoin):
        d(node.left)
        d(node.right)
        return EMPTY
    if isinstance(node, P.Window):
        d(node.source)
        return _window_props()
    if isinstance(node, P.Exchange):
        d(node.source)
        return EMPTY  # repartition/broadcast/gather interleave rows
    if isinstance(node, P.Union):
        for s in node.sources_:
            d(s)
        return EMPTY  # concatenation of sorted runs is not sorted
    if isinstance(node, P.Unnest):
        # probe rows expand in nondecreasing source order; dead slots
        # land interior
        return dataclasses.replace(d(node.source), all_live_or_tail=False)
    if isinstance(node, P.Values):
        return EMPTY
    for s in getattr(node, "sources", []):
        d(s)
    return EMPTY


def annotate(plan: P.QueryPlan, session) -> None:
    """Attach ordering hints the executor's guarded routing consults:

    - ``Aggregate.ordering_hint`` + ``ordering_pack_order`` (+
      ``ordering_hint_safe``): the input is claimed sorted on a leading
      group key — pack it most significant and route to the
      run-boundary scan (no grouping sort, no unpermute) behind a
      monotonicity guard.  ``safe`` means every remaining key is
      provably constant within leading-key runs (sorted-prefix-covered
      or FD-of-leading), so the guard cannot trip for structural
      reasons — the compiled path only exploits safe hints, because a
      tripped static guard costs a whole-query dynamic re-run, while
      the dynamic path host-checks cheaply and exploits all hints.
    - ``Join.build_ordering_hint``: the single-criterion build side is
      claimed sorted on the join key with masked rows structurally
      confined to a suffix — elides the build argsort behind a
      full-array monotone guard.

    Hints are advisory; every exploitation is guard-verified at
    runtime, so stale or wrong metadata degrades, never corrupts."""
    catalog = getattr(session, "catalog", None)
    memo: dict = {}
    seen: set = set()

    def walk(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for s in node.sources:
            walk(s)
        if isinstance(node, P.Aggregate) and node.group_keys:
            src = derive(node.source, catalog, memo)
            lead = src.leading
            if lead not in node.group_keys or not src.sorted_on[0][1]:
                return
            # pack the sorted-covered run first (in sorted order), then
            # the remaining keys: monotone iff the remainder is
            # constant within leading runs
            prefix = []
            for s, asc in src.sorted_on:
                if not asc or s not in node.group_keys or s in prefix:
                    break
                prefix.append(s)
            rest = [k for k in node.group_keys if k not in prefix]
            node.ordering_hint = lead
            node.ordering_pack_order = prefix + rest
            node.ordering_hint_safe = all(k in src.fd_leading
                                          for k in rest)
        elif isinstance(node, P.Join) and len(node.criteria) == 1 \
                and node.join_type not in ("CROSS",):
            rk = node.criteria[0][1]
            rp = derive(node.right, catalog, memo)
            if rp.leading == rk and rp.sorted_on[0][1] \
                    and rp.all_live_or_tail:
                node.build_ordering_hint = True

    walk(plan.root)
    for sub in plan.subplans.values():
        walk(sub)
