"""Extract per-column Domains (TupleDomain analog) from filter
conjuncts, for scan-time stats pruning inside the file readers.

Reference: presto-spi/.../spi/predicate/TupleDomain.java +
DomainTranslator (presto-main/.../sql/planner/DomainTranslator.java),
trimmed to the shapes that prune stripes/row groups: range comparisons
against literals, BETWEEN, and OR-of-equalities (how the planner lowers
IN lists).  The extraction is ADVISORY — the Filter node still runs, so
an unextractable conjunct simply contributes no pruning.
"""

from __future__ import annotations

from typing import Dict, Optional

from presto_tpu.plan import ir
from presto_tpu.storage.shard import Domain

_CMP = {"lt": "hi_open", "le": "hi", "gt": "lo_open", "ge": "lo",
        "eq": "eq"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}

# types whose literal space equals the reader's stats space (DATE =
# days, TIMESTAMP = micros, strings compare lexically); DECIMAL is
# excluded (unscaled-int literals vs scaled stats)
_PRUNABLE = ("TINYINT", "SMALLINT", "INTEGER", "BIGINT", "REAL",
             "DOUBLE", "DATE", "TIMESTAMP", "VARCHAR", "CHAR")


def _lit_value(e) -> Optional[object]:
    # see through literal-widening casts the planner inserts around
    # comparison operands (CAST(2000 AS BIGINT), CAST(7 AS DOUBLE))
    while isinstance(e, ir.CastExpr) and not e.safe \
            and e.type.name in _PRUNABLE and isinstance(e.arg, ir.Lit):
        v = e.arg.value
        if v is None or not isinstance(v, (int, float, str)):
            return None
        if e.type.name in ("REAL", "DOUBLE"):
            if not isinstance(v, (int, float)):
                return None
            e = ir.Lit(float(v), e.type)
        elif e.type.name in ("TINYINT", "SMALLINT", "INTEGER", "BIGINT",
                             "DATE", "TIMESTAMP"):
            if not isinstance(v, int) or not e.arg.type.name in (
                    "TINYINT", "SMALLINT", "INTEGER", "BIGINT", "DATE",
                    "TIMESTAMP", "UNKNOWN"):
                return None  # float->int rounds; string parses — skip
            e = ir.Lit(v, e.type)
        elif e.type.name in ("VARCHAR", "CHAR") and isinstance(v, str) \
                and e.arg.type.name in ("VARCHAR", "CHAR"):
            e = ir.Lit(v, e.type)
        else:
            return None
    if isinstance(e, ir.Lit) and e.value is not None \
            and e.type.name in _PRUNABLE:
        if e.type.name == "REAL" and isinstance(e.value, float):
            # REAL stats decode from float32 storage; an un-rounded f64
            # literal (0.1 != f32(0.1)) would fail to overlap stats of
            # stripes whose rows the f32 Filter matches
            import numpy as _np

            return float(_np.float32(e.value))
        return e.value
    return None


def _ref_lit(c: ir.Call):
    """(ref, lit, op) for `ref op lit` / `lit op ref`, else None."""
    if len(c.args) != 2 or c.fn not in _CMP:
        return None
    a, b = c.args
    if isinstance(a, ir.Ref) and _lit_value(b) is not None:
        return a, _lit_value(b), c.fn
    if isinstance(b, ir.Ref) and _lit_value(a) is not None:
        return b, _lit_value(a), _FLIP[c.fn]
    return None


def _eq_chain(e) -> Optional[tuple]:
    """OR-of-equalities over one Ref (lowered IN list) ->
    (ref_name, [values]); None otherwise."""
    if not isinstance(e, ir.Call):
        return None
    if e.fn == "eq":
        rl = _ref_lit(e)
        if rl is None or rl[2] != "eq":
            return None
        return rl[0].name, [rl[1]]
    if e.fn == "or" and len(e.args) == 2:
        l, r = _eq_chain(e.args[0]), _eq_chain(e.args[1])
        if l is None or r is None or l[0] != r[0]:
            return None
        return l[0], l[1] + r[1]
    return None


def _merge(dom: Domain, add: Domain) -> Domain:
    """Conjunction of two domains on the same column."""
    if add.values is not None:
        vals = add.values if dom.values is None else \
            [v for v in dom.values if v in set(add.values)]
        vals = [v for v in vals
                if (dom.lo is None or v >= dom.lo)
                and (dom.hi is None or v <= dom.hi)]
        return Domain(values=vals)
    lo = add.lo if dom.lo is None else (
        dom.lo if add.lo is None else max(dom.lo, add.lo))
    hi = add.hi if dom.hi is None else (
        dom.hi if add.hi is None else min(dom.hi, add.hi))
    if dom.values is not None:
        return Domain(values=[v for v in dom.values
                              if (lo is None or v >= lo)
                              and (hi is None or v <= hi)])
    return Domain(lo, hi)


def domains_from_conjuncts(conjuncts, assignments: Dict[str, str]
                           ) -> Dict[str, Domain]:
    """symbol-level conjuncts -> {source column name: Domain}.

    `assignments` maps scan output symbols to connector column names
    (P.TableScan.assignments)."""
    out: Dict[str, Domain] = {}

    def add(sym: str, dom: Domain):
        col = assignments.get(sym)
        if col is None:
            return
        out[col] = _merge(out[col], dom) if col in out else dom

    for c in conjuncts:
        if not isinstance(c, ir.Call):
            continue
        chain = _eq_chain(c)
        if chain is not None:
            add(chain[0], Domain(values=sorted(set(chain[1]))))
            continue
        if c.fn == "between" and len(c.args) == 3 \
                and isinstance(c.args[0], ir.Ref):
            lo, hi = _lit_value(c.args[1]), _lit_value(c.args[2])
            if lo is not None or hi is not None:
                add(c.args[0].name, Domain(lo, hi))
            continue
        rl = _ref_lit(c) if c.fn in _CMP else None
        if rl is None:
            continue
        ref, val, op = rl
        # zone maps are closed ranges: open bounds keep the value as an
        # inclusive endpoint (an equal-to-bound stripe survives; the
        # Filter still removes its rows) — same relaxation the
        # reference applies mapping Marker.ABOVE/BELOW onto min/max
        if op in ("lt", "le"):
            add(ref.name, Domain(None, val))
        elif op in ("gt", "ge"):
            add(ref.name, Domain(val, None))
        else:  # eq
            add(ref.name, Domain(values=[val]))
    return {c: d for c, d in out.items()}


def merge_domain_maps(static: Dict[str, Domain],
                      runtime: Dict[str, Domain]) -> Dict[str, Domain]:
    """INTERSECT runtime-derived domains (dynamic filtering,
    plan/runtime_filters.py) with the statically extracted ones instead
    of replacing them: both constraints hold conjunctively, so a stripe
    must overlap BOTH to survive.  A column present in only one map
    keeps that map's domain unchanged."""
    out = dict(static or {})
    for col, dom in (runtime or {}).items():
        out[col] = _merge(out[col], dom) if col in out else dom
    return out


def domains_pickle_safe(domains: Dict[str, Domain]) -> Dict[str, Domain]:
    """numpy scalars -> python scalars so plan fragments serialize
    identically everywhere."""
    import numpy as np

    def clean(v):
        return v.item() if isinstance(v, np.generic) else v

    out = {}
    for c, d in domains.items():
        out[c] = Domain(clean(d.lo), clean(d.hi),
                        None if d.values is None
                        else [clean(v) for v in d.values])
    return out
