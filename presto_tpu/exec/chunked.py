"""Chunked (grouped) execution: run plans whose inputs exceed HBM by
streaming the big bucketed tables chunk-by-chunk through ONE compiled
per-chunk program.

Reference parity: grouped execution — `Lifespan.driverGroup(bucket)`
runs one bucket at a time through a whole pipeline so memory stays
bounded to 1/N of the table (execution/Lifespan.java:26-38,
StageExecutionDescriptor, BucketNodeMap), plus the partial->final
aggregation split and partial topN of AddExchanges.  TPU-native
adaptation:

- WHICH tables can stream, on WHICH bucket column, and HOW a bucket's
  rows are produced on device is connector metadata — the ChunkFamily
  SPI (`ConnectorTable.bucketing()`, the analog of
  ConnectorNodePartitioningProvider, spi/connector/Connector.java:74):
  a family is a set of co-bucketed tables (tpch lineitem+orders on
  orderkey; tpcds store_sales+store_returns on ticket_number,
  catalog_sales+catalog_returns on order_number) with a chunk grid and
  an in-trace device scan builder;
- the distributed planner (plan/distribute.py) plans chunks as shards
  over a VIRTUAL TIME AXIS: bucketed scans are `hashed` on the bucket
  column (range-bucketing colocates equi-joins exactly like
  hash-bucketing), resident tables are `replicated` (whole in HBM,
  visible to every chunk);
- the plan is cut at Exchange nodes (parallel/cluster.cut_fragments,
  the PlanFragmenter analog); an exchange between a chunk-looped
  fragment and its consumer is an ON-DEVICE concat buffer — partial
  states are tiny after per-chunk aggregation/topN, so "shuffle"
  degenerates to concatenation on one chip; a query may chunk-loop
  SEVERAL families (q64 streams the store channel and the catalog
  channel through separate loops whose buffered outputs join);
- each chunk-looped fragment compiles ONCE: chunk shapes are padded to
  a static capacity and the chunk start offsets enter as traced
  scalars; scan batches are GENERATED ON DEVICE inside the same
  compiled program (connectors/tpch_device.py, tpcds_device.py), so a
  600M-row scan never exists anywhere — not in host RAM, not in HBM.
"""

from __future__ import annotations

import dataclasses
import sys
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from presto_tpu.batch import Batch, Column
from presto_tpu.exec import compile_cache as CC
from presto_tpu.exec import kernels as K
from presto_tpu.observe import trace as TR
from presto_tpu.plan import agg_strategy as AS
from presto_tpu.plan import nodes as P


def _pow2(n: int) -> int:
    """Geometric quantization of compact bounds to the next power of
    two: near-identical stats-derived bounds (across fragments, mult
    growth steps, and sessions) collapse onto one padded shape, so
    bound misses stop minting fresh executables for near-identical
    programs — and the persistent compile cache hits across processes.
    A larger capacity never changes results: compaction keeps the same
    live rows and overflow still compares the live count to the
    (quantized) bound."""
    return 1 << max(int(n) - 1, 0).bit_length()


class Unchunkable(Exception):
    """Plan/catalog shape the chunked runner can't handle; callers fall
    back to whole-table execution."""


class _CompactOverflow(Exception):
    """A fragment produced more live rows than its compact bound.  NOT a
    correctness failure: the runner grows the bound and re-runs the
    fragment (the reference's grouped execution never hard-fails on
    bucket size either — Lifespan-per-bucket isolates it).  Raised only
    internally; callers of run_chunked never see it."""


# scans above this row count stream chunk-wise instead of residing whole
DEFAULT_STREAM_THRESHOLD = 120_000_000


def _collect_scans(node, out):
    if isinstance(node, P.TableScan):
        out.append(node)
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, P.PlanNode):
            _collect_scans(v, out)
        elif isinstance(v, list):
            for x in v:
                if isinstance(x, P.PlanNode):
                    _collect_scans(x, out)


def _threshold(session) -> int:
    return int(session.properties.get(
        "chunked_rows_threshold", DEFAULT_STREAM_THRESHOLD))


def _bucketing(table) -> Optional[object]:
    fn = getattr(table, "bucketing", None)
    return fn() if fn is not None else None


def catalog_may_need_chunks(session) -> bool:
    """Cheap pre-check (no planning): any bucketed big table at all?"""
    threshold = _threshold(session)
    for t in session.catalog.tables.values():
        if _bucketing(t) is not None and t.row_count() > threshold:
            return True
    return False


def chunk_plan_needed(session, plan) -> bool:
    """True when some scanned bucketed table is too big to reside in
    HBM whole."""
    threshold = _threshold(session)
    scans: List[P.TableScan] = []
    _collect_scans(plan.root, scans)
    for n in scans:
        try:
            t = session.catalog.get(n.table)
        except KeyError:
            return False
        if _bucketing(t) is not None and t.row_count() > threshold:
            return True
    return False


def _plan_streaming(session, scans) -> Dict[str, object]:
    """{table: family} for every plan table whose chunk family has at
    least one member over the streaming threshold (family members
    stream TOGETHER — their colocated bucketing is what keeps the
    family's equi-joins chunk-local)."""
    threshold = _threshold(session)
    by_family: Dict[str, list] = {}
    for tname in {n.table for n in scans}:
        try:
            t = session.catalog.get(tname)
        except KeyError:
            continue
        fam = _bucketing(t)
        if fam is not None:
            by_family.setdefault(fam.name, []).append((tname, t, fam))
    streamed: Dict[str, object] = {}
    for members in by_family.values():
        if any(t.row_count() > threshold for _, t, _f in members):
            for tname, _t, fam in members:
                streamed[tname] = fam
    return streamed


def run_chunked(session, stmt, text: str, mon=None):
    """Plan + execute a chunked query; returns a QueryResult.  The
    prepared execution (distributed plan, fragments, jitted per-chunk
    programs) memoizes per session so warm runs skip planning AND
    XLA compilation (a fresh jax.jit closure would otherwise recompile
    every run — ~minutes at SF100)."""
    from presto_tpu.exec.executor import Executor, plan_statement
    from presto_tpu.parallel.cluster import cut_fragments
    from presto_tpu.plan.distribute import Undistributable, distribute

    cache = getattr(session, "_chunked_cache", None)
    if cache is None:
        cache = session._chunked_cache = {}
    from presto_tpu.exec.executor import query_cache_key

    key = query_cache_key(session, text)
    prepared = cache.get(key)
    if prepared is not None:
        return _execute_prepared(session, *prepared, mon=mon)

    # ALWAYS re-plan (the executor's probe plan used inference ON):
    # chunked mode needs transitive semi-join inference OFF (see
    # plan/optimizer._optimize_node — the inferred probe-side semi can
    # never compact at chunk capacities and costs a join per chunk)
    prev_tsi = session.properties.get("transitive_semijoin_inference", True)
    session.properties["transitive_semijoin_inference"] = False
    try:
        plan = plan_statement(session, stmt)
    finally:
        session.properties["transitive_semijoin_inference"] = prev_tsi
    if plan.subplans:
        raise Unchunkable("scalar subplans not supported in chunked mode")

    scans: List[P.TableScan] = []
    _collect_scans(plan.root, scans)
    streamed = _plan_streaming(session, scans)
    if not streamed:
        raise Unchunkable("no bucketed big table in plan")

    for n in scans:
        fam = streamed.get(n.table)
        if fam is not None:
            missing = set(n.assignments.values()) \
                - fam.device_columns(n.table)
            if missing:
                raise Unchunkable(
                    f"{n.table} columns not device-generable: {missing}")

    grids = {}
    for fam in streamed.values():
        if fam.name not in grids:
            grids[fam.name] = fam.make_grid(session)
    table_family = {t: fam.name for t, fam in streamed.items()}
    bucketed = {t: fam.bucket_column(t) for t, fam in streamed.items()}
    nchunks = max(g.nchunks for g in grids.values())
    try:
        dplan = distribute(plan, session, ndev=nchunks, bucketed=bucketed)
    except Undistributable as e:
        raise Unchunkable(f"undistributable: {e}")

    frags = cut_fragments(dplan.root)
    f32 = bool(session.properties.get("float32_compute", False))

    runner = _FragmentRunner(session, f32, table_family, grids, {},
                             bucketed=bucketed)
    consumer_eid = {}  # producer fid -> eid of the exchange it feeds
    for f in frags:
        for inp in f.inputs:
            consumer_eid[inp.producer] = inp.eid
    # compile-ahead (exec/compile_cache.py): AOT-compile fragments 2..N
    # on the bounded pool while fragment 1 executes below — the serial
    # compile wall a cold chunked query otherwise pays per fragment
    runner.compile_ahead(frags, table_family)
    result = _execute_prepared(session, dplan, frags, runner, table_family,
                               consumer_eid, mon=mon)
    cache[key] = (dplan, frags, runner, table_family, consumer_eid)
    return result


def _execute_prepared(session, dplan, frags, runner, table_family,
                      consumer_eid, mon=None):
    from presto_tpu.exec.executor import (Executor, StaticFallback,
                                          _merge_sort_stats)

    runner.buffers.clear()
    runner.run_stats = {}  # per-run counters (chunk pruning)
    try:
        final_batch = _run_fragments(session, frags, runner, table_family,
                                     consumer_eid)
        ex = Executor(session)
        return ex.materialize(dplan, final_batch)
    finally:
        if mon is not None:
            # trace-time routing decisions of the per-chunk programs
            # (warm runs replay the same totals, not re-accumulate) +
            # this run's host-side dynamic-filter chunk pruning
            _merge_sort_stats(mon.stats, runner.sort_stats)
            _merge_sort_stats(mon.stats, runner.run_stats)
        runner.buffers.clear()  # don't pin HBM between runs


def _run_fragments(session, frags, runner, table_family, consumer_eid):
    from presto_tpu.exec.executor import StaticFallback
    from presto_tpu.observe import trace as TR

    final_batch = None
    for frag in frags:
        fscans: List[P.TableScan] = []
        _collect_scans(frag.root, fscans)
        chunked = any(s.table in table_family for s in fscans)
        t0 = TR.clock_ns()
        span_cm = TR.maybe_span(f"fragment f{frag.fid}", kind="fragment",
                                fid=frag.fid, chunked=chunked)
        span_cm.__enter__()
        try:
            if chunked:
                out = runner.run_chunk_loop(frag, fscans)
            elif frag.fid in runner.dynamic_fids \
                    or _spill_routes_dynamic(session, frag.root):
                # spill-tiered degradation (exec/spill_exec.py) cannot
                # run inside a static trace; when a deterministic spill
                # knob is armed, run-once join/aggregate fragments (the
                # buffered-exchange consumers holding the big hash
                # state) execute on the dynamic, spillable path.  Chunk
                # LOOPS stay static: their per-chunk working set is
                # already bounded by the chunk capacity.
                out = runner.run_once_dynamic(frag, fscans)
            else:
                try:
                    out = runner.run_once(frag, fscans)
                except (StaticFallback, Unchunkable):
                    # a run-once fragment (resident scans / buffered
                    # exchange inputs, e.g. q64's cross_sales self-join
                    # whose fanout has no static bound, or a fragment
                    # whose runtime guard tripped) executes ONCE on
                    # already-reduced data — the dynamic executor with
                    # host syncs is fine there, only chunk LOOPS must
                    # stay sync-free.  Memoized so warm runs skip the
                    # doomed trace.
                    runner.dynamic_fids.add(frag.fid)
                    out = runner.run_once_dynamic(frag, fscans)
        except StaticFallback as e:
            # a chunk-loop shape the static executor can't bound: let
            # the caller fall back to whole-table paths
            raise Unchunkable(f"static fallback: {e}")
        finally:
            span_cm.__exit__(None, None, None)
            # per-RUN fragment wall (EXPLAIN ANALYZE attribution)
            runner.frag_wall_ns[frag.fid] = TR.clock_ns() - t0
        eid = consumer_eid.get(frag.fid)
        if eid is None:  # no consumer: the root fragment's result
            final_batch = out
        else:
            runner.buffers[eid] = out
    return final_batch


def _spill_routes_dynamic(session, root) -> bool:
    """True when an armed spill knob should send this run-once fragment
    to the dynamic executor: the fragment contains a spill-eligible
    operator (grouped aggregate, or an INNER/LEFT/FULL equi-join)."""
    from presto_tpu.exec import spill_exec as SE

    if not SE.routing_enabled(session):
        return False

    def walk(node) -> bool:
        t = type(node).__name__
        if t == "Aggregate" and node.group_keys:
            return True
        if t == "Join" and node.criteria \
                and node.join_type in ("INNER", "LEFT", "FULL", "RIGHT"):
            return True
        return any(walk(s) for s in getattr(node, "sources", []))

    return walk(root)


def _root_order_insensitive(root) -> bool:
    """May this fragment's OUTPUT rows arrive in any order?  True for a
    partial-aggregate root: its consumer is the FINAL aggregate, which
    re-groups whatever order the buffered partials arrive in.  (Join
    subtrees below an in-fragment aggregate are covered by the
    executor's walk independent of this root flag.)"""
    node = root
    while type(node).__name__ in ("Output", "Project", "Filter"):
        node = node.source
    return type(node).__name__ == "Aggregate" \
        and getattr(node, "step", "SINGLE") == "PARTIAL"


class _PrunedGridView:
    """Grid façade exposing only the chunks whose zone ranges overlap a
    runtime-filter domain (dynamic filtering at chunk grain): the loop
    streams the kept chunks and never dispatches the rest."""

    def __init__(self, base, keep):
        self.base = base
        self.keep = list(keep)
        self.nchunks = len(self.keep)

    def __getattr__(self, name):
        return getattr(self.base, name)

    def chunk_args(self, i: int):
        return self.base.chunk_args(self.keep[i])


def _rf_resident_domains(root, resident) -> Dict[str, object]:
    """{filter id: storage.shard.Domain} for every rf-producing join in
    this fragment whose BUILD input is a resident batch (an exchange
    buffer or a resident catalog scan) reachable through Filter /
    identity-Project edges.  Filters applied deeper in the fragment make
    the resident values a SUPERSET of the final build keys — chunk
    pruning on a superset is sound, merely less sharp."""
    import numpy as np

    from presto_tpu.plan import ir
    from presto_tpu.storage.shard import Domain

    out: Dict[str, object] = {}

    def resolve(node, sym):
        while True:
            if isinstance(node, P.TableScan):
                return (node, sym) if id(node) in resident else None
            if isinstance(node, P.Filter):
                node = node.source
            elif isinstance(node, P.Project):
                e = node.assignments.get(sym)
                if not isinstance(e, ir.Ref):
                    return None
                sym = e.name
                node = node.source
            else:
                return None

    def walk(node):
        for s in getattr(node, "sources", []):
            walk(s)
        if not isinstance(node, P.Join) \
                or node.join_type not in ("INNER", "SEMI"):
            return
        for spec in getattr(node, "rf_produce", None) or []:
            hit = resolve(node.right, spec["build_sym"])
            if hit is None:
                continue
            scan, sym = hit
            b = resident[id(scan)]
            col = b.columns.get(sym)
            if col is None or col.dictionary is not None \
                    or getattr(col.data, "ndim", 1) != 1 \
                    or jnp.issubdtype(col.data.dtype, jnp.floating):
                continue
            live = np.asarray(b.sel)
            if col.valid is not None:
                live = live & np.asarray(col.valid)
            vals = np.asarray(col.data)[live]
            if vals.size == 0:
                out[spec["fid"]] = Domain(values=[])  # prunes everything
                continue
            uniq = np.unique(vals.astype(np.int64))
            if uniq.size <= 4096:  # Domain.overlaps scans values per chunk
                out[spec["fid"]] = Domain(values=[int(v) for v in uniq])
            else:
                out[spec["fid"]] = Domain(int(uniq[0]), int(uniq[-1]))

    walk(root)
    return out


class _LaneFrag:
    """A fragment façade for an alternate execution LANE of the same
    fragment (the adaptive partial-agg pass-through lane): its own fid
    key and root, sharing the base fragment's scan subtree so the
    runner's scan_inputs id-keying and executable caches line up."""

    __slots__ = ("fid", "root", "inputs")

    def __init__(self, fid, root, inputs=()):
        self.fid = fid
        self.root = root
        self.inputs = list(inputs)


class _MeshGridView:
    """Presents a base chunk grid as a grid of SUPERSTEPS: superstep i
    covers micro-chunks [i*n, (i+1)*n), one per mesh device, with args
    stacked along the device axis (trailing supersteps pad with empty
    micro-chunks whose live counts are zero)."""

    def __init__(self, base, n: int):
        self.base = base
        self.n = n
        self.nchunks = -(-base.nchunks // n)
        self._empty = tuple(jnp.zeros_like(a) for a in base.chunk_args(0))

    def exchange_bound(self) -> int:
        return self.base.exchange_bound() * self.n

    def chunk_args(self, step: int):
        argsets = []
        for d in range(self.n):
            i = step * self.n + d
            argsets.append(self.base.chunk_args(i)
                           if i < self.base.nchunks else self._empty)
        return tuple(jnp.stack([a[j] for a in argsets])
                     for j in range(len(argsets[0])))


class _ChunkTableView:
    """Stats façade for one streamed table: per-chunk row count and a
    bucket-column ndv bounded by the grid's buckets-per-chunk, so
    stats.derive sees the table AT CHUNK GRAIN (a per-chunk GROUP BY
    bucket_key then bounds at bucket grain, a lineitem-grain projection
    at fact grain — the distinction round 3's single family-wide
    exchange_bound() got wrong)."""

    def __init__(self, table, cap: int, bucket_col: Optional[str],
                 bucket_ndv: Optional[int]):
        self._t = table
        self._cap = cap
        self._bcol = bucket_col
        self._bndv = bucket_ndv

    def row_count(self) -> int:
        return self._cap

    def column_stats(self, col):
        cs = self._t.column_stats(col) \
            if hasattr(self._t, "column_stats") else None
        if col == self._bcol and self._bndv:
            from presto_tpu.plan.stats import ColStats

            ndv = self._bndv if cs is None or not cs.ndv \
                else min(cs.ndv, self._bndv)
            return ColStats(cs.min if cs else None,
                            cs.max if cs else None, ndv)
        return cs

    def unique_keys(self):
        return self._t.unique_keys() if hasattr(self._t, "unique_keys") \
            else []

    def max_rows_per_key(self):
        return self._t.max_rows_per_key() \
            if hasattr(self._t, "max_rows_per_key") else {}


class _BufferTableView:
    """Stats façade for an __exch_N scan: the buffered batch's capacity
    is the row bound; column stats unknown."""

    def __init__(self, rows: int):
        self._rows = rows

    def row_count(self) -> int:
        return self._rows


class _ChunkStatsCatalog:
    """Catalog façade handed to stats.derive when bounding a fragment's
    per-chunk output (see _FragmentRunner._fragment_bound); each
    streamed table resolves its own family's grid."""

    def __init__(self, runner):
        self.runner = runner

    def get(self, name: str):
        r = self.runner
        if name.startswith("__exch_"):
            b = r.buffers.get(int(name[len("__exch_"):]))
            if b is None:
                raise KeyError(name)
            return _BufferTableView(int(b.sel.shape[0]))
        t = r.session.catalog.get(name)
        fam = r.table_family.get(name)
        if fam is None:
            return t
        grid = r.grids[fam]
        bndv = grid.bucket_ndv() if hasattr(grid, "bucket_ndv") else None
        return _ChunkTableView(t, grid.capacity(name),
                               r.bucketed.get(name), bndv)


class _FragmentRunner:
    def __init__(self, session, f32, table_family: Dict[str, str],
                 grids: Dict[str, object], buffers, bucketed=None):
        self.session = session
        self.f32 = f32
        self.table_family = table_family  # table -> family name
        self.grids = grids                # family name -> ChunkGrid
        self.buffers = buffers
        self.bucketed = bucketed or {}    # table -> bucket column
        # run-once fragments consume concatenated exchange buffers; their
        # compact fallback bound follows the largest family's per-chunk
        # reduction bound
        self.default_bound = max(g.exchange_bound() for g in grids.values())
        # runner-local executable view: (fid, mult)/aux key -> Executable.
        # Entries are VIEWS over the process-wide compile_cache memo —
        # a second runner (or session) with an identical fragment reuses
        # the executable through its serde fingerprint.  The lock covers
        # compile-ahead threads populating alongside the query thread.
        self._jit = {}
        self._jit_lock = threading.Lock()
        self._frag_fps: Dict[object, str] = {}  # fid -> serde fp ("" = n/a)
        self.dynamic_fids = set()  # run-once fids that fell back dynamic
        self.bound_mult: Dict[object, int] = {}  # fid -> compact growth
        self._bound_cache: Dict[object, int] = {}  # fid -> stats bound
        # adaptive partial aggregation (plan/agg_strategy.py): fid ->
        # FlipState (persists across runs of this prepared query, so a
        # warm run starts from the flip the last run learned) or False
        # when the fragment is known not monitorable; fid -> _LaneFrag
        # for the pass-through lane
        self.agg_monitors: Dict[object, object] = {}
        self._bypass_lanes: Dict[object, _LaneFrag] = {}
        # trace-time sort-economics counters across fragment programs
        self.sort_stats: Dict[str, int] = {}
        # PER-RUN counters (chunk pruning happens host-side every run,
        # unlike the trace-time totals above which warm runs replay)
        self.run_stats: Dict[str, int] = {}
        # per-RUN fragment wall clocks (EXPLAIN ANALYZE attribution +
        # the chunked fragment trace spans)
        self.frag_wall_ns: Dict[object, int] = {}

    # ---- fragment execution ------------------------------------------
    def _scan_builder(self, node: P.TableScan, chunk_args, grid):
        """Returns a Batch for one scan node inside the traced program.
        chunk_args = the grid's traced scalars, or None for run-once
        fragments."""
        from presto_tpu.exec.executor import scan_batch

        if node.table.startswith("__exch_"):
            eid = int(node.table[len("__exch_"):])
            b = self.buffers[eid]
            # remap buffer symbols onto the scan's assignments
            cols = {}
            for sym, src in node.assignments.items():
                c = b.columns[src]
                cols[sym] = Column(c.data, c.valid, node.types[sym],
                                   c.dictionary)
            return Batch(cols, b.sel)
        if chunk_args is not None and node.table in self.table_family:
            cols = list(dict.fromkeys(node.assignments.values()))
            raw, sel = grid.build_scan(node.table, cols, chunk_args,
                                       self.f32)
            cols_out = {}
            for sym, src in node.assignments.items():
                c = raw[src]
                cols_out[sym] = Column(c.data, c.valid, node.types[sym],
                                       c.dictionary)
            return Batch(cols_out, sel)
        table = self.session.catalog.get(node.table)
        return scan_batch(table, node, self.f32)

    def _fragment_bound(self, frag, grid) -> int:
        """Per-chunk compact bound for this fragment's output, derived
        from plan stats over a PER-CHUNK view of the catalog — the
        fragment's root grain (order-grain aggregate vs lineitem-grain
        projection) falls out of the ordinary stats rules instead of a
        single family-wide guess (round-3 VERDICT weak #2)."""
        cached = self._bound_cache.get(frag.fid)
        if cached is not None:
            return cached
        from presto_tpu.plan import stats as S

        try:
            st = S.derive(frag.root, _ChunkStatsCatalog(self))
            bound = max(int(st.rows), grid.exchange_bound())
        except Exception:
            bound = grid.exchange_bound()
        self._bound_cache[frag.fid] = bound
        return bound

    def _execute(self, frag, scan_inputs, bound_cap,
                 capture_partial_rows=False):
        from presto_tpu.exec.executor import (Executor, _compact_batch,
                                              _static_root_bound)

        ex = Executor(self.session, static=True, scan_inputs=scan_inputs,
                      sort_stats=self.sort_stats)
        if capture_partial_rows:
            # the monitored partial-agg lane also returns the live row
            # count INTO the partial stage (traced scalar; the runner's
            # ratio monitor reads it per chunk)
            ex.capture_partial_agg_rows = True
        # sort-order materialization hint (gather.py): a chunk
        # fragment's OUTPUT rows are compacted, buffered, and consumed
        # by the next fragment's aggregate/TopN/join — all of which
        # re-sort or re-group, so a partial-aggregate root's row order
        # is free and the joins below it may materialize in
        # sorted-gather order.  A projection-rooted fragment (rows
        # surface as-is) stays conservative.
        ex.mark_order_insensitive(frag.root,
                                  _root_order_insensitive(frag.root))
        out = ex.exec_node(frag.root)
        # shrink inside the compiled program: the eager compact outside
        # would otherwise walk a chunk-capacity-sized batch at peak HBM.
        # A fragment root with a static bound (partial topN/limit)
        # compacts to it; otherwise compact to the fragment's
        # stats-derived per-chunk bound with an OVERFLOW flag — kept
        # SEPARATE from the executor's static-assumption guards because
        # the two have different recoveries: overflow grows the bound
        # and re-runs the fragment; a tripped guard means the static
        # plan shape itself is wrong and the whole query falls back.
        bound = _static_root_bound(frag.root)
        overflow = jnp.asarray(False)
        if bound is None and out.sel.shape[0] > 4 * bound_cap:
            bound = bound_cap
            overflow = jnp.sum(out.sel) > bound
        if bound is not None and out.sel.shape[0] > 4 * bound:
            out = _compact_batch(out, bound)
        if ex.guards:
            guard = jnp.any(jnp.stack([jnp.asarray(g) for g in ex.guards]))
        else:
            guard = jnp.asarray(False)
        if capture_partial_rows:
            rows = getattr(ex, "captured_agg_rows", None)
            if rows is None:
                rows = jnp.asarray(0, jnp.int32)
            return out, guard, overflow, rows
        return out, guard, overflow

    def _split_scans(self, fscans, chunked: bool):
        """(resident {id: Batch} — passed as jit args, chunk scan nodes
        — generated in-trace)."""
        resident = {}
        chunk_nodes = []
        for n in fscans:
            if chunked and n.table in self.table_family \
                    and not n.table.startswith("__exch_"):
                chunk_nodes.append(n)
            else:
                resident[id(n)] = self._scan_builder(n, None, None)
        return resident, chunk_nodes

    def _fragment_grid(self, chunk_nodes):
        fams = {self.table_family[n.table] for n in chunk_nodes}
        if len(fams) != 1:
            # distribute() cuts exchanges between differently-bucketed
            # sides, so a mixed-family fragment means a planning hole
            raise Unchunkable(f"fragment mixes chunk families: {fams}")
        return self.grids[fams.pop()]

    # ---- executable builds (views over the shared compile cache) -----
    def _frag_fp(self, frag) -> Optional[str]:
        fp = self._frag_fps.get(frag.fid)
        if fp is None:
            fp = self._frag_fps[frag.fid] = \
                CC.plan_fingerprint(frag.root) or ""
        return fp or None

    def _gkey(self, frag, kind: str, mult: int, avals_fp) -> Optional[str]:
        """Process-wide executable key: fragment serde fingerprint x
        compact-bound mult x mesh/kind x dtype layout of the resident
        inputs, plus catalog identity and the full property map (which
        every trace bakes in)."""
        fp = self._frag_fp(frag)
        if fp is None:
            return None
        return CC.fingerprint(kind, fp, mult,
                              CC.session_fingerprint(self.session),
                              self.f32, avals_fp)

    def _cached_exec(self, local_key, gkey, build, ahead: bool):
        """Runner-local lookup fronting the shared memo.  Compile-ahead
        builds go straight to the memo (never the local dict), so the
        query thread's first local miss flows through get_or_build and
        the ahead hit is counted."""
        if ahead:
            return CC.get_or_build(gkey, build, ahead=True)
        with self._jit_lock:
            cached = self._jit.get(local_key)
        if cached is None:
            cached = CC.get_or_build(gkey, build)
            with self._jit_lock:
                self._jit[local_key] = cached
        return cached

    def _once_exec(self, frag, resident, ids, mult, ahead=False):
        args = [resident[i] for i in ids]
        gkey = self._gkey(frag, "once", mult, CC.avals_fingerprint(args))

        def build():
            bound = _pow2(self.default_bound * mult)

            def fn(batches):
                return self._execute(frag, dict(zip(ids, batches)), bound)

            return CC.build_jit(fn, example=(args,))

        return self._cached_exec((frag.fid, mult), gkey, build, ahead)

    def _loop_exec(self, frag, resident, ids, chunk_nodes, grid, mult,
                   ahead=False):
        args = [resident[i] for i in ids]
        gkey = self._gkey(frag, "loop", mult, CC.avals_fingerprint(args))
        nodes = list(chunk_nodes)

        def build():
            bound = _pow2(self._fragment_bound(frag, grid) * mult)

            def fn(batches, cargs):
                scan_inputs = dict(zip(ids, batches))
                for n in nodes:
                    scan_inputs[id(n)] = self._scan_builder(n, cargs, grid)
                return self._execute(frag, scan_inputs, bound)

            return CC.build_jit(fn, example=(args, grid.chunk_args(0)))

        return self._cached_exec((frag.fid, mult), gkey, build, ahead)

    # ---- adaptive partial aggregation (plan/agg_strategy.py) ---------
    def _agg_monitor(self, frag):
        """The per-fragment FlipState when this chunk-loop fragment's
        root chain is a bypassable PARTIAL aggregate (None otherwise).
        Persists across runs — the runner is the prepared-query cache
        entry, so a warm run resumes from the learned flip."""
        if not AS.enabled(self.session):
            return None
        with self._jit_lock:
            cached = self.agg_monitors.get(frag.fid)
            if cached is None:
                agg = AS.find_partial_agg(frag.root)
                cached = AS.FlipState() \
                    if agg is not None and AS.bypassable(agg) else False
                self.agg_monitors[frag.fid] = cached
        return cached or None

    def _bypass_lane(self, frag) -> Optional[_LaneFrag]:
        """The pass-through lane fragment: the PARTIAL aggregate swapped
        for its per-row partial-schema Project, sharing the scan
        subtree.  Its own fid/serde fingerprint key both the runner's
        local executable dict and the shared compile-cache memo, so the
        flip never recompiles a warm query — both lanes are pre-keyed."""
        lane = self._bypass_lanes.get(frag.fid)
        if lane is None:
            root = AS.bypass_root(frag.root)
            if root is None:
                return None
            lane = self._bypass_lanes[frag.fid] = _LaneFrag(
                (frag.fid, "bypass"), root, frag.inputs
                if hasattr(frag, "inputs") else ())
        return lane

    def _loop_exec_pa(self, frag, resident, ids, chunk_nodes, grid, mult,
                      ahead=False):
        """The MONITORED grouped lane: same per-chunk program as
        _loop_exec plus a fourth output — the live row count into the
        partial stage — feeding the reduction-ratio monitor.  Distinct
        compile-cache kind ("loop_pa") and local key, so monitored and
        plain programs never collide."""
        args = [resident[i] for i in ids]
        gkey = self._gkey(frag, "loop_pa", mult,
                          CC.avals_fingerprint(args))
        nodes = list(chunk_nodes)

        def build():
            bound = _pow2(self._fragment_bound(frag, grid) * mult)

            def fn(batches, cargs):
                scan_inputs = dict(zip(ids, batches))
                for n in nodes:
                    scan_inputs[id(n)] = self._scan_builder(n, cargs, grid)
                return self._execute(frag, scan_inputs, bound,
                                     capture_partial_rows=True)

            return CC.build_jit(fn, example=(args, grid.chunk_args(0)))

        return self._cached_exec((frag.fid, "pa", mult), gkey, build,
                                 ahead)

    def _pa_flush(self, mon, pending, buffered, chunk_cap, remaining,
                  budget) -> None:
        """Host-sync the window's (rows in, groups out) scalars and feed
        the flip state — ONE device fetch per RATIO_WINDOW chunks, so
        the pipelined loop stalls once per window, not per chunk.  A
        flip is memory-vetoed when pass-through buffering of the
        remaining chunks (at chunk capacity, no reduction) would blow
        the exchange-buffer budget — bypass trades exchange volume for
        compute, and the trade is only taken when the buffer affords
        it."""
        obs = jax.device_get(list(pending))
        pending.clear()
        thr = AS.min_reduction(self.session)
        for rows, groups in obs:
            ratio = float(rows) / max(float(groups), 1.0)
            self.run_stats["partial_agg_ratio"] = ratio
            event = mon.observe(ratio, thr)
            if event == "flipped":
                if buffered + chunk_cap * max(remaining, 0) > budget:
                    mon.bypassed = False  # veto: buffer can't afford it
                    mon.strikes = 0
                else:
                    self.run_stats["partial_aggs_bypassed"] = \
                        self.run_stats.get("partial_aggs_bypassed", 0) + 1
            elif event == "reenabled":
                self.run_stats["partial_aggs_reenabled"] = \
                    self.run_stats.get("partial_aggs_reenabled", 0) + 1

    def compile_ahead(self, frags, table_family) -> int:
        """Background AOT-compile of fragments 2..N on the shared pool
        while fragment 1 executes in the query thread (reference role:
        compile-once bytecode generation happening OFF the query path,
        sql/gen/PageFunctionCompiler's async cache loader).  Only
        fragments whose inputs are all catalog tables qualify — an
        exchange-fed fragment's input shapes are unknown until its
        producer ran.  Returns the number of jobs scheduled."""
        if not CC.ahead_enabled(self.session):
            return 0
        sink = CC.current_sink()
        n = 0
        for frag in frags[1:]:
            fscans: List[P.TableScan] = []
            _collect_scans(frag.root, fscans)
            if any(s.table.startswith("__exch_") for s in fscans):
                continue
            chunked = any(s.table in self.table_family for s in fscans)
            n += self._submit_ahead(frag, fscans, chunked, sink)
        return n

    def _submit_ahead(self, frag, fscans, chunked, sink, mult=None) -> int:
        m = mult if mult is not None else self.bound_mult.get(frag.fid, 1)

        def job():
            resident, chunk_nodes = self._split_scans(fscans,
                                                      chunked=chunked)
            ids = list(resident)
            if chunked and chunk_nodes:
                grid = self._fragment_grid(chunk_nodes)
                mesh_n = int(self.session.properties.get(
                    "chunk_mesh_devices", 1))
                if mesh_n > 1:
                    self._mesh_exec(frag, chunk_nodes, resident, ids,
                                    grid, mesh_n, m, ahead=True)
                elif self._agg_monitor(frag) is not None:
                    # monitored fragments run the loop_pa lane — ahead-
                    # compile THAT program, not the plain one
                    self._loop_exec_pa(frag, resident, ids, chunk_nodes,
                                       grid, m, ahead=True)
                else:
                    self._loop_exec(frag, resident, ids, chunk_nodes,
                                    grid, m, ahead=True)
            else:
                self._once_exec(frag, resident, ids, m, ahead=True)

        return 1 if CC.submit(job, stats_sink=sink) else 0

    def run_once(self, frag, fscans) -> Batch:
        resident, _ = self._split_scans(fscans, chunked=False)
        ids = list(resident)
        for _attempt in range(4):
            mult = self.bound_mult.get(frag.fid, 1)
            jitted = self._once_exec(frag, resident, ids, mult)
            out, guard, overflow = jitted([resident[i] for i in ids])
            if bool(overflow):
                # bound miss, not a correctness failure: grow + re-jit
                self.bound_mult[frag.fid] = mult * 4
                CC.mark_miss_prone(self._frag_fp(frag))
                continue
            if bool(guard):
                raise Unchunkable(
                    "static guard tripped in resident fragment")
            return out
        raise Unchunkable("compact bound kept overflowing (run_once)")

    def run_once_dynamic(self, frag, fscans) -> Batch:
        """Eager (non-jit) dynamic execution of a run-once fragment —
        per-op device dispatch with host syncs, like the whole-table
        executor."""
        from presto_tpu.exec.executor import Executor

        resident, _ = self._split_scans(fscans, chunked=False)
        # sort_stats is the shared counter funnel: spill-degradation
        # counters from fragment executors merge into QueryStats at the
        # end of the chunked run like the sort/df economics do
        ex = Executor(self.session, scan_inputs=resident,
                      sort_stats=self.sort_stats)
        return ex.exec_node(frag.root)

    def run_chunk_loop(self, frag, fscans) -> Batch:
        """Stream the fragment over its family's chunk grid, growing the
        fragment's compact bound and retrying on overflow (a bound miss
        degrades to a recompile, never to Unchunkable — the cliff the
        round-3 dryrun fell off).  Miss-prone fragments pre-compile the
        next growth step in the background while the loop streams, so
        the recompile is ready when (if) the miss repeats."""
        for _attempt in range(4):
            try:
                return self._run_chunk_loop(frag, fscans)
            except _CompactOverflow:
                self.bound_mult[frag.fid] = \
                    self.bound_mult.get(frag.fid, 1) * 4
                CC.mark_miss_prone(self._frag_fp(frag))
        raise Unchunkable("compact bound kept overflowing (chunk loop)")

    def _run_chunk_loop(self, frag, fscans) -> Batch:
        """One attempt at streaming the fragment.

        PIPELINED by default: only chunk 0 host-syncs (to calibrate a
        fixed per-chunk output capacity); every later chunk is
        dispatched asynchronously — generation, execution and
        compaction of chunk i+1 enqueue while chunk i still computes,
        so the device queue never drains and no per-chunk tunnel
        round-trip is paid (reference: the streaming page pump,
        operator/Driver.java:347 + ExchangeClient.java:69; round-2
        VERDICT item 4).  Guards and capacity-overflow flags sync ONCE
        after the loop; an overflow (a later chunk produced more than
        4x chunk 0's rows) redoes the loop in the per-chunk syncing
        mode, which is always correct."""
        resident, chunk_nodes = self._split_scans(fscans, chunked=True)
        grid = self._fragment_grid(chunk_nodes)
        grid = self._rf_chunk_view(frag, resident, chunk_nodes, grid)
        mult = self.bound_mult.get(frag.fid, 1)
        ids = list(resident)
        mesh_n = int(self.session.properties.get("chunk_mesh_devices", 1))
        mon = jitted4 = None
        if mesh_n > 1:
            jitted = self._mesh_exec(frag, chunk_nodes, resident, ids,
                                     grid, mesh_n, mult)
            grid = _MeshGridView(grid, mesh_n)
        else:
            # adaptive partial aggregation: a bypassable PARTIAL-agg
            # fragment runs the MONITORED grouped lane (adds the
            # rows-into-partial scalar); the fallback paths see the
            # same program through a 3-tuple view
            mon = self._agg_monitor(frag)
            if mon is not None:
                jitted4 = self._loop_exec_pa(frag, resident, ids,
                                             chunk_nodes, grid, mult)
                jitted = lambda rl, ca: jitted4(rl, ca)[:3]  # noqa: E731
            else:
                jitted = self._loop_exec(frag, resident, ids, chunk_nodes,
                                         grid, mult)
        res_list = [resident[i] for i in ids]
        budget = int(self.session.properties.get(
            "chunk_buffer_max_rows", 64_000_000))
        pipelined = bool(self.session.properties.get("chunk_pipeline",
                                                     True))
        if grid.nchunks > 1 and CC.ahead_enabled(self.session) \
                and CC.is_miss_prone(self._frag_fp(frag)):
            # this fragment has overflowed its bound before: AOT-compile
            # the next growth step while the loop streams, hiding the
            # "bound miss -> grow + re-jit" stall behind execution
            self._submit_ahead(frag, fscans, True, CC.current_sink(),
                               mult=mult * 4)
        if not pipelined or grid.nchunks <= 1:
            return self._chunk_loop_syncing(jitted, res_list, grid, budget)

        if mon is not None:
            # chunk 0 always runs the grouped lane: it calibrates the
            # compact capacity AND (when a warm run resumes bypassed)
            # doubles as the hysteresis probe
            out0, g0, ov0, rin0 = jitted4(res_list, grid.chunk_args(0))
        else:
            out0, g0, ov0 = jitted(res_list, grid.chunk_args(0))
            rin0 = None
        part0 = K.compact(out0)  # the ONE sync: calibrates capacity
        n0 = part0.capacity
        cap = 1 << max(16, (4 * max(n0, 1)).bit_length())
        cap = min(cap, out0.sel.shape[0])
        if n0 + cap * (grid.nchunks - 1) > budget:
            # fixed-cap buffering of every chunk would blow HBM: fold
            # chunks into a bounded on-device accumulator instead —
            # still pipelined, peak HBM ~ chunk working set + cap + A
            # (round-3 VERDICT item 4; the per-chunk syncing loop
            # remains the fallback when the accumulator can't apply)
            r = self._chunk_loop_accumulate(frag, jitted, res_list, grid,
                                            budget, cap, out0, g0, ov0)
            if r is not None:
                return r
            return self._chunk_loop_syncing(
                jitted, res_list, grid, budget,
                prefix=[part0], guards=[g0], overflows=[ov0], start=1)

        cjit = self._compact_exec(frag, cap, out0)

        parts: List[Batch] = [part0]
        guards = [g0]
        overflows = [ov0]
        counts = []
        profile = bool(self.session.properties.get("chunk_profile",
                                                   False))
        # adaptive monitor state: pending (rows in, groups out) scalars
        # flushed (one host sync) every RATIO_WINDOW chunks; bypassed
        # chunks run the pass-through lane and buffer uncompacted
        bjit = None
        chunk_cap = int(out0.sel.shape[0])
        buffered = int(n0)
        bypassed_chunks = 0
        flips_before = self.run_stats.get("partial_aggs_bypassed", 0)
        pending = [(rin0, n0)] if mon is not None else []
        for i in range(1, grid.nchunks):
            if mon is not None and mon.bypassed and not mon.probe_due():
                if bjit is None:
                    lane = self._bypass_lane(frag)
                    if lane is None:  # lost the row form: stay grouped
                        mon.bypassed = False
                    else:
                        bjit = self._loop_exec(lane, resident, ids,
                                               chunk_nodes, grid, mult)
            if bjit is not None and mon is not None and mon.bypassed \
                    and not mon.probe_due():
                out, guard, ov = bjit(res_list, grid.chunk_args(i))
                parts.append(out)  # pass-through rows, uncompacted
                buffered += chunk_cap
                bypassed_chunks += 1
                mon.note_bypassed()
                guards.append(guard)
                overflows.append(ov)
                continue
            t0 = TR.clock_ns() if profile else 0
            if mon is not None:
                out, guard, ov, rin = jitted4(res_list, grid.chunk_args(i))
            else:
                out, guard, ov = jitted(res_list, grid.chunk_args(i))
                rin = None
            part, cnt = cjit(out)  # async: no host sync in this loop
            if profile:
                # per-chunk wall time, device-synced (diagnostics only —
                # syncing defeats the pipeline; keep the property off in
                # production runs)
                jax.block_until_ready(part)
                print(f"chunk_profile: chunk {i} "
                      f"{(TR.clock_ns() - t0) / 1e6:.0f}ms",
                      file=sys.stderr)
            guards.append(guard)
            overflows.append(ov)
            counts.append(cnt)
            parts.append(part)
            buffered += cap
            if mon is not None:
                pending.append((rin, cnt))
                if len(pending) >= AS.RATIO_WINDOW:
                    self._pa_flush(mon, pending, buffered, chunk_cap,
                                   grid.nchunks - 1 - i, budget)
        if mon is not None and pending:
            self._pa_flush(mon, pending, buffered, chunk_cap, 0, budget)
        if mon is not None and bypassed_chunks \
                and self.run_stats.get("partial_aggs_bypassed",
                                       0) == flips_before:
            # a warm run resumed an earlier flip: no new flip event, but
            # this run DID serve pass-through chunks — count the bypass
            self.run_stats["partial_aggs_bypassed"] = flips_before + 1
        cap_overflow = bool(jnp.any(jnp.stack(
            [c > cap for c in counts]))) if counts else False
        if cap_overflow:
            return self._chunk_loop_syncing(jitted, res_list, grid, budget)
        if bool(jnp.any(jnp.stack(overflows))):
            raise _CompactOverflow
        if bool(jnp.any(jnp.stack(guards))):
            raise Unchunkable("static guard tripped in chunk loop")
        return K.concat_batches(parts) if len(parts) > 1 else parts[0]

    def _rf_chunk_view(self, frag, resident, chunk_nodes, grid):
        """Dynamic filtering at chunk grain: build summaries from the
        fragment's RESIDENT inputs (exchange buffers / resident scans —
        available host-side BEFORE the loop) are compared against the
        grid's per-chunk zone maps; chunks whose ranges miss every
        runtime domain are never dispatched.  Strictly best-effort: no
        grid hook or no resident build means no pruning, and the
        in-trace row filter still applies inside every kept chunk."""
        from presto_tpu.plan import runtime_filters as RF

        if not RF.enabled(self.session):
            return grid
        hook = getattr(grid, "chunk_column_domain", None)
        if hook is None:
            return grid
        doms = _rf_resident_domains(frag.root, resident)
        if not doms:
            return grid
        keep = None
        for n in chunk_nodes:
            for spec in getattr(n, "rf_consume", None) or []:
                dom = doms.get(spec["fid"])
                col = spec.get("column")
                if dom is None or col is None:
                    continue
                kept = []
                for i in (range(grid.nchunks) if keep is None else keep):
                    zr = hook(n.table, col, i)
                    if zr is None or dom.overlaps(zr[0], zr[1]):
                        kept.append(i)
                keep = kept
        if keep is None or len(keep) == grid.nchunks:
            return grid
        pruned = grid.nchunks - len(keep)
        if not keep:
            # degenerate all-pruned grid: keep one chunk — the in-trace
            # filter masks its rows, so the output is empty anyway and
            # every downstream shape stays well-formed
            keep = [0]
            pruned = grid.nchunks - 1
        self.run_stats["df_chunks_pruned"] = \
            self.run_stats.get("df_chunks_pruned", 0) + pruned
        return _PrunedGridView(grid, keep)

    def _fold_exec(self, frag, cap: int, A: int, part0):
        """Bounded-accumulator fold program (_chunk_loop_accumulate):
        scatter one compacted chunk into the A-row accumulator at a
        running offset, donating the accumulator buffers.  AOT-compiled
        against shape structs so no second A-row buffer materializes
        just to compile."""

        def build():
            A_ = A

            def fold(acc, n, part):
                live = part.sel
                pos = n + jnp.cumsum(live.astype(jnp.int32)) - 1
                # overflowing rows land in the dump slot A (caught by
                # the final count check, then A grows)
                idx = jnp.where(live & (pos < A_), pos,
                                A_).astype(jnp.int32)
                cols = {}
                for name, c in part.columns.items():
                    a = acc.columns[name]
                    data = a.data.at[idx].set(c.data)
                    cv = c.valid if c.valid is not None else \
                        jnp.ones((c.data.shape[0],), bool)
                    valid = a.valid.at[idx].set(cv)
                    cols[name] = Column(data, valid, c.type,
                                        c.dictionary)
                n2 = n + jnp.sum(live, dtype=jnp.int32)
                return Batch(cols, acc.sel), n2

            def sds(shape, dtype):
                return jax.ShapeDtypeStruct(shape, dtype)

            acc_ex = Batch(
                {name: Column(sds((A + 1,) + tuple(c.data.shape[1:]),
                                  c.data.dtype),
                              sds((A + 1,), jnp.bool_), c.type,
                              c.dictionary)
                 for name, c in part0.columns.items()},
                sds((A + 1,), jnp.bool_))
            return CC.build_jit(fold,
                                example=(acc_ex, jnp.int32(0), part0),
                                donate_argnums=(0, 1))

        gkey = self._gkey(frag, "fold", (cap, A),
                          CC.avals_fingerprint(part0))
        return self._cached_exec(("fold", frag.fid, cap, A), gkey, build,
                                 ahead=False)

    def _compact_exec(self, frag, cap: int, example_out):
        """Per-chunk compaction program (shared with the accumulate
        path): compact to the calibrated cap + live count."""
        from presto_tpu.exec.executor import _compact_batch

        def build():
            def cfn(b):
                return _compact_batch(b, cap), jnp.sum(b.sel)

            return CC.build_jit(cfn, example=(example_out,))

        gkey = self._gkey(frag, "compact", cap,
                          CC.avals_fingerprint(example_out))
        return self._cached_exec(("compact", frag.fid, cap), gkey, build,
                                 ahead=False)

    def _mesh_exec(self, frag, chunk_nodes, resident, ids, grid, mesh_n,
                   mult=1, ahead=False):
        """Chunked execution x the device mesh (round-2 VERDICT item 5):
        one superstep runs `mesh_n` bucket-aligned MICRO-chunks, one per
        device, inside a single shard_map program.  Bucket colocation
        makes the fragment embarrassingly parallel within a superstep —
        the collectives stay at fragment boundaries (host-buffered
        exchanges), exactly like the reference schedules lifespans
        across nodes (execution/scheduler/group/LifespanScheduler.java).
        Callers stream it over a _MeshGridView whose "chunks" are
        supersteps."""
        try:
            from jax import shard_map
        except ImportError:  # moved to core in newer jax; 0.4.x path:
            from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as PS

        from presto_tpu.parallel.mesh import AXIS, make_mesh

        args = [resident[i] for i in ids]
        nodes = list(chunk_nodes)
        gkey = self._gkey(frag, f"mesh{mesh_n}", mult,
                          CC.avals_fingerprint(args))

        def build():
            mesh = make_mesh(mesh_n)
            bound = _pow2(self._fragment_bound(frag, grid) * mult)

            def fn(batches, cargs):
                args1 = tuple(a[0] for a in cargs)  # per-device slice
                scan_inputs = dict(zip(ids, batches))
                for n in nodes:
                    scan_inputs[id(n)] = self._scan_builder(n, args1, grid)
                out, guard, ov = self._execute(frag, scan_inputs, bound)
                return (out, jnp.asarray(guard).reshape(1),
                        jnp.asarray(ov).reshape(1))

            sharded = shard_map(fn, mesh=mesh,
                                in_specs=(PS(), PS(AXIS)),
                                out_specs=(PS(AXIS), PS(AXIS), PS(AXIS)))
            # no AOT example: the live jit's automatic input resharding
            # (host-stacked superstep args -> the mesh axis) is load-
            # bearing here; an AOT signature would pin one placement
            return CC.build_jit(sharded)

        return self._cached_exec(("mesh", frag.fid, mesh_n, mult), gkey,
                                 build, ahead)

    def _chunk_loop_accumulate(self, frag, jitted, res_list, grid,
                               budget, cap, out0, g0, ov0):
        """Pipelined chunk loop with a BOUNDED on-device accumulator:
        each chunk's output compacts to a fixed `cap` and scatters into
        one A-row buffer at a running offset — no per-chunk host sync,
        no cap x nchunks buffering.  A grows geometrically (re-running
        the loop) until the live total fits or the budget is hit.
        Returns None when the shape can't accumulate (per-chunk
        dictionaries differ) so the caller falls back."""
        cjit = self._compact_exec(frag, cap, out0)
        part0, cnt0 = cjit(out0)
        dicts0 = {name: c.dictionary for name, c in part0.columns.items()}

        A = max(4 * cap, 1 << 20)
        while True:
            A = min(A, budget)
            fjit = self._fold_exec(frag, cap, A, part0)

            def empty_acc():
                cols = {}
                for name, c in part0.columns.items():
                    shape = (A + 1,) + tuple(c.data.shape[1:])
                    cols[name] = Column(
                        jnp.zeros(shape, c.data.dtype),
                        jnp.zeros((A + 1,), bool), c.type, c.dictionary)
                return Batch(cols, jnp.zeros((A + 1,), bool))

            acc, n = fjit(empty_acc(), jnp.int32(0), part0)
            guards = [g0]
            overflows = [ov0]
            cap_over = []  # a later chunk outgrew chunk-0's calibration
            profile = bool(self.session.properties.get("chunk_profile",
                                                       False))
            for i in range(1, grid.nchunks):
                t0 = TR.clock_ns() if profile else 0
                out, guard, ov = jitted(res_list, grid.chunk_args(i))
                part, cnt = cjit(out)
                if profile:  # diagnostics only: syncing kills pipelining
                    jax.block_until_ready(part)
                    print(f"chunk_profile: chunk {i} "
                          f"{(TR.clock_ns() - t0) / 1e6:.0f}ms",
                          file=sys.stderr)
                if any(part.columns[name].dictionary is not d
                       for name, d in dicts0.items()):
                    return None  # unstable dictionaries: caller falls back
                guards.append(guard)
                overflows.append(ov)
                cap_over.append(cnt > cap)
                acc, n = fjit(acc, n, part)
            n_host = int(n)
            if cap_over and bool(jnp.any(jnp.stack(cap_over))):
                return None  # recalibrate via the exact syncing loop
            if bool(jnp.any(jnp.stack(overflows))):
                raise _CompactOverflow
            if bool(jnp.any(jnp.stack(guards))):
                raise Unchunkable("static guard tripped in chunk loop")
            if n_host <= A:
                sel = jnp.arange(A + 1) < n_host
                out_cols = {name: c for name, c in acc.columns.items()}
                return Batch(out_cols, sel)
            if A >= budget:
                raise Unchunkable(
                    f"accumulator exceeds budget ({n_host} rows)")
            A *= 4  # grown accumulator, re-run the loop

    def _chunk_loop_syncing(self, jitted, res_list, grid, budget,
                            prefix=None, guards=None, overflows=None,
                            start=0) -> Batch:
        parts: List[Batch] = list(prefix or [])
        guards = list(guards or [])
        overflows = list(overflows or [])
        buffered = sum(p.capacity for p in parts)
        for i in range(start, grid.nchunks):
            out, guard, ov = jitted(res_list, grid.chunk_args(i))
            guards.append(guard)
            overflows.append(ov)
            part = K.compact(out)  # host-syncs the live count
            parts.append(part)
            buffered += part.capacity
            if buffered > budget:
                # a plan whose exchange carries ~the whole input cannot
                # be buffered chunk-wise — bail BEFORE exhausting HBM
                raise Unchunkable(
                    f"exchange buffer exceeds budget ({buffered} rows)")
        if bool(jnp.any(jnp.stack(overflows))):
            raise _CompactOverflow
        if bool(jnp.any(jnp.stack(guards))):
            raise Unchunkable("static guard tripped in chunk loop")
        return K.concat_batches(parts) if len(parts) > 1 else parts[0]
