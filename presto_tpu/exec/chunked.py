"""Chunked (grouped) execution: run plans whose inputs exceed HBM by
streaming the big bucketed tables chunk-by-chunk through ONE compiled
per-chunk program.

Reference parity: grouped execution — `Lifespan.driverGroup(bucket)`
runs one bucket at a time through a whole pipeline so memory stays
bounded to 1/N of the table (execution/Lifespan.java:26-38,
StageExecutionDescriptor, BucketNodeMap), plus the partial->final
aggregation split and partial topN of AddExchanges.  TPU-native
adaptation:

- the distributed planner (plan/distribute.py) plans chunks as shards
  over a VIRTUAL TIME AXIS: bucketed scans are `hashed` on the bucket
  column (range-bucketing colocates orderkey equi-joins exactly like
  hash-bucketing), resident tables are `replicated` (whole in HBM,
  visible to every chunk);
- the plan is cut at Exchange nodes (parallel/cluster.cut_fragments,
  the PlanFragmenter analog); an exchange between a chunk-looped
  fragment and its consumer is an ON-DEVICE concat buffer — partial
  states are tiny after per-chunk aggregation/topN, so "shuffle"
  degenerates to concatenation on one chip;
- each chunk-looped fragment compiles ONCE: chunk shapes are padded to
  a static capacity and the chunk start offsets enter as traced
  scalars; scan batches are GENERATED ON DEVICE inside the same
  compiled program (connectors/tpch_device.py), so a 600M-row scan
  never exists anywhere — not in host RAM, not in HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column
from presto_tpu.exec import kernels as K
from presto_tpu.plan import nodes as P


class Unchunkable(Exception):
    """Plan/catalog shape the chunked runner can't handle; callers fall
    back to whole-table execution."""


# default chunk size in ORDERS rows (~4x lineitems per chunk)
DEFAULT_CHUNK_ORDERS = 2_000_000
# scans above this row count stream chunk-wise instead of residing whole
DEFAULT_STREAM_THRESHOLD = 120_000_000


def _collect_scans(node, out):
    if isinstance(node, P.TableScan):
        out.append(node)
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, P.PlanNode):
            _collect_scans(v, out)
        elif isinstance(v, list):
            for x in v:
                if isinstance(x, P.PlanNode):
                    _collect_scans(x, out)


def catalog_may_need_chunks(session) -> bool:
    """Cheap pre-check (no planning): any bucketed big table at all?"""
    threshold = int(session.properties.get(
        "chunked_rows_threshold", DEFAULT_STREAM_THRESHOLD))
    for name in ("lineitem", "orders"):
        if name in session.catalog:
            t = session.catalog.get(name)
            if hasattr(t, "sf") and t.row_count() > threshold:
                return True
    return False


def chunk_plan_needed(session, plan) -> bool:
    """True when some scanned table is too big to reside in HBM whole."""
    threshold = int(session.properties.get(
        "chunked_rows_threshold", DEFAULT_STREAM_THRESHOLD))
    scans: List[P.TableScan] = []
    _collect_scans(plan.root, scans)
    for n in scans:
        try:
            t = session.catalog.get(n.table)
        except KeyError:
            return False
        if n.table in ("lineitem", "orders") and hasattr(t, "sf") \
                and t.row_count() > threshold:
            return True
    return False


def run_chunked(session, stmt, text: str, plan=None):
    """Plan + execute a chunked query; returns a QueryResult.  The
    prepared execution (distributed plan, fragments, jitted per-chunk
    programs) memoizes per session so warm runs skip planning AND
    XLA compilation (a fresh jax.jit closure would otherwise recompile
    every run — ~minutes at SF100)."""
    from presto_tpu.exec.executor import Executor, plan_statement
    from presto_tpu.parallel.cluster import cut_fragments
    from presto_tpu.plan.distribute import Undistributable, distribute
    from presto_tpu.connectors import tpch as H

    cache = getattr(session, "_chunked_cache", None)
    if cache is None:
        cache = session._chunked_cache = {}
    # raw text key: whitespace normalization would merge queries that
    # differ only inside string literals
    key = (text, getattr(session.catalog, "version", 0),
           tuple(sorted((k, repr(v)) for k, v in session.properties.items())))
    prepared = cache.get(key)
    if prepared is not None:
        return _execute_prepared(session, *prepared)

    if plan is None:
        plan = plan_statement(session, stmt)
    if plan.subplans:
        raise Unchunkable("scalar subplans not supported in chunked mode")

    scans: List[P.TableScan] = []
    _collect_scans(plan.root, scans)
    tables = {n.table for n in scans}
    streamed = {t for t in tables if t in ("lineitem", "orders")}
    if not streamed & {"lineitem", "orders"}:
        raise Unchunkable("no bucketed big table in plan")
    from presto_tpu.connectors import tpch_device as D

    for n in scans:
        if n.table in streamed:
            missing = set(n.assignments.values()) \
                - D.DEVICE_COLUMNS.get(n.table, set())
            if missing:
                raise Unchunkable(
                    f"{n.table} columns not device-generable: {missing}")
    sf = session.catalog.get(next(iter(streamed))).sf

    chunk_orders = int(session.properties.get(
        "chunk_orders", DEFAULT_CHUNK_ORDERS))
    order_edges, line_offsets = H.chunk_grid(sf, chunk_orders)
    nchunks = len(order_edges) - 1
    cap_orders = max(b - a for a, b in zip(order_edges[:-1],
                                           order_edges[1:]))
    cap_lines = max(b - a for a, b in zip(line_offsets[:-1],
                                          line_offsets[1:]))

    bucketed = {}
    if "lineitem" in streamed:
        bucketed["lineitem"] = "l_orderkey"
    if "orders" in streamed:
        bucketed["orders"] = "o_orderkey"
    try:
        dplan = distribute(plan, session, ndev=nchunks, bucketed=bucketed)
    except Undistributable as e:
        raise Unchunkable(f"undistributable: {e}")

    frags = cut_fragments(dplan.root)
    f32 = bool(session.properties.get("float32_compute", False))

    runner = _FragmentRunner(session, f32, sf, order_edges, line_offsets,
                             cap_orders, cap_lines, {})
    consumer_eid = {}  # producer fid -> eid of the exchange it feeds
    for f in frags:
        for inp in f.inputs:
            consumer_eid[inp.producer] = inp.eid
    result = _execute_prepared(session, dplan, frags, runner, bucketed,
                               consumer_eid)
    cache[key] = (dplan, frags, runner, bucketed, consumer_eid)
    return result


def _execute_prepared(session, dplan, frags, runner, bucketed,
                      consumer_eid):
    from presto_tpu.exec.executor import Executor, StaticFallback

    runner.buffers.clear()
    try:
        final_batch = _run_fragments(session, frags, runner, bucketed,
                                     consumer_eid)
        ex = Executor(session)
        return ex.materialize(dplan, final_batch)
    finally:
        runner.buffers.clear()  # don't pin HBM between runs


def _run_fragments(session, frags, runner, bucketed, consumer_eid):
    from presto_tpu.exec.executor import StaticFallback

    final_batch = None
    for frag in frags:
        fscans: List[P.TableScan] = []
        _collect_scans(frag.root, fscans)
        chunked = any(s.table in bucketed for s in fscans)
        try:
            out = runner.run_chunk_loop(frag, fscans) if chunked \
                else runner.run_once(frag, fscans)
        except StaticFallback as e:
            # plan shape the static executor can't bound (e.g. unbounded
            # join fanout): let the caller fall back to whole-table paths
            raise Unchunkable(f"static fallback: {e}")
        eid = consumer_eid.get(frag.fid)
        if eid is None:  # no consumer: the root fragment's result
            final_batch = out
        else:
            runner.buffers[eid] = out
    return final_batch


class _FragmentRunner:
    def __init__(self, session, f32, sf, order_edges, line_offsets,
                 cap_orders, cap_lines, buffers):
        self.session = session
        self.f32 = f32
        self.sf = sf
        self.order_edges = order_edges
        self.line_offsets = line_offsets
        self.cap_orders = cap_orders
        self.cap_lines = cap_lines
        self.buffers = buffers
        self._jit = {}  # fragment fid -> (jitted fn, ids, chunk_nodes)

    # ---- fragment execution ------------------------------------------
    def _scan_builder(self, node: P.TableScan, chunk_args):
        """Returns a Batch for one scan node inside the traced program.
        chunk_args = (o0, line0, n_ord_live, n_line_live) traced scalars,
        or None for run-once fragments."""
        from presto_tpu.connectors import tpch_device as D
        from presto_tpu.exec.executor import scan_batch

        if node.table.startswith("__exch_"):
            eid = int(node.table[len("__exch_"):])
            b = self.buffers[eid]
            # remap buffer symbols onto the scan's assignments
            cols = {}
            for sym, src in node.assignments.items():
                c = b.columns[src]
                cols[sym] = Column(c.data, c.valid, node.types[sym],
                                   c.dictionary)
            return Batch(cols, b.sel)
        table = self.session.catalog.get(node.table)
        if chunk_args is not None and node.table in ("lineitem", "orders"):
            o0, line0, n_ord, n_line = chunk_args
            cols = list(dict.fromkeys(node.assignments.values()))
            if node.table == "lineitem":
                raw = D.generate_device(
                    "lineitem", self.sf, cols, row0=o0, f32=self.f32,
                    pad=self.cap_lines, n_orders=self.cap_orders,
                    line_row0=line0)
                sel = jnp.arange(self.cap_lines) < n_line
            else:
                raw = D.generate_device(
                    "orders", self.sf, cols, row0=o0, f32=self.f32,
                    pad=self.cap_orders)
                sel = jnp.arange(self.cap_orders) < n_ord
            cols_out = {}
            for sym, src in node.assignments.items():
                c = raw[src]
                cols_out[sym] = Column(c.data, c.valid, node.types[sym],
                                       c.dictionary)
            return Batch(cols_out, sel)
        return scan_batch(table, node, self.f32)

    def _execute(self, frag, scan_inputs):
        from presto_tpu.exec.executor import (Executor, _compact_batch,
                                              _static_root_bound)

        ex = Executor(self.session, static=True, scan_inputs=scan_inputs)
        out = ex.exec_node(frag.root)
        # shrink inside the compiled program: the eager compact outside
        # would otherwise walk a chunk-capacity-sized batch at peak HBM.
        # A fragment root with a static bound (partial topN/limit)
        # compacts to it; otherwise compact to the per-chunk order count
        # (exchange outputs are reductions of the chunk — aggregates on
        # the bucket key, selective filters) with an overflow GUARD so a
        # miss falls back instead of silently truncating.
        bound = _static_root_bound(frag.root)
        guards = list(ex.guards)
        if bound is None and out.sel.shape[0] > 4 * self.cap_orders:
            bound = self.cap_orders
            guards.append(jnp.sum(out.sel) > bound)
        if bound is not None and out.sel.shape[0] > 4 * bound:
            out = _compact_batch(out, bound)
        if guards:
            guard = jnp.any(jnp.stack([jnp.asarray(g) for g in guards]))
        else:
            guard = jnp.asarray(False)
        return out, guard

    def _split_scans(self, fscans, chunked: bool):
        """(resident {id: Batch} — passed as jit args, chunk scan nodes
        — generated in-trace)."""
        resident = {}
        chunk_nodes = []
        for n in fscans:
            if chunked and n.table in ("lineitem", "orders") \
                    and not n.table.startswith("__exch_"):
                chunk_nodes.append(n)
            else:
                resident[id(n)] = self._scan_builder(n, None)
        return resident, chunk_nodes

    def run_once(self, frag, fscans) -> Batch:
        resident, _ = self._split_scans(fscans, chunked=False)
        cached = self._jit.get(frag.fid)
        if cached is None:
            ids = list(resident)

            def fn(batches):
                return self._execute(frag, dict(zip(ids, batches)))

            cached = self._jit[frag.fid] = (jax.jit(fn), ids, None)
        jitted, ids, _ = cached
        out, guard = jitted([resident[i] for i in ids])
        if bool(guard):
            raise Unchunkable("static guard tripped in resident fragment")
        return out

    def run_chunk_loop(self, frag, fscans) -> Batch:
        resident, chunk_nodes = self._split_scans(fscans, chunked=True)
        cached = self._jit.get(frag.fid)
        if cached is None:
            ids = list(resident)
            nodes = chunk_nodes

            def fn(batches, args):
                scan_inputs = dict(zip(ids, batches))
                for n in nodes:
                    scan_inputs[id(n)] = self._scan_builder(n, args)
                return self._execute(frag, scan_inputs)

            cached = self._jit[frag.fid] = (jax.jit(fn), ids, nodes)
        jitted, ids, _ = cached
        res_list = [resident[i] for i in ids]
        parts: List[Batch] = []
        guards = []
        buffered = 0
        budget = int(self.session.properties.get(
            "chunk_buffer_max_rows", 64_000_000))
        for i in range(len(self.order_edges) - 1):
            o0 = self.order_edges[i]
            o1 = self.order_edges[i + 1]
            args = (jnp.asarray(o0, jnp.int64),
                    jnp.asarray(self.line_offsets[i], jnp.int64),
                    jnp.asarray(o1 - o0, jnp.int32),
                    jnp.asarray(self.line_offsets[i + 1]
                                - self.line_offsets[i], jnp.int32))
            out, guard = jitted(res_list, args)
            guards.append(guard)
            part = K.compact(out)  # host-syncs the live count
            parts.append(part)
            buffered += part.capacity
            if buffered > budget:
                # a plan whose exchange carries ~the whole input cannot
                # be buffered chunk-wise — bail BEFORE exhausting HBM
                raise Unchunkable(
                    f"exchange buffer exceeds budget ({buffered} rows)")
        if bool(jnp.any(jnp.stack(guards))):
            raise Unchunkable("static guard tripped in chunk loop")
        return K.concat_batches(parts) if len(parts) > 1 else parts[0]
