"""Two-limb Int128 decimal arithmetic on device.

Reference parity: long decimals (precision 19..38) are Int128 values in
the reference — `spi/type/UnscaledDecimal128Arithmetic.java` (add/
multiply/compare/rescale over two 64-bit limbs) stored in
`spi/block/Int128ArrayBlock.java` (two longs per position).  TPU-native
adaptation: a long-decimal column is an int64 array of shape (n, 2) —
[..., 0] = signed high limb, [..., 1] = low limb (the unsigned low 64
bits, stored in int64 with wrapping semantics).  value = hi * 2^64 +
u64(lo), two's complement.  All ops are elementwise integer vector math
(VPU-friendly); 64x64->128 products split operands into 32-bit halves;
exact segmented SUM splits the 128-bit value into four unsigned 32-bit
lanes whose int64 segment sums cannot overflow for any n < 2^31, then
recombines with carry propagation — so a SUM over an entire SF100
column is bit-exact, where the reference pays a per-row Int128 add
(UnscaledDecimal128Arithmetic.addWithOverflow).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

HI = 0
LO = 1

_M32 = (1 << 32) - 1
_SIGNBIT = -(1 << 63)  # int64 min: xor-bias turns unsigned order into signed


def _u(x):
    return x.astype(jnp.uint64)


def _i(x):
    return x.astype(jnp.int64)


def from_int64(x: jnp.ndarray) -> jnp.ndarray:
    """Sign-extend int64 unscaled values to (n, 2) limbs."""
    x = jnp.asarray(x, jnp.int64)
    hi = x >> 63  # arithmetic shift: 0 or -1
    return jnp.stack([hi, x], axis=-1)


def from_host_int(v: int) -> np.ndarray:
    """One python int (|v| < 2^127) to host [hi, lo] limbs."""
    m = v & ((1 << 128) - 1)  # two's complement mod 2^128
    lo = m & ((1 << 64) - 1)
    hi = m >> 64
    if hi >= 1 << 63:
        hi -= 1 << 64
    if lo >= 1 << 63:
        lo -= 1 << 64  # int64 wrap of the unsigned low limb
    return np.asarray([hi, lo], dtype=np.int64)


def from_host_ints(vals) -> np.ndarray:
    return np.stack([from_host_int(int(v)) for v in vals]) \
        if len(vals) else np.zeros((0, 2), np.int64)


def to_host_ints(limbs: np.ndarray) -> list:
    """(n, 2) int64 limbs -> python ints."""
    limbs = np.asarray(limbs)
    out = []
    for hi, lo in limbs.reshape(-1, 2):
        v = (int(hi) << 64) + (int(lo) & ((1 << 64) - 1))
        out.append(v)
    return out


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    lo = _i(_u(a[..., LO]) + _u(b[..., LO]))
    # unsigned overflow iff result < either addend
    carry = (_u(lo) < _u(a[..., LO])).astype(jnp.int64)
    hi = a[..., HI] + b[..., HI] + carry
    return jnp.stack([hi, lo], axis=-1)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    lo = _i(~_u(a[..., LO]) + jnp.uint64(1))
    carry = (lo == 0).astype(jnp.int64)
    hi = ~a[..., HI] + carry
    return jnp.stack([hi, lo], axis=-1)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return add(a, neg(b))


def lt(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return (a[..., HI] < b[..., HI]) | (
        (a[..., HI] == b[..., HI])
        & (_u(a[..., LO]) < _u(b[..., LO])))


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return (a[..., HI] == b[..., HI]) & (a[..., LO] == b[..., LO])


def mul_int64(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Exact int64 x int64 -> (n, 2) limbs (the overflow-free product
    the reference computes in UnscaledDecimal128Arithmetic.multiply).
    Signed via unsigned mulhi + sign corrections."""
    x = jnp.asarray(x, jnp.int64)
    y = jnp.asarray(y, jnp.int64)
    ux, uy = _u(x), _u(y)
    xl = ux & jnp.uint64(_M32)
    xh = ux >> jnp.uint64(32)
    yl = uy & jnp.uint64(_M32)
    yh = uy >> jnp.uint64(32)
    ll = xl * yl
    lh = xl * yh
    hl = xh * yl
    hh = xh * yh
    mid = (ll >> jnp.uint64(32)) + (lh & jnp.uint64(_M32)) \
        + (hl & jnp.uint64(_M32))
    lo = _i((ll & jnp.uint64(_M32)) | (mid << jnp.uint64(32)))
    uhi = hh + (lh >> jnp.uint64(32)) + (hl >> jnp.uint64(32)) \
        + (mid >> jnp.uint64(32))
    # unsigned -> signed mulhi: subtract (x<0)*y and (y<0)*x
    hi = _i(uhi) - jnp.where(x < 0, y, 0) - jnp.where(y < 0, x, 0)
    return jnp.stack([hi, lo], axis=-1)


def mul_small(a: jnp.ndarray, c: int) -> jnp.ndarray:
    """128-bit x small positive int (< 2^31), wrapping mod 2^128."""
    assert 0 <= c < (1 << 31)
    cu = jnp.uint64(c)
    lo_l = (_u(a[..., LO]) & jnp.uint64(_M32)) * cu
    lo_h = (_u(a[..., LO]) >> jnp.uint64(32)) * cu + (lo_l >> jnp.uint64(32))
    lo = _i((lo_l & jnp.uint64(_M32)) | (lo_h << jnp.uint64(32)))
    carry = _i(lo_h >> jnp.uint64(32))
    hi = a[..., HI] * c + carry
    return jnp.stack([hi, lo], axis=-1)


def scale_up(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a * 10^k (k >= 0) via repeated small multiplies (10^9 < 2^31)."""
    while k > 0:
        step = min(k, 9)
        a = mul_small(a, 10 ** step)
        k -= step
    return a


def _divmod_small_nonneg(a: jnp.ndarray, c: int):
    """(a // c, a % c) for NON-NEGATIVE a and 0 < c < 2^31, via 32-bit
    long division over the four limbs (remainder < c keeps every
    intermediate inside int64)."""
    l3 = (_u(a[..., HI]) >> jnp.uint64(32)).astype(jnp.int64)
    l2 = (_u(a[..., HI]) & jnp.uint64(_M32)).astype(jnp.int64)
    l1 = (_u(a[..., LO]) >> jnp.uint64(32)).astype(jnp.int64)
    l0 = (_u(a[..., LO]) & jnp.uint64(_M32)).astype(jnp.int64)
    r = jnp.zeros_like(l3)
    qs = []
    for limb in (l3, l2, l1, l0):
        cur = (r << 32) | limb
        qs.append(cur // c)
        r = cur % c
    q3, q2, q1, q0 = qs
    hi = _i((_u(q3) << jnp.uint64(32)) | _u(q2))
    lo = _i((_u(q1) << jnp.uint64(32)) | _u(q0))
    return jnp.stack([hi, lo], axis=-1), r


def scale_down_round(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a / 10^k rounded half away from zero (Presto decimal rounding,
    UnscaledDecimal128Arithmetic.rescale)."""
    if k <= 0:
        return scale_up(a, -k)
    sign_neg = a[..., HI] < 0
    mag = jnp.where(sign_neg[..., None], neg(a), a)
    # the step chain discards least-significant digits first, so with
    # rem = r_last*prev_div + rem_prev and rem_prev < prev_div,
    # 2*rem >= total_div  <=>  2*r_last >= c_last: half-away rounding
    # needs ONLY the final step's remainder — exact at every k, no
    # wide-remainder arithmetic required
    r = jnp.zeros_like(a[..., HI])
    c = 1
    while k > 0:
        step = min(k, 9)
        c = 10 ** step
        mag, r = _divmod_small_nonneg(mag, c)
        k -= step
    round_up = 2 * r >= c
    mag = jnp.where(round_up[..., None],
                    add(mag, from_int64(jnp.ones_like(mag[..., HI]))), mag)
    return jnp.where(sign_neg[..., None], neg(mag), mag)


def floor_divmod_pow10(a: jnp.ndarray, k: int):
    """(a // 10^k, a mod 10^k) with FLOOR semantics (remainder in
    [0, 10^k) for any sign) — exact, never overflows."""
    assert 0 <= k <= 18
    sign_neg = a[..., HI] < 0
    mag = jnp.where(sign_neg[..., None], neg(a), a)
    q = mag
    rem = jnp.zeros_like(a[..., HI])
    mult = 1
    kk = k
    while kk > 0:
        step = min(kk, 9)
        c = 10 ** step
        q, r = _divmod_small_nonneg(q, c)
        rem = rem + r * mult
        mult *= c
        kk -= step
    c_total = 10 ** k
    # negative a: floor division rounds away from zero when rem > 0
    q_neg = neg(q)
    adj = sign_neg & (rem > 0)
    q_final = jnp.where(sign_neg[..., None],
                        jnp.where(adj[..., None],
                                  sub(q_neg, from_int64(
                                      jnp.ones_like(rem))), q_neg),
                        q)
    r_final = jnp.where(adj, c_total - rem, jnp.where(sign_neg, 0, rem))
    return q_final, r_final


def cmp_scaled(a: jnp.ndarray, sa: int, b: jnp.ndarray, sb: int):
    """(lt, eq) between a at scale sa and b at scale sb — exact for the
    full 38-digit range (scaling the larger-scale side DOWN with a
    floor remainder instead of scaling the smaller up, which would wrap
    past 2^128; reference: UnscaledDecimal128Arithmetic.compare)."""
    if sa == sb:
        return lt(a, b), eq(a, b)
    if sa > sb:
        l, e = cmp_scaled(b, sb, a, sa)
        return ~l & ~e, e
    # sb > sa: b = bq * 10^k + br; a*10^k <=> b reduces to (a, 0) vs
    # (bq, br) lexicographically
    bq, br = floor_divmod_pow10(b, sb - sa)
    less = lt(a, bq) | (eq(a, bq) & (br > 0))
    equal = eq(a, bq) & (br == 0)
    return less, equal


_FITS38_LIMIT = None


def exceeds_38_digits(a: jnp.ndarray) -> jnp.ndarray:
    """|a| >= 10^38 (the reference's DECIMAL overflow boundary,
    UnscaledDecimal128Arithmetic.exceedsOrEqualTenToThirtyEight)."""
    global _FITS38_LIMIT
    if _FITS38_LIMIT is None:
        _FITS38_LIMIT = from_host_int(10 ** 38), from_host_int(-(10 ** 38))
    hi_pos, hi_neg = _FITS38_LIMIT
    pos = jnp.asarray(hi_pos)
    neg_l = jnp.asarray(hi_neg)
    return ~lt(a, pos) | lt(a, neg_l)


def to_float64(a: jnp.ndarray) -> jnp.ndarray:
    hi = a[..., HI].astype(jnp.float64)
    lo = _u(a[..., LO]).astype(jnp.float64)
    return hi * (2.0 ** 64) + lo


def sort_operands(a: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(primary, secondary) int64 sort keys whose lexicographic order is
    the signed 128-bit order: signed hi, then lo xor-biased so its
    unsigned order sorts as int64."""
    return a[..., HI], a[..., LO] ^ jnp.int64(_SIGNBIT)


def segment_sum128(a: jnp.ndarray, valid, gid: jnp.ndarray,
                   n_groups: int) -> jnp.ndarray:
    """Exact segmented sum of (n, 2)-limb values: four unsigned 32-bit
    lanes segment-summed as int64 (lane sums < 2^63 for n < 2^31), then
    carry-recombined — mod-2^128 exact for any sign mix."""
    from presto_tpu.exec import kernels as K

    if valid is not None:
        a = jnp.where(jnp.asarray(valid)[..., None], a,
                      jnp.zeros_like(a))
    lanes = [
        (_u(a[..., LO]) & jnp.uint64(_M32)).astype(jnp.int64),
        (_u(a[..., LO]) >> jnp.uint64(32)).astype(jnp.int64),
        (_u(a[..., HI]) & jnp.uint64(_M32)).astype(jnp.int64),
        (_u(a[..., HI]) >> jnp.uint64(32)).astype(jnp.int64),
    ]
    sums = [K.segment_sum(l, gid, n_groups).astype(jnp.int64)
            for l in lanes]
    c0 = _u(sums[0])
    r0 = c0 & jnp.uint64(_M32)
    c1 = _u(sums[1]) + (c0 >> jnp.uint64(32))
    r1 = c1 & jnp.uint64(_M32)
    c2 = _u(sums[2]) + (c1 >> jnp.uint64(32))
    r2 = c2 & jnp.uint64(_M32)
    c3 = _u(sums[3]) + (c2 >> jnp.uint64(32))
    r3 = c3 & jnp.uint64(_M32)  # overflow past 2^128 wraps (mod arith)
    lo = _i(r0 | (r1 << jnp.uint64(32)))
    hi = _i(r2 | (r3 << jnp.uint64(32)))
    return jnp.stack([hi, lo], axis=-1)


def segment_minmax128(a: jnp.ndarray, valid, gid: jnp.ndarray,
                      n_groups: int, is_min: bool) -> jnp.ndarray:
    """Exact segmented min/max: two-pass lexicographic (extremize the
    high limb, then the biased low limb among rows matching it)."""
    from presto_tpu.exec import kernels as K

    f = K.segment_min if is_min else K.segment_max
    # sentinels must dominate the FULL int64 range (biased low limbs
    # span all of it; high limbs reach ~5.4e18 at 38 digits)
    sent = jnp.int64((1 << 63) - 1 if is_min else -(1 << 63))
    hi = a[..., HI]
    lo_b = a[..., LO] ^ jnp.int64(_SIGNBIT)
    v = jnp.ones_like(hi, bool) if valid is None else jnp.asarray(valid)
    hi_m = f(jnp.where(v, hi, sent), gid, n_groups)
    on_best = v & (hi == hi_m[gid])
    lo_m = f(jnp.where(on_best, lo_b, sent), gid, n_groups)
    return jnp.stack([hi_m, lo_m ^ jnp.int64(_SIGNBIT)], axis=-1)
