"""Incremental materialized views: manifest-delta refresh, sketch-state
rollups, and MV-routed serving.

CREATE MATERIALIZED VIEW analyzes the view query into a MERGEABLE shape
when possible: single-table FROM, simple conjunctive WHERE, plain-column
group keys, and aggregates whose partial states fold (count / sum / avg
/ min / max re-aggregate exactly; approx_distinct persists HLL register
rows and approx_percentile persists KLL summaries as 2-D rollup columns,
exec/kernels.py).  The backing table stores one row per group: the
visible finals plus hidden state columns (`__mv_n{i}` non-null counts,
`__mv_s{i}` avg sums, `__mv_hll{i}` / `__mv_kll{i}` sketch states,
`__mv_knull{j}` key null flags — localfile storage has no null channel).

REFRESH asks connectors/delta.py to diff the source against the
watermark recorded in the MV's own manifest (stamped atomically with
each snapshot commit).  An append-only delta aggregates JUST the new
rows and folds into the stored states — elementwise max for HLL,
weighted re-summarize for KLL, plain re-aggregation for exact
aggregates; anything else degrades LOUDLY to a full recompute
(QueryStats.mv_refresh_full — counted, never wrong).  The commit is the
PR-9 refresh-and-serve cut-over: a staged replace publishes atomically,
concurrent readers keep the previous generation (retire_depth=2 on the
backing keeps files through TWO refreshes for long-poll readers), and a
fault mid-merge aborts the sink leaving the prior snapshot serving.

Serving: try_route() — the containment matcher — routes a SELECT to the
freshest MV snapshot when its source, WHERE (recorded conjuncts plus
extra key-column predicates evaluated on the stored domain), grouping
prefix, and aggregates are covered; APPROX_DISTINCT reads the stored
HLL columns through the same merge-estimate the engine uses, so rollup
estimates stay exact under HLL union.  Kill switches:
`materialized_view_routing` session knob / PRESTO_TPU_MV_ROUTING=off.

Host-side grouping here is deliberately numpy (np.unique / ufunc.at):
device grouping primitives stay confined to the aggregation layer
(tests/test_lint.py), and MV rollup tables are small by construction.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import column_from_numpy
from presto_tpu.connectors import delta as DELTA
from presto_tpu.session import QueryResult
from presto_tpu.sql import ast

MV_PREFIX = "__mv__"

#: aggregate functions whose partial states the backing table can fold
MERGEABLE_AGGS = {"count", "sum", "min", "max", "avg",
                  "approx_distinct", "approx_percentile"}


class MatViewError(Exception):
    pass


def routing_enabled(session) -> bool:
    if os.environ.get("PRESTO_TPU_MV_ROUTING", "").lower() in (
            "off", "0", "false"):
        return False
    return bool(session.properties.get("materialized_view_routing", True))


# ---------------------------------------------------------------------------
# definition + analysis
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AggSpec:
    out: str                 # visible output column name
    fn: str                  # count | count_col | sum | min | max | avg
    #                        # | approx_distinct | approx_percentile
    arg: Optional[str]       # source column (None for count(*))
    out_type: T.Type
    arg_type: Optional[T.Type] = None
    m: int = 0               # HLL register count
    kk: int = 0              # KLL summary points (state width 2*kk)
    p: float = 0.5           # recorded percentile for the visible final
    idx: int = 0             # position in MvDefinition.aggs

    @property
    def n_col(self) -> str:
        return f"__mv_n{self.idx}"

    @property
    def s_col(self) -> str:
        return f"__mv_s{self.idx}"

    @property
    def hll_col(self) -> str:
        return f"__mv_hll{self.idx}"

    @property
    def kll_col(self) -> str:
        return f"__mv_kll{self.idx}"


@dataclasses.dataclass
class MvDefinition:
    name: str                # registry key (lowercased statement name)
    backing: str             # backing table name in the catalog
    query: object            # parsed ast.Query of the view definition
    query_repr: str          # structural fingerprint for exact matching
    properties: dict
    mergeable: bool
    reason: str = ""         # why NOT mergeable (degrade-loudly message)
    source: str = ""         # source table name as written
    keys: List[Tuple[str, str]] = dataclasses.field(default_factory=list)
    aggs: List[AggSpec] = dataclasses.field(default_factory=list)
    conjuncts: Optional[list] = None   # canonical simple WHERE conjuncts
    columns: List[Tuple[str, T.Type]] = dataclasses.field(
        default_factory=list)         # output columns in select order
    backing_schema: Dict[str, T.Type] = dataclasses.field(
        default_factory=dict)
    key_types: Dict[str, T.Type] = dataclasses.field(default_factory=dict)
    watermark: Optional[dict] = None   # backings without a manifest

    def knull_col(self, j: int) -> str:
        return f"__mv_knull{j}"


def _mv_key(catalog, name: str) -> str:
    n = name.lower()
    if n in catalog.matviews:
        return n
    if "." in n:
        flat = catalog._flat_name(n)
        if flat and flat in catalog.matviews:
            return flat
    return n


def _literal(e) -> tuple:
    """(ok, value) for a plain literal usable in a simple conjunct."""
    if isinstance(e, ast.Literal) and e.type_hint is None \
            and isinstance(e.value, (int, float, str, bool)):
        return True, e.value
    return False, None


_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "<>": "<>"}


def simple_conjuncts(expr) -> Optional[list]:
    """Decompose a WHERE tree into canonical column-vs-literal conjuncts,
    or None when any piece is more complex than the matcher handles."""
    if expr is None:
        return []
    if isinstance(expr, ast.BinaryOp) and expr.op == "AND":
        left = simple_conjuncts(expr.left)
        right = simple_conjuncts(expr.right)
        if left is None or right is None:
            return None
        return left + right
    if isinstance(expr, ast.BinaryOp) \
            and expr.op in ("=", "<>", "<", "<=", ">", ">="):
        if isinstance(expr.left, ast.Identifier):
            ok, v = _literal(expr.right)
            if ok:
                return [("cmp", expr.left.name.lower(), expr.op, v)]
        if isinstance(expr.right, ast.Identifier):
            ok, v = _literal(expr.left)
            if ok:
                return [("cmp", expr.right.name.lower(),
                         _FLIP[expr.op], v)]
        return None
    if isinstance(expr, ast.Between) and not expr.negated \
            and isinstance(expr.value, ast.Identifier):
        ok1, lo = _literal(expr.low)
        ok2, hi = _literal(expr.high)
        if ok1 and ok2:
            return [("between", expr.value.name.lower(), lo, hi)]
        return None
    if isinstance(expr, ast.InList) and not expr.negated \
            and isinstance(expr.value, ast.Identifier):
        vals = []
        for it in expr.items:
            ok, v = _literal(it)
            if not ok:
                return None
            vals.append(v)
        return [("in", expr.value.name.lower(),
                 tuple(sorted(vals, key=repr)))]
    if isinstance(expr, ast.IsNull) and isinstance(expr.value,
                                                   ast.Identifier):
        return [("isnull", expr.value.name.lower(), bool(expr.negated))]
    return None


def _conjunct_cols(conjuncts: list) -> set:
    return {c[1] for c in conjuncts}


def _agg_params(session, fn: str, args: list) -> dict:
    """Mirror the engine's sketch parameter derivation exactly
    (plan/distribute.py) so stored states fold with engine states."""
    from presto_tpu.exec import kernels as K

    if fn == "approx_distinct":
        m = 1024
        if len(args) == 2:
            ok, err = _literal(args[1])
            if not ok or not isinstance(err, (int, float)):
                return {}
            m = K.hll_m_for_error(float(err))
        return {"m": m}
    if fn == "approx_percentile":
        acc = float(session.properties.get("approx_percentile_accuracy",
                                           0.01))
        kk = max(16, int(math.ceil(2.0 / max(acc, 1e-6))))
        ok, p = _literal(args[1]) if len(args) == 2 else (False, None)
        if not ok or not isinstance(p, (int, float)):
            return {}
        return {"kk": kk, "p": float(p)}
    return {}


def analyze(session, name: str, query, properties: dict) -> MvDefinition:
    """Classify the view query as mergeable (delta refresh + rollup
    serving) or not (full-recompute refresh + exact-match serving)."""
    from presto_tpu.functions import aggregate as AGG

    catalog = session.catalog
    key = name.lower()
    backing = MV_PREFIX + key.replace(".", "_")
    mv = MvDefinition(name=key, backing=backing, query=query,
                      query_repr=repr(query), properties=dict(properties),
                      mergeable=False)

    def degrade(reason: str) -> MvDefinition:
        mv.reason = reason
        return mv

    spec = query.body
    if query.ctes or not isinstance(spec, ast.QuerySpec):
        return degrade("CTEs / set operations")
    # resolve the source FIRST: even non-mergeable views keep their
    # source binding so exact-match serving and write invalidation
    # know which table they shadow
    if not isinstance(spec.from_, ast.Table) or spec.from_.sample:
        return degrade("FROM is not a single plain table")
    source_name = spec.from_.name
    try:
        src = catalog.get(source_name)
    except KeyError:
        raise MatViewError(f"Table '{source_name}' does not exist")
    mv.source = source_name.lower()
    if query.order_by or query.limit is not None:
        return degrade("ORDER BY / LIMIT in view definition")
    if spec.distinct or spec.having is not None or spec.grouping_sets:
        return degrade("DISTINCT / HAVING / GROUPING SETS")

    conjuncts = simple_conjuncts(spec.where)
    if conjuncts is None:
        return degrade("WHERE is not a conjunction of simple predicates")
    for c in conjuncts:
        if c[1] not in src.schema:
            return degrade(f"WHERE references unknown column '{c[1]}'")

    group_cols: List[str] = []
    for g in spec.group_by:
        if not isinstance(g, ast.Identifier) \
                or g.name.lower() not in src.schema:
            return degrade("GROUP BY is not plain source columns")
        group_cols.append(g.name.lower())
    key_seen = set()

    agg_idx = 0
    for item in spec.select:
        e = item.expr
        if isinstance(e, ast.Identifier):
            col = e.name.lower()
            if col not in group_cols:
                return degrade(f"selected column '{col}' is not grouped")
            out = (item.alias or e.name).lower()
            mv.keys.append((out, col))
            mv.key_types[out] = src.schema[col]
            mv.columns.append((out, src.schema[col]))
            key_seen.add(col)
            continue
        if not isinstance(e, ast.FunctionCall):
            return degrade("select item is not a column or aggregate")
        fn = e.name.lower()
        if fn not in MERGEABLE_AGGS or e.distinct or e.filter is not None \
                or e.window is not None:
            return degrade(f"aggregate '{fn}' is not mergeable")
        args = e.args
        star = len(args) == 0 or (len(args) == 1
                                  and isinstance(args[0], ast.Star))
        out = (item.alias or fn).lower()
        if fn == "count" and star:
            spec_a = AggSpec(out, "count", None, T.BIGINT, idx=agg_idx)
        else:
            if not args or not isinstance(args[0], ast.Identifier):
                return degrade(f"'{fn}' argument is not a plain column")
            arg = args[0].name.lower()
            at = src.schema.get(arg)
            if at is None:
                return degrade(f"unknown column '{arg}'")
            if fn == "count":
                if len(args) != 1:
                    return degrade("count() with extra arguments")
                spec_a = AggSpec(out, "count_col", arg, T.BIGINT,
                                 arg_type=at, idx=agg_idx)
            elif fn in ("sum", "avg"):
                if len(args) != 1 or not (at.is_integer or at.is_floating):
                    return degrade(f"'{fn}' needs a plain int/float column")
                spec_a = AggSpec(out, fn, arg,
                                 AGG.resolve(fn, [at]), arg_type=at,
                                 idx=agg_idx)
            elif fn in ("min", "max"):
                if len(args) != 1 or not (at.is_integer or at.is_floating
                                          or at.is_temporal
                                          or at.name == "BOOLEAN"):
                    return degrade(f"'{fn}' over {at} is not mergeable")
                spec_a = AggSpec(out, fn, arg, at, arg_type=at,
                                 idx=agg_idx)
            elif fn == "approx_distinct":
                if len(args) not in (1, 2) or at.is_decimal:
                    return degrade("approx_distinct arguments")
                params = _agg_params(session, fn, args)
                if not params:
                    return degrade("approx_distinct error argument")
                spec_a = AggSpec(out, fn, arg, T.BIGINT, arg_type=at,
                                 m=params["m"], idx=agg_idx)
            else:  # approx_percentile
                if len(args) != 2 or not (at.is_integer or at.is_floating):
                    return degrade(
                        "approx_percentile needs (numeric column, p)")
                params = _agg_params(session, fn, args)
                if not params:
                    return degrade("approx_percentile percentile argument")
                spec_a = AggSpec(out, fn, arg, at, arg_type=at,
                                 kk=params["kk"], p=params["p"],
                                 idx=agg_idx)
        mv.aggs.append(spec_a)
        mv.columns.append((out, spec_a.out_type))
        agg_idx += 1

    if set(group_cols) - key_seen:
        return degrade("GROUP BY column missing from SELECT")
    if len({o for o, _ in mv.keys} | {a.out for a in mv.aggs}) \
            != len(mv.keys) + len(mv.aggs):
        return degrade("duplicate output column names")
    if not mv.aggs:
        return degrade("no aggregates to materialize")

    # backing schema: visible columns in select order + hidden states
    schema: Dict[str, T.Type] = {}
    for out, t in mv.columns:
        schema[out] = t
    for j, (out, _col) in enumerate(mv.keys):
        schema[mv.knull_col(j)] = T.BOOLEAN
    for a in mv.aggs:
        if a.fn in ("sum", "min", "max", "avg", "approx_percentile"):
            schema[a.n_col] = T.BIGINT
        if a.fn == "avg":
            schema[a.s_col] = T.DOUBLE
        if a.fn == "approx_distinct":
            schema[a.hll_col] = T.hll_state(a.m)
        if a.fn == "approx_percentile":
            schema[a.kll_col] = T.kll_state(2 * a.kk)
    mv.backing_schema = schema
    mv.conjuncts = conjuncts
    mv.mergeable = True
    return mv


# ---------------------------------------------------------------------------
# host-side aggregation + fold (numpy; device sketch kernels for states)
# ---------------------------------------------------------------------------


def _split_col(a) -> Tuple[np.ndarray, np.ndarray]:
    """(filled values, valid mask) from a connector host column."""
    if isinstance(a, np.ma.MaskedArray):
        valid = ~np.ma.getmaskarray(a)
        fill = "" if a.dtype == object or a.dtype.kind in ("U", "S") else 0
        return np.asarray(a.filled(fill)), np.asarray(valid)
    a = np.asarray(a)
    return a, np.ones(len(a), dtype=bool)


def _eval_conjunct(conj, vals: np.ndarray, valid: np.ndarray) -> np.ndarray:
    kind = conj[0]
    if kind == "isnull":
        return valid if conj[2] else ~valid
    if kind == "cmp":
        _, _c, op, v = conj
        with np.errstate(invalid="ignore"):
            if op == "=":
                m = vals == v
            elif op == "<>":
                m = vals != v
            elif op == "<":
                m = vals < v
            elif op == "<=":
                m = vals <= v
            elif op == ">":
                m = vals > v
            else:
                m = vals >= v
        return valid & np.asarray(m, dtype=bool)
    if kind == "between":
        _, _c, lo, hi = conj
        with np.errstate(invalid="ignore"):
            m = (vals >= lo) & (vals <= hi)
        return valid & np.asarray(m, dtype=bool)
    # in
    _, _c, items = conj
    return valid & np.isin(vals, np.array(list(items), dtype=vals.dtype
                                          if vals.dtype != object
                                          else object))


def _apply_where(mv: MvDefinition, data: dict, n: int) -> np.ndarray:
    mask = np.ones(n, dtype=bool)
    for conj in mv.conjuncts or []:
        vals, valid = _split_col(data[conj[1]])
        mask &= _eval_conjunct(conj, vals, valid)
    return mask


def _factorize(cols: List[Tuple[np.ndarray, np.ndarray]], n: int):
    """Group ids over (values, valid) key columns: NULL is its own key.
    Returns (gid, n_groups, first_row_index_per_group)."""
    if not cols:
        return np.zeros(n, dtype=np.int64), 1, np.zeros(1, dtype=np.int64)
    codes = []
    for vals, valid in cols:
        _u, inv = np.unique(vals, return_inverse=True)
        inv = inv.astype(np.int64) + 1
        inv[~valid] = 0
        codes.append(inv)
    stacked = np.stack(codes, axis=1)
    _uniq, gid = np.unique(stacked, axis=0, return_inverse=True)
    gid = gid.reshape(-1).astype(np.int64)
    n_groups = int(gid.max()) + 1 if len(gid) else 0
    first = np.full(n_groups, n, dtype=np.int64)
    np.minimum.at(first, gid, np.arange(n, dtype=np.int64))
    return gid, n_groups, first


def _minmax_sentinel(dtype, is_min: bool):
    if np.issubdtype(dtype, np.floating):
        return np.inf if is_min else -np.inf
    info = np.iinfo(dtype) if np.issubdtype(dtype, np.integer) else None
    if info is not None:
        return info.max if is_min else info.min
    return True if is_min else False  # booleans


def _hll_states(arg_vals, valid, gid, n_groups, m, arg_type):
    from presto_tpu.exec import kernels as K
    import jax.numpy as jnp

    if len(arg_vals) == 0 or n_groups == 0:
        return np.zeros((n_groups, m), dtype=np.uint8)
    col = column_from_numpy(arg_vals, arg_type, valid)
    h = K.hll_hash64(col)
    st = K.hll_partial(h, jnp.asarray(valid), jnp.asarray(gid),
                       n_groups, m)
    return np.asarray(st, dtype=np.uint8)


def _kll_summarize(gv: np.ndarray, gw: np.ndarray, kk: int):
    """Compress value-sorted (value, weight) pairs of ONE group into at
    most kk pairs.  Equal values are merged first (lossless); while the
    surviving pair count fits in kk the summary IS the exact weighted
    multiset, so readouts equal the engine's exact group_percentile and
    delta-merged results match a full recompute bit-for-bit.  Only past
    kk distinct values does it resample: bucket j owns the weight-rank
    interval [floor(j*W/kk), floor((j+1)*W/kk)) and its representative
    is the value COVERING the bucket's first rank — value and weight
    stay aligned, unlike a naive midpoint gather."""
    uniq, inv = np.unique(gv, return_inverse=True)
    w = np.zeros(len(uniq), dtype=np.float64)
    np.add.at(w, inv, gw)
    if len(uniq) <= kk:
        return uniq, w
    W = float(w.sum())
    cum = np.cumsum(w)
    edges = np.floor(np.arange(kk + 1, dtype=np.float64) * W / kk)
    wgt = edges[1:] - edges[:-1]
    idx = np.searchsorted(cum, edges[:-1], side="right")
    idx = np.minimum(idx, len(uniq) - 1)
    return uniq[idx], wgt


def _kll_states(arg_vals, valid, gid, n_groups, kk):
    """Per-group quantile summaries from raw rows, built host-side so
    that groups with <= kk distinct values store their EXACT weighted
    multiset (the device kll_partial kernel resamples unconditionally,
    which loses rank fidelity on small groups and would break the
    merge == full-recompute identity)."""
    out = np.zeros((n_groups, 2 * kk), dtype=np.float64)
    if n_groups == 0 or len(arg_vals) == 0:
        return out
    x = np.asarray(arg_vals, dtype=np.float64)
    g = np.asarray(gid, dtype=np.int64)
    keep = np.asarray(valid, dtype=bool)
    x, g = x[keep], g[keep]
    if len(x) == 0:
        return out
    order = np.lexsort((x, g))
    x, g = x[order], g[order]
    bounds = np.searchsorted(g, np.arange(n_groups + 1, dtype=np.int64),
                             side="left")
    for grp in range(n_groups):
        s, e = bounds[grp], bounds[grp + 1]
        if s == e:
            continue
        v, w = _kll_summarize(x[s:e], np.ones(e - s, dtype=np.float64), kk)
        out[grp, :len(v)] = v
        out[grp, kk:kk + len(w)] = w
    return out


def _hll_estimate(states: np.ndarray) -> np.ndarray:
    from presto_tpu.exec import kernels as K
    import jax.numpy as jnp

    if len(states) == 0:
        return np.zeros(0, dtype=np.int64)
    return np.asarray(K.hll_estimate(jnp.asarray(states)),
                      dtype=np.int64)


def _kll_fold(states: np.ndarray, gid: np.ndarray, n_groups: int,
              kk: int) -> np.ndarray:
    """Fold partial KLL summaries per group: flatten every contributing
    state's (value, weight) pairs and re-summarize via _kll_summarize.
    While a group's pairs keep fitting in kk slots the fold is lossless,
    so delta-merged percentiles match a full recompute bit-for-bit
    (tests/test_matview.py)."""
    out = np.zeros((n_groups, 2 * kk), dtype=np.float64)
    if len(states) == 0 or n_groups == 0:
        return out
    vals = states[:, :kk]
    wts = states[:, kk:]
    g = np.repeat(np.asarray(gid, dtype=np.int64), kk)
    v = vals.ravel()
    w = wts.ravel()
    keep = w > 0
    g, v, w = g[keep], v[keep], w[keep]
    order = np.lexsort((v, g))
    g, v, w = g[order], v[order], w[order]
    bounds = np.searchsorted(g, np.arange(n_groups + 1, dtype=np.int64),
                             side="left")
    for grp in range(n_groups):
        s, e = bounds[grp], bounds[grp + 1]
        if s == e:
            continue
        sv, sw = _kll_summarize(v[s:e], w[s:e], kk)
        out[grp, :len(sv)] = sv
        out[grp, kk:kk + len(sw)] = sw
    return out


def _kll_readout(states: np.ndarray, kk: int, p: float):
    """Percentile from stored KLL states with the engine's weighted-rank
    readout (kernels.kll_percentile): target rank floor(p*(W-1))+1, first
    value whose cumulative weight reaches it."""
    n = len(states)
    out = np.zeros(n, dtype=np.float64)
    nonempty = np.zeros(n, dtype=bool)
    for g in range(n):
        w = states[g, kk:]
        keep = w > 0
        if not keep.any():
            continue
        v = states[g, :kk][keep]
        ww = w[keep]
        order = np.argsort(v, kind="stable")
        v, ww = v[order], ww[order]
        W = float(ww.sum())
        t = math.floor(p * (W - 1)) + 1
        cum = np.cumsum(ww)
        i = int(np.searchsorted(cum, t, side="left"))
        out[g] = v[min(i, len(v) - 1)]
        nonempty[g] = True
    return out, nonempty


def _cast_final(vals: np.ndarray, typ: T.Type) -> np.ndarray:
    if typ.is_integer or typ.is_temporal:
        return np.asarray(vals).astype(np.int64)
    return np.asarray(vals)


def aggregate_rows(mv: MvDefinition, data: dict, n: int) -> dict:
    """View-query aggregation over host rows -> MV-shaped arrays (one
    row per group, visible finals + hidden states)."""
    mask = _apply_where(mv, data, n)
    key_cols = []
    for _out, col in mv.keys:
        vals, valid = _split_col(data[col])
        key_cols.append((vals[mask], valid[mask]))
    gid, n_groups, first = _factorize(key_cols, int(mask.sum()))
    out: Dict[str, np.ndarray] = {}
    for j, (kout, _col) in enumerate(mv.keys):
        vals, valid = key_cols[j]
        sel = np.minimum(first, max(len(vals) - 1, 0))
        out[kout] = vals[sel] if len(vals) else vals
        out[mv.knull_col(j)] = ~(valid[sel] if len(valid) else valid)
    for a in mv.aggs:
        if a.arg is not None:
            av, avalid = _split_col(data[a.arg])
            av, avalid = av[mask], avalid[mask]
        else:
            av = avalid = None
        _agg_into(out, a, av, avalid, gid, n_groups)
    return out


def _agg_into(out: dict, a: AggSpec, av, avalid, gid, n_groups) -> None:
    """One aggregate's visible final + hidden state columns."""
    if a.fn == "count":
        cnt = np.zeros(n_groups, dtype=np.int64)
        np.add.at(cnt, gid, 1)
        out[a.out] = cnt
        return
    if a.fn == "count_col":
        cnt = np.zeros(n_groups, dtype=np.int64)
        np.add.at(cnt, gid, avalid.astype(np.int64))
        out[a.out] = cnt
        return
    nn = np.zeros(n_groups, dtype=np.int64)
    if avalid is not None:
        np.add.at(nn, gid, avalid.astype(np.int64))
    if a.fn in ("sum", "avg"):
        acc = np.zeros(n_groups, dtype=np.float64
                       if a.arg_type.is_floating or a.fn == "avg"
                       else np.int64)
        vv = av.astype(acc.dtype)
        np.add.at(acc, gid[avalid], vv[avalid])
        if a.fn == "sum":
            out[a.out] = _cast_final(acc, a.out_type)
            out[a.n_col] = nn
        else:
            out[a.s_col] = acc.astype(np.float64)
            out[a.n_col] = nn
            with np.errstate(invalid="ignore", divide="ignore"):
                out[a.out] = np.where(nn > 0, acc / np.maximum(nn, 1), 0.0)
        return
    if a.fn in ("min", "max"):
        is_min = a.fn == "min"
        dt = np.float64 if a.arg_type.is_floating else (
            np.bool_ if a.arg_type.name == "BOOLEAN" else np.int64)
        acc = np.full(n_groups, _minmax_sentinel(np.dtype(dt), is_min),
                      dtype=dt)
        vv = av.astype(dt)
        if is_min:
            np.minimum.at(acc, gid[avalid], vv[avalid])
        else:
            np.maximum.at(acc, gid[avalid], vv[avalid])
        out[a.out] = np.where(nn > 0, acc, np.zeros(1, dtype=dt))
        out[a.n_col] = nn
        return
    if a.fn == "approx_distinct":
        st = _hll_states(av, avalid, gid, n_groups, a.m, a.arg_type)
        out[a.hll_col] = st
        out[a.out] = _hll_estimate(st)
        return
    # approx_percentile
    st = _kll_states(av, avalid, gid, n_groups, a.kk)
    vals, _ne = _kll_readout(st, a.kk, a.p)
    out[a.kll_col] = st
    out[a.n_col] = nn
    out[a.out] = np.where(nn > 0, _cast_final(vals, a.out_type),
                          np.zeros(1, dtype=_cast_final(vals,
                                                        a.out_type).dtype))


def fold_groups(mv: MvDefinition, arrays: dict, group_keys: List[str],
                percentiles: Optional[Dict[str, float]] = None) -> dict:
    """Re-aggregate MV-shaped arrays onto a (sub)set of the MV's key
    columns by folding the stored partial states: additive exact states,
    elementwise-max HLL registers, weighted KLL re-summarize.  The merge
    path (stored + delta) and the serving rollup path share this."""
    n = len(next(iter(arrays.values()))) if arrays else 0
    key_out = [k for k in group_keys]
    key_idx = {out: j for j, (out, _c) in enumerate(mv.keys)}
    key_cols = []
    for out in key_out:
        vals = np.asarray(arrays[out])
        valid = ~np.asarray(arrays[mv.knull_col(key_idx[out])], dtype=bool)
        key_cols.append((vals, valid))
    gid, n_groups, first = _factorize(key_cols, n)
    merged: Dict[str, np.ndarray] = {}
    for jj, out in enumerate(key_out):
        vals, valid = key_cols[jj]
        sel = np.minimum(first, max(n - 1, 0))
        merged[out] = vals[sel] if n else vals
        merged[f"__fold_knull{jj}"] = ~(valid[sel] if n else valid)
    for a in mv.aggs:
        p = (percentiles or {}).get(a.out, a.p)
        _fold_agg(mv, merged, a, arrays, gid, n_groups, p)
    return merged


def _fold_agg(mv, merged, a: AggSpec, arrays, gid, n_groups,
              p: float) -> None:
    def _sum64(col, dtype):
        acc = np.zeros(n_groups, dtype=dtype)
        np.add.at(acc, gid, np.asarray(arrays[col]).astype(dtype))
        return acc

    if a.fn in ("count", "count_col"):
        merged[a.out] = _sum64(a.out, np.int64)
        return
    nn = _sum64(a.n_col, np.int64) if a.n_col in arrays else None
    if a.fn == "sum":
        dt = np.float64 if a.out_type.is_floating else np.int64
        acc = _sum64(a.out, dt)
        merged[a.out] = np.where(nn > 0, acc, np.zeros(1, dtype=dt))
        merged[a.n_col] = nn
        return
    if a.fn == "avg":
        s = _sum64(a.s_col, np.float64)
        merged[a.s_col] = s
        merged[a.n_col] = nn
        with np.errstate(invalid="ignore", divide="ignore"):
            merged[a.out] = np.where(nn > 0, s / np.maximum(nn, 1), 0.0)
        return
    if a.fn in ("min", "max"):
        is_min = a.fn == "min"
        vals = np.asarray(arrays[a.out])
        dt = vals.dtype
        acc = np.full(n_groups, _minmax_sentinel(dt, is_min), dtype=dt)
        rows_n = np.asarray(arrays[a.n_col], dtype=np.int64)
        live = rows_n > 0
        if is_min:
            np.minimum.at(acc, gid[live], vals[live])
        else:
            np.maximum.at(acc, gid[live], vals[live])
        merged[a.out] = np.where(nn > 0, acc, np.zeros(1, dtype=dt))
        merged[a.n_col] = nn
        return
    if a.fn == "approx_distinct":
        st = np.asarray(arrays[a.hll_col], dtype=np.uint8)
        acc = np.zeros((n_groups, a.m), dtype=np.uint8)
        np.maximum.at(acc, gid, st)   # HLL union IS elementwise max
        merged[a.hll_col] = acc
        merged[a.out] = _hll_estimate(acc)
        return
    # approx_percentile
    st = np.asarray(arrays[a.kll_col], dtype=np.float64)
    acc = _kll_fold(st, gid, n_groups, a.kk)
    vals, _ne = _kll_readout(acc, a.kk, p)
    merged[a.kll_col] = acc
    merged[a.n_col] = nn
    merged[a.out] = np.where(nn > 0, _cast_final(vals, a.out_type),
                             np.zeros(1, dtype=_cast_final(
                                 vals, a.out_type).dtype))


def merge_states(mv: MvDefinition, stored: dict, delta: dict) -> dict:
    """Fold a delta's MV-shaped arrays into the stored snapshot's."""
    n_s = len(next(iter(stored.values()))) if stored else 0
    if n_s == 0:
        return delta
    combined = {}
    for c in mv.backing_schema:
        a, b = np.asarray(stored[c]), np.asarray(delta[c])
        combined[c] = np.concatenate([a, b.astype(a.dtype, copy=False)])
    folded = fold_groups(mv, combined, [out for out, _c in mv.keys])
    # fold emits positional null flags; restore backing column names
    for j in range(len(mv.keys)):
        folded[mv.knull_col(j)] = folded.pop(f"__fold_knull{j}")
    return {c: folded[c] for c in mv.backing_schema}


# ---------------------------------------------------------------------------
# backing snapshot I/O
# ---------------------------------------------------------------------------


def _read_backing(mv: MvDefinition, backing) -> dict:
    if backing.row_count() == 0:
        return {}
    return {c: np.asarray(a)
            for c, a in backing.read(list(mv.backing_schema)).items()}


def _commit_snapshot(session, mv: MvDefinition, backing, arrays: dict,
                     stamp: dict) -> None:
    """Publish a snapshot atomically (PR-9 cut-over): stage every shard,
    then one manifest replace flips readers to the new generation WITH
    the watermark it covers.  Any failure aborts the sink — staged files
    are deleted and the PRIOR snapshot keeps serving."""
    if hasattr(backing, "page_sink"):
        sink = backing.page_sink(None, replace=True,
                                 schema=mv.backing_schema)
        try:
            sink.append_page(
                {c: arrays[c] for c in mv.backing_schema})
            backing.set_mv_stamp({"source": mv.source,
                                  "watermark": stamp})
            sink.finish()
        except BaseException:
            backing._mv_stamp = None
            try:
                sink.abort()
            except Exception:
                pass
            raise
        mv.watermark = stamp
    else:  # memory backing: swap columns wholesale
        backing.data = {c: np.asarray(arrays[c])
                        for c in mv.backing_schema}
        backing._rows = len(next(iter(backing.data.values()))) \
            if backing.data else 0
        backing._invalidate()
        mv.watermark = stamp
    session.catalog.version += 1
    _notify_write(session, mv)


def _notify_write(session, mv: MvDefinition) -> None:
    from presto_tpu.exec import writer as W

    try:
        W._invalidate_server_caches(
            session, tables={mv.name, mv.backing, mv.source})
    except TypeError:  # older serving tier without table scoping
        W._invalidate_server_caches(session)


def _recorded_watermark(mv: MvDefinition, backing) -> Optional[dict]:
    rec = None
    if hasattr(backing, "mv_watermarks"):
        rec = backing.mv_watermarks()
    if rec is None and mv.watermark is not None:
        return mv.watermark
    if isinstance(rec, dict):
        return rec.get("watermark")
    return None


def _stats(mon):
    return getattr(mon, "stats", None) if mon is not None else None


def _bump(mon, field: str, by: int = 1) -> None:
    st = _stats(mon)
    if st is not None and hasattr(st, field):
        setattr(st, field, getattr(st, field) + by)


# ---------------------------------------------------------------------------
# statement handlers (wired from executor._dispatch_statement)
# ---------------------------------------------------------------------------


def create(session, stmt, mon) -> QueryResult:
    from presto_tpu import types as TT
    from presto_tpu.catalog import MemoryTable
    from presto_tpu.exec import writer as W

    catalog = session.catalog
    key = _mv_key(catalog, stmt.name)
    session.access_control.check_can_create_table(session.user, stmt.name)
    if key in catalog.matviews:
        if stmt.if_not_exists:
            return QueryResult([("result", TT.BOOLEAN)], [(True,)])
        if not stmt.or_replace:
            raise MatViewError(
                f"Materialized view '{stmt.name}' already exists")
        _drop_backing(session, catalog.matviews[key])
    elif stmt.name in catalog:
        raise MatViewError(
            f"Table '{stmt.name}' already exists")

    mv = analyze(session, key, stmt.query, stmt.properties)
    if mv.mergeable:
        props = dict(stmt.properties)
        props.setdefault("connector", "localfile")
        backing, _conn = W.build_target_table(
            session, mv.backing, mv.backing_schema, props)
        if hasattr(backing, "drop_data") and backing.row_count() > 0:
            backing.drop_data()  # stale directory from a dead MV
        # long-poll readers may span TWO refresh cut-overs; keep retired
        # shards an extra generation before GC (tests/test_matview.py)
        backing.retire_depth = 2
        catalog.register(backing)
        _refresh_into(session, mv, backing, mon, force_full=True)
    else:
        _bump(mon, "mv_refresh_full")
        arrays, types_ = _full_recompute(session, mv)
        mv.columns = list(types_.items())
        schema = dict(types_)
        backing = MemoryTable(mv.backing, schema, arrays)
        mv.backing_schema = schema
        catalog.register(backing)
        mv.watermark = DELTA.capture(catalog.get(mv.source)) \
            if mv.source else None
    catalog.matviews[key] = mv
    return QueryResult([("result", TT.BOOLEAN)], [(True,)])


def drop(session, stmt, mon) -> QueryResult:
    from presto_tpu import types as TT

    catalog = session.catalog
    key = _mv_key(catalog, stmt.name)
    mv = catalog.matviews.get(key)
    if mv is None:
        if stmt.if_exists:
            return QueryResult([("result", TT.BOOLEAN)], [(False,)])
        raise MatViewError(
            f"Materialized view '{stmt.name}' does not exist")
    session.access_control.check_can_drop_table(session.user, stmt.name)
    _drop_backing(session, mv)
    del catalog.matviews[key]
    _notify_write(session, mv)
    return QueryResult([("result", TT.BOOLEAN)], [(True,)])


def _drop_backing(session, mv: MvDefinition) -> None:
    catalog = session.catalog
    t = catalog.tables.get(mv.backing)
    if t is not None and hasattr(t, "drop_data"):
        t.drop_data()
    catalog.tables.pop(mv.backing, None)
    catalog.version += 1


def show(session) -> QueryResult:
    from presto_tpu import types as TT

    rows = sorted(
        (mv.name, mv.mergeable,
         mv.source if mv.mergeable else (mv.reason or ""))
        for mv in session.catalog.matviews.values())
    return QueryResult(
        [("Materialized View", TT.VARCHAR), ("Mergeable", TT.BOOLEAN),
         ("Detail", TT.VARCHAR)], rows)


def refresh(session, stmt, mon) -> QueryResult:
    from presto_tpu import types as TT

    catalog = session.catalog
    key = _mv_key(catalog, stmt.name)
    mv = catalog.matviews.get(key)
    if mv is None:
        raise MatViewError(
            f"Materialized view '{stmt.name}' does not exist")
    backing = catalog.tables.get(mv.backing)
    if backing is None:
        raise MatViewError(
            f"Materialized view '{stmt.name}' lost its backing table")
    if mv.mergeable:
        n, mode = _refresh_into(session, mv, backing, mon)
    else:
        source = catalog.get(mv.source) if mv.source else None
        verdict = DELTA.diff(source, mv.watermark) if source is not None \
            else DELTA.DeltaVerdict("full", reason="no source table")
        if verdict.kind == "empty":
            return QueryResult(
                [("rows", TT.BIGINT), ("refresh", TT.VARCHAR)],
                [(0, "noop")])
        _bump(mon, "mv_refresh_full")
        _bump(mon, "mv_source_splits", verdict.total_splits)
        arrays, types_ = _full_recompute(session, mv)
        mv.columns = list(types_.items())
        mv.backing_schema = dict(types_)
        backing.schema = dict(types_)
        stamp = DELTA.capture(source) if source is not None else None
        backing.data = {c: (v if isinstance(v, np.ma.MaskedArray)
                            else np.asarray(v))
                        for c, v in arrays.items()}
        backing._rows = len(next(iter(arrays.values()))) if arrays else 0
        backing._invalidate()
        mv.watermark = stamp
        session.catalog.version += 1
        _notify_write(session, mv)
        n, mode = backing._rows, "full: non-mergeable view"
    return QueryResult([("rows", TT.BIGINT), ("refresh", TT.VARCHAR)],
                       [(n, mode)])


def _full_recompute(session, mv: MvDefinition):
    """Run the view query through the regular engine (any execution
    mode) and return (arrays, types) — the never-wrong fallback."""
    from presto_tpu.exec.executor import execute_plan_to_host

    return execute_plan_to_host(session, ast.QueryStatement(mv.query))


def _source_columns(mv: MvDefinition) -> List[str]:
    cols = {c for _out, c in mv.keys}
    cols |= {a.arg for a in mv.aggs if a.arg is not None}
    cols |= _conjunct_cols(mv.conjuncts or [])
    return sorted(cols)


def _refresh_into(session, mv: MvDefinition, backing, mon,
                  force_full: bool = False):
    """Mergeable refresh: delta-fold when the source verdict allows it,
    loud full recompute otherwise.  Returns (rows, mode_string)."""
    catalog = session.catalog
    try:
        source = catalog.get(mv.source)
    except KeyError:
        raise MatViewError(
            f"Materialized view '{mv.name}' source '{mv.source}' "
            "does not exist")
    mode_knob = str(session.properties.get("mv_refresh_mode", "auto"))
    recorded = None if force_full else _recorded_watermark(mv, backing)
    verdict = DELTA.diff(source, recorded)
    if not force_full and mode_knob != "full":
        if verdict.kind == "empty":
            return 0, "noop"
    if mode_knob == "delta" and verdict.kind != "append" \
            and not force_full:
        raise MatViewError(
            f"mv_refresh_mode=delta but delta refresh of '{mv.name}' "
            f"is impossible: {verdict.reason or verdict.kind}")

    cols = _source_columns(mv)
    delta_ok = (not force_full and mode_knob != "full"
                and verdict.kind == "append")
    # capture AFTER the verdict; pin the read to the captured row count
    # so the stamped watermark covers exactly the rows aggregated
    current = DELTA.capture(source)
    if delta_ok:
        a = verdict.row_range[0]
        b = int(current["row_count"])
        data = source.read(cols, split=(a, b)) if cols else {}
        delta_mv = aggregate_rows(mv, data, b - a)
        stored = _read_backing(mv, backing)
        merged = merge_states(mv, stored, delta_mv) if stored \
            else delta_mv
        _bump(mon, "mv_refresh_delta")
        _bump(mon, "mv_delta_splits", verdict.delta_splits)
        _bump(mon, "mv_source_splits", verdict.total_splits)
        mode = "delta"
    else:
        n_rows = int(current["row_count"])
        data = source.read(cols, split=(0, n_rows)) if cols else {}
        merged = aggregate_rows(mv, data, n_rows)
        _bump(mon, "mv_refresh_full")
        _bump(mon, "mv_source_splits", verdict.total_splits)
        mode = "full" if force_full or mode_knob == "full" \
            else f"full: {verdict.reason or verdict.kind}"
    _commit_snapshot(session, mv, backing, merged, current)
    n = len(next(iter(merged.values()))) if merged else 0
    return n, mode


# ---------------------------------------------------------------------------
# serving: the containment matcher (MV-routed SELECTs)
# ---------------------------------------------------------------------------


def _py(v):
    if isinstance(v, np.generic):
        v = v.item()
    return v


def _to_result(cols, order_by, limit) -> Optional[QueryResult]:
    """cols: [(name, Type, values, valid)] -> QueryResult with host rows,
    applying output-column ORDER BY and LIMIT (or None to decline)."""
    names = [c[0] for c in cols]
    n = len(cols[0][2]) if cols else 0
    rows = []
    for i in range(n):
        rows.append(tuple(
            _py(vals[i]) if bool(valid[i]) else None
            for _nm, _t, vals, valid in cols))
    for si in reversed(order_by or []):
        e = si.expr
        if not isinstance(e, ast.Identifier) or e.name not in names:
            return None
        asc = bool(si.ascending)
        if si.nulls_first is not None and bool(si.nulls_first) == asc:
            return None  # non-default null placement
        j = names.index(e.name)
        rows.sort(key=lambda r: (r[j] is None,
                                 r[j] if r[j] is not None else 0),
                  reverse=not asc)
    if limit is not None:
        rows = rows[:int(limit)]
    return QueryResult([(nm, t) for nm, t, _v, _m in cols], rows)


def _final_validity(mv: MvDefinition, arrays: dict, a: AggSpec,
                    n: int) -> np.ndarray:
    if a.fn in ("count", "count_col", "approx_distinct"):
        return np.ones(n, dtype=bool)
    return np.asarray(arrays[a.n_col], dtype=np.int64) > 0


def _match_agg(session, mv: MvDefinition, e: ast.FunctionCall) \
        -> Optional[Tuple[AggSpec, Optional[float]]]:
    """Match a query aggregate to a stored AggSpec; the optional float
    is a percentile override read out of the stored KLL state."""
    fn = e.name.lower()
    if e.distinct or e.filter is not None or e.window is not None:
        return None
    args = e.args
    star = len(args) == 0 or (len(args) == 1
                              and isinstance(args[0], ast.Star))
    if fn == "count" and star:
        for a in mv.aggs:
            if a.fn == "count":
                return a, None
        return None
    if not args or not isinstance(args[0], ast.Identifier):
        return None
    arg = args[0].name.lower()
    want = {"count": "count_col"}.get(fn, fn)
    for a in mv.aggs:
        if a.fn != want or a.arg != arg:
            continue
        if fn == "approx_distinct":
            params = _agg_params(session, fn, args)
            if params.get("m") != a.m:
                continue
            return a, None
        if fn == "approx_percentile":
            if len(args) != 2:
                continue
            ok, p = _literal(args[1])
            if not ok or not isinstance(p, (int, float)):
                continue
            return a, float(p)
        if len(args) != 1:
            continue
        return a, None
    return None


def try_route(session, stmt, mon) -> Optional[QueryResult]:
    """Route a SELECT to a materialized view snapshot when the MV
    provably contains it; None falls through to the engine."""
    catalog = session.catalog
    if not catalog.matviews or not routing_enabled(session):
        return None
    if getattr(session.txn, "current", None) is not None:
        return None
    q = getattr(stmt, "query", None)
    if q is None or q.ctes:
        return None
    spec = q.body
    if not isinstance(spec, ast.QuerySpec) \
            or not isinstance(spec.from_, ast.Table) or spec.from_.sample:
        return None
    tname = spec.from_.name.lower()

    mv_key = _mv_key(catalog, tname)
    if mv_key in catalog.matviews:
        res = _route_direct(session, catalog.matviews[mv_key], q, spec)
        if res is not None:
            _bump(mon, "mv_routed")
            st = _stats(mon)
            if st is not None:
                st.execution_mode = "mv_routed"
        return res

    try:
        src = catalog.get(tname)
    except KeyError:
        return None
    for mv in catalog.matviews.values():
        backing = catalog.tables.get(mv.backing)
        if backing is None:
            continue
        try:
            if catalog.get(mv.source) is not src:
                continue
        except KeyError:
            continue
        if mv.mergeable:
            res = _route_rollup(session, mv, backing, q, spec)
        else:
            res = _route_exact(mv, backing, q)
        if res is not None:
            _bump(mon, "mv_routed")
            st = _stats(mon)
            if st is not None:
                st.execution_mode = "mv_routed"
            return res
    return None


def _route_exact(mv: MvDefinition, backing, q) -> Optional[QueryResult]:
    """Non-mergeable MVs serve structurally identical queries only."""
    if repr(q) != mv.query_repr:
        return None
    data = backing.read(list(mv.backing_schema))
    cols = []
    for nm, t in mv.columns:
        a = data[nm]
        if isinstance(a, np.ma.MaskedArray):
            vals, valid = np.asarray(a.filled(
                "" if a.dtype == object else 0)), ~np.ma.getmaskarray(a)
        else:
            vals, valid = np.asarray(a), np.ones(len(a), dtype=bool)
        cols.append((nm, t, vals, valid))
    n = len(cols[0][2]) if cols else 0
    rows = [tuple(_py(vals[i]) if bool(valid[i]) else None
                  for _nm, _t, vals, valid in cols) for i in range(n)]
    return QueryResult([(nm, t) for nm, t, _v, _m in cols], rows)


def _route_direct(session, mv: MvDefinition, q, spec) \
        -> Optional[QueryResult]:
    """SELECT ... FROM <mv>: read the stored finals as a table."""
    catalog = session.catalog
    backing = catalog.tables.get(mv.backing)
    if backing is None:
        return None
    if spec.distinct or spec.having is not None or spec.grouping_sets \
            or spec.group_by:
        return None
    arrays = backing.read(list(mv.backing_schema))
    arrays = {c: np.asarray(a) if not isinstance(a, np.ma.MaskedArray)
              else a for c, a in arrays.items()}
    n = len(next(iter(arrays.values()))) if arrays else 0
    finals: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    if mv.mergeable:
        key_idx = {out: j for j, (out, _c) in enumerate(mv.keys)}
        for nm, _t in mv.columns:
            if nm in key_idx:
                valid = ~np.asarray(arrays[mv.knull_col(key_idx[nm])],
                                    dtype=bool)
                finals[nm] = (np.asarray(arrays[nm]), valid)
            else:
                a = next(x for x in mv.aggs if x.out == nm)
                finals[nm] = (np.asarray(arrays[nm]),
                              _final_validity(mv, arrays, a, n))
    else:
        for nm, _t in mv.columns:
            a = arrays[nm]
            if isinstance(a, np.ma.MaskedArray):
                finals[nm] = (np.asarray(a.filled(
                    "" if a.dtype == object else 0)),
                    ~np.ma.getmaskarray(a))
            else:
                finals[nm] = (np.asarray(a), np.ones(n, dtype=bool))
    conjs = simple_conjuncts(spec.where)
    if conjs is None:
        return None
    mask = np.ones(n, dtype=bool)
    for c in conjs:
        if c[1] not in finals:
            return None
        vals, valid = finals[c[1]]
        mask &= _eval_conjunct(c, vals, valid)
    typemap = dict(mv.columns)
    out_cols = []
    for item in spec.select:
        e = item.expr
        if isinstance(e, ast.Star):
            if item.alias:
                return None
            for nm, t in mv.columns:
                vals, valid = finals[nm]
                out_cols.append((nm, t, vals[mask], valid[mask]))
            continue
        if not isinstance(e, ast.Identifier) or e.name not in finals:
            return None
        vals, valid = finals[e.name]
        out_cols.append(((item.alias or e.name), typemap[e.name],
                         vals[mask], valid[mask]))
    if not out_cols:
        return None
    return _to_result(out_cols, q.order_by, q.limit)


def _route_rollup(session, mv: MvDefinition, backing, q, spec) \
        -> Optional[QueryResult]:
    """The containment matcher proper: query groups ⊆ MV keys, query
    WHERE ⊇ MV WHERE with extras on key columns, aggregates covered by
    stored finals/states.  Equal group sets serve stored finals
    directly; strict subsets fold the rollup states."""
    if spec.distinct or spec.having is not None or spec.grouping_sets:
        return None
    conjs = simple_conjuncts(spec.where)
    if conjs is None:
        return None
    mv_set = {c for c in (mv.conjuncts or [])}
    q_set = set(conjs)
    if not mv_set <= q_set:
        return None
    src_to_out = {c: out for out, c in mv.keys}
    extra = [c for c in conjs if c not in mv_set]
    if any(c[1] not in src_to_out for c in extra):
        return None

    group_srcs = []
    for g in spec.group_by:
        if not isinstance(g, ast.Identifier) \
                or g.name.lower() not in src_to_out:
            return None
        group_srcs.append(g.name.lower())

    # select coverage: group identifiers + matched aggregates
    items = []      # ("key", out_name) | ("agg", AggSpec, p_override)
    names_types = []
    for item in spec.select:
        e = item.expr
        if isinstance(e, ast.Identifier):
            col = e.name.lower()
            if col not in group_srcs:
                return None
            out = src_to_out[col]
            items.append(("key", out))
            names_types.append((item.alias or e.name,
                                mv.key_types[out]))
            continue
        if not isinstance(e, ast.FunctionCall):
            return None
        m = _match_agg(session, mv, e)
        if m is None:
            return None
        a, p_override = m
        items.append(("agg", a, p_override))
        names_types.append((item.alias or e.name.lower(), a.out_type))

    arrays = _read_backing(mv, backing)
    if not arrays:
        arrays = {c: (np.zeros((0, int(t.params[0])), t.numpy_dtype())
                      if t.name in ("HLL_STATE", "KLL_STATE")
                      else np.empty(0, t.numpy_dtype()
                                    if not t.is_string else object))
                  for c, t in mv.backing_schema.items()}
    n = len(next(iter(arrays.values()))) if arrays else 0

    # extra key predicates: constant within a group, so filtering stored
    # rows before any fold filters exactly the covered source rows
    mask = np.ones(n, dtype=bool)
    key_idx = {out: j for j, (out, _c) in enumerate(mv.keys)}
    for c in extra:
        out = src_to_out[c[1]]
        valid = ~np.asarray(arrays[mv.knull_col(key_idx[out])],
                            dtype=bool)
        mask &= _eval_conjunct(c, np.asarray(arrays[out]), valid)
    if not mask.all():
        arrays = {c: np.asarray(a)[mask] for c, a in arrays.items()}
        n = int(mask.sum())

    group_outs = [src_to_out[c] for c in group_srcs]
    if set(group_srcs) == {c for _o, c in mv.keys}:
        # fast path: stored grain == query grain; finals serve as-is
        folded = arrays
        knull = {out: np.asarray(arrays[mv.knull_col(key_idx[out])],
                                 dtype=bool) for out in group_outs}
    else:
        overrides = {it[1].out: it[2] for it in items
                     if it[0] == "agg" and it[2] is not None}
        folded = fold_groups(mv, arrays, group_outs,
                             percentiles=overrides)
        n = len(next(iter(folded.values()))) if folded else 0
        knull = {out: np.asarray(folded[f"__fold_knull{j}"], dtype=bool)
                 for j, out in enumerate(group_outs)}

    out_cols = []
    for (nm, t), it in zip(names_types, items):
        if it[0] == "key":
            out = it[1]
            out_cols.append((nm, t, np.asarray(folded[out]),
                             ~knull[out]))
            continue
        a, p_override = it[1], it[2]
        vals = np.asarray(folded[a.out])
        if a.fn == "approx_percentile" and p_override is not None \
                and folded is arrays:
            # fast path with a different percentile: read the stored
            # KLL states back out at the query's p
            st = np.asarray(arrays[a.kll_col], dtype=np.float64)
            raw, _ne = _kll_readout(st, a.kk, p_override)
            vals = _cast_final(raw, a.out_type)
        out_cols.append((nm, t, vals,
                         _final_validity(mv, folded, a, n)))
    if not out_cols:
        return None
    return _to_result(out_cols, q.order_by, q.limit)
