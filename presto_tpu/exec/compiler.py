"""Expression evaluation: typed IR -> ColVal over a Batch.

Reference parity: sql/gen/ExpressionCompiler + PageFunctionCompiler — the
reference generates JVM bytecode per expression; here evaluation IS tracing,
so "compilation" is just recursive emission of jnp ops (XLA fuses the
result).  Dictionary-typed intermediates trigger host-side per-entry
compute (see exec/colval.py)."""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from presto_tpu.batch import Batch
from presto_tpu.exec.colval import ColVal, LambdaVal
from presto_tpu.functions import scalar as scalar_fns
from presto_tpu.plan import ir


class EvalContext:
    """Carries scalar-subquery results (python scalars) into evaluation,
    plus (in compiled mode) the executor's runtime-guard list so
    expression-level overflow checks can abort the compiled program to
    the dynamic path, which raises properly."""

    def __init__(self, scalar_results: Dict[int, tuple] | None = None,
                 guards: list | None = None):
        self.scalar_results = scalar_results or {}  # plan_id -> (value, valid)
        self.guards = guards  # Executor.guards in static mode, else None
        # prepared-statement parameters (server/serving.py): position ->
        # (value, valid) — host scalars in dynamic mode, traced 0-d
        # device scalars in compiled mode (the ScalarSub channel)
        self.params = None


def eval_expr(expr: ir.RowExpr, batch: Batch, ctx: EvalContext) -> ColVal:
    from presto_tpu import session_ctx

    # per-row volatile emitters (random()) need a row count that the
    # argument ColVals cannot provide
    session_ctx.set_batch_capacity(batch.capacity)
    if isinstance(expr, ir.Ref):
        c = batch.columns[expr.name]
        return ColVal(c.data, c.valid, c.type, c.dictionary)
    if isinstance(expr, ir.Lit):
        if expr.value is None:
            return ColVal(False, False, expr.type)
        return ColVal(expr.value, None, expr.type)
    if isinstance(expr, ir.Param):
        if ctx.params is None or expr.position >= len(ctx.params):
            raise TypeError(
                f"parameter ${expr.position} is not bound "
                "(EXECUTE ... USING)")
        v, valid = ctx.params[expr.position]
        return ColVal(v, valid, expr.type)
    if isinstance(expr, ir.ScalarSub):
        v, valid = ctx.scalar_results[expr.plan_id]
        if isinstance(valid, (bool, type(None))):  # host-evaluated subplan
            if expr.type.is_decimal and valid \
                    and not hasattr(v, "shape"):
                # _single_value decodes decimals to SCALED host values
                # (Decimal for long, float for short); decimal ColVals
                # carry UNSCALED integers
                import decimal as _d

                s = expr.type.decimal_scale
                with _d.localcontext() as ctx2:
                    ctx2.prec = 80
                    v = int(_d.Decimal(str(v)).scaleb(s).quantize(
                        _d.Decimal(1), rounding=_d.ROUND_HALF_EVEN))
            return ColVal(v, None if valid else False, expr.type)
        return ColVal(v, valid, expr.type)  # traced 0-d value (distributed)
    if isinstance(expr, ir.CastExpr):
        return scalar_fns.emit_cast(eval_expr(expr.arg, batch, ctx), expr.type, expr.safe,
                                    guards=ctx.guards)
    if isinstance(expr, ir.Call):
        args = [LambdaVal(a.params, a.param_types, a.body, ctx, a.type)
                if isinstance(a, ir.LambdaExpr)
                else eval_expr(a, batch, ctx) for a in expr.args]
        return scalar_fns.lookup(expr.fn).emit(args)
    raise TypeError(f"cannot evaluate {type(expr).__name__}")


def eval_predicate(expr: ir.RowExpr, batch: Batch, ctx: EvalContext) -> jnp.ndarray:
    """Boolean expression -> row mask (SQL: NULL predicate == not selected)."""
    v = eval_expr(expr, batch, ctx)
    data = v.data
    if not hasattr(data, "shape") or getattr(data, "ndim", 0) == 0:
        data = jnp.full((batch.capacity,), bool(data) if not hasattr(data, "shape") else data)
    mask = data
    if v.valid is not None:
        valid = _expand_valid(v.valid, batch.capacity)
        mask = mask & valid
    return mask


def to_column(v: ColVal, capacity: int):
    """Materialize a ColVal as a full-capacity Column."""
    from presto_tpu.batch import Column

    data = v.data
    if not hasattr(data, "shape") or getattr(data, "ndim", 0) == 0:
        if v.type.is_decimal and v.type.is_long_decimal:
            from presto_tpu.exec import dec128 as D128

            limbs = jnp.asarray(D128.from_host_int(int(data)))
            data = jnp.broadcast_to(limbs, (capacity, 2))
            return Column(data, _expand_valid(v.valid, capacity), v.type)
        if isinstance(data, (str, bytes)):
            # string/varbinary literal column: single-entry dictionary
            import numpy as np

            from presto_tpu.batch import Dictionary

            vals = np.empty(1, dtype=object)
            vals[0] = data
            d = Dictionary(vals)
            data = jnp.zeros((capacity,), dtype=jnp.int32)
            valid = _expand_valid(v.valid, capacity)
            return Column(data, valid, v.type, d)
        data = jnp.full((capacity,), data, dtype=v.type.numpy_dtype())
    valid = _expand_valid(v.valid, capacity)
    return Column(data, valid, v.type, v.dictionary)


def _expand_valid(valid, capacity):
    if valid is None:
        return None
    if not hasattr(valid, "shape") or getattr(valid, "ndim", 0) == 0:
        if hasattr(valid, "dtype"):  # 0-d traced value
            return jnp.broadcast_to(valid, (capacity,))
        return jnp.full((capacity,), bool(valid))
    return valid
