"""Column values flowing through compiled expressions.

A ColVal is (data, valid, dictionary, type):
- data: jnp array (codes for strings), or a python scalar for literals
  not yet broadcast (kept scalar so XLA folds constants).
- valid: None (all valid) or bool jnp array / python bool.
- dictionary: host-side Dictionary for string-typed values (sorted+unique
  invariant — see batch.py).

This is the value-plane analog of the reference's Block +
DictionaryAwarePageProjection (operator/project/DictionaryAwarePageProjection.java):
string compute happens once per dictionary entry on host, then flows to
the device as gathers through int32 codes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Dictionary
from presto_tpu.types import Type


@dataclasses.dataclass
class ColVal:
    data: object  # jnp array | python scalar
    valid: object  # None | jnp bool array | python bool
    type: Type
    dictionary: Optional[Dictionary] = None

    @property
    def is_scalar(self) -> bool:
        return not hasattr(self.data, "shape") or getattr(self.data, "ndim", 0) == 0


@dataclasses.dataclass
class LambdaVal:
    """An unevaluated lambda argument to a higher-order function.

    `apply` evaluates the body over a synthetic batch whose columns are the
    parameter bindings — i.e. the lambda is vectorized over array *elements*
    with the same tracing machinery used for rows (the reference compiles
    LambdaDefinitionExpression to a JVM method; here it traces to XLA)."""

    params: tuple
    param_types: tuple
    body: object  # ir.RowExpr
    ctx: object  # EvalContext
    type: Type  # FUNCTION(ret)

    @property
    def ret_type(self) -> Type:
        return self.type.params[0]

    def free_refs(self) -> set:
        return self.body.refs() - set(self.params)

    def apply(self, cols: dict) -> "ColVal":
        from presto_tpu.batch import Batch
        from presto_tpu.exec import compiler

        n = 0
        for v in cols.values():
            if hasattr(v.data, "shape") and getattr(v.data, "ndim", 0) > 0:
                n = max(n, int(v.data.shape[0]))
        n = max(n, 1)
        batch = Batch({s: compiler.to_column(v, n) for s, v in cols.items()},
                      jnp.ones((n,), dtype=bool))
        return compiler.eval_expr(self.body, batch, self.ctx)


def and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def all_valid(*vals):
    v = None
    for x in vals:
        v = and_valid(v, x.valid if isinstance(x, ColVal) else x)
    return v


def valid_array(v: ColVal, n: int):
    if v.valid is None:
        return jnp.ones((n,), dtype=bool)
    if not hasattr(v.valid, "shape"):
        return jnp.full((n,), bool(v.valid))
    return v.valid


def decode_strings(v: ColVal) -> np.ndarray:
    """Host-side decode (only outside jit)."""
    codes = np.asarray(v.data)
    return v.dictionary.values[np.clip(codes, 0, len(v.dictionary) - 1)]


def normalize_dictionary(values: np.ndarray, codes: ColVal) -> ColVal:
    """Restore the sorted+unique dictionary invariant after a host
    transform of dictionary values: unique the transformed values and remap
    codes through a device-side LUT gather."""
    uniq, inverse = np.unique(values.astype(str), return_inverse=True)
    lut = jnp.asarray(inverse.astype(np.int32))
    new_codes = lut[jnp.clip(codes.data, 0, len(inverse) - 1)]
    return ColVal(new_codes, codes.valid, codes.type, Dictionary(uniq))


def translate_codes(frm: Dictionary, to: Dictionary):
    """Host LUT mapping codes in `frm` to codes in `to` (-1 = not present).
    Used to compare/join string columns with different dictionaries."""
    idx = np.searchsorted(to.values, frm.values)
    idx = np.clip(idx, 0, max(len(to) - 1, 0))
    ok = (len(to) > 0) & (to.values[idx] == frm.values)
    return np.where(ok, idx, -1).astype(np.int32)
