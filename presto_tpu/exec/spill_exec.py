"""Spill-tiered execution: memory-pressure-driven graceful degradation
for hash joins and grouped aggregation.

The blueprint is *Design Trade-offs for a Robust Dynamic Hybrid Hash
Join* (PAPERS.md): an operator whose state outgrows HBM must degrade in
steps, never fall off a cliff.  The tier model (docs/SPILL.md):

  tier 0 — resident: the whole working set fits; the normal
           build_probe / sort-grouping path runs, nothing here engages.
  tier 1 — partial spill (hybrid): both inputs are partitioned by the
           splitmix64 mixing family (kernels.spill_partition_ids — the
           same family as the rf_* runtime filters and write buckets).
           Partitions whose combined working set fits the budget stay
           ON-CHIP and run through the normal join/aggregation path in
           ONE pass; cold partitions spill to disk as checksummed PTPG
           frames (memory/spill.FileSpiller) and stream back one at a
           time.
  tier 2 — recursive partitioning: a spilled partition that STILL does
           not fit re-partitions with a level-salted remix (the unsalted
           hash could never split rows sharing a level-N residue) and
           recurses, to a bounded depth.  Past the bound the query fails
           LOUDLY (SpillRecursionError) — a hot-key partition that
           cannot split must never silently blow the budget.

Join correctness: partitioning is on the equi-join key hash, so every
match pair lands in one partition and unmatched (LEFT/FULL) rows
surface exactly once, in their own partition.  Aggregation correctness:
groups never span partitions, so per-partition re-aggregation on
unspill is mergeable by construction — the concat IS the merge.

Ordering with dynamic filtering (the interaction the paper highlights):
`Executor._exec_join` runs the PR-5 build-side filter BEFORE calling
into this module, and `plan_degradation` re-probes the LIVE row estimate
when the capacity estimate trips — a probe the filter shrank enough is
compacted and kept fully resident instead of spilled
(recovery counter `spill_df_resident`).

Memory handshake (memory/context.py): the operator first declares its
estimated state as a REVOCABLE reservation.  If the pool refuses it, or
the query limit could not absorb its conversion, the reservation is
revoked — that revocation IS the degradation trigger.  Otherwise it
converts to a regular reservation and the operator stays resident with
its state accounted.

Everything here is deterministic and chaos-testable: the
`PRESTO_TPU_FORCE_SPILL` env / `force_spill` session property forces
each tier regardless of memory, `spill_threshold_bytes` forces it by
size, and the spill-I/O fault kinds in parallel/faults.py (truncate /
corrupt / enospc) must surface as typed failures or transparent
re-spills — never wrong results.

No file I/O lives here: the spiller (memory/spill.py) owns every byte
that touches disk (tests/test_lint.py enforces).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch
from presto_tpu.exec import kernels as K
from presto_tpu.memory.context import ExceededMemoryLimitError, batch_bytes
from presto_tpu.memory.spill import SpillError, SpillSpaceExhausted

TIER_RESIDENT = 0
TIER_PARTIAL = 1
TIER_RECURSIVE = 2

#: worst-case working-set multiplier over the input bytes (sort
#: scratch + packed keys + gathered output), shared with the trigger
#: estimates in Executor._exec_join/_exec_aggregate
WORKING_SET_FACTOR = 2

_FORCE_ENV = "PRESTO_TPU_FORCE_SPILL"
_MAX_PARTS = 64  # fan-out ceiling per partitioning pass


class SpillRecursionError(SpillError):
    """A partition still exceeded its budget at the recursion bound —
    typically a single hot key that no re-partitioning can split.  The
    loud alternative to silently blowing HBM."""


@dataclasses.dataclass
class Degradation:
    """One operator's degradation decision (plan_degradation)."""

    degrade: bool
    budget: int = 0        # resident working-set byte budget (0 = spill all)
    nparts: int = 0
    max_depth: int = 3     # bounded recursion (re-partition levels)
    forced: str = ""       # "" | "partial" | "recursive"
    mem_key: int = 0       # converted revocable reservation to release


def force_mode(session) -> str:
    """The deterministic tier-forcing knob: env PRESTO_TPU_FORCE_SPILL
    outranks the `force_spill` session property; values `partial` /
    `recursive` force that tier, anything else means memory-driven."""
    mode = os.environ.get(_FORCE_ENV, "") \
        or str(session.properties.get("force_spill", "") or "")
    return mode.strip().lower()


def routing_enabled(session) -> bool:
    """True when spill degradation can engage WITHOUT a memory context —
    the deterministic knobs.  The chunked runner uses this to route
    run-once join/aggregate fragments through the dynamic (spillable)
    executor instead of the static trace."""
    if not session.properties.get("spill_enabled", True):
        return False
    return force_mode(session) in ("partial", "recursive") \
        or int(session.properties.get("spill_threshold_bytes", 0)) > 0 \
        or int(session.properties.get("spill_trigger_rows", 0)) > 0


def plan_degradation(ex, node, est_bytes: int, capacity: int,
                     live_est_fn=None) -> Degradation:
    """Decide the operator's tier BEFORE it builds state.

    Order of authority: static mode / kill switch -> forced tier ->
    size threshold -> row trigger -> the revocable-memory handshake.
    `live_est_fn` (optional, host-syncing) re-estimates from LIVE rows
    when the capacity estimate trips — the dynamic-filter interaction:
    a filter-shrunken probe whose live bytes fit stays resident."""
    session = ex.session
    if ex.static or not session.properties.get("spill_enabled", True):
        return Degradation(False)
    # adaptive partial aggregation (plan/agg_strategy.py): a bypassed
    # partial emits pass-through rows and never builds grouped state —
    # consult the flip decision BEFORE reserving revocable memory.
    # (The executor already serves the bypass before planning spill;
    # this guard keeps the invariant even for callers that plan
    # degradation directly.)
    if getattr(node, "step", "SINGLE") == "PARTIAL":
        from presto_tpu.plan import agg_strategy as AS

        if AS.enabled(session):
            st = AS.flip_state(session, node)
            if st is not None and st.bypassed:
                return Degradation(False)
    nparts = int(session.properties.get("spill_partition_count", 8))
    max_depth = int(session.properties.get("spill_max_recursion_depth", 3))

    mode = force_mode(session)
    if mode in ("partial", "recursive"):
        budget = est_bytes // 2 if mode == "partial" else 0
        return Degradation(True, budget, nparts, max_depth, forced=mode)

    threshold = int(session.properties.get("spill_threshold_bytes", 0))
    trigger = int(session.properties.get("spill_trigger_rows", 0))
    mem = ex.mem

    degrade = False
    budget = 0
    mem_key = 0
    if threshold and est_bytes > threshold:
        degrade, budget = True, threshold
    elif trigger and capacity >= trigger:
        degrade, budget = True, 0  # classic Grace: every partition spills
    elif mem is not None:
        key = -id(node)  # operator-STATE ledger; the output ledger
        # (set_bytes in _exec_node_inner) keys on +id(node)
        pressure = not mem.set_revocable(key, est_bytes) \
            or mem.would_exceed(est_bytes)
        if not pressure:
            try:
                mem.convert_revocable(key)
                return Degradation(False, mem_key=key)
            except ExceededMemoryLimitError:
                pressure = True
        if pressure:
            mem.revoke(key)
            if live_est_fn is not None:
                # dynamic-filter interaction: the capacity estimate counts
                # filter-pruned rows; if the LIVE working set fits, the
                # caller compacts and stays resident (tier 0)
                live_est = int(live_est_fn())
                if live_est < est_bytes and not mem.would_exceed(live_est) \
                        and mem.set_revocable(key, live_est):
                    try:
                        mem.convert_revocable(key)
                        _count(ex, "spill_df_resident")
                        return Degradation(False, mem_key=key,
                                           budget=-1)  # -1: compact inputs
                    except ExceededMemoryLimitError:
                        mem.revoke(key)
            degrade, budget = True, mem.headroom()
    if not degrade:
        return Degradation(False)
    # planner-stats gating of the fan-out: size nparts so ONE
    # partitioning pass normally suffices (est/nparts fits the budget)
    # instead of discovering the recursion tier the hard way
    if budget > 0:
        while nparts < _MAX_PARTS and est_bytes / nparts > budget:
            nparts *= 2
    return Degradation(True, budget, nparts, max_depth, mem_key=mem_key)


# ---------------------------------------------------------------------------
# counters: routed through the executor's sort_stats dict (the same
# funnel the sort/df economics use), merged into QueryStats by
# executor._merge_sort_stats — which works for the chunked runner's
# fragment executors too, where no QueryMonitor is in scope
# ---------------------------------------------------------------------------


def _count(ex, key: str, n: int = 1) -> None:
    ex.sort_stats[key] = ex.sort_stats.get(key, 0) + n


def _note_tier(ex, tier: int) -> None:
    ex.sort_stats["degradation_tier"] = max(
        ex.sort_stats.get("degradation_tier", 0), tier)


# ---------------------------------------------------------------------------
# partition planning
# ---------------------------------------------------------------------------


def _partition_bytes(b: Batch, part: np.ndarray, nparts: int) -> np.ndarray:
    """Estimated LIVE bytes per partition: live row count per partition
    (host bincount over the already-host partition ids) times the
    batch's bytes-per-row."""
    sel = np.asarray(b.sel)
    live = np.bincount(part[sel], minlength=nparts).astype(np.float64)
    bpr = batch_bytes(b) / max(b.capacity, 1)
    return live * bpr


def _choose_resident(combined: np.ndarray, dec: Degradation) -> set:
    """Pick the resident partition set: smallest-first while the
    cumulative working set fits the budget (the hybrid in hybrid hash
    join).  Forced modes are deterministic regardless of memory:
    `partial` keeps the smaller half resident, `recursive` spills all."""
    nparts = len(combined)
    if dec.forced == "partial":
        order = np.argsort(combined, kind="stable")
        return set(int(p) for p in order[:max(nparts // 2, 1)])
    if dec.forced == "recursive" or dec.budget <= 0:
        return set()
    resident: set = set()
    cum = 0.0
    for p in np.argsort(combined, kind="stable"):
        if WORKING_SET_FACTOR * (cum + combined[p]) > dec.budget:
            break
        resident.add(int(p))
        cum += combined[p]
    return resident


def _needs_recurse(dec: Degradation, level: int, est: int) -> bool:
    if dec.forced == "recursive":
        return level == 1  # exactly one deterministic re-partition round
    if dec.forced == "partial" or dec.budget <= 0:
        return False
    return est > dec.budget


def _check_depth(dec: Degradation, level: int) -> None:
    if level > dec.max_depth:
        raise SpillRecursionError(
            f"spill partition still exceeds the {dec.budget / 1e6:.1f}MB "
            f"budget after {dec.max_depth} recursive re-partitions "
            "(hot key that cannot split?); raise "
            "spill_max_recursion_depth or query_max_memory_bytes")


def _mask_part(b: Batch, part: np.ndarray, keep) -> Batch:
    return b.with_sel(b.sel & jnp.asarray(np.isin(part, keep)))


def _spill_parts(ex, spiller, b: Batch, part: np.ndarray,
                 cold: List[int]) -> Dict[int, str]:
    handles = {}
    for p in cold:
        handles[p] = spiller.spill(_mask_part(b, part, [p]))
    _count(ex, "spill_partitions", len(cold))
    return handles


def _restore(ex, spiller, handle: str) -> Batch:
    _count(ex, "spill_restores")
    return spiller.unspill(handle)


def _fold_spiller(ex, spiller) -> None:
    """Fold one spiller's written bytes + transparent rewrites into the
    counters once its files are accounted."""
    _count(ex, "spill_bytes", sum(s for _, s in spiller.files))
    if spiller.rewrites:
        _count(ex, "spill_rewrites", spiller.rewrites)


def _count_enospc(ex) -> None:
    _count(ex, "spill_enospc")


# ---------------------------------------------------------------------------
# hybrid hash join
# ---------------------------------------------------------------------------


def hybrid_join(ex, holder: list, node, dec: Degradation) -> Batch:
    """Partition-wise hybrid hash join (tiers 1-2).  `holder` carries
    the sole references to both inputs so their device arrays free as
    soon as the cold partitions are spilled and the resident slice is
    compacted."""
    from presto_tpu.exec.executor import _unify_key_dictionaries

    left, right = holder
    holder.clear()
    lkeys = [left.columns[lk] for lk, _ in node.criteria]
    rkeys = [right.columns[rk] for _, rk in node.criteria]
    lkeys, rkeys = _unify_key_dictionaries(lkeys, rkeys)
    lpart = K.spill_partition_ids(lkeys, left.sel, dec.nparts)
    rpart = K.spill_partition_ids(rkeys, right.sel, dec.nparts)
    combined = _partition_bytes(left, lpart, dec.nparts) \
        + _partition_bytes(right, rpart, dec.nparts)
    resident = _choose_resident(combined, dec)
    cold = [p for p in range(dec.nparts) if p not in resident]
    if cold:
        _note_tier(ex, TIER_PARTIAL)
    # else: the per-partition replan found everything fits (the capacity
    # estimate was pessimistic) — effectively tier 0, nothing spills
    spiller = ex._make_spiller()
    try:
        try:
            lh = _spill_parts(ex, spiller, left, lpart, cold)
            rh = _spill_parts(ex, spiller, right, rpart, cold)
        except SpillSpaceExhausted:
            _count_enospc(ex)
            raise
        _fold_spiller(ex, spiller)
        outs = []
        if resident:
            keep = sorted(resident)
            lres = K.compact(_mask_part(left, lpart, keep))
            rres = K.compact(_mask_part(right, rpart, keep))
            del left, right, lkeys, rkeys  # cold copies live on disk now
            # the whole resident set joins in ONE normal build_probe
            # pass: partitions are key-disjoint, so the union of
            # per-partition joins IS the join of the union
            outs.append(K.compact(ex._join_batches(lres, rres, node)))
            del lres, rres
        else:
            del left, right, lkeys, rkeys
        load, store, bucket_done, finish = ex._grouped_recovery(dec.nparts)
        for p in cold:
            cached = load(p)
            if cached is None:
                lb = _restore(ex, spiller, lh[p])
                rb = _restore(ex, spiller, rh[p])
                cached = _join_partition(ex, node, lb, rb, dec, level=1)
                store(p, cached)
            outs.append(cached)
            bucket_done()
        finish()
        return K.concat_batches(outs)
    finally:
        spiller.close()


def _join_partition(ex, node, lb: Batch, rb: Batch, dec: Degradation,
                    level: int) -> Batch:
    """Process one unspilled partition pair: join it if it fits,
    recursively re-partition (level-salted) if it does not."""
    from presto_tpu.exec.executor import _unify_key_dictionaries

    est = WORKING_SET_FACTOR * (batch_bytes(lb) + batch_bytes(rb))
    if not _needs_recurse(dec, level, est):
        return K.compact(ex._join_batches(lb, rb, node))
    _check_depth(dec, level)
    _note_tier(ex, TIER_RECURSIVE)
    _count(ex, "spill_recursions")
    lkeys = [lb.columns[lk] for lk, _ in node.criteria]
    rkeys = [rb.columns[rk] for _, rk in node.criteria]
    lkeys, rkeys = _unify_key_dictionaries(lkeys, rkeys)
    lpart = K.spill_partition_ids(lkeys, lb.sel, dec.nparts, level=level)
    rpart = K.spill_partition_ids(rkeys, rb.sel, dec.nparts, level=level)
    spiller = ex._make_spiller()
    try:
        try:
            lh = _spill_parts(ex, spiller, lb, lpart,
                              list(range(dec.nparts)))
            rh = _spill_parts(ex, spiller, rb, rpart,
                              list(range(dec.nparts)))
        except SpillSpaceExhausted:
            _count_enospc(ex)
            raise
        _fold_spiller(ex, spiller)
        del lb, rb, lkeys, rkeys, lpart, rpart
        outs = []
        for p in range(dec.nparts):
            slb = _restore(ex, spiller, lh[p])
            srb = _restore(ex, spiller, rh[p])
            outs.append(_join_partition(ex, node, slb, srb, dec, level + 1))
        return K.concat_batches(outs)
    finally:
        spiller.close()


# ---------------------------------------------------------------------------
# spill-tiered grouped aggregation
# ---------------------------------------------------------------------------


def hybrid_aggregate(ex, node, holder: list, dec: Degradation) -> Batch:
    """Partition-wise tiered aggregation: partition by group-key hash,
    aggregate the resident union in one pass, spill cold
    group-partitions and re-aggregate each on unspill.  Groups are
    partition-disjoint, so the concat IS the merge (*Partial Partial
    Aggregates*' mergeable-by-construction property)."""
    b = holder.pop()
    part = K.spill_partition_ids([b.columns[k] for k in node.group_keys],
                                 b.sel, dec.nparts)
    pbytes = _partition_bytes(b, part, dec.nparts)
    resident = _choose_resident(pbytes, dec)
    cold = [p for p in range(dec.nparts) if p not in resident]
    if cold:
        _note_tier(ex, TIER_PARTIAL)
    spiller = ex._make_spiller()
    try:
        try:
            handles = _spill_parts(ex, spiller, b, part, cold)
        except SpillSpaceExhausted:
            _count_enospc(ex)
            raise
        _fold_spiller(ex, spiller)
        outs = []
        if resident:
            bres = K.compact(_mask_part(b, part, sorted(resident)))
            del b  # cold copies live on disk; resident slice compacted
            outs.append(K.compact(
                ex._aggregate(bres, node.group_keys, node.aggs, node)))
            del bres
        else:
            del b
        load, store, bucket_done, finish = ex._grouped_recovery(dec.nparts)
        for p in cold:
            cached = load(p)
            if cached is None:
                pb = _restore(ex, spiller, handles[p])
                cached = _agg_partition(ex, node, pb, dec, level=1)
                store(p, cached)
            outs.append(cached)
            bucket_done()
        finish()
        return K.concat_batches(outs)
    finally:
        spiller.close()


def _agg_partition(ex, node, pb: Batch, dec: Degradation,
                   level: int) -> Batch:
    est = WORKING_SET_FACTOR * batch_bytes(pb)
    if not _needs_recurse(dec, level, est):
        return K.compact(
            ex._aggregate(pb, node.group_keys, node.aggs, node))
    _check_depth(dec, level)
    _note_tier(ex, TIER_RECURSIVE)
    _count(ex, "spill_recursions")
    part = K.spill_partition_ids(
        [pb.columns[k] for k in node.group_keys], pb.sel, dec.nparts,
        level=level)
    spiller = ex._make_spiller()
    try:
        try:
            handles = _spill_parts(ex, spiller, pb, part,
                                   list(range(dec.nparts)))
        except SpillSpaceExhausted:
            _count_enospc(ex)
            raise
        _fold_spiller(ex, spiller)
        del pb, part
        outs = []
        for p in range(dec.nparts):
            spb = _restore(ex, spiller, handles[p])
            outs.append(_agg_partition(ex, node, spb, dec, level + 1))
        return K.concat_batches(outs)
    finally:
        spiller.close()
