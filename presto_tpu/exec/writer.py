"""TableWriter subsystem: CTAS / INSERT streamed through connector
PageSinks (reference: TableWriterOperator + TableFinishOperator over
ConnectorPageSink, PAPER.md §L4).

What used to be `executor._insert_into` — materialize the WHOLE query
to host numpy, then one bulk `table.append` — becomes a write pipeline:

    begin_write -> append_page(s) -> finish

- the plan grows TableWriter / TableFinish nodes (plan/nodes.py), so
  EXPLAIN shows the write and the dynamic executor runs it like any
  other operator;
- chunked mode streams an over-threshold scan split-by-split, appending
  each chunk to the sink (bounded host memory, no whole-result
  materialization);
- distributed mode fans splits over writer workers, each appending its
  OWN pages (files), with the coordinator running the single
  finish/commit step (the DrJAX sharded-materialization shape: no host
  gather between produce and persist);
- compiled mode executes the source query as one compiled program and
  feeds its fetched columns to the sink.

Write layout properties (`WITH (bucketed_by=..., bucket_count=...,
sorted_by=..., partitioned_by=...)`) are applied here — bucket
assignment through the splitmix mixing in exec/kernels.py
(kernels.write_bucket_ids), within-bucket sorts through the routed sort
entry points (kernels.write_sort_perm) — and then RECORDED into the
catalog entry (ConnectorTable.ordering()/write_properties()), so
ordering-aware execution, zone-map stripe pruning, and bucket-aligned
dynamic filters fire on engine-written tables exactly as on
generator-declared ones.  An ordering claim is only recorded when the
written file sequence VERIFIES as globally nondecreasing on the sort
keys (per-page sort + monotone page boundaries); hash-bucketed layouts
keep their per-file sort (zone maps) without the table-level claim.

Commit is transactional: file sinks stage invisible files and publish
atomically (manifest rewrite); transaction.py snapshots the manifest
(record_table_write / record_presnapshot) so ROLLBACK restores the
pre-write snapshot, and a CREATE OR REPLACE cut-over leaves concurrent
readers on the previous snapshot's files (docs/WRITES.md, the
refresh-and-serve recipe).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import batch_from_numpy
from presto_tpu.connectors import AppendPageSink, open_sink
from presto_tpu.exec import kernels as K
from presto_tpu.observe import trace as TR
from presto_tpu.plan import nodes as P
from presto_tpu.session import QueryResult
from presto_tpu.sql import ast


class WriteError(Exception):
    pass


#: default rows per streamed write chunk (session: write_page_rows)
DEFAULT_WRITE_PAGE_ROWS = 1 << 20
#: cap on auto-sized distributed writer workers (session:
#: write_parallelism; 0 = auto: one thread per core up to this cap)
MAX_WRITE_WORKERS = 8


# ---------------------------------------------------------------------------
# write properties
# ---------------------------------------------------------------------------


def _namelist(v) -> List[str]:
    """Property value -> column name list: ARRAY['a','b'] parses to a
    python list; 'a,b' (the hive partitioned_by convention already used
    by connectors/hive.py) splits on commas."""
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [str(x).strip() for x in v if str(x).strip()]
    return [s.strip() for s in str(v).split(",") if s.strip()]


@dataclass
class WriteProperties:
    """Parsed physical-layout write properties (reference: the hive
    connector's bucketed_by/bucket_count/sorted_by table properties,
    HiveTableProperties.java)."""

    bucketed_by: List[str] = field(default_factory=list)
    bucket_count: int = 0
    sorted_by: List[Tuple[str, bool]] = field(default_factory=list)
    partitioned_by: List[str] = field(default_factory=list)
    # range: buckets are contiguous slices of the globally sorted rows
    #   (sorted_by leads with the bucket columns) — the layout that makes
    #   the whole-table scan order a verifiable ordering claim, same
    #   trick as the TPC chunk grids ("range-bucketing colocates
    #   equi-joins exactly like hash-bucketing", exec/chunked.py);
    # hash: splitmix64 bucket assignment (kernels.write_bucket_ids) —
    #   the only kind streamed (multi-page) writes can keep consistent.
    bucketing: str = "hash"

    def empty(self) -> bool:
        return not (self.bucketed_by or self.sorted_by
                    or self.partitioned_by)

    @classmethod
    def parse(cls, props: dict, schema: Dict[str, T.Type],
              connector: str) -> Optional["WriteProperties"]:
        if not props:
            return None
        bby = _namelist(props.get("bucketed_by"))
        sby_raw = _namelist(props.get("sorted_by"))
        pby = _namelist(props.get("partitioned_by"))
        if connector == "hive":
            # hive's own partitioned_by semantics (partition columns move
            # to the end of the schema) stay with the hive connector
            pby = []
        if not (bby or sby_raw or pby):
            return None
        sby: List[Tuple[str, bool]] = []
        for item in sby_raw:
            parts = item.split()
            col = parts[0]
            asc = True
            if len(parts) > 1:
                d = parts[1].lower()
                if d not in ("asc", "desc"):
                    raise WriteError(f"sorted_by entry '{item}': expected "
                                     "'col [asc|desc]'")
                asc = d == "asc"
            sby.append((col, asc))

        def canon(col: str) -> str:
            for c in schema:
                if c.lower() == col.lower():
                    return c
            raise WriteError(f"write property references unknown column "
                             f"'{col}' (have {list(schema)})")

        bby = [canon(c) for c in bby]
        sby = [(canon(c), a) for c, a in sby]
        pby = [canon(c) for c in pby]
        count = int(props.get("bucket_count", 8)) if bby else 0
        if bby and count <= 0:
            raise WriteError("bucket_count must be positive")
        # range bucketing iff the bucket columns are exactly the leading
        # ASCENDING sorted_by prefix: the global sort then makes bucket
        # slices contiguous AND the full-table scan order claimable
        kind = "hash"
        if bby and len(sby) >= len(bby) and all(
                sby[i][0] == bby[i] and sby[i][1]
                for i in range(len(bby))):
            kind = "range"
        if kind == "hash":
            for c in bby:
                t = schema[c]
                if t.is_string or getattr(t, "is_decimal", False) \
                        or t.numpy_dtype().kind not in ("i", "u"):
                    raise WriteError(
                        f"hash bucketing needs an integer column; "
                        f"'{c}' is {t} (declare it as the leading "
                        "sorted_by prefix for range bucketing)")
        return cls(bucketed_by=bby, bucket_count=count, sorted_by=sby,
                   partitioned_by=pby, bucketing=kind)

    def to_dict(self) -> dict:
        return {"bucketed_by": list(self.bucketed_by),
                "bucket_count": self.bucket_count,
                "sorted_by": [[c, bool(a)] for c, a in self.sorted_by],
                "partitioned_by": list(self.partitioned_by),
                "bucketing": self.bucketing}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["WriteProperties"]:
        if not d:
            return None
        return cls(bucketed_by=list(d.get("bucketed_by", [])),
                   bucket_count=int(d.get("bucket_count", 0)),
                   sorted_by=[(c, bool(a))
                              for c, a in d.get("sorted_by", [])],
                   partitioned_by=list(d.get("partitioned_by", [])),
                   bucketing=d.get("bucketing", "hash"))


# ---------------------------------------------------------------------------
# page layout: partition split -> bucket split -> within-bucket sort
# ---------------------------------------------------------------------------


def _orderable_host(a: np.ndarray) -> np.ndarray:
    """Host sort key for one column: strings become sorted-dictionary
    codes (order-exact within one page), masked rows get a nulls-last
    flag handled by the caller."""
    if isinstance(a, np.ma.MaskedArray):
        a = a.filled("" if a.dtype.kind in ("U", "S", "O") else 0)
    if a.dtype.kind in ("U", "S", "O"):
        _, codes = np.unique(a.astype(str), return_inverse=True)
        return codes.astype(np.int64)
    return np.asarray(a)


def _null_flags(a: np.ndarray) -> Optional[np.ndarray]:
    if isinstance(a, np.ma.MaskedArray) and a.mask is not np.ma.nomask \
            and np.any(a.mask):
        return np.ma.getmaskarray(a).astype(np.int8)  # 1 = null -> last
    return None


def _page_sort(arrays: Dict[str, np.ndarray],
               sorted_by: List[Tuple[str, bool]]) -> Dict[str, np.ndarray]:
    keys: List[np.ndarray] = []
    asc: List[bool] = []
    for col, up in sorted_by:
        nf = _null_flags(arrays[col])
        if nf is not None:
            keys.append(nf)  # nulls last regardless of direction
            asc.append(True)
        keys.append(_orderable_host(arrays[col]))
        asc.append(up)
    if not keys:
        return arrays
    perm = K.write_sort_perm(keys, asc)
    return {c: a[perm] for c, a in arrays.items()}


def _key_ranges(arrays: Dict[str, np.ndarray],
                sorted_by: List[Tuple[str, bool]]) -> Optional[list]:
    """[first-row, last-row] sort-key tuples of an ALREADY-SORTED page
    (json-able), or None when unavailable (empty page / NULL sort keys)
    — pages without ranges can never support a table-level ordering
    claim.  Since the page is sorted, first/last rows are the
    lexicographic extremes, which is exactly what the boundary verifier
    (connectors.files_ordered) needs."""
    first, last = [], []
    for col, _asc in sorted_by:
        a = arrays[col]
        if isinstance(a, np.ma.MaskedArray):
            if a.mask is not np.ma.nomask and np.any(a.mask):
                return None  # NULL keys: boundary tuples unrepresentable
            a = a.data
        if len(a) == 0:
            return None
        lo, hi = a[0], a[-1]
        first.append(str(lo) if a.dtype.kind in ("U", "S", "O")
                     else lo.item() if hasattr(lo, "item") else lo)
        last.append(str(hi) if a.dtype.kind in ("U", "S", "O")
                    else hi.item() if hasattr(hi, "item") else hi)
    return [first, last]


def pages_ordered(metas: list, sorted_by: List[Tuple[str, bool]]) -> bool:
    """True when the page/file sequence is globally nondecreasing on the
    sort keys: each page internally sorted (the writer sorted it —
    pages lacking key_ranges don't qualify) and every boundary
    lexicographically monotone.  This is the verifier that upgrades a
    per-file sort into a ConnectorTable.ordering() claim; anything
    unverifiable simply records no claim."""
    from presto_tpu.connectors import files_ordered

    if not sorted_by or not all(a for _c, a in sorted_by):
        return False  # descending keys: ordering() claims are asc-only
    return files_ordered([m.key_ranges for m in metas])


class PageLayout:
    """Applies the write properties to one host page, yielding
    (bucket, partition, arrays, key_ranges) sub-pages in publish order
    (partition-major, then bucket, preserving the global sort for range
    bucketing)."""

    def __init__(self, props: Optional[WriteProperties],
                 streaming: bool = False):
        self.props = props
        # streamed (multi-page) writes can't range-bucket — bucket b's
        # key range would differ per page — so they fall back to hash
        self.streaming = streaming
        if props is not None and streaming and props.bucketing == "range":
            props.bucketing = "hash"

    def split(self, arrays: Dict[str, np.ndarray]):
        wp = self.props
        n = len(next(iter(arrays.values()))) if arrays else 0
        if wp is None or wp.empty() or n == 0:
            yield None, None, arrays, None
            return
        for part, sub in self._partitions(arrays, n):
            if wp.bucketed_by:
                if wp.bucketing == "range":
                    yield from self._range_buckets(part, sub)
                else:
                    yield from self._hash_buckets(part, sub)
            else:
                page = _page_sort(sub, wp.sorted_by)
                yield None, part, page, _key_ranges(page, wp.sorted_by)

    def _partitions(self, arrays, n):
        wp = self.props
        if not wp.partitioned_by:
            yield None, arrays
            return
        code = np.zeros(n, dtype=np.int64)
        uniques = []
        for c in wp.partitioned_by:
            vals = arrays[c]
            if isinstance(vals, np.ma.MaskedArray):
                raise WriteError(
                    f"NULL partition values in '{c}' are not supported")
            u, inv = np.unique(np.asarray(vals), return_inverse=True)
            uniques.append(u)
            code = code * (len(u) + 1) + inv
        for pc in np.unique(code):
            idx = np.flatnonzero(code == pc)
            sub = {c: a[idx] for c, a in arrays.items()}
            part = tuple((c, sub[c][0].item()
                          if hasattr(sub[c][0], "item") else sub[c][0])
                         for c in wp.partitioned_by)
            yield part, sub

    def _range_buckets(self, part, sub):
        wp = self.props
        page = _page_sort(sub, wp.sorted_by)
        n = len(next(iter(page.values())))
        edges = np.linspace(0, n, wp.bucket_count + 1).astype(int)
        for b, (lo, hi) in enumerate(zip(edges[:-1], edges[1:])):
            if lo >= hi:
                continue
            bp = {c: a[lo:hi] for c, a in page.items()}
            yield b, part, bp, _key_ranges(bp, wp.sorted_by)

    def _hash_buckets(self, part, sub):
        wp = self.props
        keys = [_mixable_int(sub[c], c) for c in wp.bucketed_by]
        bids = K.write_bucket_ids(keys, wp.bucket_count)
        for b in range(wp.bucket_count):
            idx = np.flatnonzero(bids == b)
            if len(idx) == 0:
                continue
            bp = {c: a[idx] for c, a in sub.items()}
            bp = _page_sort(bp, wp.sorted_by)
            yield b, part, bp, _key_ranges(bp, wp.sorted_by)


def _mixable_int(a: np.ndarray, col: str) -> np.ndarray:
    if isinstance(a, np.ma.MaskedArray):
        if a.mask is not np.ma.nomask and np.any(a.mask):
            raise WriteError(f"NULL bucket keys in '{col}' are not "
                             "supported")
        a = a.data
    a = np.asarray(a)
    if a.dtype.kind not in ("i", "u", "b"):
        raise WriteError(f"hash bucketing needs integer keys; '{col}' "
                         f"is {a.dtype}")
    return a.astype(np.int64, copy=False)


# ---------------------------------------------------------------------------
# column coercion (the old _insert_into rules + null-fill)
# ---------------------------------------------------------------------------


def coerce_insert_page(arrays: Dict[str, np.ndarray],
                       types: Dict[str, T.Type],
                       targets: List[str], table, sink) -> Dict[str, np.ndarray]:
    """Coerce a query-output page onto the target schema for INSERT:
    positional target mapping, type coercion checks, decimal rescale,
    NULL handling — and the null-fill path for partial column lists on
    sinks whose storage carries a null channel (parquet/orc).  Raw-array
    sinks keep the original clear error."""
    src_cols = list(arrays)
    if len(src_cols) != len(targets):
        raise WriteError(
            f"INSERT column count mismatch: query produces "
            f"{len(src_cols)}, target list has {len(targets)}")
    unknown = [c for c in targets if c not in table.schema]
    if unknown:
        raise WriteError(f"unknown INSERT columns: {unknown}")
    n = len(arrays[src_cols[0]]) if src_cols else 0
    missing = [c for c in table.schema if c not in targets]
    if missing and not sink.supports_null_append:
        raise WriteError(
            f"INSERT must cover all columns (missing {missing}); "
            "partial inserts with null fill are not supported by this "
            "connector")
    out: Dict[str, np.ndarray] = {}
    for tgt, src in zip(targets, src_cols):
        want = table.schema[tgt]
        a = arrays[src]
        if isinstance(a, np.ma.MaskedArray):
            if sink.supports_null_append:
                pass  # the sink writes a null channel (parquet/orc)
            elif a.mask is not np.ma.nomask and np.any(a.mask):
                # raw-array sinks have no validity mask; silently
                # writing fill values would corrupt NULLs
                raise WriteError(
                    f"INSERT of NULL values into column '{tgt}' is not "
                    "supported by this connector")
            else:
                a = a.data
        if not isinstance(a, np.ma.MaskedArray):
            a = np.asarray(a)
        have = types.get(src, want)
        if have != want and not T.can_coerce(have, want) \
                and not (have.is_numeric and want.is_numeric):
            raise WriteError(f"cannot insert {have} into {tgt} ({want})")
        if want.is_decimal and a.dtype.kind == "f":
            # decoded decimals arrive as unscaled floats; rescale like
            # batch.column_from_numpy, never truncate (and never wrap)
            scaled = a * (10 ** want.decimal_scale)
            T.check_decimal_overflow(scaled, what="inserted value")
            a = np.round(scaled).astype(np.int64)
        elif not want.is_string and a.dtype != want.numpy_dtype() \
                and a.dtype != object:
            a = a.astype(want.numpy_dtype())
        out[tgt] = a
    for c in missing:  # null-fill: an all-masked column of the right dtype
        t = table.schema[c]
        fill = np.full(n, "", dtype=object) if t.is_string \
            else np.zeros(n, dtype=t.numpy_dtype())
        out[c] = np.ma.masked_array(fill, mask=np.ones(n, dtype=bool))
    return {c: out[c] for c in table.schema}


def clean_ctas_page(arrays: Dict[str, np.ndarray], sink,
                    what: str = "CTAS") -> Dict[str, np.ndarray]:
    """CTAS pages define the schema, so no type coercion — only the
    NULL-channel rule: null-carrying sinks take masked arrays verbatim,
    raw-array sinks reject actual NULLs loudly."""
    if sink.supports_null_append:
        return dict(arrays)
    clean = {}
    for c, a in arrays.items():
        if isinstance(a, np.ma.MaskedArray):
            if a.mask is not np.ma.nomask and np.any(a.mask):
                raise WriteError(
                    f"{what} with NULL values in column '{c}' is not "
                    "supported by this connector")
            a = a.data
        clean[c] = np.asarray(a)
    return clean


# ---------------------------------------------------------------------------
# WriteContext: the runtime state behind TableWriter/TableFinish
# ---------------------------------------------------------------------------


class WriteContext:
    """One write's engine-side state: the sink, the layout transform,
    the coercion rule, counters, and the commit/abort protocol.  Shared
    by every execution mode; thread-safe for distributed writer
    workers (compute in parallel, append under the lock)."""

    def __init__(self, session, table, sink, props: Optional[WriteProperties],
                 targets: Optional[List[str]] = None, is_ctas: bool = True,
                 streaming: bool = False, on_commit=None):
        self.session = session
        self.table = table
        self.sink = sink
        self.props = props
        self.layout = PageLayout(props, streaming=streaming)
        self.targets = targets
        self.is_ctas = is_ctas
        self.on_commit = on_commit  # callable(ctx) after sink commit
        self.rows = 0
        self.bytes = 0
        self.write_ns = 0
        self._lock = threading.Lock()
        self._done = False
        self._aborted = False

    # -- page path -----------------------------------------------------
    def write_page(self, arrays: Dict[str, np.ndarray],
                   types: Dict[str, T.Type]) -> int:
        t0 = TR.clock_ns()
        if self.is_ctas:
            page = clean_ctas_page(arrays, self.sink)
        else:
            page = coerce_insert_page(arrays, types, self.targets,
                                      self.table, self.sink)
        n = len(next(iter(page.values()))) if page else 0
        if n == 0:
            return 0
        subs = list(self.layout.split(page))
        with self._lock:
            for bucket, part, sub, ranges in subs:
                self.sink.append_page(sub, bucket=bucket, partition=part,
                                      key_ranges=ranges)
            self.rows += n
            self.bytes += sum(int(getattr(a, "nbytes", 0))
                              for a in page.values())
            self.write_ns += TR.clock_ns() - t0
        return n

    # -- commit protocol ----------------------------------------------
    def finish(self):
        with self._lock:
            if self._done:
                return self.sink.finished
            t0 = TR.clock_ns()
            # non-staged sinks (AppendPageSink) can't verify an ordering
            # claim against pre-existing rows themselves — the writer
            # does it here; staged file sinks verify inside their own
            # commit (manifest ranges cover pre-existing files too)
            if isinstance(self.sink, AppendPageSink):
                self._record_append_claim()
            res = self.sink.finish()
            if res.bytes:
                self.bytes = res.bytes
            if self.on_commit is not None:
                self.on_commit(self)
            self._done = True
            self.write_ns += TR.clock_ns() - t0
            return res

    def abort(self):
        with self._lock:
            if self._done or self._aborted:
                return
            self._aborted = True
            self.sink.abort()

    @property
    def files(self) -> int:
        res = self.sink.finished
        return len(res.files) if res is not None else 0

    def _record_append_claim(self):
        """Record write_props (+ a verified ordering claim) on an
        append-SPI table (memory connector): the claim holds when this
        write's page sequence is monotone AND the table was empty before
        it (a fresh CTAS / first INSERT)."""
        wp = self.props
        table = self.table
        rec = getattr(table, "record_write_properties", None)
        if wp is None or wp.empty() or rec is None:
            return
        prior = getattr(table, "_rows", None)
        fresh = (prior == self.rows) if prior is not None else False
        ordered = bool(wp.sorted_by) and fresh \
            and pages_ordered(self.sink.pages, wp.sorted_by)
        rec(wp.to_dict(), ordered)


# ---------------------------------------------------------------------------
# target-table construction (the getPageSinkProvider dispatch)
# ---------------------------------------------------------------------------


def _default_directory(session, name: str) -> str:
    import tempfile

    root = session.properties.get("localfile_root") or os.path.join(
        tempfile.gettempdir(), "presto_tpu_tables")
    return os.path.join(root, name.replace(".", "_"))


def build_target_table(session, name: str, schema: Dict[str, T.Type],
                       properties: dict):
    """Construct (but do NOT register) the CTAS target table for the
    WITH-selected connector — registration is the TableFinish commit.
    Returns (table, connector)."""
    connector = str(properties.get("connector", "memory")).lower()
    if connector == "memory":
        from presto_tpu.catalog import MemoryTable

        empty = {c: np.empty(0, t.numpy_dtype()
                             if not t.is_string else object)
                 for c, t in schema.items()}
        return MemoryTable(name, schema, empty), connector
    if connector == "blackhole":
        from presto_tpu.connectors.localfile import BlackholeTable

        return BlackholeTable(name, schema), connector
    if connector in ("localfile", "parquet", "orc"):
        directory = properties.get("path") or properties.get(
            "directory") or _default_directory(session, name)
        if connector == "localfile":
            from presto_tpu.connectors.localfile import LocalFileTable as cls
        elif connector == "parquet":
            from presto_tpu.connectors.parquet import ParquetTable as cls
        else:
            from presto_tpu.connectors.orc import OrcTable as cls
        return cls(name, directory, schema), connector
    raise WriteError(f"unknown connector '{connector}'")


def target_connector(properties: dict, session=None, name: str = "") -> str:
    c = str(properties.get("connector", "memory")).lower()
    if session is not None and c != "hive":
        from presto_tpu.connectors.hive import is_hive_name

        # a name under an attached hive catalog's prefix routes to the
        # hive connector (reference: the catalog name selects the
        # connector in MetadataManager.createTable)
        if is_hive_name(session.catalog, name):
            return "hive"
    return c


def connector_kind(table) -> str:
    mod = type(table).__module__
    for k in ("localfile", "parquet", "orc", "hive"):
        if mod.endswith(k):
            if type(table).__name__ == "BlackholeTable":
                return "blackhole"
            return k
    return "memory"


# ---------------------------------------------------------------------------
# write planning (TableWriter/TableFinish wrap the optimized query plan)
# ---------------------------------------------------------------------------


def output_schema(out: P.Output) -> Tuple[Dict[str, T.Type], List[str]]:
    """The host-array schema a materialized Output produces: duplicate
    names suffix `_i` exactly like executor.execute_plan_to_host, so
    CTAS schemas match the arrays byte-for-byte."""
    types = dict(out.source.outputs())
    schema: Dict[str, T.Type] = {}
    order: List[str] = []
    used: Dict[str, int] = {}
    for name, sym in zip(out.names, out.symbols):
        n = name
        i = used.get(name, 0)
        used[name] = i + 1
        if i:
            n = f"{name}_{i}"
        schema[n] = types.get(sym, T.VARCHAR)
        order.append(n)
    return schema, order


def plan_write_statement(session, stmt) -> P.QueryPlan:
    """Plan a CTAS/INSERT as Output <- TableFinish <- TableWriter <-
    <optimized query plan> (reference: LogicalPlanner.createTableWriterPlan).
    The inner query plans + optimizes through the normal path, so
    ordering propagation / dynamic filters / CBO all apply to the
    source side of a write."""
    from presto_tpu.exec.executor import plan_statement

    from presto_tpu.plan.planner import Planner

    inner = plan_statement(session, ast.QueryStatement(stmt.query))
    if isinstance(stmt, ast.CreateTableAs):
        target, props = stmt.name, (stmt.properties or {})
        schema, order = output_schema(inner.root)
        columns = order
        connector = target_connector(props, session, target)
        wp = WriteProperties.parse(props, schema, connector)
    else:
        target = stmt.table
        table = session.catalog.get(target)
        columns = stmt.columns if stmt.columns is not None \
            else list(table.schema)
        connector = connector_kind(table)
        wp = WriteProperties.from_dict(
            table.write_properties()
            if hasattr(table, "write_properties") else None)
    return Planner.wrap_write(
        inner, target, connector, columns,
        wp.to_dict() if wp is not None else None)


# ---------------------------------------------------------------------------
# execution modes
# ---------------------------------------------------------------------------


def _host_arrays(out: P.Output, batch) -> Tuple[Dict[str, np.ndarray],
                                                Dict[str, T.Type]]:
    from presto_tpu.batch import to_numpy

    arrays, sel = to_numpy(batch)
    types = dict(out.source.outputs())
    schema, order = output_schema(out)
    result = {}
    used: Dict[str, int] = {}
    for name, sym in zip(out.names, out.symbols):
        n = name
        i = used.get(name, 0)
        used[name] = i + 1
        if i:
            n = f"{name}_{i}"
        v = arrays[sym][sel]
        result[n] = v if isinstance(v, np.ma.MaskedArray) else np.asarray(v)
    return result, schema


def _stream_target(session, plan: P.QueryPlan):
    """(scan_node, inner_output) when the write's source is a streamable
    single-scan pipeline (Output <- Project/Filter* <- TableScan, no
    subplans): these are the plans chunked/distributed writes can
    evaluate split-by-split with bounded host memory."""
    if plan.subplans:
        return None
    tw = plan.root.source.source  # Output <- TableFinish <- TableWriter
    inner = tw.source
    node = inner.source
    while isinstance(node, (P.Project, P.Filter)):
        node = node.source
    if not isinstance(node, P.TableScan):
        return None
    scans: List[P.TableScan] = []

    def walk(n):
        if isinstance(n, P.TableScan):
            scans.append(n)
        for s in n.sources:
            walk(s)

    walk(inner)
    if len(scans) != 1 or scans[0] is not node:
        return None
    return node, inner


def _split_batch(session, table, scan: P.TableScan, split):
    data = table.read(list(dict.fromkeys(scan.assignments.values())),
                      split=split)
    arrays = {}
    for sym, src in scan.assignments.items():
        arrays[sym] = data[src]
    return batch_from_numpy(arrays, dict(scan.types))


def _stream_write(session, plan: P.QueryPlan, ctx: WriteContext,
                  scan: P.TableScan, inner: P.Output,
                  workers: int, mon=None) -> int:
    """Chunked / distributed write: evaluate the source pipeline one
    split at a time, appending each chunk's page to the sink — bounded
    host memory, no whole-result materialization.  workers > 1 fans
    splits over writer threads (each producing its OWN staged files);
    the caller's finish() is the coordinator's single commit step."""
    from presto_tpu.exec.executor import Executor

    table = session.catalog.get(scan.table)
    chunk_rows = int(session.properties.get(
        "write_page_rows", DEFAULT_WRITE_PAGE_ROWS))
    n_splits = max(-(-int(table.row_count()) // max(chunk_rows, 1)), 1)
    splits = table.splits(n_splits) or [(0, table.row_count())]
    errors: List[BaseException] = []
    total = [0]
    total_lock = threading.Lock()

    def run_splits(assigned):
        try:
            for sp in assigned:
                b = _split_batch(session, table, scan, sp)
                ex = Executor(session, scan_inputs={id(scan): b})
                out = ex.exec_node(inner)
                arrays, types = _host_arrays(inner, out)
                n = ctx.write_page(arrays, types)
                with total_lock:
                    total[0] += n
        except BaseException as e:
            errors.append(e)

    if workers <= 1:
        run_splits(splits)
    else:
        lanes = [splits[i::workers] for i in range(workers)]
        threads = [threading.Thread(target=run_splits, args=(lane,),
                                    daemon=True)
                   for lane in lanes if lane]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        raise errors[0]
    return total[0]


def _compiled_arrays(session, text: str, query: ast.Query, mon):
    """Compiled-mode source execution: the SELECT compiles/runs as ONE
    XLA program (executor.run_compiled, sharing its executable memo
    under a write-scoped key) and the fetched result converts back to
    host columns.  Returns None when the materialized rows don't
    round-trip losslessly to arrays (exotic object columns) — the
    caller falls back to the dynamic pipeline."""
    from presto_tpu.exec.executor import run_compiled

    res = run_compiled(session, f"__write__:{text}",
                       ast.QueryStatement(query), mon=mon)
    arrays: Dict[str, np.ndarray] = {}
    types: Dict[str, T.Type] = {}
    used: Dict[str, int] = {}
    for i, (name, typ) in enumerate(res.columns):
        n = name
        k = used.get(name, 0)
        used[name] = k + 1
        if k:
            n = f"{name}_{k}"
        vals = [r[i] for r in res.rows]
        has_null = any(v is None for v in vals)
        if typ.is_string:
            a = np.asarray([("" if v is None else v) for v in vals],
                           dtype=object)
        else:
            dt = np.float64 if (typ.is_decimal
                                or typ.name == "DOUBLE") else None
            try:
                a = np.asarray([(0 if v is None else v) for v in vals],
                               dtype=dt)
            except (TypeError, ValueError):
                return None
            if a.dtype == object:
                return None
        if has_null:
            a = np.ma.masked_array(
                a, mask=np.asarray([v is None for v in vals]))
        arrays[n] = a
        types[n] = typ
    return arrays, types


# ---------------------------------------------------------------------------
# the statement entry point
# ---------------------------------------------------------------------------


def run_write(session, text: str, stmt, mon) -> QueryResult:
    """CTAS / INSERT lifecycle: authorize -> plan (TableWriter) ->
    begin_write -> execute in the session's mode (appending pages) ->
    finish/commit -> row-count result."""
    is_ctas = isinstance(stmt, ast.CreateTableAs)
    if is_ctas:
        session.access_control.check_can_create_table(session.user,
                                                      stmt.name)
        or_replace = bool(getattr(stmt, "or_replace", False))
        if stmt.name in session.catalog and not or_replace:
            if stmt.if_not_exists:
                return QueryResult([("rows", T.BIGINT)], [(0,)])
            raise WriteError(f"Table '{stmt.name}' already exists")
    else:
        session.access_control.check_can_insert(session.user, stmt.table)

    from presto_tpu.exec import chunked as CH
    from presto_tpu.exec.executor import Executor, plan_statement

    with mon.phase("plan"):
        plan = plan_statement(session, stmt)
    tw: P.TableWriter = plan.root.source.source
    wp = WriteProperties.from_dict(tw.write_props)

    ctx = _begin_write(session, stmt, plan, tw, wp)
    try:
        with mon.phase("execute"):
            stream = _stream_target(session, plan)
            mode = session.properties.get("execution_mode", "auto")
            threshold = int(session.properties.get(
                "chunked_rows_threshold", CH.DEFAULT_STREAM_THRESHOLD))
            executed = False
            if stream is not None:
                scan, inner = stream
                table = session.catalog.get(scan.table)
                if session.properties.get("distributed", False):
                    mon.stats.execution_mode = "distributed"
                    workers = int(session.properties.get(
                        "write_parallelism", 0)) or min(
                        MAX_WRITE_WORKERS, max(os.cpu_count() or 2, 2))
                    ctx.layout.streaming = True
                    _demote_range_bucketing(ctx)
                    _stream_write(session, plan, ctx, scan, inner,
                                  workers, mon)
                    executed = True
                elif mode == "chunked" or (
                        mode == "auto"
                        and table.row_count() > threshold):
                    mon.stats.execution_mode = "chunked"
                    ctx.layout.streaming = True
                    _demote_range_bucketing(ctx)
                    _stream_write(session, plan, ctx, scan, inner, 1, mon)
                    executed = True
            if not executed and mode == "compiled":
                from presto_tpu.exec.executor import StaticFallback

                try:
                    got = _compiled_arrays(session, text, stmt.query, mon)
                except StaticFallback:
                    got = None
                if got is not None:
                    mon.stats.execution_mode = "compiled"
                    arrays, types = got
                    ctx.write_page(arrays, types)
                    executed = True
            if not executed:
                # the normal executor pipeline: TableWriter/TableFinish
                # run as plan nodes (dynamic mode)
                mon.stats.execution_mode = "dynamic"
                ex = Executor(session, monitor=mon)
                ex.write_ctx = ctx
                ex.run(plan)
            ctx.finish()  # idempotent (TableFinish commits inline)
    except BaseException:
        ctx.abort()
        raise
    mon.stats.rows_written = ctx.rows
    mon.stats.bytes_written = ctx.bytes
    mon.stats.write_files = ctx.files
    mon.stats.write_ms = ctx.write_ns / 1e6
    return QueryResult([("rows", T.BIGINT)], [(ctx.rows,)])


def _demote_range_bucketing(ctx: WriteContext) -> None:
    """Streamed writes can't hold the whole result, so range bucketing
    (which needs ONE global sort) degrades to hash bucketing — pages
    stay per-bucket sorted for zone maps; the table-level ordering claim
    simply doesn't record unless the boundary verifier still passes."""
    wp = ctx.props
    if wp is not None and wp.bucketing == "range":
        wp.bucketing = "hash"


def _begin_write(session, stmt, plan: P.QueryPlan, tw: P.TableWriter,
                 wp: Optional[WriteProperties]) -> WriteContext:
    """Build the target table / sink and wire the commit callback
    (catalog registration + transaction undo records)."""
    is_ctas = isinstance(stmt, ast.CreateTableAs)
    inner: P.Output = tw.source
    if not is_ctas:
        table = session.catalog.get(stmt.table)
        if not (hasattr(table, "page_sink") or hasattr(table, "append")):
            raise WriteError(
                f"table '{stmt.table}' does not support INSERT")
        # transactional snapshot BEFORE the first page: manifest
        # snapshot for staged sinks, data pre-image for memory tables
        session.txn.record_table_write(table)
        iprops = wp if wp is not None else None
        sink = open_sink(table, iprops, defer_gc=session.txn.active)
        return WriteContext(session, table, sink, iprops,
                            targets=list(tw.columns), is_ctas=False,
                            on_commit=lambda c: _invalidate_server_caches(
                                session, tables={table.name}))

    schema, _order = output_schema(inner)
    props = stmt.properties or {}
    connector = tw.connector
    or_replace = bool(getattr(stmt, "or_replace", False))
    replacing = or_replace and stmt.name in session.catalog
    old_table = session.catalog.get(stmt.name) if replacing else None

    session.txn.check_write_allowed()
    if connector == "hive":
        from presto_tpu.connectors.hive import create_hive_table

        if replacing:
            raise WriteError("CREATE OR REPLACE is not supported for "
                             "hive tables")
        table = create_hive_table(session.catalog, stmt.name, schema,
                                  props)  # registers itself
        session.txn.record_create(stmt.name)
        sink = open_sink(table, wp)
        return WriteContext(session, table, sink, wp, is_ctas=True,
                            on_commit=lambda c: _invalidate_server_caches(
                                session, tables={stmt.name}))

    new_dir = props.get("path") or props.get("directory")
    old_dir = getattr(old_table, "dir", None) \
        or getattr(old_table, "path", None)
    in_place = (replacing and connector in ("localfile", "parquet", "orc")
                and connector_kind(old_table) == connector
                and (not new_dir or (old_dir is not None
                                     and os.path.abspath(str(new_dir))
                                     == os.path.abspath(str(old_dir)))))
    if replacing and not in_place and old_dir is not None \
            and new_dir is not None \
            and os.path.abspath(str(new_dir)) \
            == os.path.abspath(str(old_dir)):
        raise WriteError(
            f"CREATE OR REPLACE of '{stmt.name}' cannot reuse the old "
            f"storage directory across connectors; choose a new path")
    if in_place:
        # same-storage replace: the staged sink publishes a NEW manifest
        # generation over the SAME directory — concurrent readers on the
        # previous generation keep their files (snapshot isolation)
        table = old_table
        session.txn.record_presnapshot(table)  # pre-commit manifest
        sink = table.page_sink(wp, replace=True, schema=schema,
                               defer_gc=session.txn.active)
    else:
        table, _ = build_target_table(session, stmt.name, schema, props)
        sink = open_sink(table, wp)

    def on_commit(ctx: WriteContext):
        txn = session.txn
        if replacing:
            txn.record_replace(stmt.name, old_table,
                               in_place=in_place)
        else:
            txn.record_create(stmt.name)
        if not in_place:
            session.catalog.register(ctx.table)
            if replacing and old_table is not None \
                    and old_table is not ctx.table \
                    and hasattr(old_table, "drop_data") \
                    and txn.current is None:
                # cross-storage replace: old managed storage goes away
                # (same-storage replaces retire files via the manifest)
                old_table.drop_data()
        else:
            session.catalog.version += 1
        _invalidate_server_caches(session, tables={stmt.name})

    return WriteContext(session, table, sink, wp, is_ctas=True,
                        on_commit=on_commit)


def _invalidate_server_caches(session, tables=None) -> None:
    """Engine-path writes must invalidate the serving result cache the
    same way protocol-path writes do (server/serving.py belt rule);
    `tables` scopes the eviction to entries referencing the written
    tables (None still clears everything)."""
    tier = getattr(session, "_serving_tier", None)
    if tier is not None:
        try:
            tier.on_write_statement(tables=tables)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# SHOW CREATE TABLE rendering
# ---------------------------------------------------------------------------


def render_create_table(table) -> str:
    """CREATE TABLE DDL with the recorded physical-layout properties —
    executing the rendered statement (fresh name/path) reproduces the
    layout (reference: ShowCreateTable rewrite)."""
    cols = ",\n".join(f"   {c} {str(t).lower()}"
                      for c, t in table.schema.items())
    props = [("connector", f"'{connector_kind(table)}'")]
    d = getattr(table, "dir", None) or getattr(table, "path", None)
    if d:
        props.append(("directory", f"'{d}'"))
    wp = WriteProperties.from_dict(
        table.write_properties()
        if hasattr(table, "write_properties") else None)
    if wp is not None and not wp.empty():
        if wp.bucketed_by:
            props.append(("bucketed_by", _render_array(wp.bucketed_by)))
            props.append(("bucket_count", str(wp.bucket_count)))
        if wp.sorted_by:
            props.append(("sorted_by", _render_array(
                [f"{c} {'asc' if a else 'desc'}" for c, a in wp.sorted_by])))
        if wp.partitioned_by:
            props.append(("partitioned_by",
                          _render_array(wp.partitioned_by)))
    with_clause = ",\n".join(f"   {k} = {v}" for k, v in props)
    return (f"CREATE TABLE {table.name} (\n{cols}\n)\n"
            f"WITH (\n{with_clause}\n)")


def _render_array(items: List[str]) -> str:
    return "ARRAY[" + ", ".join(f"'{i}'" for i in items) + "]"


def describe_extra_rows(table) -> List[tuple]:
    """Layout rows DESCRIBE/SHOW COLUMNS append for tables with recorded
    write properties (tables without them are unchanged)."""
    wp = WriteProperties.from_dict(
        table.write_properties()
        if hasattr(table, "write_properties") else None)
    if wp is None or wp.empty():
        return []
    rows = []
    if wp.sorted_by:
        rows.append(("# sorted_by", ", ".join(
            f"{c} {'ASC' if a else 'DESC'}" for c, a in wp.sorted_by)))
    if wp.bucketed_by:
        rows.append(("# bucketed_by",
                     f"{', '.join(wp.bucketed_by)} "
                     f"({wp.bucketing}, {wp.bucket_count} buckets)"))
    if wp.partitioned_by:
        rows.append(("# partitioned_by", ", ".join(wp.partitioned_by)))
    return rows
