"""Local query executor: logical plan -> device kernels -> host result.

Reference parity: the whole worker data plane — LocalExecutionPlanner
emitting DriverFactories + the Driver page-pump loop
(operator/Driver.java:347) — collapsed into a bottom-up plan walk where
each node materializes a whole-column Batch.  What the reference streams
page-at-a-time, XLA executes as fused whole-column programs; streaming
returns at the distributed layer as superstep chunking (parallel/).

Subquery plans (uncorrelated scalars) are evaluated first, like the
reference's gather exchanges from pre-requisite stages.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import (Batch, Column, batch_from_numpy,
                              decode_host_column, to_numpy)
from presto_tpu.exec import compile_cache as CC
from presto_tpu.exec import gather as GA
from presto_tpu.exec import kernels as K
from presto_tpu.exec.compiler import EvalContext, eval_expr, eval_predicate, to_column
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P
from presto_tpu.plan.optimizer import optimize
from presto_tpu.plan.planner import Planner
from presto_tpu.session import QueryResult
from presto_tpu.sql import ast
from presto_tpu.sql.parser import parse


import threading as _threading

_pool_init_lock = _threading.Lock()


class ExecutionError(Exception):
    pass


def _merge_sort_stats(stats, counts: dict) -> None:
    """Fold an executor's sort-economics + dynamic-filtering +
    spill-degradation + adaptive-aggregation counters into QueryStats."""
    for k in ("sorts_taken", "sorts_elided", "sort_memo_hits",
              "ordering_guard_trips",
              "df_filters_produced", "df_filters_applied",
              "df_rows_pruned", "df_chunks_pruned", "df_splits_pruned",
              "fragments_fused", "exchange_bytes_host",
              "exchange_bytes_collective", "exchange_bytes_sketch",
              "approx_rewrites",
              "spill_partitions", "spill_bytes", "spill_restores",
              "spill_recursions",
              "partial_aggs_bypassed", "partial_aggs_reenabled"):
        setattr(stats, k, getattr(stats, k, 0) + int(counts.get(k, 0)))
    if counts.get("partial_agg_ratio"):
        # a gauge, not a sum: the last ratio a partial stage observed
        stats.partial_agg_ratio = float(counts["partial_agg_ratio"])
    for k, v in counts.items():
        # "agg_strategy::<name>" -> QueryStats.agg_strategy[name] (the
        # per-strategy execution counter, exported with labels)
        if k.startswith("agg_strategy::") and v:
            name = k.split("::", 1)[1]
            stats.agg_strategy[name] = \
                stats.agg_strategy.get(name, 0) + int(v)
    if counts.get("df_wait_ms"):
        stats.df_wait_ms = getattr(stats, "df_wait_ms", 0.0) \
            + float(counts["df_wait_ms"])
    # degradation_tier is a high-water mark, not a sum
    stats.degradation_tier = max(getattr(stats, "degradation_tier", 0),
                                 int(counts.get("degradation_tier", 0)))
    # legacy aliases (pre-round-15 dashboards + tests key on these)
    stats.spilled_partitions = getattr(stats, "spilled_partitions", 0) \
        + int(counts.get("spill_partitions", 0))
    stats.spilled_bytes = getattr(stats, "spilled_bytes", 0) \
        + int(counts.get("spill_bytes", 0))
    # spill-I/O recovery events ride the recovery dict (the
    # docs/ROBUSTNESS.md schema): enospc failures + transparent rewrites
    for k in ("spill_enospc", "spill_rewrites", "spill_df_resident"):
        if counts.get(k):
            rec = getattr(stats, "recovery", None)
            if rec is not None:
                rec[k] = rec.get(k, 0) + int(counts[k])


class StaticFallback(Exception):
    """Raised when a plan shape can't be made static (missing stats /
    unbounded join fanout); auto mode falls back to eager execution."""


def execute_query(session, text: str) -> QueryResult:
    """Query lifecycle wrapper: stats + events around the actual dispatch
    (reference: SqlQueryManager.createQuery + QueryStateMachine +
    QueryMonitor events, execution/SqlQueryManager.java:299)."""
    from presto_tpu.observe.stats import QueryMonitor

    mon = QueryMonitor.begin(session, text)
    from presto_tpu import session_ctx
    from presto_tpu.exec import compile_cache as CC
    from presto_tpu.observe import profile as PR
    from presto_tpu.observe import trace as TR

    session_ctx.activate(session)  # zone + query-stable now()
    CC.configure(session)  # honor a per-session compile_cache_dir
    try:
        # tracer activation makes nested instrumentation (compile
        # spans, cluster RPCs, chunked fragments) land on THIS query's
        # trace; maybe_profile wraps the query in jax.profiler capture
        # when profile_query / PRESTO_TPU_PROFILE asks for one
        with CC.recording(mon.stats), TR.activate(mon.tracer), \
                PR.maybe_profile(session):  # compile-economics counters
            with mon.phase("parse"):
                stmt = parse(text)
            result = _dispatch_statement(session, text, stmt, mon)
        mon.finish(result)
        result.stats = mon.stats  # this query's stats, race-free under
        return result             # concurrent sessions (vs last_stats)
    except BaseException as e:
        mon.fail(e)
        raise


def _dispatch_statement(session, text: str, stmt, mon) -> QueryResult:
    if isinstance(stmt, ast.Prepare):
        # serving-tier registry (server/serving.py): parses + validates
        # the template ONCE, infers parameter types for DESCRIBE INPUT,
        # and mirrors into session.prepared_statements (compat surface)
        from presto_tpu.server import serving as SV

        SV.prepare(session, stmt.name, stmt.statement_text)
        return QueryResult([("result", T.BOOLEAN)], [(True,)])
    if isinstance(stmt, ast.Execute):
        # typed aval-abstracted binding when possible (plan + executable
        # shared across parameter values), else text substitution
        from presto_tpu.server import serving as SV

        return SV.execute_prepared(session, stmt, mon, _dispatch_statement)
    if isinstance(stmt, ast.Deallocate):
        from presto_tpu.server import serving as SV

        SV.deallocate(session, stmt.name)  # unknown name is an error
        return QueryResult([("result", T.BOOLEAN)], [(True,)])
    if isinstance(stmt, ast.TransactionStatement):
        if stmt.action == "START":
            session.txn.begin(stmt.read_only)
        elif stmt.action == "COMMIT":
            session.txn.commit()
        else:
            session.txn.rollback()
        return QueryResult([("result", T.BOOLEAN)], [(True,)])
    if isinstance(stmt, ast.SetSession):
        session.access_control.check_can_set_session_property(
            session.user, stmt.name)
        session.set(stmt.name, stmt.value)
        return QueryResult([("result", T.BOOLEAN)], [(True,)])
    if isinstance(stmt, ast.ShowTables):
        from presto_tpu.exec.matview import MV_PREFIX

        # MV backing tables are engine-internal; SHOW MATERIALIZED VIEWS
        # lists the views themselves
        rows = sorted((t,) for t in session.catalog.tables
                      if not t.startswith(MV_PREFIX))
        return QueryResult([("Table", T.VARCHAR)], rows)
    if isinstance(stmt, ast.ShowColumns):
        t = session.catalog.get(stmt.table)
        rows = [(c, str(ty)) for c, ty in t.schema.items()]
        # recorded physical-layout properties surface as trailing
        # marker rows (tables without a recorded layout are unchanged)
        from presto_tpu.exec.writer import describe_extra_rows

        rows += describe_extra_rows(t)
        return QueryResult([("Column", T.VARCHAR), ("Type", T.VARCHAR)], rows)
    if isinstance(stmt, ast.ShowFunctions):
        from presto_tpu.functions import aggregate as _agg
        from presto_tpu.functions import scalar as _sc

        rows = sorted(
            [(n, "scalar") for n in _sc.REGISTRY
             if not n.startswith("$")]
            + [(n, "aggregate") for n in _agg.AGG_NAMES]
            + [(n, "window") for n in _agg.WINDOW_ONLY])
        return QueryResult(
            [("Function", T.VARCHAR), ("Type", T.VARCHAR)], rows)
    if isinstance(stmt, ast.ShowSession):
        rows = sorted((k, str(v)) for k, v in session.properties.items())
        return QueryResult(
            [("Name", T.VARCHAR), ("Value", T.VARCHAR)], rows)
    if isinstance(stmt, ast.ShowCatalogs):
        rows = sorted((q,) for q in session.catalog.known_qualifiers)
        return QueryResult([("Catalog", T.VARCHAR)], rows)
    if isinstance(stmt, ast.ShowSchemas):
        schemas = {"default"}
        for name in session.catalog.tables:
            parts = name.split(".")
            if len(parts) >= 2:
                schemas.add(parts[-2])
        return QueryResult([("Schema", T.VARCHAR)],
                           sorted((s,) for s in schemas))
    if isinstance(stmt, ast.ShowStats):
        # reference: ShowStatsRewrite — per-column connector statistics
        # plus the table row-count summary row
        t = session.catalog.get(stmt.table)
        rows = []
        for c in t.schema:
            st = t.column_stats(c)
            rows.append((c,
                         float(st.ndv) if st is not None
                         and st.ndv is not None else None,
                         st.min if st is not None else None,
                         st.max if st is not None else None,
                         None))
        rows.append((None, None, None, None, float(t.row_count())))
        return QueryResult(
            [("column_name", T.VARCHAR),
             ("distinct_values_count", T.DOUBLE),
             ("low_value", T.DOUBLE), ("high_value", T.DOUBLE),
             ("row_count", T.DOUBLE)], rows)
    if isinstance(stmt, ast.Explain):
        if stmt.analyze:
            text_plan = explain_analyze_text(session, stmt.statement, mon)
        elif stmt.type_ == "VALIDATE":
            # reference: ExplainType.VALIDATE — analysis only
            plan_statement(session, stmt.statement)
            return QueryResult([("Valid", T.BOOLEAN)], [(True,)])
        elif stmt.type_ == "DISTRIBUTED":
            text_plan = explain_distributed_text(session, stmt.statement)
        else:
            text_plan = explain_text(session, stmt.statement)
        return QueryResult([("Query Plan", T.VARCHAR)], [(text_plan,)])
    if isinstance(stmt, ast.DescribeInput):
        # reference: DescribeInputRewrite — parameter positions + types
        # inferred from the template's column comparisons (serving tier;
        # positions the inference cannot resolve report 'unknown')
        from presto_tpu.server import serving as SV

        rows = SV.describe_input(session, stmt.name)
        return QueryResult([("Position", T.BIGINT), ("Type", T.VARCHAR)],
                           rows)
    if isinstance(stmt, ast.DescribeOutput):
        # reference: DescribeOutputRewrite — plan with parameters bound
        # to NULL, report output names and types
        prepared = getattr(session, "prepared_statements", {}).get(stmt.name)
        if prepared is None:
            raise ExecutionError(f"prepared statement '{stmt.name}' not found")
        null_params = [ast.Literal(None)] * _count_placeholders(prepared)
        bound = _substitute_parameters(prepared, null_params)
        plan = plan_statement(session, parse(bound))
        types = dict(plan.root.source.outputs())
        rows = [(n, str(types.get(s, T.VARCHAR)).lower())
                for n, s in zip(plan.root.names, plan.root.symbols)]
        return QueryResult(
            [("Column Name", T.VARCHAR), ("Type", T.VARCHAR)], rows)
    if isinstance(stmt, ast.CreateTableAs):
        # PageSink write pipeline (exec/writer.py): TableWriter /
        # TableFinish plan, staged sinks, bucketed/sorted/partitioned
        # layout, atomic commit
        from presto_tpu.exec import writer as W

        return W.run_write(session, text, stmt, mon)
    if isinstance(stmt, ast.ShowCreateTable):
        from presto_tpu.exec import writer as W

        t = session.catalog.get(stmt.table)
        return QueryResult([("Create Table", T.VARCHAR)],
                           [(W.render_create_table(t),)])
    if isinstance(stmt, ast.CreateTable):
        session.access_control.check_can_create_table(session.user, stmt.name)
        if stmt.name in session.catalog:
            if stmt.if_not_exists:
                return QueryResult([("result", T.BOOLEAN)], [(True,)])
            raise ExecutionError(f"Table '{stmt.name}' already exists")
        schema = {c: T.parse_type(t) for c, t in stmt.columns}
        session.txn.record_create(stmt.name)
        _create_table(session, stmt.name, schema, stmt.properties, None)
        return QueryResult([("result", T.BOOLEAN)], [(True,)])
    if isinstance(stmt, ast.DropTable):
        session.access_control.check_can_drop_table(session.user, stmt.name)
        if stmt.name in session.catalog:
            t = session.catalog.get(stmt.name)
            session.txn.record_drop(t)
            if session.txn.current is None and hasattr(t, "drop_data"):
                t.drop_data()  # engine-managed storage goes with the table
        session.catalog.drop(stmt.name, stmt.if_exists)
        return QueryResult([("result", T.BOOLEAN)], [(True,)])
    if isinstance(stmt, ast.InsertInto):
        from presto_tpu.exec import writer as W

        return W.run_write(session, text, stmt, mon)
    if isinstance(stmt, ast.Delete):
        n = _delete_from(session, stmt)
        return QueryResult([("rows", T.BIGINT)], [(n,)])
    if isinstance(stmt, ast.CreateMaterializedView):
        from presto_tpu.exec import matview as MV

        return MV.create(session, stmt, mon)
    if isinstance(stmt, ast.RefreshMaterializedView):
        from presto_tpu.exec import matview as MV

        return MV.refresh(session, stmt, mon)
    if isinstance(stmt, ast.DropMaterializedView):
        from presto_tpu.exec import matview as MV

        return MV.drop(session, stmt, mon)
    if isinstance(stmt, ast.ShowMaterializedViews):
        from presto_tpu.exec import matview as MV

        return MV.show(session)

    if isinstance(stmt, ast.QueryStatement) \
            and getattr(session.catalog, "matviews", None):
        # MV-routed serving: a SELECT provably contained in a
        # materialized view reads the freshest snapshot instead of
        # executing (exec/matview.py try_route)
        from presto_tpu.exec import matview as MV

        routed = MV.try_route(session, stmt, mon)
        if routed is not None:
            return routed

    if session.properties.get("distributed", False):
        from presto_tpu.parallel.dist_executor import run_distributed
        from presto_tpu.plan.distribute import Undistributable

        try:
            with mon.phase("execute"):
                mon.stats.execution_mode = "distributed"
                return run_distributed(session, text, stmt)
        except (Undistributable, StaticFallback,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError):
            pass  # single-device paths below
    mode = session.properties.get("execution_mode", "auto")
    if mode in ("auto", "compiled", "chunked"):
        # grouped/chunked execution when a scanned table exceeds the HBM
        # residency threshold (reference: grouped execution, Lifespan)
        from presto_tpu.exec import chunked as CH

        needs_chunks = False
        plan_probe = None
        warm_key = query_cache_key(session, text)
        if warm_key in getattr(session, "_chunked_cache", {}):
            needs_chunks = True  # memo hit: skip the planning probe
        elif mode == "chunked" or CH.catalog_may_need_chunks(session):
            try:
                plan_probe = plan_statement(session, stmt)
                needs_chunks = CH.chunk_plan_needed(session, plan_probe)
            except Exception:
                needs_chunks = False
        if needs_chunks or mode == "chunked":
            try:
                with mon.phase("execute"):
                    mon.stats.execution_mode = "chunked"
                    return CH.run_chunked(session, stmt, text, mon=mon)
            except (CH.Unchunkable, jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError):
                if mode == "chunked":
                    raise
    if mode in ("auto", "compiled"):
        try:
            with mon.phase("execute"):
                mon.stats.execution_mode = "compiled"
                return run_compiled(session, text, stmt, mon=mon)
        except (StaticFallback, jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError) as e:
            if mode == "compiled":
                raise StaticFallback(str(e)) from e
    mon.stats.execution_mode = "dynamic"
    with mon.phase("plan"):
        plan = plan_statement(session, stmt)
    with mon.phase("execute"):
        ex = Executor(session, monitor=mon)
        return ex.run(plan)


def _substitute_parameters(sql: str, params) -> str:
    """Replace `?` placeholders (outside string literals) with rendered
    literal parameters (reference: ParameterRewriter)."""
    rendered = []
    for p in params:
        neg = False
        while isinstance(p, ast.UnaryOp) and p.op == "-" \
                and isinstance(p.operand, ast.Literal) \
                and isinstance(p.operand.value, (int, float)):
            neg = not neg
            p = p.operand
        if not isinstance(p, ast.Literal):
            raise ExecutionError("EXECUTE parameters must be literals")
        v = p.value
        if v is None:
            rendered.append("NULL")
        elif isinstance(v, bool):
            rendered.append("TRUE" if v else "FALSE")
        elif isinstance(v, (int, float)):
            rendered.append(repr(-v if neg else v))
        elif getattr(p, "type_hint", None) == "date":
            rendered.append(f"DATE '{v}'")
        elif getattr(p, "type_hint", None) == "timestamp":
            rendered.append(f"TIMESTAMP '{v}'")
        else:
            rendered.append("'" + str(v).replace("'", "''") + "'")
    out = []
    i = n_used = 0
    in_str = False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
        if ch == "?" and not in_str:
            if n_used >= len(rendered):
                raise ExecutionError(
                    f"{len(rendered)} parameters for more placeholders")
            out.append(rendered[n_used])
            n_used += 1
        else:
            out.append(ch)
        i += 1
    if n_used != len(rendered):
        raise ExecutionError(
            f"{len(rendered)} parameters but {n_used} placeholders")
    return "".join(out)


def _create_table(session, name, schema, properties, arrays):
    """Create + register an EMPTY table on the connector chosen by WITH
    properties (reference: StaticCatalogStore catalogs + per-connector
    metadata.createTable; default is the memory connector).  CTAS and
    INSERT route through exec/writer.py instead — `arrays` is kept for
    API compatibility and must be None.  Declared layout properties
    (sorted_by/bucketed_by/partitioned_by) record onto the empty table
    so later INSERTs apply and verify them."""
    assert arrays is None, "CTAS routes through exec/writer.run_write"
    from presto_tpu.exec import writer as W

    connector = W.target_connector(properties, session, name)
    if connector == "hive":
        from presto_tpu.connectors.hive import create_hive_table

        create_hive_table(session.catalog, name, schema, properties)
        return
    try:
        t, _ = W.build_target_table(session, name, schema, properties)
    except W.WriteError as e:
        raise ExecutionError(str(e)) from e
    try:
        wp = W.WriteProperties.parse(properties, schema, connector)
    except W.WriteError as e:
        raise ExecutionError(str(e)) from e
    if wp is not None and hasattr(t, "record_write_properties"):
        t.record_write_properties(wp.to_dict(), ordered=False)
    session.catalog.register(t)


def _delete_from(session, stmt: ast.Delete) -> int:
    """DELETE FROM t [WHERE pred]: evaluate the predicate over the whole
    table (a scan+project plan, preserving row order) and hand the keep
    mask to the connector (reference: MetadataDeleteOperator /
    DeleteOperator)."""
    session.access_control.check_can_delete(session.user, stmt.table)
    table = session.catalog.get(stmt.table)
    if not hasattr(table, "delete_where"):
        raise ExecutionError(f"table '{stmt.table}' does not support DELETE")
    session.txn.record_table_write(table)
    n = table.row_count()
    if stmt.where is None:
        keep = np.zeros(n, dtype=bool)
        return table.delete_where(keep)
    # SELECT <pred> FROM t  — project-only plan, row order == table order
    q = ast.Query(
        body=ast.QuerySpec(
            select=[ast.SelectItem(stmt.where, "__pred__")],
            from_=ast.Table(stmt.table)))
    arrays, _types = execute_plan_to_host(session, ast.QueryStatement(q))
    pred = next(iter(arrays.values()))
    if isinstance(pred, np.ma.MaskedArray):
        pred = pred.filled(False)
    keep = ~np.asarray(pred, dtype=bool)  # NULL predicate rows are kept
    return table.delete_where(keep)


def _collect_tablescans(node: P.PlanNode, out: list):
    if isinstance(node, P.TableScan):
        out.append(node)
    for s in node.sources:
        _collect_tablescans(s, out)


def _static_root_bound(node: P.PlanNode):
    """Row-count bound of the plan root when provable (TopN/Limit under
    Output/Project): lets the compiled program compact its output to k
    rows on device instead of shipping a scan-sized capacity to host."""
    while isinstance(node, (P.Output, P.Project)):
        node = node.source
    if isinstance(node, (P.TopN, P.Limit)) and node.count <= 1_000_000:
        return int(node.count)
    return None


def _compact_batch(out: Batch, bound: int) -> Batch:
    """Order-preserving on-device compaction to a fixed capacity.
    top_k over a positional score finds the first `bound` live rows —
    far cheaper on TPU than jnp.nonzero's cumsum+scatter lowering
    (~400ms -> ~10ms at 6M rows, measured via xplane)."""
    cap = out.sel.shape[0]
    pos = jnp.arange(cap, dtype=jnp.int32)
    score = jnp.where(out.sel, cap - pos, 0)  # earliest live = largest
    top = jax.lax.top_k(score, bound)[0]
    idx = jnp.clip(cap - top, 0, cap - 1)
    count = jnp.sum(out.sel)
    # idx is nondecreasing by construction (descending top_k scores →
    # ascending positions, dead-slot tail clips to cap-1), so the
    # materialization is one presorted packed gather — the staged tier
    # streams it through VMEM windows at chunk-compaction sizes
    raw, _ = K.take_columns(out.columns, idx, presorted=True)
    cols = {n: Column(data, valid, out.columns[n].type,
                      out.columns[n].dictionary)
            for n, (data, valid) in raw.items()}
    return Batch(cols, jnp.arange(bound) < count)


# results larger than this skip pack_fetch in favor of to_numpy's
# selective fetch (pull sel, gather survivors) — matches batch.py's
# _COMPACT_THRESHOLD reasoning
_PACK_FETCH_MAX = 262_144


def _plan_has_long_decimal(node) -> bool:
    import dataclasses as _dc

    for _s, t in node.outputs():
        if getattr(t, "is_decimal", False) and t.is_long_decimal:
            return True
    for f in _dc.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, P.PlanNode) and _plan_has_long_decimal(v):
            return True
        if isinstance(v, list) and any(
                isinstance(x, P.PlanNode) and _plan_has_long_decimal(x)
                for x in v):
            return True
    return False


import re as _re

#: functions whose value must differ between executions of the SAME query
#: text (reference: FunctionMetadata deterministic=false / the session
#: start instant).  A cached compiled program bakes their values in at
#: trace time, so volatile queries key the program caches per query.
_VOLATILE_RE = _re.compile(
    r"\b(?:now|random|rand|uuid|shuffle)\s*\("
    r"|\bcurrent_(?:date|time|timestamp)\b|\blocaltime(?:stamp)?\b"
    r"|\btablesample\b",  # lowers to a random() filter
    _re.IGNORECASE)


def _volatile_nonce(text: str) -> int:
    """0 for deterministic queries (cache shared across executions);
    the per-query sequence number otherwise (every execution retraces,
    so now()/random() are fresh — matching per-query semantics)."""
    if _VOLATILE_RE.search(text) is None:
        return 0
    from presto_tpu import session_ctx

    return session_ctx.query_seq()


def query_cache_key(session, text: str) -> tuple:
    """The per-session program-cache key shared by the compiled and
    chunked executors (and EXPLAIN ANALYZE's profiled lookups): raw
    text (whitespace normalization would merge queries differing only
    inside string literals) x catalog version x the full property map x
    the volatile nonce."""
    return (text, getattr(session.catalog, "version", 0),
            tuple(sorted((k, repr(v))
                         for k, v in session.properties.items())),
            _volatile_nonce(text))


def bind_param_values(session, params):
    """Host (value, Type) pairs -> device 0-d scalars with the dtypes the
    traced program expects.  DOUBLE follows the session's
    float32_compute lane so a bound parameter never promotes an f32
    column expression back to f64 (serving tier, server/serving.py)."""
    f32 = bool(session.properties.get("float32_compute", False))
    out = []
    for v, t in params:
        dt = t.numpy_dtype()
        if f32 and t.name == "DOUBLE":
            dt = jnp.float32
        out.append(jnp.asarray(v, dtype=dt))
    return tuple(out)


def run_compiled(session, text: str, stmt, mon=None, params=None) -> QueryResult:
    """Compiled execution: the WHOLE plan traces into one jitted XLA
    program over the scan batches (the reference compiles expressions to
    bytecode per operator, sql/gen/; we compile the entire fragment DAG —
    XLA fuses scan->filter->project->agg->join chains end to end).

    Static shapes come from connector stats (plan/stats.py).  Runtime
    guards verify the static assumptions (group capacity, join fanout);
    a tripped guard re-runs the query in dynamic eager mode.

    `params`: prepared-statement bindings as (host_value, Type) pairs
    (server/serving.py).  They trace as 0-d device scalars, so the
    executable is VALUE-free: the memo keys on their avals and a new
    binding is a device transfer, not a retrace — the caller's `text`
    must then be the type-signature key, not the rendered SQL."""
    cache = getattr(session, "_compiled_cache", None)
    if cache is None:
        cache = session._compiled_cache = {}
    host_params = tuple((v, None) for v, _t in params) \
        if params is not None else None
    key = query_cache_key(session, text)
    entry = cache.get(key)
    if entry == "DYNAMIC":  # static assumptions known-violated for this query
        plan = plan_statement(session, stmt)
        return Executor(session, monitor=mon, params=host_params).run(plan)
    if entry is None:
        plan = plan_statement(session, stmt)
        if _plan_has_long_decimal(plan.root):
            # two-limb Int128 columns don't pack through the compiled
            # fetch plane yet; the dynamic executor carries them exactly
            cache[key] = "DYNAMIC"
            return Executor(session, monitor=mon, params=host_params).run(plan)
        # uncorrelated scalar subqueries: evaluate eagerly (tiny), bake in;
        # populate ctx as we go — later subplans may reference earlier ones
        sort_counts = {}  # trace-time sort routing decisions
        ex0 = Executor(session, sort_stats=sort_counts)
        scalar_results = ex0.ctx.scalar_results
        for pid, sub in sorted(plan.subplans.items()):
            scalar_results[pid] = _single_value(ex0.exec_node(sub))
        scan_nodes: list = []
        _collect_tablescans(plan.root, scan_nodes)

        bound = _static_root_bound(plan.root)
        f32 = bool(session.properties.get("float32_compute", False))
        batches = [scan_batch(session.catalog.get(n.table), n, f32)
                   for n in scan_nodes]
        pvals = bind_param_values(session, params) \
            if params is not None else None
        # process-wide executable memo (exec/compile_cache.py): keyed by
        # the plan's serde fingerprint + catalog identity + properties +
        # scan dtype layout, so a second session (or the same SQL under
        # a different text) reuses the executable instead of retracing.
        # Baked scalar-subquery values ride the key: same catalog+plan
        # => same values, anything else must not share.
        plan_fp = CC.plan_fingerprint(
            (plan.root, sorted(plan.subplans.items())))
        gkey = None if plan_fp is None else CC.fingerprint(
            "compiled", plan_fp, CC.session_fingerprint(session),
            _volatile_nonce(text), CC.avals_fingerprint(batches),
            CC.avals_fingerprint(pvals) if pvals is not None else "",
            sorted(scalar_results.items()))

        def build():
            meta_box: list = []  # static pack layout, set at trace time

            def trace(batches, pvals):
                ex = Executor(session, static=True,
                              scan_inputs={id(n): b for n, b
                                           in zip(scan_nodes, batches)},
                              sort_stats=sort_counts)
                ex.ctx.scalar_results = scalar_results
                if pvals is not None:
                    ex.ctx.params = tuple((pv, None) for pv in pvals)
                out = ex.exec_node(plan.root)
                if bound is not None and out.sel.shape[0] > 4 * bound:
                    out = _compact_batch(out, bound)
                if ex.guards:
                    guard = jnp.any(jnp.stack(
                        [jnp.asarray(g) for g in ex.guards]))
                else:
                    guard = jnp.asarray(False)
                meta_box.clear()
                if out.capacity > _PACK_FETCH_MAX or any(
                        getattr(c.data, "ndim", 1) > 1
                        for c in out.columns.values()):
                    # unbounded root over a scan-sized capacity — or a
                    # matrix-shaped column (sketch state, Int128 limbs)
                    # the u32 pack cannot flatten: keep the Batch so
                    # to_numpy's selective fetch (pull sel, gather
                    # survivors) can avoid shipping full columns
                    meta_box.append(None)
                    return out, guard
                # flat buffer -> ONE host fetch (see kernels.pack_fetch)
                buf, meta = K.pack_fetch(out, guard)
                meta_box.append(meta)
                return buf

            # AOT lower+compile: traces now (may raise StaticFallback),
            # counts compiles/compile_ms, and loads from the persistent
            # disk cache when this program was compiled before.  The
            # parameterless signature is kept distinct so existing
            # programs keep their persistent-cache identity.
            if params is None:
                def fn(batches):
                    return trace(batches, None)

                jitted = CC.build_jit(fn, example=(batches,))
            else:
                def fn(batches, pvals):
                    return trace(batches, pvals)

                jitted = CC.build_jit(fn, example=(batches, pvals))
            return (plan, jitted, scan_nodes, meta_box[0],
                    dict(sort_counts))

        # cache only after success; sort_counts are the program's
        # trace-time routing decisions, replayed into stats per run
        entry = CC.get_or_build(gkey, build)
        cache[key] = entry
        plan, jitted, scan_nodes, meta, sort_counts = entry
        buf = jitted(batches) if params is None else jitted(batches, pvals)
    else:
        plan, jitted, scan_nodes, meta, sort_counts = entry
        f32 = bool(session.properties.get("float32_compute", False))
        batches = [scan_batch(session.catalog.get(n.table), n, f32)
                   for n in scan_nodes]
        if params is None:
            buf = jitted(batches)
        else:
            # warm prepared EXECUTE: binding is a device transfer into
            # the cached executable — no parse, no plan, no compile
            buf = jitted(batches, bind_param_values(session, params))
    if mon is not None:
        _merge_sort_stats(mon.stats, sort_counts)
    ex = Executor(session)
    if meta is None:  # sparse/unbounded result: selective to_numpy fetch
        out_batch, guard = buf
        result, guard_h = ex.materialize(plan, out_batch, extra=guard)
    else:
        # single device fetch: result columns + guard ride one buffer
        datas, sel, guard_h = K.unpack_fetch(jax.device_get(buf), meta)
        result = ex.materialize_host(plan, meta, datas, sel)
    if bool(guard_h):
        # static assumption violated (incl. a tripped ordering-claim
        # monotonicity guard); data is static so it will trip again —
        # remember to go straight to dynamic next time (no retrace loop)
        cache[key] = "DYNAMIC"
        plan2 = plan_statement(session, stmt)
        return Executor(session, monitor=mon, params=host_params).run(plan2)
    return result


class Unbatchable(Exception):
    """Raised when a prepared program's shape cannot serve a coalesced
    batch (long decimals, unbounded pack-skipping roots, trace failures
    under vmap): the coalescer's riders re-run solo — never a wrong
    result, never a stall."""


def run_compiled_batched(session, text: str, stmt, params_list,
                         mons) -> list:
    """Query coalescing's device lane: serve N concurrent EXECUTEs of
    ONE prepared signature with ONE XLA launch (server/serving.py's
    QueryCoalescer is the admission-side batcher that collects them).

    The PR-6 symbolic-parameter channel makes the prepared trace
    value-free, so batching is a `jax.vmap` of that same trace over a
    LEADING parameter axis: each rider's bound scalars stack into
    shape-(B,) arrays, the scan batches broadcast (in_axes=None — the
    table is shared, only the parameters vary), and the packed result
    buffer comes back with a leading batch axis that unstacks into
    per-rider results.  Batch sizes quantize to the next power of two
    (the PR-4 `_pow2` discipline) with pad slots filled by replaying
    rider 0's values — a padded slot computes a real (discarded) result,
    so near-identical batch sizes share ONE executable instead of
    minting a fresh compile per arrival count.  The executable memoizes
    in exec/compile_cache.py keyed by (plan fingerprint x session
    fingerprint x scan avals x stacked-parameter avals), so a warm
    coalesced batch records compiles == 0.

    `params_list`: one (host_value, Type)-pair tuple per rider, all of
    the same type signature.  `mons`: the riders' QueryMonitors (batch
    facts + sort economics are recorded per rider).  Returns one
    QueryResult per rider, in order.  Raises Unbatchable when this
    program cannot batch; the caller re-runs every rider solo."""
    from presto_tpu.exec.chunked import _pow2

    cache = getattr(session, "_coalesced_cache", None)
    if cache is None:
        cache = session._coalesced_cache = {}
    nbatch = len(params_list)
    bpad = _pow2(nbatch)
    solo_key = query_cache_key(session, text)
    if getattr(session, "_compiled_cache", {}).get(solo_key) == "DYNAMIC":
        # static assumptions known-violated for this signature: the solo
        # path already degraded to dynamic — batching would re-trip
        raise Unbatchable("signature marked DYNAMIC")
    key = (solo_key, bpad)

    def stack_params():
        cols = []
        for j in range(len(params_list[0])):
            vals = [bind_param_values(session, (p[j],))[0]
                    for p in params_list]
            vals += [vals[0]] * (bpad - nbatch)  # pad: replay rider 0
            cols.append(jnp.stack(vals))
        return tuple(cols)

    entry = cache.get(key)
    if entry is None:
        plan = plan_statement(session, stmt)
        if _plan_has_long_decimal(plan.root):
            raise Unbatchable("long-decimal output")
        sort_counts = {}
        ex0 = Executor(session, sort_stats=sort_counts)
        scalar_results = ex0.ctx.scalar_results
        for pid, sub in sorted(plan.subplans.items()):
            scalar_results[pid] = _single_value(ex0.exec_node(sub))
        scan_nodes: list = []
        _collect_tablescans(plan.root, scan_nodes)
        bound = _static_root_bound(plan.root)
        f32 = bool(session.properties.get("float32_compute", False))
        batches = [scan_batch(session.catalog.get(n.table), n, f32)
                   for n in scan_nodes]
        stacked = stack_params()
        plan_fp = CC.plan_fingerprint(
            (plan.root, sorted(plan.subplans.items())))
        gkey = None if plan_fp is None else CC.fingerprint(
            "coalesced", plan_fp, CC.session_fingerprint(session),
            CC.avals_fingerprint(batches), CC.avals_fingerprint(stacked),
            sorted(scalar_results.items()))

        def build():
            meta_box: list = []

            def trace_one(batches, pvals):
                ex = Executor(session, static=True,
                              scan_inputs={id(n): b for n, b
                                           in zip(scan_nodes, batches)},
                              sort_stats=sort_counts)
                ex.ctx.scalar_results = scalar_results
                ex.ctx.params = tuple((pv, None) for pv in pvals)
                out = ex.exec_node(plan.root)
                if bound is not None and out.sel.shape[0] > 4 * bound:
                    out = _compact_batch(out, bound)
                if ex.guards:
                    guard = jnp.any(jnp.stack(
                        [jnp.asarray(g) for g in ex.guards]))
                else:
                    guard = jnp.asarray(False)
                if out.capacity > _PACK_FETCH_MAX:
                    # the solo path's selective-fetch lane doesn't have
                    # a batched twin: results this wide stay solo
                    raise Unbatchable("result capacity exceeds the "
                                      "packed-fetch plane")
                buf, meta = K.pack_fetch(out, guard)
                meta_box.clear()
                meta_box.append(meta)
                return buf

            def fn(batches, stacked):
                return jax.vmap(
                    lambda pv: trace_one(batches, pv),
                    in_axes=(0,))(stacked)

            try:
                jitted = CC.build_jit(fn, example=(batches, stacked))
            except Unbatchable:
                raise
            except (StaticFallback, jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError) as e:
                raise Unbatchable(str(e)) from e
            return (plan, jitted, scan_nodes, meta_box[0],
                    dict(sort_counts))

        entry = CC.get_or_build(gkey, build)
        cache[key] = entry
        warm = False
    else:
        stacked = stack_params()
        warm = True
    plan, jitted, scan_nodes, meta, sort_counts = entry
    f32 = bool(session.properties.get("float32_compute", False))
    batches = [scan_batch(session.catalog.get(n.table), n, f32)
               for n in scan_nodes]
    buf, side = jax.device_get(jitted(batches, stacked))
    results = []
    any_guard = False
    for i in range(nbatch):
        datas, sel, guard_h = K.unpack_fetch(
            (buf[i], [s[i] for s in side]), meta)
        any_guard = any_guard or bool(guard_h)
        results.append(Executor(session).materialize_host(
            plan, meta, datas, sel))
    if any_guard:
        # a static assumption tripped for at least one binding; the data
        # is static so it would trip again — degrade the whole signature
        # to the dynamic path and re-run every rider solo
        scache = getattr(session, "_compiled_cache", None)
        if scache is None:
            scache = session._compiled_cache = {}
        scache[solo_key] = "DYNAMIC"
        cache.pop(key, None)
        raise Unbatchable("runtime guard tripped in batched program")
    for mon in mons:
        if mon is not None:
            _merge_sort_stats(mon.stats, sort_counts)
            mon.stats.coalesced_batch_size = nbatch
            mon.stats.execution_mode = "compiled"
            if warm:
                mon.stats.prepared_plan_hits += 1
    return results


def plan_statement(session, stmt) -> P.QueryPlan:
    """Plan + authorize: every table the plan scans is checked against
    the session's access control (reference: AccessControlManager
    .checkCanSelectFromColumns during analysis)."""
    if isinstance(stmt, (ast.CreateTableAs, ast.InsertInto)):
        # write statements plan as Output <- TableFinish <- TableWriter
        # over the (normally optimized) query plan (exec/writer.py)
        from presto_tpu.exec import writer as W

        return W.plan_write_statement(session, stmt)
    planner = Planner(session)
    plan = planner.plan_statement(stmt)
    if session.properties.get("optimizer_enabled", True):
        plan = optimize(plan, session)
    scans: list = []
    _collect_tablescans(plan.root, scans)
    for sub in plan.subplans.values():
        _collect_tablescans(sub, scans)
    for t in {n.table for n in scans}:
        session.access_control.check_can_select(session.user, t)
    return plan


def execute_plan_to_host(session, stmt):
    plan = plan_statement(session, stmt)
    ex = Executor(session)
    batch = ex.evaluate(plan)
    out = plan.root
    arrays, sel = to_numpy(batch)
    types = {}
    result = {}
    used = {}
    for name, sym in zip(out.names, out.symbols):
        n = name
        i = used.get(name, 0)
        used[name] = i + 1
        if i:
            n = f"{name}_{i}"
        a = arrays[sym]
        v = a[sel]
        # keep the mask — write sinks must see NULLs to reject/handle them
        result[n] = v if isinstance(v, np.ma.MaskedArray) else np.asarray(v)
        types[n] = dict(out.source.outputs())[sym] if sym in dict(out.source.outputs()) else T.VARCHAR
    return result, types


def explain_text(session, stmt) -> str:
    plan = plan_statement(session, stmt)
    from presto_tpu.plan import stats as S

    memo = {}

    def ann(node):
        try:
            st = S.derive(node, session.catalog, memo)
            return f"  {{rows: {st.est_rows:,.0f}}}"
        except Exception:
            return ""

    lines = [P.plan_tree_str(plan.root, annotate=ann)]
    for pid, sub in sorted(plan.subplans.items()):
        lines.append(f"\nSubplan {pid}:")
        lines.append(P.plan_tree_str(sub, 1, annotate=ann))
    return "\n".join(lines)


def _count_placeholders(sql: str) -> int:
    n = 0
    in_str = False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
        elif ch == "?" and not in_str:
            n += 1
    return n


def explain_distributed_text(session, stmt) -> str:
    """EXPLAIN (TYPE DISTRIBUTED): fragment the optimized plan the way
    the cluster scheduler would and print each fragment (reference:
    PlanPrinter.textDistributedPlan over SubPlan fragments)."""
    from presto_tpu.parallel.cluster import cut_fragments
    from presto_tpu.plan.distribute import Undistributable, distribute

    plan = plan_statement(session, stmt)
    ndev = int(session.properties.get("explain_ndev", 8))
    try:
        dplan = distribute(plan, session, ndev)
    except Undistributable as e:
        return (f"single fragment (undistributable: {e})\n\n"
                + explain_text(session, stmt))
    lines = []
    for f in cut_fragments(dplan.root):
        lines.append(f"Fragment {f.fid}:")
        lines.append(P.plan_tree_str(f.root, 1))
        lines.append("")
    for pid, sub in sorted(dplan.subplans.items()):
        lines.append(f"Subplan {pid}:")
        lines.append(P.plan_tree_str(sub, 1))
        lines.append("")
    return "\n".join(lines).rstrip()


def explain_analyze_text(session, stmt, mon) -> str:
    """EXPLAIN ANALYZE, profiled per execution mode.

    dynamic/auto: execute eagerly with per-node stats and render the
    plan annotated with rows/time (reference: ExplainAnalyzeOperator +
    PlanPrinter stats rendering) — the richest attribution, one host
    sync per operator.

    compiled/chunked (execution_mode set accordingly): execute through
    the REAL compiled path, then attach per-fragment measured wall plus
    XLA cost analysis (FLOPs, HBM bytes, roofline-estimated wall) read
    off the fragment executables — the compiler-sourced attribution for
    programs that have no per-operator boundary at runtime.  Cluster
    mode has its own path (parallel/cluster.ClusterSession handles
    EXPLAIN ANALYZE with per-task attribution from worker spans)."""
    from presto_tpu.observe.stats import annotated_plan

    mode = str(session.properties.get("execution_mode", "auto"))
    if mode == "compiled":
        return _explain_analyze_compiled(session, stmt, mon)
    if mode == "chunked":
        return _explain_analyze_chunked(session, stmt, mon)
    mon.stats.execution_mode = "dynamic"
    mon.collect_node_stats = True  # ANALYZE implies per-node stats
    with mon.phase("plan"):
        plan = plan_statement(session, stmt)
    with mon.phase("execute"):
        ex = Executor(session, monitor=mon)
        result = ex.run(plan)
    mon.stats.output_rows = len(result)
    mon.rows_preset = True  # finish() must not overwrite with the 1-row plan text
    return annotated_plan(plan.root, plan.subplans, mon.stats)


def _phase_summary(stats) -> str:
    ph = ", ".join(f"{k}: {v / 1e6:.1f}ms"
                   for k, v in stats.phase_ns.items())
    return (f"Query {stats.query_id}: {ph}; output rows: "
            f"{stats.output_rows}")


def _explain_analyze_compiled(session, stmt, mon) -> str:
    """Profiled EXPLAIN ANALYZE through run_compiled: the whole plan is
    ONE fused XLA program (one 'fragment'); its cost analysis comes off
    the AOT executable the compiled cache holds."""
    from presto_tpu.observe import profile as PR
    from presto_tpu.observe.stats import trace_summary_line

    mon.stats.execution_mode = "compiled"
    text = mon.stats.sql  # a valid (distinct) program-cache key
    with mon.phase("execute"):
        result = run_compiled(session, text, stmt, mon=mon)
    mon.stats.output_rows = len(result)
    mon.rows_preset = True
    wall_ms = mon.stats.phase_ns.get("execute", 0) / 1e6
    entry = getattr(session, "_compiled_cache", {}).get(
        query_cache_key(session, text))
    lines = []
    if entry is None or entry == "DYNAMIC":
        # static assumptions were violated: the query really ran on the
        # dynamic path — say so instead of attributing a program that
        # never executed
        plan = plan_statement(session, stmt)
        lines.append(P.plan_tree_str(plan.root))
        lines.append("\nFragment 0 (compiled -> DYNAMIC fallback: "
                     "static assumptions violated):")
        lines.append(f"   {PR.cost_line(None, wall_ms, 'dynamic re-run')}")
    else:
        plan, jitted, _scan_nodes, _meta, _sort_counts = entry
        lines.append(P.plan_tree_str(plan.root))
        for pid, sub in sorted(plan.subplans.items()):
            lines.append(f"\nSubplan {pid} (evaluated eagerly, baked "
                         "into the trace):")
            lines.append(P.plan_tree_str(sub, 1))
        cost = PR.executable_cost(jitted)
        lines.append("\nFragment 0 (compiled, whole plan as one fused "
                     "XLA program):")
        lines.append(f"   {PR.cost_line(cost, wall_ms)}")
    lines.append("")
    lines.append(_phase_summary(mon.stats))
    lines.append(trace_summary_line(mon.stats))
    return "\n".join(lines)


def _explain_analyze_chunked(session, stmt, mon) -> str:
    """Profiled EXPLAIN ANALYZE through the chunked executor: one
    attribution block per fragment — measured wall from the per-run
    fragment timings, XLA cost analysis summed over the fragment's
    program family (chunk-loop + fold + compact executables)."""
    from presto_tpu.exec import chunked as CH
    from presto_tpu.observe import profile as PR
    from presto_tpu.observe.stats import trace_summary_line

    mon.stats.execution_mode = "chunked"
    text = mon.stats.sql
    with mon.phase("execute"):
        result = CH.run_chunked(session, stmt, text, mon=mon)
    mon.stats.output_rows = len(result)
    mon.rows_preset = True
    entry = getattr(session, "_chunked_cache", {}).get(
        query_cache_key(session, text))
    lines = []
    if entry is None:
        lines.append("(chunked prepared state unavailable)")
    else:
        _dplan, frags, runner, _table_family, _consumer_eid = entry
        def frag_key(key, fid):
            # runner._jit keys: (fid, mult) for the main program,
            # ("fold"|"compact"|"mesh", fid, ...) for the auxiliaries
            if not isinstance(key, tuple):
                return key == fid
            if key[0] in ("fold", "compact", "mesh"):
                return len(key) >= 2 and key[1] == fid
            return key[0] == fid

        for frag in frags:
            wall_ns = runner.frag_wall_ns.get(frag.fid, 0)
            cost = PR.merge_costs(
                PR.executable_cost(ex)
                for key, ex in runner._jit.items()
                if frag_key(key, frag.fid))
            note = "dynamic fragment" \
                if frag.fid in runner.dynamic_fids else ""
            lines.append(f"Fragment {frag.fid} (chunked"
                         + (", dynamic" if note else "") + "):")
            lines.append(f"   {PR.cost_line(cost, wall_ns / 1e6, note)}")
            lines.append(P.plan_tree_str(frag.root, 1))
            lines.append("")
    lines.append(_phase_summary(mon.stats))
    lines.append(trace_summary_line(mon.stats))
    return "\n".join(lines)


def explain_query(session, text: str, analyze: bool = False) -> str:
    stmt = parse(text)
    if isinstance(stmt, ast.Explain):
        analyze = analyze or stmt.analyze
        stmt = stmt.statement
    if analyze:
        from presto_tpu.observe import profile as PR
        from presto_tpu.observe import trace as TR
        from presto_tpu.observe.stats import QueryMonitor

        mon = QueryMonitor.begin(session, text)
        try:
            with TR.activate(mon.tracer), PR.maybe_profile(session):
                text_plan = explain_analyze_text(session, stmt, mon)
        except BaseException as e:
            mon.fail(e)
            raise
        mon.finish(None)
        return text_plan
    return explain_text(session, stmt)


class Executor:
    # index joins assume whole-table natural-order build batches; sharded
    # executors (DistExecutor, cluster FragmentExecutor) re-split scans
    # and must turn this off (the layout guard would catch it anyway, at
    # the cost of a spurious whole-query dynamic fallback)
    allow_index_join = True

    def __init__(self, session, static: bool = False, scan_inputs=None,
                 monitor=None, mem=None, sort_stats=None, params=None):
        self.session = session
        self.static = static  # compiled mode: no host syncs, static shapes
        self.scan_inputs = scan_inputs  # {node id: Batch} traced jit args
        self.guards = []  # traced bools: True => static assumption violated
        # ordering-aware execution state (plan/properties.py):
        # - sort economics counters (flow into QueryStats)
        # - the per-trace sort-permutation memo: key fingerprint ->
        #   (refs, (skey, order)) so a key sorted once in a fragment is
        #   never sorted again (refs hold the fingerprinted arrays
        #   alive, so a recycled id() can never alias a dead entry)
        # - the runtime CERTAIN-ordering channel: id(Batch) -> (batch,
        #   keys) for orderings this executor constructed itself
        #   (grouped output with an exact pack layout, sort output) —
        #   the only claims Sort/TopN elision may trust without a guard
        self.sort_stats = sort_stats if sort_stats is not None else {
            "sorts_taken": 0, "sorts_elided": 0, "sort_memo_hits": 0,
            "ordering_guard_trips": 0}
        self._sort_memo: Dict[tuple, tuple] = {}
        self._perm_memo: Dict[tuple, tuple] = {}
        # group-id mapping memo (round 17): key fingerprint ->
        # (refs, (gid, rep_rows, n_groups)) — a repeat grouping over
        # identical key arrays (AVG/STDDEV fold passes over a resident
        # build) replays the mapping instead of rebuilding the group
        # index; refs pin the fingerprinted arrays (id-reuse aliasing,
        # same discipline as _sort_memo)
        self._gid_memo: Dict[tuple, tuple] = {}
        self._batch_order: Dict[int, tuple] = {}
        # dynamic filtering (plan/runtime_filters.py): filter id ->
        # device summary (exec/kernels.rf_build), registered by producer
        # joins BEFORE their probe subtree executes; _rf_host carries the
        # host-side min/max Domain for stripe/zone-map pruning (dynamic
        # mode only — static mode must stay sync-free)
        self._rf: Dict[str, dict] = {}
        self._rf_host: Dict[str, object] = {}
        # static mode: expression-level overflow checks (decimal casts)
        # append to the SAME guard list, so a violation aborts the
        # compiled program to the dynamic path, which raises properly
        self.ctx = EvalContext(guards=self.guards if static else None)
        # prepared-statement parameters (server/serving.py): position ->
        # (value, valid) pairs ir.Param evaluation reads
        self.ctx.params = params
        self.monitor = monitor  # QueryMonitor collecting per-node stats
        # memory accounting: only for monitored (top-level) executions —
        # helper executors (subplan eval, CTAS materialization) must not
        # leave reservations behind, since only run() releases them
        if mem is None and not static and monitor is not None:
            from presto_tpu.memory import MemoryPool, QueryMemoryContext

            pool_cap = int(session.properties.get("memory_pool_bytes", 16 << 30))
            with _pool_init_lock:
                pool = getattr(session, "_memory_pool", None)
                if pool is None:
                    pool = session._memory_pool = MemoryPool(pool_cap)
            pool.capacity = pool_cap  # honor property changes mid-session
            mem = QueryMemoryContext(
                monitor.stats.query_id, pool,
                int(session.properties.get("query_max_memory_bytes", 4 << 30)))
        self.mem = mem

    # aggregates whose VALUE depends on input row order (beyond float
    # rounding): reordering their input would change results, not just
    # permute them
    _ORDER_SENSITIVE_AGGS = frozenset({
        "array_agg", "map_agg", "multimap_agg", "arbitrary", "any_value"})

    def mark_order_insensitive(self, root: P.PlanNode, root_flag: bool):
        """Precompute which plan nodes may emit their output in ANY row
        order — the hint behind sort-order materialization (gather.py):
        a join below an aggregation can leave its rows in sorted-gather
        order and skip the inverse permutation, because grouping sorts
        by key anyway and semi-join membership is a set question.

        `root_flag` says whether the ROOT's own output order is free
        (chunked partial fragments feeding a final aggregate/TopN: yes;
        a whole query's result rows: no).  The walk ANDs over every
        path to a node, so a shared DAG subtree feeding one
        order-sensitive consumer stays unmarked."""
        flags: Dict[int, bool] = {}

        def walk(node, flag):
            prev = flags.get(id(node))
            flags[id(node)] = flag if prev is None else (prev and flag)
            t = type(node).__name__
            if t == "Aggregate":
                # an ordering-exploiting aggregate (presorted grouping
                # hint) WANTS its input order: sort-order-materializing
                # joins below it would scramble the claimed ordering and
                # trade the elided grouping sort for a guard trip
                walk(node.source, not any(
                    a.fn in self._ORDER_SENSITIVE_AGGS
                    for a in node.aggs.values())
                    and getattr(node, "ordering_hint", None) is None)
            elif t in ("Filter", "Project", "Output"):
                # row-wise: input permutation = same output permutation
                walk(node.source, flag)
            elif t == "Join":
                walk(node.left, flag)
                # SEMI/ANTI/MARK consume the build side as a SET
                walk(node.right, True if node.join_type in
                     ("SEMI", "ANTI", "MARK") else flag)
            elif t == "Union":
                for s in node.sources_:
                    walk(s, flag)
            else:
                # Sort/TopN/Limit/Window/Unnest/...: input order shows
                # through (tie-breaking, first-n, frames) — conservative
                for s in getattr(node, "sources", []):
                    walk(s, False)

        walk(root, root_flag)
        self._oi_ids = {i for i, f in flags.items() if f}

    def _order_ok(self, node) -> bool:
        oi = getattr(self, "_oi_ids", None)
        return oi is not None and id(node) in oi

    # ---- ordering-aware execution plumbing ---------------------------
    def _count(self, key: str, n: int = 1) -> None:
        self.sort_stats[key] = self.sort_stats.get(key, 0) + n

    def _ordering_enabled(self) -> bool:
        return bool(self.session.properties.get(
            "ordering_aware_execution", True))

    # ---- dynamic filtering (plan/runtime_filters.py) -----------------
    def _df_enabled(self) -> bool:
        from presto_tpu.plan import runtime_filters as RF

        return RF.enabled(self.session)

    def rf_inject(self, summaries: Dict[str, dict]) -> None:
        """Register remotely produced filter summaries (the cluster side
        channel) so probe scans in this executor consume them."""
        self._rf.update(summaries)

    def _rf_build_complete(self, node) -> bool:
        """May this executor derive a filter from the join's build batch?
        True iff the batch it will see is the COMPLETE build key set.
        Single-device executors always see the whole build; sharded
        executors (DistExecutor, the cluster FragmentExecutor) override
        this — a shard/bucket/split-local build is a PARTIAL key set,
        and a membership filter over a partial set would prune probe
        rows that match on other shards."""
        return True

    def _rf_register(self, specs, right: Batch) -> None:
        """Producer side: derive + register the build-key summaries of
        one join.  Skips keys the kernels can't summarize (dictionary
        codes, float storage, limb pairs) — the consumer then simply
        never finds the id and runs filter-free."""
        for spec in specs:
            col = right.columns.get(spec["build_sym"])
            if col is None or col.dictionary is not None \
                    or getattr(col.data, "ndim", 1) != 1 \
                    or jnp.issubdtype(col.data.dtype, jnp.floating):
                continue
            live = right.sel
            if col.valid is not None:
                live = live & col.valid
            self._rf[spec["fid"]] = K.rf_build(col, live)
            self._count("df_filters_produced")
            if not self.static:
                # LAZY host min/max domain for stripe/zone-map pruning:
                # the refs are stashed and only synced if a consumer
                # scan's table actually supports domain pushdown —
                # generator/device tables never pay the fetch
                self._rf_host[spec["fid"]] = (col, live)

    def _rf_host_domain(self, fid: str):
        entry = self._rf_host.get(fid)
        if entry is None:
            return None
        from presto_tpu.storage.shard import Domain

        if isinstance(entry, Domain):
            return entry
        col, live = entry
        lo, hi = (int(v) for v in jax.device_get(K.rf_domain(col, live)))
        dom = Domain(lo, hi) if lo <= hi else Domain(values=[])
        self._rf_host[fid] = dom
        return dom

    def _rf_scan_domains(self, node: P.TableScan):
        """{source column: Domain} of runtime filters consumable by this
        scan as zone-map constraints (dynamic mode only; the caller
        checks the table supports pushdown before we pay any sync)."""
        specs = getattr(node, "rf_consume", None)
        if not specs or self.static or not self._df_enabled():
            return None
        out = {}
        for spec in specs:
            dom = self._rf_host_domain(spec["fid"])
            col = spec.get("column")
            if dom is not None and col is not None:
                out[col] = dom
        return out or None

    def _rf_apply(self, node: P.TableScan, b: Batch) -> Batch:
        """Consumer side: AND every registered filter's membership mask
        into the scan's sel.  Unproduced ids are skipped — dynamic
        filtering is strictly best-effort and never changes results."""
        specs = getattr(node, "rf_consume", None)
        if not specs or not self._df_enabled():
            return b
        sel = b.sel
        applied = False
        for spec in specs:
            summary = self._rf.get(spec["fid"])
            if summary is None:
                continue
            col = b.columns.get(spec["sym"])
            if col is None or col.dictionary is not None \
                    or getattr(col.data, "ndim", 1) != 1 \
                    or jnp.issubdtype(col.data.dtype, jnp.floating):
                continue
            mask = K.rf_probe(summary, col)
            if self.static:
                sel = sel & mask  # counted at trace time only
            else:
                sel2 = sel & mask
                # ONE host fetch for both counts (dynamic mode only)
                before, after = jax.device_get((jnp.sum(sel),
                                                jnp.sum(sel2)))
                self._count("df_rows_pruned", int(before) - int(after))
                sel = sel2
            self._count("df_filters_applied")
            applied = True
        if not applied:
            return b
        out = b.with_sel(sel)
        # masking never moves rows; like Filter it punches interior holes
        self._copy_order(b, out, tail_ok=False)
        return out

    def _key_fp(self, cols, sel, layout):
        """(fingerprint, refs) identifying a packed key by the IDENTITY
        of its source arrays + pack layout — the sort-permutation memo
        key.  refs must be stored with the memo entry so the
        fingerprinted objects stay alive (id() reuse would otherwise
        alias entries).  None fp => not fingerprintable (2-D limbs)."""
        parts = []
        refs = [sel]
        for c in cols:
            d = c.data
            if getattr(d, "ndim", 1) != 1:
                return None, ()
            parts.append((id(d),
                          None if c.valid is None else id(c.valid)))
            refs.append(d)
            if c.valid is not None:
                refs.append(c.valid)
        lay = None if layout is None else tuple(tuple(x) for x in layout)
        return (tuple(parts), id(sel), lay), tuple(refs)

    def _memo_pair(self, key, fp, refs):
        """(skey, order) for a packed key, through the memo: the second
        and later group-bys/joins on the same key ride the cached
        permutation instead of re-sorting."""
        if not self._ordering_enabled():
            fp = None  # kill switch disables the memo too
        entry = self._sort_memo.get(fp) if fp is not None else None
        if entry is not None:
            self._count("sort_memo_hits")
            self._count("sorts_elided")
            return entry[1]
        self._count("sorts_taken")
        pair = K.sort_pair(key)
        if fp is not None:
            self._sort_memo[fp] = (refs, pair)
        return pair

    def _note_order(self, batch: Batch, keys, tail_ok: bool = True) -> None:
        """Record a CERTAIN output ordering this executor constructed
        (sorted over live rows on `keys`: tuple of (symbol, asc)).
        tail_ok: masked rows are confined to a suffix, so the FULL
        array (sentinels included) is nondecreasing once packed — what
        a presorted join build needs; live-row order alone (tail_ok
        False after a filter) still satisfies Sort/TopN elision."""
        if keys:
            self._batch_order[id(batch)] = (batch, tuple(keys), tail_ok)

    def _copy_order(self, src: Batch, dst: Batch, tail_ok=None) -> None:
        e = self._batch_order.get(id(src))
        if e is not None and e[0] is src:
            self._note_order(dst, e[1],
                             e[2] if tail_ok is None else (e[2] and tail_ok))

    def _order_satisfies(self, b: Batch, want) -> bool:
        """Does the runtime-certain ordering of `b` satisfy the
        requested sort keys?  `want`: list of (sym, asc, nulls_first).
        Requires the request to be a prefix of the known ordering and,
        because packed orderings place the NULL group first while SQL
        defaults differ, null-free key columns (valid is None)."""
        e = self._batch_order.get(id(b))
        if e is None or e[0] is not b:
            return False
        have = e[1]
        if len(want) > len(have):
            return False
        for (sym, asc, _nf), (hsym, hasc) in zip(want, have):
            if sym != hsym or bool(asc) != bool(hasc):
                return False
            col = b.columns.get(sym)
            if col is None or col.valid is not None:
                return False
        return True

    def _build_order_certain(self, node, right: Batch, rkeys) -> bool:
        """Runtime-certain presorted build: this executor constructed
        `right` sorted on the join key with masked rows in a suffix
        (e.g. a grouped output joined on its leading group key)."""
        if len(node.criteria) != 1 or rkeys[0].valid is not None:
            return False
        e = self._batch_order.get(id(right))
        if e is None or e[0] is not right or not e[2]:
            return False
        keys = e[1]
        rk = node.criteria[0][1]
        return bool(keys) and keys[0] == (rk, True)

    def _build_presorted(self, node, right: Batch, rkeys) -> bool:
        if len(node.criteria) != 1:
            return False
        return bool(getattr(node, "build_ordering_hint", False)) \
            or self._build_order_certain(node, right, rkeys)

    @staticmethod
    def _agg_pack_order(node, group_keys):
        """Key pack order: a presorted-input hint rotates the sorted
        key run to the front (most significant — kernels pack
        first-key-major), so the packed key is monotone whenever the
        claim + the remaining keys' functional dependence hold; the
        guard verifies both at once."""
        order = getattr(node, "ordering_pack_order", None) \
            if node is not None else None
        if order is not None and sorted(order) == sorted(group_keys):
            return list(order)
        hint = getattr(node, "ordering_hint", None) if node is not None \
            else None
        if hint is not None and hint in group_keys:
            return [hint] + [k for k in group_keys if k != hint]
        return list(group_keys)

    # ------------------------------------------------------------------
    def run(self, plan: P.QueryPlan) -> QueryResult:
        if self.monitor is not None:
            self.monitor.plan = plan  # rendered at finish (UI plan pane)
        try:
            batch = self.evaluate(plan)
            return self.materialize(plan, batch)
        finally:
            if self.monitor is not None:
                _merge_sort_stats(self.monitor.stats, self.sort_stats)
            if self.mem is not None:
                if self.monitor is not None:
                    self.monitor.stats.peak_memory_bytes = self.mem.peak
                self.mem.release_all()

    def materialize(self, plan: P.QueryPlan, batch: Batch,
                    extra=None):
        """Batch -> QueryResult; `extra` (e.g. a guard scalar) rides the
        same device fetch, saving a tunnel round trip."""
        if extra is not None:
            arrays, sel, extra_h = to_numpy(batch, extra)
        else:
            arrays, sel = to_numpy(batch)
        result = self._format_result(plan, arrays, sel)
        return (result, extra_h) if extra is not None else result

    def materialize_host(self, plan: P.QueryPlan, meta: dict,
                         datas: Dict[str, tuple], sel) -> QueryResult:
        """Materialize from an unpack_fetch result (host numpy arrays):
        dictionary/decimal decode, then row formatting."""
        arrays = {}
        for name, _dtype_s, _words, _has_valid, typ, dic in meta["cols"]:
            data, valid = datas[name]
            arrays[name] = decode_host_column(data, valid, typ, dic)
        return self._format_result(plan, arrays, sel)

    def _format_result(self, plan: P.QueryPlan, arrays, sel) -> QueryResult:
        out = plan.root
        cols = []
        rows_data = []
        out_types = dict(out.source.outputs())
        for name, sym in zip(out.names, out.symbols):
            cols.append((name, out_types.get(sym, T.VARCHAR)))
            a = arrays[sym]
            vals = a[sel]
            rows_data.append(vals)
        rows = []
        n = len(rows_data[0]) if rows_data else 0
        for i in range(n):
            row = []
            for a in rows_data:
                v = a[i] if not np.ma.is_masked(a[i]) else None
                if isinstance(v, np.generic):
                    v = v.item()
                row.append(v)
            rows.append(tuple(row))
        return QueryResult(cols, rows)

    def evaluate(self, plan: P.QueryPlan) -> Batch:
        # evaluate scalar subplans first (dependency order is registration order)
        for pid, sub in sorted(plan.subplans.items()):
            b = self.exec_node(sub)
            val, valid = _single_value(b)
            self.ctx.scalar_results[pid] = (val, valid)
        return self.exec_node(plan.root)

    # ------------------------------------------------------------------
    def exec_node(self, node: P.PlanNode) -> Batch:
        if getattr(node, "shared_subtree", False):
            # plan DAGs (transitive semi-join inference shares the
            # filter subquery between both join sides): run once
            cache = getattr(self, "_shared_results", None)
            if cache is None:
                cache = self._shared_results = {}
            hit = cache.get(id(node))
            if hit is not None and hit[0] is node:
                return hit[1]
            b = self._exec_node_inner(node)
            cache[id(node)] = (node, b)
            return b
        return self._exec_node_inner(node)

    def _exec_node_inner(self, node: P.PlanNode) -> Batch:
        method = getattr(self, f"_exec_{type(node).__name__.lower()}", None)
        if method is None:
            raise ExecutionError(f"no executor for {type(node).__name__}")
        node_stats = self.monitor is not None and self.monitor.collect_node_stats
        if not node_stats and self.mem is None:
            # jax.named_scope at the operator-lowering site: inside a
            # static trace every op this node emits is scoped under the
            # plan-node name, so profiler timelines (PRESTO_TPU_PROFILE)
            # map back to plan nodes even though the compiled program is
            # one fused blob.  Trace-time only — a warm compiled run
            # never re-enters this path, so the hot loop pays nothing.
            with jax.named_scope(type(node).__name__):
                return method(node)
        # node stats collection (reference: OperationTimer around every
        # operator call, operator/Driver.java:380); the row count forces a
        # device sync, which is why it is opt-in / EXPLAIN ANALYZE only
        from presto_tpu.memory.context import batch_bytes
        from presto_tpu.observe import trace as _TR

        t0 = _TR.clock_ns()
        with jax.named_scope(type(node).__name__):
            b = method(node)
        if self.mem is not None:
            # live-set accounting: a node's output is resident until the
            # parent consumes it; child outputs die here (GC'd by Python,
            # mirroring operator page hand-off in Driver.processInternal)
            self.mem.set_bytes(id(node), batch_bytes(b))
            for child in node.sources:
                self.mem.set_bytes(id(child), 0)
        if node_stats:
            rows = int(b.row_count())
            self.monitor.record_node(node, rows, _TR.clock_ns() - t0)
        return b

    def _exec_window(self, node: P.Window) -> Batch:
        from presto_tpu.exec.window import execute_window

        return execute_window(self, node)

    # ---- leaves ------------------------------------------------------
    def _exec_tablescan(self, node: P.TableScan) -> Batch:
        if self.scan_inputs is not None:
            return self._rf_apply(node, self.scan_inputs[id(node)])
        table = self.session.catalog.get(node.table)
        rdoms = self._rf_scan_domains(node) \
            if getattr(table, "supports_domain_pushdown", False) else None
        if rdoms and hasattr(table, "pruned_stats"):
            # runtime domains intersected with the static scan_domains
            # prune EXTRA stripes — count only the delta the runtime
            # half removed (the static half prunes with filtering off)
            from presto_tpu.plan.domains import merge_domain_maps

            static = getattr(node, "scan_domains", None)
            kept_static, _tot = table.pruned_stats(static or None)
            kept_merged, _tot = table.pruned_stats(
                merge_domain_maps(static or {}, rdoms))
            self._count("df_splits_pruned",
                        max(kept_static - kept_merged, 0))
        b = scan_batch(
            table, node,
            bool(self.session.properties.get("float32_compute", False)),
            runtime_domains=rdoms)
        return self._rf_apply(node, b)

    def _exec_values(self, node: P.Values) -> Batch:
        arrays = {}
        valids = {}
        types = {}
        n = len(node.rows)
        collection_cols = {}
        for j, (sym, t) in enumerate(zip(node.symbols, node.types_)):
            vals = [r[j] for r in node.rows]
            if t.name in ("ARRAY", "MAP", "ROW"):
                # collection literals (folded ARRAY[..]/MAP(..) ctors):
                # dictionary-encode the tuple values like any column
                from presto_tpu.functions.scalar import _colval_from_pylist

                collection_cols[sym] = to_column(
                    _colval_from_pylist(vals, t), n)
                continue
            mask = np.asarray([v is not None for v in vals])
            if t.is_string:
                arr = np.asarray([v if v is not None else "" for v in vals], dtype=object)
            else:
                arr = np.asarray([v if v is not None else 0 for v in vals],
                                 dtype=t.numpy_dtype())
            arrays[sym] = arr
            types[sym] = t
            if not mask.all():
                valids[sym] = mask
        b = batch_from_numpy(arrays, types, valids or None) if arrays \
            else Batch({}, jnp.ones((n,), bool))
        if collection_cols:
            cols = dict(b.columns)
            cols.update(collection_cols)
            b = Batch(cols, b.sel)
        return b

    # ---- row-wise ----------------------------------------------------
    def _exec_filter(self, node: P.Filter) -> Batch:
        b = self.exec_node(node.source)
        mask = eval_predicate(node.predicate, b, self.ctx)
        out = b.with_sel(b.sel & mask)
        # masking never moves rows, but it punches interior holes
        self._copy_order(b, out, tail_ok=False)
        return out

    def _exec_project(self, node: P.Project) -> Batch:
        b = self.exec_node(node.source)
        cols = {}
        for sym, e in node.assignments.items():
            v = eval_expr(e, b, self.ctx)
            cols[sym] = to_column(v, b.capacity)
        out = Batch(cols, b.sel)
        src_order = self._batch_order.get(id(b))
        if src_order is not None and src_order[0] is b:
            # row-wise: certain orderings survive under identity (Ref)
            # renames up to the first non-Ref key
            renames = {}
            for sym, e in node.assignments.items():
                if isinstance(e, ir.Ref):
                    renames.setdefault(e.name, sym)
            mapped = []
            for sym, asc in src_order[1]:
                if sym not in renames:
                    break
                mapped.append((renames[sym], asc))
            self._note_order(out, tuple(mapped), tail_ok=src_order[2])
        return out

    # ---- aggregation -------------------------------------------------
    def _exec_aggregate(self, node: P.Aggregate) -> Batch:
        from presto_tpu.memory.context import batch_bytes

        b = self.exec_node(node.source)
        strat = getattr(node, "agg_strategy", None)
        if strat and node.group_keys and node.step != "FINAL":
            # planner strategy counter (plan/agg_strategy.py) — counted
            # where the aggregate EXECUTES (trace-time in static mode,
            # like the sort economics); FINAL merges are the other half
            # of an already-counted two-phase pair
            self._count("agg_strategy::" + strat)
        if any(a.distinct for a in node.aggs.values()):
            return self._exec_aggregate_with_distinct(node, b)
        # monitored chunked lane (exec/chunked.py): record the live row
        # count INTO the first PARTIAL stage as a traced scalar — the
        # runner's reduction-ratio monitor reads it per chunk
        if getattr(self, "capture_partial_agg_rows", False) \
                and node.step == "PARTIAL" and node.group_keys \
                and getattr(self, "captured_agg_rows", None) is None:
            self.captured_agg_rows = jnp.sum(b.sel, dtype=jnp.int32)
        # adaptive partial-aggregation bypass (plan/agg_strategy.py):
        # consulted BEFORE spill planning, so a bypassed partial never
        # builds grouped state or reserves revocable memory
        flip = self._pa_flip_state(node)
        if flip is not None and flip.bypassed and not flip.probe_due():
            flip.note_bypassed()
            self._count("partial_aggs_bypassed")
            return self._pa_passthrough(node, b)
        rows_in = None
        if flip is not None:
            # device scalar now (the spill path may free b); host-synced
            # only after the grouped pass ran
            rows_in = jnp.sum(b.sel, dtype=jnp.int64)
        if node.group_keys and not self.static:
            from presto_tpu.exec import spill_exec as SE

            # hash/agg state is ~2x its input in the worst case
            dec = SE.plan_degradation(
                self, node, SE.WORKING_SET_FACTOR * batch_bytes(b),
                b.capacity)
            if dec.degrade:
                holder = [b]
                del b  # holder owns the only reference; spill path frees it
                return SE.hybrid_aggregate(self, node, holder, dec)
            if dec.mem_key:
                try:
                    out = self._aggregate(b, node.group_keys, node.aggs,
                                          node)
                finally:
                    # converted revocable operator-state reservation
                    self.mem.set_bytes(dec.mem_key, 0)
                self._pa_observe(flip, rows_in, out)
                return out
        out = self._aggregate(b, node.group_keys, node.aggs, node)
        self._pa_observe(flip, rows_in, out)
        return out

    # ---- adaptive partial aggregation (plan/agg_strategy.py) ---------
    def _pa_flip_state(self, node):
        """The hysteresis flip state for a bypassable PARTIAL aggregate,
        or None (static traces make their flip decisions in the chunked
        runner, outside the program)."""
        if self.static or getattr(node, "step", "SINGLE") != "PARTIAL" \
                or not node.group_keys:
            return None
        from presto_tpu.plan import agg_strategy as AS

        if not AS.enabled(self.session):
            return None
        return AS.flip_state(self.session, node)

    def _pa_passthrough(self, node: P.Aggregate, b: Batch) -> Batch:
        """Serve a bypassed PARTIAL aggregate: every live row projected
        into the partial-output schema (count -> 0/1, sum -> x, ...) —
        no group build; the FINAL stage re-groups the raw stream."""
        from presto_tpu.plan import agg_strategy as AS

        proj = AS.passthrough_project(node)
        cols = {}
        for sym, e in proj.assignments.items():
            cols[sym] = to_column(eval_expr(e, b, self.ctx), b.capacity)
        return Batch(cols, b.sel)

    def _pa_observe(self, flip, rows_in, out: Batch) -> None:
        """Feed the grouped pass's reduction ratio into the flip state
        (one host fetch; dynamic mode only — callers pass flip=None in
        static traces).  The spill path skips observation: a degraded
        build's partition-local group counts are not the fragment
        ratio."""
        if flip is None or rows_in is None:
            return
        from presto_tpu.plan import agg_strategy as AS

        groups = int(out.capacity)  # dynamic grouping: sel == ones(n)
        rows = int(jax.device_get(rows_in))
        ratio = rows / max(groups, 1)
        self.sort_stats["partial_agg_ratio"] = ratio
        event = flip.observe(ratio, AS.min_reduction(self.session))
        if event == "flipped":
            self._count("partial_aggs_bypassed")
        elif event == "reenabled":
            self._count("partial_aggs_reenabled")

    # ---- spill / grouped execution (exec/spill_exec.py) --------------
    def _make_spiller(self):
        from presto_tpu.memory.spill import (FileSpiller, SpillCipher,
                                             SpillSpaceTracker,
                                             default_spill_dir)

        path = self.session.properties.get("spill_path") or default_spill_dir()
        tracker = getattr(self.session, "_spill_tracker", None)
        if tracker is None:
            tracker = self.session._spill_tracker = SpillSpaceTracker(
                int(self.session.properties.get("max_spill_bytes", 64 << 30)))
        tracker.max_bytes = int(
            self.session.properties.get("max_spill_bytes", 64 << 30))
        cipher = None
        if self.session.properties.get("spill_encryption", False):
            cipher = SpillCipher()  # ephemeral per-query key
        return FileSpiller(
            path, tracker, cipher,
            verify_writes=bool(self.session.properties.get(
                "spill_verify_writes", False)))

    def _grouped_recovery(self, nparts: int):
        """Per-bucket checkpoint hooks for recoverable grouped execution
        (reference: RECOVERABLE_GROUPED_EXECUTION lifespans re-scheduled
        after a node dies, StageExecutionDescriptor.java:26 — here a
        re-run resumes from completed buckets on disk).  Also carries
        the fault-injection hook used to test it.  Returns
        (load, store, bucket_done, finish)."""
        from presto_tpu.memory.spill import (default_spill_dir, load_batch,
                                             save_batch)

        # "auto" (the session default) means ON only for CLUSTER
        # durable-exchange recovery (parallel/cluster.py) — the
        # single-node checkpoint path here stays opt-in via an explicit
        # True/"on"
        rge = self.session.properties.get(
            "recoverable_grouped_execution", False)
        enabled = rge is True or str(rge).strip().lower() in (
            "true", "on", "1")
        # without a monitor there is no query text to fingerprint; sharing
        # a checkpoint key across unknown queries could serve query A's
        # buckets to query B, so recovery requires the monitored path
        if self.monitor is None or not self.monitor.stats.sql:
            enabled = False
        fail_after = int(self.session.properties.get(
            "fault_injection_fail_after_buckets", 0))
        seq = self._ckpt_seq = getattr(self, "_ckpt_seq", 0) + 1
        done_count = [0]
        if not enabled:
            def bucket_done():
                done_count[0] += 1
                if fail_after and done_count[0] >= fail_after:
                    raise ExecutionError("fault injection: worker died")
            return (lambda p: None), (lambda p, b: None), bucket_done, \
                (lambda: None)
        sql = self.monitor.stats.sql
        from presto_tpu import native

        fp = native.xxh64((" ".join(sql.split()) + f"|op{seq}").encode())
        d = os.path.join(
            self.session.properties.get("spill_path") or default_spill_dir(),
            f"ckpt_{fp:016x}_{nparts}")
        os.makedirs(d, exist_ok=True)

        def load(p):
            path = os.path.join(d, f"bucket_{p}.ptpg")
            if os.path.exists(path):
                if self.monitor is not None:
                    self.monitor.stats.recovered_buckets += 1
                return load_batch(path)
            return None

        def store(p, batch):
            save_batch(os.path.join(d, f"bucket_{p}.ptpg"), batch)

        def bucket_done():
            done_count[0] += 1
            if fail_after and done_count[0] >= fail_after:
                raise ExecutionError("fault injection: worker died")

        def finish():
            import shutil

            shutil.rmtree(d, ignore_errors=True)

        return load, store, bucket_done, finish

    def _exec_aggregate_with_distinct(self, node: P.Aggregate, b: Batch) -> Batch:
        """Rewrite: pre-group by (keys + distinct arg) then count non-null
        (reference: MultipleDistinctAggregationToMarkDistinct — single
        distinct column supported)."""
        distinct_aggs = {s: a for s, a in node.aggs.items() if a.distinct}
        plain_aggs = {s: a for s, a in node.aggs.items() if not a.distinct}
        if plain_aggs:
            # evaluate the two halves separately and merge: both group
            # passes enumerate the same key set in the same slot order
            # (sorted-unique dynamically; hash slots statically), so the
            # outputs align column-wise without a join (reference:
            # MarkDistinct keeps one pass; this is the two-pass analog)
            pb = self._aggregate(b, node.group_keys, plain_aggs)
            db = self._exec_aggregate_with_distinct(
                P.Aggregate(node.source, node.group_keys, distinct_aggs,
                            node.step), b)
            if pb.capacity != db.capacity:
                raise ExecutionError("distinct/plain group alignment failed")
            merged = dict(db.columns)
            for s in plain_aggs:
                merged[s] = pb.columns[s]
            # preserve the aggregate-declaration order for output mapping
            cols = {k: merged[k] for k in list(db.columns) if k not in node.aggs}
            for s in node.aggs:
                cols[s] = merged[s]
            return Batch(cols, db.sel)
        # one pre-group pass per distinct column; every pass enumerates
        # the same final key set in the same sorted-unique order, so the
        # outputs align column-wise (reference:
        # MultipleDistinctAggregationToMarkDistinct generalization)
        for a in distinct_aggs.values():
            if a.filter is not None:
                # the filter must apply BEFORE dedup, but the pre-group
                # output no longer carries the filter's columns; a clear
                # error beats a KeyError (or silently-wrong post-dedup
                # filtering)
                raise ExecutionError(
                    "DISTINCT aggregates with FILTER are not supported yet")
        by_col: Dict[str, Dict[str, ir.AggCall]] = {}
        for s, a in distinct_aggs.items():
            by_col.setdefault(a.args[0].name, {})[s] = a
        result = None
        for darg in sorted(by_col):
            pre = self._aggregate(b, node.group_keys + [darg], {})
            aggs2 = {}
            for s, a in by_col[darg].items():
                if a.fn in ("count", "approx_distinct"):
                    aggs2[s] = ir.AggCall("count", a.args, a.type, False,
                                          a.filter)
                elif a.fn in ("sum", "avg", "array_agg", "min", "max"):
                    # over the deduped pre-group these equal their
                    # DISTINCT forms
                    aggs2[s] = ir.AggCall(a.fn, a.args, a.type, False,
                                          a.filter)
                else:
                    raise ExecutionError(f"DISTINCT {a.fn} not supported")
            db = self._aggregate(pre, node.group_keys, aggs2)
            if result is None:
                result = db
            else:
                if result.capacity != db.capacity:
                    raise ExecutionError("distinct group alignment failed")
                cols = dict(result.columns)
                for s in aggs2:
                    cols[s] = db.columns[s]
                result = Batch(cols, result.sel)
        return result

    def _aggregate(self, b: Batch, group_keys: List[str],
                   aggs: Dict[str, ir.AggCall], node: Optional[P.Aggregate] = None) -> Batch:
        if not group_keys:
            return self._global_aggregate(b, aggs)
        key_cols = [b.columns[k] for k in group_keys]
        if self.static:
            return self._aggregate_static(b, group_keys, key_cols, aggs, node)
        pack_order = self._agg_pack_order(node, group_keys)
        pack_cols = [b.columns[k] for k in pack_order]
        key, layout = K.pack_keys(pack_cols, b.sel)
        gid = rep_rows = n_groups = None
        if layout is not None and self._ordering_enabled() \
                and getattr(node, "ordering_hint", None) == pack_order[0]:
            # presorted grouping: run-boundary scan, no sort, no
            # unpermute.  Dynamic mode host-checks the monotonicity
            # guard (one fetch shared with the group count) and falls
            # back to the sort path when the ordering claim lied.
            g2, newgrp, ng_t, guard = K.group_ids_presorted(key, b.sel)
            guard_h, ng = jax.device_get((guard, ng_t))
            if not bool(guard_h):
                n_groups = int(ng)
                gid = g2
                rep_rows = K.nonzero_i32(
                    newgrp, max(n_groups, 1), 0)[:n_groups] \
                    if n_groups else jnp.zeros((0,), jnp.int32)
                self._count("sorts_elided", 2)
            else:
                self._count("ordering_guard_trips")
        if gid is None:
            fp, refs = self._key_fp(pack_cols, b.sel, layout)
            hit = self._gid_memo.get(fp) if fp is not None \
                and self._ordering_enabled() else None
            if hit is not None:
                # group-id mapping memo: a second grouping over the SAME
                # key arrays (AVG/STDDEV fold passes over a resident
                # build, distinct pre-passes) reuses the whole
                # (gid, representatives, count) mapping — both the
                # grouping sort AND the unpermute co-sort elide
                gid, rep_rows, n_groups = hit[1]
                self._count("sort_memo_hits")
                self._count("sorts_elided", 2)
            else:
                pair = self._memo_pair(key, fp, refs)
                self._count("sorts_taken")  # the unpermute co-sort
                gid, rep_rows, n_groups = K.group_ids(key, b.sel,
                                                      sorted_pair=pair)
                if fp is not None:
                    self._gid_memo[fp] = (refs, (gid, rep_rows, n_groups))
        out_cols: Dict[str, Column] = {}
        raw, _ = K.take_columns({k: b.columns[k] for k in group_keys},
                                rep_rows)
        for k, (data, valid) in raw.items():
            c = b.columns[k]
            out_cols[k] = Column(data, valid, c.type, c.dictionary)
        fused = self._fused_sum_aggs(b, aggs, gid, n_groups)
        for sym, a in aggs.items():
            out_cols[sym] = fused.get(sym) or self._agg_column(b, a, gid, n_groups)
        sel = jnp.ones((max(n_groups, 0),), dtype=bool)
        if n_groups == 0:
            out_cols = {k: Column(c.data[:0], None if c.valid is None else c.valid[:0],
                                  c.type, c.dictionary) for k, c in out_cols.items()}
        out = Batch(out_cols, sel)
        if layout is not None:
            # exact packing: group rows emitted ascending on the packed
            # key = lexicographic on pack_order (certain by construction
            # — both the sorted and the run-scan path number groups in
            # ascending key order)
            self._note_order(out, tuple((k, True) for k in pack_order))
        return out

    # layouts this small use the packed key AS the group id (no sort at
    # all); key columns are reconstructed from slot arithmetic
    _DIRECT_GID_BITS = 12

    def _aggregate_static(self, b: Batch, group_keys, key_cols, aggs, node) -> Batch:
        cap = getattr(node, "capacity_hint", None) if node is not None else None
        if cap is None:
            cap = b.capacity
        cap = min(cap, b.capacity) or 1
        # Guarded pre-aggregation compaction: after selective joins the
        # live set is often orders of magnitude below the mask-not-
        # compact capacity, and every grouping pass (sorts, segment
        # reductions, representative gathers) scales with CAPACITY.
        # Compact to an estimate-derived power-of-two bound (top_k path,
        # ~10ms) under a guard that aborts to dynamic if the estimate
        # lied.  Q3-class join->group queries drop ~3x wall-clock.
        est = getattr(node, "input_est_hint", None) if node is not None \
            else None
        b2 = self._maybe_compact_static(b, est)
        if b2 is not b:
            # order-preserving compaction (ascending top_k indices):
            # presorted-input claims survive it
            b = b2
            key_cols = [b.columns[k] for k in group_keys]
            cap = min(cap, b.capacity)
        key_stats = getattr(node, "key_stats", {}) if node is not None else {}
        pack_order = self._agg_pack_order(node, group_keys)
        pack_cols = [b.columns[k] for k in pack_order]
        layout = K.static_layout(pack_cols, [key_stats.get(k) for k in pack_order])
        key = K.pack_with_layout(pack_cols, b.sel, layout)  # None -> hash, sync-free
        if layout is not None:
            self.guards.append(K.layout_range_guard(pack_cols, b.sel, layout))
            total_bits = sum(w for _, _, w in layout)
            if total_bits <= self._DIRECT_GID_BITS and all(
                    not jnp.issubdtype(c.data.dtype, jnp.floating)
                    for c in pack_cols):
                return self._aggregate_direct(
                    b, pack_order, pack_cols, aggs, key, layout, total_bits)
        if layout is not None and self._ordering_enabled() \
                and getattr(node, "ordering_hint", None) == pack_order[0] \
                and getattr(node, "ordering_hint_safe", False):
            # presorted grouping, compiled mode: the traced monotonicity
            # guard rides the existing static-guard channel — a wrong
            # ordering claim re-runs the query on the dynamic path.
            # SAFE hints only (remaining keys provably constant within
            # leading runs): a static trip costs the whole program,
            # where the dynamic path's host check costs one fetch
            gid, rep_rows, exists, overflow, guard = \
                K.group_ids_presorted_static(key, cap)
            self.guards.append(guard)
            self._count("sorts_elided", 2)
        else:
            fp, refs = self._key_fp(pack_cols, b.sel, layout)
            pair = self._memo_pair(key, fp, refs)
            self._count("sorts_taken")  # the unpermute co-sort
            gid, rep_rows, exists, overflow = K.group_ids_static(
                key, cap, sorted_pair=pair)
        self.guards.append(overflow)
        out_cols: Dict[str, Column] = {}
        raw, _ = K.take_columns({k: b.columns[k] for k in group_keys},
                                rep_rows)
        for k, (data, valid) in raw.items():
            c = b.columns[k]
            out_cols[k] = Column(
                data, None if valid is None else (valid & exists),
                c.type, c.dictionary)
        fused = self._fused_sum_aggs(b, aggs, gid, cap)
        for sym, a in aggs.items():
            out_cols[sym] = fused.get(sym) or self._agg_column(b, a, gid, cap)
        out = Batch(out_cols, exists)
        if layout is not None:
            # live prefix ascending on the packed key; dead slots carry
            # sentinels, so downstream full-array monotone guards hold
            self._note_order(out, tuple((k, True) for k in pack_order))
        return out

    def _aggregate_direct(self, b: Batch, group_keys, key_cols, aggs,
                          key, layout, total_bits: int) -> Batch:
        """Sort-free grouping for small static layouts: the packed key IS
        the group id (a dense slot in [0, 2^total_bits)), and the key
        columns come back from slot arithmetic instead of representative-
        row gathers.  TPC-H Q1's whole grouping collapses to one
        elementwise pass + the fused segmented reduction (reference
        analog: BigintGroupByHash's direct small-range fast path,
        operator/BigintGroupByHash.java)."""
        cap = 1 << total_bits
        # masked rows carry key_sentinel (huge) — clip sends them to the
        # dead slot `cap`, which every segment kernel already ignores
        gid = jnp.clip(key, 0, cap).astype(jnp.int32)
        counts = K.segment_sum(
            jnp.where(b.sel, 1.0, 0.0).astype(jnp.float32), gid, cap)
        exists = counts > 0.5
        slots = jnp.arange(cap, dtype=jnp.int64)
        out_cols: Dict[str, Column] = {}
        for k, c, (lo, stride, width) in zip(group_keys, key_cols, layout):
            code = (slots // stride) & ((1 << width) - 1)
            data = (code - 1 + lo).astype(c.data.dtype)
            valid = None if c.valid is None else ((code != 0) & exists)
            out_cols[k] = Column(data, valid, c.type, c.dictionary)
        fused = self._fused_sum_aggs(b, aggs, gid, cap)
        for sym, a in aggs.items():
            out_cols[sym] = fused.get(sym) or self._agg_column(b, a, gid, cap)
        out = Batch(out_cols, exists)
        # slot order IS packed-key order (live slots ascending), but
        # EMPTY slots sit interspersed: not tail-masked
        self._note_order(out, tuple((k, True) for k in group_keys),
                         tail_ok=False)
        return out

    def _fused_sum_aggs(self, b: Batch, aggs: Dict[str, ir.AggCall],
                        gid, n_groups: int) -> Dict[str, Column]:
        """Prepass: compute all sum-shaped aggregates (count/count_if/
        sum/avg over DOUBLE) in ONE Pallas pass over the rows
        (kernels.fused_group_sums) instead of one scatter-add per
        aggregate.  Returns {} when not worthwhile; callers fall through
        to _agg_column per aggregate."""
        if not self.session.properties.get("pallas_fused_agg", True):
            return {}
        n = b.capacity
        if n < 32_768 or not (1 <= n_groups <= 4096) or len(aggs) < 1:
            return {}

        # pre-select fusable aggregates from METADATA ONLY, so a below-
        # threshold set bails out before any expression is evaluated
        # (otherwise _agg_column would redo each eval)
        def fusable(a):
            if a.fn == "count" and not a.args:
                return True
            if a.fn == "count_if":
                return True
            if a.fn in ("sum", "avg", "partial_sum_double") and a.args:
                t = getattr(a.args[0], "type", None)
                return t is not None and t.name in ("DOUBLE", "REAL")
            return False

        chosen = {sym: a for sym, a in aggs.items() if fusable(a)}
        f32_mode = bool(self.session.properties.get("float32_compute", False))
        if not f32_mode and not K._pallas_interpret():
            # the TPU kernel accumulates f32 block partials; without the
            # float32_compute opt-in the session promises full-precision
            # f64, so stay on the (slower) exact scatter-add path
            return {}
        # with f32 compute even a single aggregate is worth fusing (the
        # kernel's block-partial + f64 merge beats one long f32 reduce)
        if len(chosen) < (1 if f32_mode else 2):
            return {}

        rows: List[jnp.ndarray] = []
        plan: Dict[str, tuple] = {}
        any_f32 = False
        for sym, a in chosen.items():
            mask = b.sel
            if a.filter is not None:
                mask = mask & eval_predicate(a.filter, b, self.ctx)
            if a.fn == "count" and not a.args:
                plan[sym] = ("count", len(rows))
                rows.append(mask)
            elif a.fn == "count_if":
                v = eval_expr(a.args[0], b, self.ctx)
                m = mask & jnp.asarray(v.data)
                if v.valid is not None:
                    m = m & v.valid
                plan[sym] = ("count", len(rows))
                rows.append(m)
            else:
                v = eval_expr(a.args[0], b, self.ctx)
                col = to_column(v, n)
                if col.data.dtype not in (jnp.float64, jnp.float32):
                    continue
                any_f32 = any_f32 or col.data.dtype == jnp.float32
                valid = mask if col.valid is None else (mask & col.valid)
                vi = len(rows)
                rows.append(jnp.where(valid, col.data,
                                      jnp.zeros((), col.data.dtype)))
                ci = len(rows)
                rows.append(valid)
                plan[sym] = (a.fn, vi, ci, a.type)
        if len(plan) < (1 if any_f32 else 2):
            return {}
        # on the TPU path the kernel uses f32 block partials with an f64
        # cross-block merge either way; the interpreter path accumulates
        # in acc_t across ALL blocks, so it must stay f64 (counts are
        # exact-integer semantics)
        acc_t = (jnp.float32 if any_f32 and not K._pallas_interpret()
                 else jnp.float64)
        sums = K.fused_group_sums(
            jnp.stack([r.astype(acc_t) for r in rows]),
            jnp.clip(gid, 0, n_groups - 1).astype(jnp.int32),
            n_groups)
        out: Dict[str, Column] = {}
        for sym, p in plan.items():
            if p[0] == "count":
                # float counts are exact below 2^53
                out[sym] = Column(jnp.round(sums[p[1]]).astype(jnp.int64),
                                  None, T.BIGINT)
                continue
            fn, vi, ci, out_t = p
            s = sums[vi]
            cnt = sums[ci]
            nonempty = cnt > 0.5
            if fn == "avg":
                out[sym] = Column(s / jnp.maximum(cnt, 1.0), nonempty, T.DOUBLE)
            else:
                out[sym] = Column(s, nonempty, out_t)
        return out

    def _agg_column(self, b: Batch, a: ir.AggCall, gid, n_groups) -> Column:
        mask = b.sel
        if a.filter is not None:
            mask = mask & eval_predicate(a.filter, b, self.ctx)
        if a.fn in ("count",) and not a.args:
            # i32 accumulate: an i64 scatter-add runs as u32-pair
            # emulation on TPU (~10x slower, measured); per-group row
            # counts within one batch always fit i32
            cnt = K.segment_sum(mask.astype(jnp.int32), gid, n_groups)
            return Column(cnt.astype(jnp.int64), None, T.BIGINT)
        if a.fn == "count_if":
            v = eval_expr(a.args[0], b, self.ctx)
            m = mask & jnp.asarray(v.data)
            if v.valid is not None:
                m = m & v.valid
            return Column(K.segment_sum(m.astype(jnp.int32), gid,
                                        n_groups).astype(jnp.int64),
                          None, T.BIGINT)
        if a.fn in ("merge_count", "merge_avg") or a.fn.startswith(
                ("merge_stddev", "merge_var")):
            return self._merge_agg_column(b, a, gid, n_groups, mask)
        v = eval_expr(a.args[0], b, self.ctx)
        col = to_column(v, b.capacity)
        valid = mask if col.valid is None else (mask & col.valid)
        cnt = K.segment_sum(valid.astype(jnp.int32), gid,
                            n_groups).astype(jnp.int64)  # i32: see count
        nonempty = cnt > 0
        if a.fn == "count":
            return Column(cnt, None, T.BIGINT)
        if a.fn == "approx_distinct":
            h = K.hll_hash64(col)  # value hash: matches distributed merge
            est = K.hll_registers_and_estimate(h, valid, gid, n_groups,
                                               m=_hll_m(a))
            return Column(est, None, T.BIGINT)
        if a.fn == "$hll_partial":
            # mergeable sketch partial: the state column IS the aggregate
            # output — (n_groups, m) uint8 registers, m from the TYPE
            h = K.hll_hash64(col)
            regs = K.hll_partial(h, valid, gid, n_groups,
                                 m=a.type.params[0])
            return Column(regs, None, a.type)
        if a.fn == "$hll_est":
            # final over partial states: fold register rows (elementwise
            # max) per group, then estimate; empty groups estimate 0,
            # matching the single-pass kernel (approx_distinct never
            # returns NULL)
            return Column(K.hll_merge_estimate(col.data, valid, gid,
                                               n_groups), None, T.BIGINT)
        if a.fn == "$hll_merge":
            # rollup merge: partial states in, folded state out (the
            # chunked loop's re-aggregation of partial pages)
            return Column(K.hll_merge(col.data, valid, gid, n_groups),
                          None, a.type)
        if a.fn == "$kll_partial":
            kk = a.type.params[0] // 2
            x = col.data.astype(jnp.float64) if col.data.dtype != \
                jnp.float64 else col.data
            return Column(K.kll_partial(x, valid, gid, n_groups, kk),
                          None, a.type)
        if a.fn == "$kll_pct":
            pv = eval_expr(a.args[1], b, self.ctx)
            p = pv.data if getattr(pv.data, "ndim", 0) == 0 else pv.data[0]
            kk = a.args[0].type.params[0] // 2
            vals, ok = K.kll_percentile(col.data, valid, gid, n_groups,
                                        p, kk)
            return Column(vals.astype(a.type.numpy_dtype()), ok, a.type)
        if a.fn in ("approx_count", "approx_sum"):
            # COUNT/SUM ... WITH ERROR: deterministic 1-in-8 value-hash
            # sample, scaled by exactly 8 — partition-independent, so
            # partials (the fn is its own partial) merge by plain sum
            keep = valid & K.sketch_sample_mask(K.hll_hash64(col))
            if a.fn == "approx_count":
                s = K.segment_sum(keep.astype(jnp.int32), gid, n_groups)
                return Column(s.astype(jnp.int64) * 8, None, T.BIGINT)
            x = jnp.where(keep, col.data, jnp.zeros_like(col.data))
            s = K.segment_sum(x, gid, n_groups)
            if a.type.is_integer:
                s = s.astype(jnp.int64)
            return Column(s.astype(a.type.numpy_dtype()) * 8, nonempty,
                          a.type)
        if a.fn == "checksum":
            # order-independent 64-bit checksum: wrapping sum of row
            # hashes (reference: ChecksumAggregationFunction, xor-based;
            # any commutative mix works for A/B verification)
            h = K._hash_keys([col], valid).astype(jnp.int64)
            s = K.segment_sum(jnp.where(valid, h, 0), gid, n_groups)
            return Column(s, nonempty, T.BIGINT)
        if a.fn == "approx_percentile":
            if a.type.name == "ARRAY" or len(a.args) >= 3:
                # array-of-percentiles / weighted forms: host-side
                # (reference: Approximate*PercentileArrayAggregations +
                # the weighted overloads)
                if self.static:
                    raise StaticFallback(
                        "array/weighted approx_percentile is "
                        "dynamic-mode only")
                return self._approx_percentile_host(b, a, gid, n_groups,
                                                    col, valid, nonempty)
            pv = eval_expr(a.args[1], b, self.ctx)
            p = pv.data if getattr(pv.data, "ndim", 0) == 0 else pv.data[0]
            x = col.data
            vals, ok = K.group_percentile(x, valid, gid, n_groups, p)
            return Column(vals.astype(col.data.dtype), ok, a.type,
                          col.dictionary)
        if a.fn in ("min_by", "max_by") and len(a.args) == 2:
            yv = to_column(eval_expr(a.args[1], b, self.ctx), b.capacity)
            # rank by KEY validity only: the winning row's value may be
            # NULL and must be returned as NULL (Presto MinMaxByNState)
            yvalid = mask if yv.valid is None else (mask & yv.valid)
            yi = K._orderable_int(yv)
            big = jnp.iinfo(jnp.int64).max
            ykey = jnp.where(yvalid, yi, big if a.fn == "min_by" else -big)
            extremum = (K.segment_min if a.fn == "min_by"
                        else K.segment_max)(ykey, gid, n_groups)
            hit = yvalid & (ykey == extremum[gid])
            idx = K.segment_max(
                jnp.where(hit, jnp.arange(b.capacity), -1), gid, n_groups)
            safe = jnp.clip(idx, 0, b.capacity - 1)
            ok = idx >= 0
            val_valid = ok if col.valid is None else (ok & col.valid[safe])
            return Column(col.data[safe], val_valid, a.type, col.dictionary)
        if a.fn == "array_agg":
            # ragged output: host-side build (reference: ArrayAggregation
            # over an ObjectBigArray); dynamic mode only
            if self.static:
                raise StaticFallback("array_agg is dynamic-mode only")
            gidh = np.asarray(gid)
            rows_live = np.asarray(mask)  # NULL inputs are kept as NULL
            vh = np.asarray(valid)        # elements (Presto array_agg)
            data = np.asarray(col.data)
            if col.dictionary is not None:
                data = col.dictionary.values[
                    np.clip(data, 0, len(col.dictionary) - 1)]
            groups = [[] for _ in range(n_groups)]
            for row in np.flatnonzero(rows_live):
                g = int(gidh[row])
                if 0 <= g < n_groups:
                    if not vh[row]:
                        groups[g].append(None)
                        continue
                    groups[g].append(data[row].item()
                                     if hasattr(data[row], "item")
                                     else data[row])
            tuples = np.empty(n_groups, dtype=object)
            tuples[:] = [tuple(g) for g in groups]
            return _tuples_to_dict_column(tuples, nonempty, a.type)
        if a.fn in ("approx_set", "merge", "qdigest_agg", "tdigest_agg"):
            # serializable sketch build/merge: host-side per group like
            # array_agg (reference: ApproximateSetAggregation /
            # MergeHyperLogLogAggregation / QuantileDigestAggregation);
            # the vectorized approx_distinct/approx_percentile kernels
            # remain the in-query fast path
            if self.static:
                raise StaticFallback(f"{a.fn} is dynamic-mode only")
            from presto_tpu.functions import sketches as SK

            gidh = np.asarray(gid)
            vh = np.asarray(valid)
            data = np.asarray(col.data)
            if col.dictionary is not None:
                data = col.dictionary.values[
                    np.clip(data, 0, len(col.dictionary) - 1)]
            elif col.type.is_decimal:
                data = data.astype(np.float64) / (10 ** col.type.decimal_scale)
            wdata = None
            if a.fn == "tdigest_agg" and len(a.args) >= 2:
                wcol = to_column(eval_expr(a.args[1], b, self.ctx),
                                 b.capacity)
                wdata = np.asarray(wcol.data, np.float64)
                if wdata.ndim == 0:
                    wdata = np.full(b.capacity, float(wdata))
            groups: list = [[] for _ in range(n_groups)]
            wgroups: list = [[] for _ in range(n_groups)]
            for row in np.flatnonzero(vh):
                g = int(gidh[row])
                if 0 <= g < n_groups:
                    v = data[row]
                    groups[g].append(v.item() if hasattr(v, "item") else v)
                    if wdata is not None:
                        wgroups[g].append(float(wdata[row]))
            blobs = np.empty(n_groups, dtype=object)
            if a.fn == "approx_set":
                blobs[:] = [SK.hll_from_values(g) for g in groups]
            elif a.fn == "qdigest_agg":
                blobs[:] = [SK.qdigest_from_values(g) for g in groups]
            elif a.fn == "tdigest_agg":
                from presto_tpu.functions import tdigest as TD

                compression = TD.DEFAULT_COMPRESSION
                if len(a.args) >= 3:  # constant compression argument
                    cv = np.asarray(eval_expr(a.args[2], b, self.ctx).data)
                    if cv.ndim > 0:
                        raise NotImplementedError(
                            "tdigest_agg compression must be a constant")
                    compression = float(cv)
                blobs[:] = [TD.tdigest_from_values(
                    g, weights=wg if wdata is not None else None,
                    compression=compression)
                    for g, wg in zip(groups, wgroups)]
            else:  # merge over serialized sketches
                if a.type.name in ("HLL", "P4HLL"):
                    blobs[:] = [SK.hll_merge(g) for g in groups]
                elif a.type.name == "TDIGEST":
                    from presto_tpu.functions import tdigest as TD

                    blobs[:] = [TD.tdigest_merge(g) for g in groups]
                else:
                    blobs[:] = [SK.qdigest_merge(g) for g in groups]
            return _tuples_to_dict_column(blobs, nonempty, a.type)
        if a.fn in ("map_agg", "multimap_agg"):
            # ragged output, host-side like array_agg (reference:
            # MapAggregationFunction / MultimapAggregationFunction over a
            # KeyValuePairsState); dynamic mode only
            if self.static:
                raise StaticFallback(f"{a.fn} is dynamic-mode only")
            vcol = to_column(eval_expr(a.args[1], b, self.ctx), b.capacity)
            kh = np.asarray(col.data)
            if col.dictionary is not None:
                kh = col.dictionary.values[
                    np.clip(kh, 0, len(col.dictionary) - 1)]
            vhd = np.asarray(vcol.data)
            if vcol.dictionary is not None:
                vhd = vcol.dictionary.values[
                    np.clip(vhd, 0, len(vcol.dictionary) - 1)]
            vval = np.asarray(valid)
            vok = np.ones(b.capacity, bool) if vcol.valid is None \
                else np.asarray(vcol.valid)
            gidh = np.asarray(gid)
            groups = [dict() for _ in range(n_groups)]
            for row in np.flatnonzero(vval):  # NULL keys are skipped
                g = int(gidh[row])
                if not (0 <= g < n_groups):
                    continue
                k = kh[row].item() if hasattr(kh[row], "item") else kh[row]
                if isinstance(k, np.str_):
                    k = str(k)
                val = None
                if vok[row]:
                    val = vhd[row].item() if hasattr(vhd[row], "item") \
                        else vhd[row]
                    if isinstance(val, np.str_):
                        val = str(val)
                if a.fn == "multimap_agg":
                    groups[g].setdefault(k, []).append(val)
                else:
                    groups[g].setdefault(k, val)  # first value wins
            tuples = np.empty(n_groups, dtype=object)
            if a.fn == "multimap_agg":
                tuples[:] = [tuple(sorted(((k, tuple(v)) for k, v
                                           in g.items()),
                                          key=lambda p: repr(p[0])))
                             for g in groups]
            else:
                tuples[:] = [tuple(sorted(g.items(),
                                          key=lambda p: repr(p[0])))
                             for g in groups]
            return _tuples_to_dict_column(tuples, nonempty, a.type)
        if a.fn.startswith("classification_"):
            if self.static:
                raise StaticFallback(f"{a.fn} is dynamic-mode only")
            return self._classification_host(b, a, gid, n_groups,
                                             nonempty)
        if a.fn in ("set_agg", "set_union", "map_union_sum",
                    "approx_most_frequent", "reduce_agg",
                    "evaluate_classifier_predictions") \
                or (a.fn in ("min_by", "max_by") and len(a.args) == 3):
            if self.static:
                raise StaticFallback(f"{a.fn} is dynamic-mode only")
            return self._agg_column_host(b, a, gid, n_groups, col, valid,
                                         nonempty)
        if a.fn == "geometric_mean":
            x = jnp.where(valid, col.data.astype(jnp.float64), 1.0)
            s = K.segment_sum(jnp.log(jnp.maximum(x, 1e-300)), gid, n_groups)
            return Column(jnp.exp(s / jnp.maximum(cnt, 1)), nonempty, T.DOUBLE)
        if a.fn == "sum":
            if a.type.is_decimal and a.type.is_long_decimal:
                # exact Int128 accumulation (reference:
                # DecimalSumAggregation over UnscaledDecimal128Arithmetic)
                from presto_tpu.exec import dec128 as D128

                limbs = jnp.asarray(col.data) \
                    if getattr(col.data, "ndim", 1) == 2 \
                    else D128.from_int64(jnp.asarray(col.data))
                s = D128.segment_sum128(limbs, valid, gid, n_groups)
                return Column(s, nonempty, a.type)
            x = jnp.where(valid, col.data, jnp.zeros_like(col.data))
            s = K.segment_sum(x, gid, n_groups)
            if a.type.is_integer:
                s = s.astype(jnp.int64)
            return Column(s.astype(a.type.numpy_dtype()), nonempty, a.type)
        if a.fn == "avg":
            if a.type.name.startswith("INTERVAL"):
                # interval average stays an interval: truncating integer
                # division of the micros/months sum (reference:
                # IntervalDayToSecondAverageAggregation)
                x = jnp.where(valid, col.data, jnp.zeros_like(col.data))
                s = K.segment_sum(x, gid, n_groups).astype(jnp.int64)
                d = jnp.maximum(cnt, 1)
                r = jnp.sign(s) * (jnp.abs(s) // d)
                return Column(r, nonempty, a.type)
            if getattr(col.data, "ndim", 1) == 2:  # long decimal limbs
                from presto_tpu.exec import dec128 as D128

                f = D128.to_float64(jnp.asarray(col.data)) \
                    / (10 ** col.type.decimal_scale)
                x = jnp.where(valid, f, 0.0)
                s = K.segment_sum(x, gid, n_groups)
                return Column(s / jnp.maximum(cnt, 1), nonempty, T.DOUBLE)
            x = jnp.where(valid, col.data.astype(jnp.float64), 0.0)
            if col.type.is_decimal:
                x = x / (10 ** col.type.decimal_scale)
            s = K.segment_sum(x, gid, n_groups)
            return Column(s / jnp.maximum(cnt, 1), nonempty, T.DOUBLE)
        if a.fn in ("min", "max"):
            if getattr(col.data, "ndim", 1) == 2:  # long decimal limbs
                from presto_tpu.exec import dec128 as D128

                r = D128.segment_minmax128(jnp.asarray(col.data), valid,
                                           gid, n_groups, a.fn == "min")
                return Column(r, nonempty, a.type)
            if jnp.issubdtype(col.data.dtype, jnp.floating):
                ext = jnp.inf if a.fn == "min" else -jnp.inf
            elif col.data.dtype == jnp.bool_:
                ext = a.fn == "min"
            else:
                info = jnp.iinfo(col.data.dtype)
                ext = info.max if a.fn == "min" else info.min
            x = jnp.where(valid, col.data, jnp.asarray(ext, col.data.dtype))
            f = K.segment_min if a.fn == "min" else K.segment_max
            r = f(x, gid, n_groups)
            return Column(r.astype(col.data.dtype), nonempty, a.type, col.dictionary)
        if a.fn in ("arbitrary", "any_value"):
            idx = K.segment_max(jnp.where(valid, jnp.arange(b.capacity), -1), gid, n_groups)
            safe = jnp.clip(idx, 0, b.capacity - 1)
            return Column(col.data[safe], nonempty & (idx >= 0), a.type, col.dictionary)
        if a.fn in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
            x = jnp.where(valid, col.data.astype(jnp.float64), 0.0)
            s1 = K.segment_sum(x, gid, n_groups)
            s2 = K.segment_sum(x * x, gid, n_groups)
            n = jnp.maximum(cnt, 1).astype(jnp.float64)
            var_pop = s2 / n - (s1 / n) ** 2
            var_pop = jnp.maximum(var_pop, 0.0)
            if a.fn in ("stddev", "stddev_samp", "variance", "var_samp"):
                denom = jnp.maximum(cnt - 1, 1).astype(jnp.float64)
                var = var_pop * n / denom
                ok = nonempty & (cnt > 1)
            else:
                var = var_pop
                ok = nonempty
            r = jnp.sqrt(var) if a.fn.startswith("stddev") else var
            return Column(r, ok, T.DOUBLE)
        if a.fn in ("bool_and", "every"):
            x = jnp.where(valid, jnp.asarray(col.data, bool), True)
            r = K.segment_min(x.astype(jnp.int32), gid, n_groups) > 0
            return Column(r, nonempty, T.BOOLEAN)
        if a.fn == "bool_or":
            x = jnp.where(valid, jnp.asarray(col.data, bool), False)
            r = K.segment_max(x.astype(jnp.int32), gid, n_groups) > 0
            return Column(r, nonempty, T.BOOLEAN)
        if a.fn in ("partial_sum_double", "partial_sum_sq_double"):
            # PARTIAL step of avg/stddev decomposition (plan/distribute.py):
            # the float64 running sums the reference's accumulators keep
            # (operator/aggregation/AverageAggregations, VarianceAggregation)
            x = col.data.astype(jnp.float64)
            if col.type.is_decimal:
                x = x / (10 ** col.type.decimal_scale)
            if a.fn.endswith("sq_double"):
                x = x * x
            s = K.segment_sum(jnp.where(valid, x, 0.0), gid, n_groups)
            return Column(s, nonempty, T.DOUBLE)
        if a.fn in ("corr", "covar_samp", "covar_pop", "regr_slope",
                    "regr_intercept"):
            # bivariate family from co-moment segment sums (reference:
            # operator/aggregation/{Corr,Covar,Regr}*Aggregation over
            # CovarianceState: n, meanX, meanY, c2 — same moments,
            # vectorized).  Presto argument order is (y, x).
            yv = to_column(eval_expr(a.args[1], b, self.ctx), b.capacity)
            both = valid if yv.valid is None else (valid & yv.valid)

            def f64(c):
                d = jnp.asarray(c.data).astype(jnp.float64)
                return d / (10 ** c.type.decimal_scale) \
                    if c.type.is_decimal else d

            y = jnp.where(both, f64(col), 0.0)
            x = jnp.where(both, f64(yv), 0.0)
            n = K.segment_sum(both.astype(jnp.int32), gid,
                              n_groups).astype(jnp.float64)
            sx = K.segment_sum(x, gid, n_groups)
            sy = K.segment_sum(y, gid, n_groups)
            sxy = K.segment_sum(x * y, gid, n_groups)
            sxx = K.segment_sum(x * x, gid, n_groups)
            syy = K.segment_sum(y * y, gid, n_groups)
            n1 = jnp.maximum(n, 1.0)
            covp = sxy / n1 - (sx / n1) * (sy / n1)
            varx = jnp.maximum(sxx / n1 - (sx / n1) ** 2, 0.0)
            vary = jnp.maximum(syy / n1 - (sy / n1) ** 2, 0.0)
            if a.fn == "covar_pop":
                return Column(covp, n > 0, T.DOUBLE)
            if a.fn == "covar_samp":
                r = covp * n / jnp.maximum(n - 1.0, 1.0)
                return Column(r, n > 1, T.DOUBLE)
            if a.fn == "corr":
                denom = jnp.sqrt(varx * vary)
                r = covp / jnp.maximum(denom, 1e-300)
                return Column(r, (n > 1) & (denom > 0), T.DOUBLE)
            slope = covp / jnp.maximum(varx, 1e-300)
            if a.fn == "regr_slope":
                return Column(slope, (n > 1) & (varx > 0), T.DOUBLE)
            icept = sy / n1 - slope * (sx / n1)
            return Column(icept, (n > 1) & (varx > 0), T.DOUBLE)
        if a.fn in ("skewness", "kurtosis"):
            # central moments from raw power sums (reference:
            # CentralMomentsAggregation over CentralMomentsState)
            x = jnp.where(valid, col.data.astype(jnp.float64), 0.0)
            n = jnp.maximum(cnt, 1).astype(jnp.float64)
            s1 = K.segment_sum(x, gid, n_groups)
            s2 = K.segment_sum(x * x, gid, n_groups)
            s3 = K.segment_sum(x ** 3, gid, n_groups)
            mu = s1 / n
            m2 = jnp.maximum(s2 - n * mu * mu, 0.0)
            if a.fn == "skewness":
                m3 = s3 - 3 * mu * s2 + 2 * n * mu ** 3
                sd2 = m2 / jnp.maximum(n - 1.0, 1.0)
                r = n / jnp.maximum((n - 1) * (n - 2), 1.0) \
                    * m3 / jnp.maximum(sd2 ** 1.5, 1e-300)
                return Column(r, (cnt > 2) & (m2 > 0), T.DOUBLE)
            s4 = K.segment_sum(x ** 4, gid, n_groups)
            m4 = s4 - 4 * mu * s3 + 6 * mu * mu * s2 - 3 * n * mu ** 4
            sd2 = m2 / jnp.maximum(n - 1.0, 1.0)
            d = jnp.maximum((n - 1) * (n - 2) * (n - 3), 1.0)
            r = n * (n + 1) / d * m4 / jnp.maximum(sd2 * sd2, 1e-300) \
                - 3.0 * (n - 1) ** 2 / jnp.maximum((n - 2) * (n - 3), 1.0)
            return Column(r, (cnt > 3) & (m2 > 0), T.DOUBLE)
        if a.fn == "entropy":
            # entropy of empirical distribution from count weights
            # (reference: EntropyAggregation): log2(S) - sum(c*log2 c)/S
            c = jnp.where(valid, col.data.astype(jnp.float64), 0.0)
            c = jnp.maximum(c, 0.0)
            s = K.segment_sum(c, gid, n_groups)
            clogc = K.segment_sum(
                jnp.where(c > 0, c * jnp.log2(jnp.maximum(c, 1e-300)), 0.0),
                gid, n_groups)
            r = jnp.where(s > 0,
                          jnp.log2(jnp.maximum(s, 1e-300)) - clogc
                          / jnp.maximum(s, 1e-300), 0.0)
            return Column(r, nonempty, T.DOUBLE)
        if a.fn in ("bitwise_and_agg", "bitwise_or_agg"):
            # per-bit segment min/max over an (n, 64) bit plane — ONE
            # segment op (reference: BitwiseAndAggregation/
            # BitwiseOrAggregation's running long)
            xi = jnp.asarray(col.data).astype(jnp.int64)
            shifts = jnp.arange(64, dtype=jnp.int64)
            bits = ((xi[:, None] >> shifts[None, :]) & 1).astype(jnp.int32)
            if a.fn == "bitwise_and_agg":
                bits = jnp.where(valid[:, None], bits, 1)
                red = K.segment_min(bits, gid, n_groups)
            else:
                bits = jnp.where(valid[:, None], bits, 0)
                red = K.segment_max(bits, gid, n_groups)
            r = jnp.sum(red.astype(jnp.int64) << shifts[None, :], axis=1)
            return Column(r, nonempty, T.BIGINT)
        if a.fn in ("learn_classifier", "learn_regressor"):
            # host-side training inside the aggregate (reference:
            # presto-ml LearnAggregations over libsvm; here numpy
            # logistic regression / ridge LSQ — see functions/ml.py)
            if self.static:
                raise StaticFallback(f"{a.fn} is dynamic-mode only")
            from presto_tpu.functions import ml as ML

            fv = eval_expr(a.args[1], b, self.ctx)
            feats = np.asarray(fv.data)
            labels = np.asarray(col.data)
            if col.dictionary is not None:
                labels = col.dictionary.values[
                    np.clip(labels, 0, len(col.dictionary) - 1)]
            elif col.type.is_decimal:
                labels = labels.astype(np.float64) \
                    / (10 ** col.type.decimal_scale)
            gidh = np.asarray(gid)
            vh = np.asarray(valid)
            if fv.valid is not None:  # rows with NULL features skip
                vh = vh & np.asarray(fv.valid)
            blobs = np.empty(n_groups, dtype=object)
            for g in range(n_groups):
                m = (gidh == g) & vh
                if not m.any():
                    blobs[g] = b""
                    continue
                if a.fn == "learn_classifier":
                    blobs[g] = ML.train_classifier(labels[m], feats[m])
                else:
                    blobs[g] = ML.train_regressor(
                        labels[m].astype(np.float64), feats[m])
            return _tuples_to_dict_column(blobs, nonempty, a.type)
        if a.fn in ("histogram", "numeric_histogram", "map_union"):
            # ragged MAP output, host-side like map_agg (reference:
            # Histogram / NumericHistogramAggregation / MapUnionAggregation)
            if self.static:
                raise StaticFallback(f"{a.fn} is dynamic-mode only")
            gidh = np.asarray(gid)
            vh = np.asarray(valid)
            data = np.asarray(col.data)
            if col.dictionary is not None:
                data = col.dictionary.values[
                    np.clip(data, 0, len(col.dictionary) - 1)]
            if a.fn == "numeric_histogram":
                nb_v = eval_expr(a.args[0], b, self.ctx)
                nb = int(nb_v.data if getattr(nb_v.data, "ndim", 0) == 0
                         else np.asarray(nb_v.data)[0])
                vcol = to_column(eval_expr(a.args[1], b, self.ctx),
                                 b.capacity)
                vvh = mask if vcol.valid is None else \
                    np.asarray(mask & vcol.valid)
                vdata = np.asarray(vcol.data).astype(np.float64)
                if vcol.type.is_decimal:
                    vdata = vdata / (10 ** vcol.type.decimal_scale)
                tuples = np.empty(n_groups, dtype=object)
                for g in range(n_groups):
                    vals = np.sort(vdata[(gidh == g) & vvh])
                    if not len(vals):
                        tuples[g] = ()
                        continue
                    bins = np.array_split(vals, max(min(nb, len(vals)), 1))
                    tuples[g] = tuple(sorted(
                        (float(np.mean(bin_)), float(len(bin_)))
                        for bin_ in bins if len(bin_)))
                return _tuples_to_dict_column(tuples, nonempty, a.type)
            groups = [dict() for _ in range(n_groups)]
            for row in np.flatnonzero(vh):
                g = int(gidh[row])
                if not (0 <= g < n_groups):
                    continue
                v = data[row]
                v = v.item() if hasattr(v, "item") else v
                if isinstance(v, np.str_):
                    v = str(v)
                if a.fn == "histogram":
                    groups[g][v] = groups[g].get(v, 0) + 1
                else:  # map_union: v is a map value (tuple of pairs)
                    for k, mv in v:
                        groups[g].setdefault(k, mv)
            tuples = np.empty(n_groups, dtype=object)
            tuples[:] = [tuple(sorted(g.items(), key=lambda p: repr(p[0])))
                         for g in groups]
            return _tuples_to_dict_column(tuples, nonempty, a.type)
        raise ExecutionError(f"aggregate {a.fn} not implemented")

    def _agg_column_host(self, b: Batch, a: ir.AggCall, gid, n_groups,
                         col, valid, nonempty) -> Column:
        """Host-side ragged aggregates added in round 5 (reference:
        SetAggregationFunction / SetUnionFunction / MapUnionSumAggregation
        / ApproximateMostFrequent / MinMaxByNAggregationFunction /
        ReduceAggregationFunction) — same dynamic-mode host-build shape
        as array_agg/map_agg above."""

        def decode(c):
            d = np.asarray(c.data)
            if c.dictionary is not None:
                d = c.dictionary.values[np.clip(d, 0, len(c.dictionary) - 1)]
            return d

        gidh = np.asarray(gid)
        vh = np.asarray(valid)
        data = decode(col)

        def host(v):
            v = v.item() if hasattr(v, "item") else v
            return str(v) if isinstance(v, np.str_) else v

        if a.fn == "set_agg":
            groups = [dict() for _ in range(n_groups)]  # ordered distinct
            for row in np.flatnonzero(vh):
                g = int(gidh[row])
                if 0 <= g < n_groups:
                    groups[g].setdefault(host(data[row]))
            tuples = np.empty(n_groups, dtype=object)
            tuples[:] = [tuple(g) for g in groups]
            return _tuples_to_dict_column(tuples, nonempty, a.type)
        if a.fn == "set_union":
            groups = [dict() for _ in range(n_groups)]
            for row in np.flatnonzero(vh):
                g = int(gidh[row])
                if 0 <= g < n_groups:
                    for e in data[row]:
                        groups[g].setdefault(e)
            tuples = np.empty(n_groups, dtype=object)
            tuples[:] = [tuple(g) for g in groups]
            return _tuples_to_dict_column(tuples, nonempty, a.type)
        if a.fn == "map_union_sum":
            groups = [dict() for _ in range(n_groups)]
            for row in np.flatnonzero(vh):
                g = int(gidh[row])
                if 0 <= g < n_groups:
                    for k, mv in data[row]:
                        if mv is None:
                            groups[g].setdefault(k, None)
                        else:
                            cur = groups[g].get(k)
                            groups[g][k] = mv if cur is None else cur + mv
            tuples = np.empty(n_groups, dtype=object)
            tuples[:] = [tuple(sorted(g.items(), key=lambda p: repr(p[0])))
                         for g in groups]
            return _tuples_to_dict_column(tuples, nonempty, a.type)
        if a.fn == "approx_most_frequent":
            # exact counting + top-K truncation: a superset of the
            # reference's stream-summary guarantee at this scale
            bk = np.asarray(eval_expr(a.args[0], b, self.ctx).data)
            buckets = int(bk if bk.ndim == 0 else bk.flat[0])
            vcol = to_column(eval_expr(a.args[1], b, self.ctx), b.capacity)
            vdata = decode(vcol)
            vvalid = np.asarray(b.sel if vcol.valid is None
                                else (b.sel & vcol.valid))
            counts = [dict() for _ in range(n_groups)]
            for row in np.flatnonzero(vvalid):
                g = int(gidh[row])
                if 0 <= g < n_groups:
                    k = host(vdata[row])
                    counts[g][k] = counts[g].get(k, 0) + 1
            tuples = np.empty(n_groups, dtype=object)
            tuples[:] = [
                tuple(sorted(
                    sorted(g.items(), key=lambda p: (-p[1], repr(p[0])))
                    [:buckets], key=lambda p: repr(p[0])))
                for g in counts]
            ok = jnp.asarray(
                np.asarray([len(g) > 0 for g in counts], bool))
            return _tuples_to_dict_column(tuples, ok, a.type)
        if a.fn in ("min_by", "max_by"):  # 3-arg: top-n by key
            ycol = to_column(eval_expr(a.args[1], b, self.ctx), b.capacity)
            ydata = decode(ycol)
            yvalid = vh if ycol.valid is None else (vh & np.asarray(
                ycol.valid))
            nv = np.asarray(eval_expr(a.args[2], b, self.ctx).data)
            topn = int(nv if nv.ndim == 0 else nv.flat[0])
            xvalid = np.ones(b.capacity, bool) if col.valid is None \
                else np.asarray(col.valid)
            rows_by_g = [[] for _ in range(n_groups)]
            for row in np.flatnonzero(np.asarray(b.sel) & yvalid):
                g = int(gidh[row])
                if 0 <= g < n_groups:
                    rows_by_g[g].append(row)
            tuples = np.empty(n_groups, dtype=object)
            out = []
            for g_rows in rows_by_g:
                g_rows.sort(key=lambda r: host(ydata[r]),
                            reverse=(a.fn == "max_by"))
                out.append(tuple(
                    host(data[r]) if xvalid[r] else None
                    for r in g_rows[:topn]))
            tuples[:] = out
            return _tuples_to_dict_column(tuples, nonempty, a.type)
        if a.fn == "evaluate_classifier_predictions":
            # accuracy + per-label precision/recall summary (reference:
            # presto-ml EvaluateClassifierPredictionsAggregation)
            pcol = to_column(eval_expr(a.args[1], b, self.ctx), b.capacity)
            pdata = decode(pcol)
            pvh = vh if pcol.valid is None else (vh & np.asarray(pcol.valid))
            texts = np.empty(n_groups, dtype=object)
            stats = [([], []) for _ in range(n_groups)]
            for row in np.flatnonzero(pvh):
                g = int(gidh[row])
                if 0 <= g < n_groups:
                    stats[g][0].append(host(data[row]))
                    stats[g][1].append(host(pdata[row]))
            for g, (truth, pred) in enumerate(stats):
                n = len(truth)
                if n == 0:
                    texts[g] = ""
                    continue
                correct = sum(1 for t, p in zip(truth, pred) if t == p)
                lines = [f"Accuracy: {correct}/{n} "
                         f"({100.0 * correct / n:.2f}%)"]
                for lab in sorted({*truth, *pred}, key=repr):
                    tp = sum(1 for t, p in zip(truth, pred)
                             if t == p == lab)
                    pp = sum(1 for p in pred if p == lab)
                    ap = sum(1 for t in truth if t == lab)
                    if pp:
                        lines.append(f"Precision({lab}): {tp}/{pp} "
                                     f"({100.0 * tp / pp:.2f}%)")
                    if ap:
                        lines.append(f"Recall({lab}): {tp}/{ap} "
                                     f"({100.0 * tp / ap:.2f}%)")
                texts[g] = "\n".join(lines)
            return _tuples_to_dict_column(texts, nonempty, a.type)
        # reduce_agg: vectorized input apply + per-level tree combine
        from presto_tpu.exec.colval import LambdaVal

        _value_ref, init_ref, in_lam, comb_lam = a.args
        in_l = LambdaVal(in_lam.params, in_lam.param_types, in_lam.body,
                         self.ctx, in_lam.type)
        comb_l = LambdaVal(comb_lam.params, comb_lam.param_types,
                           comb_lam.body, self.ctx, comb_lam.type)
        from presto_tpu.functions.scalar import (_colval_from_pylist,
                                                 _pylist_from_colval)

        init_v = eval_expr(init_ref, b, self.ctx)
        init_host = _pylist_from_colval(init_v, 1)[0]
        st = a.type
        rows = np.flatnonzero(vh)
        vals = [host(data[r]) for r in rows]
        if vals:
            states = _pylist_from_colval(
                in_l.apply({
                    in_lam.params[0]: _colval_from_pylist(
                        [init_host] * len(vals), st),
                    in_lam.params[1]: _colval_from_pylist(
                        vals, col.type)}), len(vals))
        else:
            states = []
        per_group: list = [[] for _ in range(n_groups)]
        for r, s in zip(rows, states):
            g = int(gidh[r])
            if 0 <= g < n_groups:
                per_group[g].append(s)
        # tree combine: one vectorized lambda apply per level
        while any(len(g) > 1 for g in per_group):
            lefts, rights, slots = [], [], []
            for gi, g in enumerate(per_group):
                nxt = []
                i = 0
                while i + 1 < len(g):
                    slots.append((gi, len(nxt)))
                    lefts.append(g[i])
                    rights.append(g[i + 1])
                    nxt.append(None)  # placeholder
                    i += 2
                if i < len(g):
                    nxt.append(g[i])
                per_group[gi] = nxt
            combined = _pylist_from_colval(
                comb_l.apply({
                    comb_lam.params[0]: _colval_from_pylist(lefts, st),
                    comb_lam.params[1]: _colval_from_pylist(rights, st)}),
                len(lefts))
            for (gi, si), val in zip(slots, combined):
                per_group[gi][si] = val
        results = [g[0] if g else None for g in per_group]
        return to_column(_colval_from_pylist(results, st), n_groups)

    def _approx_percentile_host(self, b: Batch, a: ir.AggCall, gid,
                                n_groups, col, valid, nonempty) -> Column:
        """Array-of-percentiles and weighted approx_percentile: exact
        host computation per group over (value, cumulative weight)
        (reference: Approximate*PercentileArrayAggregations and the
        weighted overloads; exact beats approximate at these sizes)."""
        has_weight = len(a.args) >= 3
        pv = eval_expr(a.args[2 if has_weight else 1], b, self.ctx)
        if pv.dictionary is not None:  # ARRAY of percentiles
            ps = list(pv.dictionary.values[int(np.asarray(pv.data).flat[0])])
            array_out = True
        else:
            p0 = np.asarray(pv.data)
            ps = [float(p0 if p0.ndim == 0 else p0.flat[0])]
            array_out = False
        data = np.asarray(col.data, np.float64)
        if col.type.is_decimal:
            data = data / (10 ** col.type.decimal_scale)
        wts = np.ones(b.capacity)
        if has_weight:
            wcol = to_column(eval_expr(a.args[1], b, self.ctx), b.capacity)
            wts = np.asarray(wcol.data, np.float64)
            if wts.ndim == 0:
                wts = np.full(b.capacity, float(wts))
        gidh = np.asarray(gid)
        vh = np.asarray(valid)
        outs = np.empty(n_groups, dtype=object)
        scalar_vals = np.zeros(n_groups)
        for g in range(n_groups):
            m = (gidh == g) & vh & (wts > 0)
            if not m.any():
                outs[g] = None
                continue
            v = data[m]
            w = wts[m]
            o = np.argsort(v, kind="stable")
            v, w = v[o], w[o]
            cw = np.cumsum(w)
            qs = []
            for p in ps:
                # first value whose cumulative weight reaches p * total
                i = int(np.searchsorted(cw, float(p) * cw[-1],
                                        side="left"))
                qs.append(float(v[min(i, len(v) - 1)]))
            outs[g] = tuple(qs)
            scalar_vals[g] = qs[0]
        if array_out:
            et = a.type.params[0]
            if et.is_integer:
                outs_t = np.empty(n_groups, dtype=object)
                outs_t[:] = [None if t is None
                             else tuple(int(x) for x in t) for t in outs]
                outs = outs_t
            ok = jnp.asarray(np.asarray(
                [t is not None for t in outs], bool)) & nonempty
            tuples = np.empty(n_groups, dtype=object)
            tuples[:] = [t if t is not None else () for t in outs]
            return _tuples_to_dict_column(tuples, ok, a.type)
        vals = scalar_vals
        if a.type.is_integer:
            vals = np.rint(vals)
        ok = jnp.asarray(np.asarray([t is not None for t in outs], bool))
        return Column(jnp.asarray(vals.astype(a.type.numpy_dtype())),
                      ok & nonempty, a.type)

    def _classification_host(self, b: Batch, a: ir.AggCall, gid,
                             n_groups, nonempty) -> Column:
        """classification_{miss_rate, fall_out, precision, recall,
        thresholds}(buckets, truth, prediction[, weight]) ->
        ARRAY(DOUBLE) at thresholds i/buckets (reference:
        PrecisionRecallAggregation family; prediction >= threshold
        counts as a positive call)."""
        bk = np.asarray(eval_expr(a.args[0], b, self.ctx).data)
        buckets = int(bk if bk.ndim == 0 else bk.flat[0])
        if buckets < 2:
            raise ExecutionError(f"{a.fn}: buckets must be >= 2")
        tcol = to_column(eval_expr(a.args[1], b, self.ctx), b.capacity)
        pcol = to_column(eval_expr(a.args[2], b, self.ctx), b.capacity)
        truth = np.asarray(tcol.data, bool)
        pred = np.asarray(pcol.data, np.float64)
        wts = np.ones(b.capacity)
        if len(a.args) > 3:
            wcol = to_column(eval_expr(a.args[3], b, self.ctx), b.capacity)
            wts = np.asarray(wcol.data, np.float64)
        vh = np.asarray(b.sel)
        for c in (tcol, pcol):
            if c.valid is not None:
                vh = vh & np.asarray(c.valid)
        if np.any(vh & ((pred < 0) | (pred > 1))):
            raise ExecutionError(
                f"{a.fn}: predictions must be in [0, 1]")
        gidh = np.asarray(gid)
        th = np.arange(buckets) / buckets
        tuples = np.empty(n_groups, dtype=object)
        for g in range(n_groups):
            m = (gidh == g) & vh
            if not m.any():
                tuples[g] = ()
                continue
            t, p, w = truth[m], pred[m], wts[m]
            pos = p[:, None] >= th[None, :]  # (rows, buckets)
            tp = (w[:, None] * (pos & t[:, None])).sum(0)
            fp = (w[:, None] * (pos & ~t[:, None])).sum(0)
            fn_ = (w[:, None] * (~pos & t[:, None])).sum(0)
            tn = (w[:, None] * (~pos & ~t[:, None])).sum(0)
            with np.errstate(invalid="ignore", divide="ignore"):
                if a.fn == "classification_thresholds":
                    out = th
                elif a.fn == "classification_precision":
                    out = tp / (tp + fp)
                elif a.fn == "classification_recall":
                    out = tp / (tp + fn_)
                elif a.fn == "classification_miss_rate":
                    out = fn_ / (tp + fn_)
                else:  # fall_out
                    out = fp / (fp + tn)
            tuples[g] = tuple(None if np.isnan(x) else float(x)
                              for x in np.broadcast_to(out, th.shape))
        return _tuples_to_dict_column(tuples, nonempty, a.type)

    def _merge_agg_column(self, b: Batch, a: ir.AggCall, gid, n_groups,
                          mask) -> Column:
        """FINAL-step merges over gathered partial states (reference:
        AggregationNode.Step.FINAL combining intermediate accumulator
        pages).  Args are Refs to partial-state columns."""

        def summed(e, zero=0.0):
            c = to_column(eval_expr(e, b, self.ctx), b.capacity)
            valid = mask if c.valid is None else (mask & c.valid)
            x = jnp.where(valid, c.data, jnp.asarray(zero, c.data.dtype))
            return K.segment_sum(x, gid, n_groups), K.segment_sum(
                valid.astype(jnp.int32), gid, n_groups).astype(jnp.int64)

        if a.fn == "merge_count":
            s, _ = summed(a.args[0], 0)
            return Column(s.astype(jnp.int64), None, T.BIGINT)
        if a.fn == "merge_avg":
            s, _ = summed(a.args[0])
            c, _ = summed(a.args[1], 0)
            c = c.astype(jnp.int64)
            return Column(s / jnp.maximum(c, 1), c > 0, T.DOUBLE)
        # merge_stddev*/merge_var*: args (sum, sum_sq, count)
        s1, _ = summed(a.args[0])
        s2, _ = summed(a.args[1])
        cnt, _ = summed(a.args[2], 0)
        cnt = cnt.astype(jnp.int64)
        n = jnp.maximum(cnt, 1).astype(jnp.float64)
        var_pop = jnp.maximum(s2 / n - (s1 / n) ** 2, 0.0)
        fn = a.fn[len("merge_"):]
        if fn in ("stddev", "stddev_samp", "variance", "var_samp"):
            denom = jnp.maximum(cnt - 1, 1).astype(jnp.float64)
            var = var_pop * n / denom
            ok = cnt > 1
        else:
            var = var_pop
            ok = cnt > 0
        r = jnp.sqrt(var) if fn.startswith("stddev") else var
        return Column(r, ok, T.DOUBLE)

    def _global_aggregate(self, b: Batch, aggs: Dict[str, ir.AggCall]) -> Batch:
        gid = jnp.zeros((b.capacity,), dtype=jnp.int64)
        out_cols = {}
        for sym, a in aggs.items():
            c = self._agg_column(b, a, gid, 1)
            out_cols[sym] = c
        return Batch(out_cols, jnp.ones((1,), bool))

    # ---- joins -------------------------------------------------------
    def _exec_spatialjoin(self, node) -> Batch:
        """Grid-indexed spatial inner join (reference:
        SpatialJoinOperator over PagesRTreeIndex; see P.SpatialJoin for
        the TPU-native redesign).  Dynamic-mode only: the match count is
        data-dependent."""
        if self.static:
            raise StaticFallback("spatial join is dynamic-mode only")
        from presto_tpu.functions import geospatial as GEO

        left = self.exec_node(node.left)
        right = self.exec_node(node.right)
        lrows = np.flatnonzero(np.asarray(left.sel))
        rrows = np.flatnonzero(np.asarray(right.sel))

        def coords(batch, rows, sym):
            c = batch.columns[sym]
            v = np.asarray(c.data, np.float64)[rows]
            if c.valid is not None:
                v = np.where(np.asarray(c.valid)[rows], v, np.nan)
            return v

        px = coords(left, lrows, node.probe_x)
        py = coords(left, lrows, node.probe_y)
        # NULL coordinates (NaN after masking) match nothing — drop them
        # BEFORE the grid, where a NaN would poison the cell math
        pkeep = np.isfinite(px) & np.isfinite(py)
        lrows, px, py = lrows[pkeep], px[pkeep], py[pkeep]
        if node.kind == "contains":
            gc = right.columns[node.build_geom]
            if gc.dictionary is None:
                raise ExecutionError("spatial join build side must be a "
                                     "geometry/varchar column")
            if gc.valid is not None:  # NULL geometry matches nothing
                rrows = rrows[np.asarray(gc.valid)[rrows]]
            codes = np.clip(np.asarray(gc.data)[rrows], 0,
                            len(gc.dictionary) - 1)
            entries = gc.dictionary.values
            # parse + index per DISTINCT referenced entry (a
            # low-cardinality geometry column must not replicate its
            # edge arrays per row, and unreferenced dictionary entries
            # must not poison the join)
            uniq, inv = np.unique(codes, return_inverse=True)
            geoms = []
            for c in uniq:
                g = entries[int(c)]
                g = g if isinstance(g, tuple) else GEO.parse_wkt(str(g))
                if g[0] not in ("polygon",):
                    raise ExecutionError(
                        f"spatial join build over {g[0]} geometries is "
                        "not supported (polygons only)")
                geoms.append(g)
            li, gi = GEO.grid_contains_join(px, py, geoms)
            # expand geometry matches back to build ROWS sharing the code
            order = np.argsort(inv, kind="stable")
            starts = np.searchsorted(inv[order], np.arange(len(uniq)))
            ends = np.searchsorted(inv[order], np.arange(len(uniq)),
                                   side="right")
            counts = ends[gi] - starts[gi]
            li = np.repeat(li, counts)
            flat = (np.arange(int(counts.sum()), dtype=np.int64)
                    - np.repeat(np.concatenate(
                        [[0], np.cumsum(counts)[:-1]]) if len(counts)
                        else np.empty(0, np.int64), counts)
                    + np.repeat(starts[gi], counts))
            ri = order[flat]
        else:
            bx = coords(right, rrows, node.build_x)
            by = coords(right, rrows, node.build_y)
            bkeep = np.isfinite(bx) & np.isfinite(by)
            rrows, bx, by = rrows[bkeep], bx[bkeep], by[bkeep]
            li, ri = GEO.grid_distance_join(px, py, bx, by, node.radius,
                                            node.strict)
        lgat = jnp.asarray(lrows[li]) if len(li) else jnp.zeros(0, jnp.int32)
        rgat = jnp.asarray(rrows[ri]) if len(ri) else jnp.zeros(0, jnp.int32)
        lb = K.gather_batch(left, lgat)
        rb = K.gather_batch(right, rgat)
        merged = dict(lb.columns)
        merged.update(rb.columns)
        out = Batch(merged, jnp.ones((len(li),), bool))
        if node.filter is not None:
            out = Batch(merged, eval_predicate(node.filter, out, self.ctx))
        return out

    def _maybe_compact_static(self, b: Batch, est) -> Batch:
        """Guarded estimate-driven compaction (see _aggregate_static):
        dropping masked rows is always semantically safe; the guard
        covers the estimate being wrong."""
        if not self.static or est is None or b.capacity < (1 << 19):
            return b
        bound = 1 << max(int(np.ceil(np.log2(max(est, 1) * 2))), 14)
        if bound > min(b.capacity // 4, 1 << 20):
            return b
        self.guards.append(jnp.sum(b.sel.astype(jnp.int32)) > bound)
        out = _compact_batch(b, bound)
        e = self._batch_order.get(id(b))
        if e is not None and e[0] is b:
            # compaction keeps live rows in order AND moves them to a
            # prefix: certainty upgrades to tail-masked
            self._note_order(out, e[1], tail_ok=True)
        return out

    def _exec_join(self, node: P.Join) -> Batch:
        from presto_tpu.memory.context import batch_bytes

        produce = getattr(node, "rf_produce", None)
        if produce and node.join_type in ("INNER", "SEMI") \
                and self._df_enabled() and self._rf_build_complete(node):
            # dynamic filtering: run the BUILD side first and register
            # its key summary, so the probe subtree's scans consume the
            # completed filter before they execute (the reference gates
            # probe-side scan startup on build completion the same way)
            right = self.exec_node(node.right)
            self._rf_register(produce, right)
            left = self.exec_node(node.left)
        else:
            left = self.exec_node(node.left)
            right = self.exec_node(node.right)
        left = self._maybe_compact_static(
            left, getattr(node, "left_est_hint", None))
        if getattr(node, "index_lookup", None) is None:
            # index joins need the build side's whole-table natural
            # order — never compact it
            right = self._maybe_compact_static(
                right, getattr(node, "right_est_hint", None))
        if node.join_type == "RIGHT":
            # RIGHT = mirrored LEFT with output order left-cols-first
            node = P.Join(node.right, node.left, "LEFT",
                          [(rk, lk) for lk, rk in node.criteria], node.filter)
            left, right = right, left
        # spill-tiered degradation (exec/spill_exec.py): correct for
        # INNER/LEFT/FULL equi-joins — every match pair lands in one
        # key-hash partition and unmatched rows surface exactly once.
        # SEMI/ANTI stay unspilled: their null-semantics couple
        # partitions.  The PR-5 dynamic filter above already pruned the
        # probe sel, and the live_est_fn re-probe lets a filter-shrunken
        # probe keep the join fully resident (compacted) — the
        # interaction the robust-HHJ paper highlights.
        if node.join_type in ("INNER", "LEFT", "FULL") and node.criteria \
                and not self.static:
            from presto_tpu.exec import spill_exec as SE

            def live_est():
                nl = int(jax.device_get(left.row_count()))
                nr = int(jax.device_get(right.row_count()))
                bl = batch_bytes(left) * nl / max(left.capacity, 1)
                br = batch_bytes(right) * nr / max(right.capacity, 1)
                return SE.WORKING_SET_FACTOR * (bl + br)

            dec = SE.plan_degradation(
                self, node,
                SE.WORKING_SET_FACTOR * (batch_bytes(left)
                                         + batch_bytes(right)),
                left.capacity + right.capacity, live_est_fn=live_est)
            if dec.degrade:
                holder = [left, right]
                del left, right  # holder owns the refs; spill path frees
                return SE.hybrid_join(self, holder, node, dec)
            if dec.mem_key:
                try:
                    if dec.budget == -1:
                        # filter-kept residency: shed the pruned rows so
                        # the live working set is what HBM actually holds
                        left = K.compact(left)
                        right = K.compact(right)
                    return self._join_batches(left, right, node)
                finally:
                    self.mem.set_bytes(dec.mem_key, 0)
        out = self._join_batches(left, right, node)
        if node.join_type in ("SEMI", "ANTI", "MARK"):
            # probe masked in place: row positions untouched
            self._copy_order(left, out)
        return out

    def _join_batches(self, left: Batch, right: Batch, node: P.Join) -> Batch:
        jt = node.join_type
        if jt == "CROSS":
            return self._cross_join(left, right, node)
        if jt == "FULL":
            return self._full_join(left, right, node)
        if right.capacity == 0 and jt in ("INNER", "LEFT", "SEMI",
                                          "ANTI", "MARK"):
            # zero-capacity build (e.g. an empty side an outer join must
            # preserve): no row matches, and gathers into zero-length
            # arrays are not representable — emit the no-match result
            # shape directly
            if jt == "SEMI":
                return left.with_sel(jnp.zeros_like(left.sel))
            if jt == "ANTI":
                return left
            merged = dict(left.columns)
            if jt == "MARK":
                # x IN (empty) is FALSE, never NULL, for every probe
                merged[node.mark] = Column(
                    jnp.zeros((left.capacity,), bool), None, T.BOOLEAN,
                    None)
                return Batch(merged, left.sel)
            never = jnp.zeros((left.capacity,), bool)
            for name, t in node.right.outputs():
                c = right.columns[name]
                shape = (left.capacity,) + tuple(c.data.shape[1:])
                merged[name] = Column(jnp.zeros(shape, c.data.dtype),
                                      never, t, c.dictionary)
            if jt == "INNER":
                return Batch(merged, never)
            return Batch(merged, left.sel)  # LEFT: all rows, NULL right
        lkeys = [left.columns[lk] for lk, _ in node.criteria]
        rkeys = [right.columns[rk] for _, rk in node.criteria]
        lkeys, rkeys = _unify_key_dictionaries(lkeys, rkeys)
        # SQL equi-join: NULL never matches NULL — exclude null-keyed rows
        # (pack_keys' null code is a GROUP BY semantic, not a join one)
        lsel = left.sel
        rsel = right.sel
        for c in lkeys:
            if c.valid is not None:
                lsel = lsel & c.valid
        for c in rkeys:
            if c.valid is not None:
                rsel = rsel & c.valid
        # P10 index join: dense unique build key -> the probe is ONE
        # gather at position key - key_min, no sorts at all (hint from
        # plan/optimizer._index_lookup_info).  The identity layout
        # (row i holds key min+i) only holds when the build batch is the
        # WHOLE table in natural order — sharded executors re-split
        # scans (allow_index_join=False there), and a build-side layout
        # verification catches everything else: a guard in static mode,
        # a host check (fall back to the sort join) in dynamic mode.
        il = getattr(node, "index_lookup", None)
        bk = il.get("block_keys", 1) if il else 1
        br = il.get("block_rows", 1) if il else 1
        strided = (bk, br) != (1, 1)
        full_build = il is not None and right.capacity == il["rows"]
        use_index = (il is not None and self.allow_index_join
                     and len(lkeys) == 1
                     # strided layouts also run over CHUNK-sized builds:
                     # bucket-aligned chunks are contiguous row ranges,
                     # so the layout holds with a chunk-local base taken
                     # from the build data itself (traced)
                     and (full_build or strided)
                     and lkeys[0].dictionary is None
                     and rkeys[0].dictionary is None
                     and getattr(lkeys[0].data, "ndim", 1) == 1)
        if use_index and strided:
            # strided builds: the index gather runs at PROBE capacity
            # and the output stays there, while the sort join's output
            # materializes at its est-driven bound — which wins big
            # whenever upstream filters/semi-joins leave the build
            # sparse (measured: SF1 Q3 6M/1.5M loses ~150ms; SF100 Q18
            # chunks with a highly selective semi-join upstream lose
            # ~7%).  Gate to probes not much wider than the build.
            use_index = lkeys[0].data.shape[0] <= 2 * right.capacity
        index_ridx = None
        if il is not None and os.environ.get("PRESTO_TPU_DEBUG_INDEX"):
            import sys as _sys

            print(f"index-join debug: {node.criteria} use_index="
                  f"{use_index} full_build={full_build} strided={strided} "
                  f"rcap={right.capacity} lcap={lkeys[0].data.shape if hasattr(lkeys[0].data, 'shape') else '?'}",
                  file=_sys.stderr, flush=True)
        if use_index:
            nrows = right.capacity
            rk_arr = jnp.asarray(rkeys[0].data).astype(jnp.int64)
            ar = jnp.arange(nrows, dtype=jnp.int64)
            # row i holds key base + (i // br) * bk + i % br — dense
            # layouts are the bk == br == 1 case (identity)
            if full_build:
                base = jnp.asarray(il["min"], jnp.int64)
            else:
                # chunk-local base from the data; the verification
                # below proves the whole layout against it in-trace
                base = rk_arr[0]
            expect = base + (ar // br) * bk + ar % br \
                if strided else base + ar
            layout_ok = ~jnp.any(rsel & (rk_arr != expect))
            if self.static:
                self.guards.append(~layout_ok)
            elif not bool(layout_ok):
                use_index = False
        if use_index:
            lk = jnp.asarray(lkeys[0].data).astype(jnp.int64)
            off = lk - base
            if strided:
                pos_raw = (off // bk) * br + off % bk
                in_slot = (off % bk) < br  # keys between blocks miss
            else:
                pos_raw = off
                in_slot = jnp.ones_like(off, bool)
            pos = jnp.clip(pos_raw, 0, nrows - 1).astype(jnp.int32)
            in_range = (off >= 0) & (pos_raw < nrows) & in_slot
            rkd = jnp.asarray(rkeys[0].data)[pos].astype(jnp.int64)
            found_idx = lsel & in_range & rsel[pos] & (rkd == lk)
            counts = found_idx.astype(jnp.int32)
            index_ridx = pos
        elif self.static:
            # compile-time layout from stats/dictionaries (shared ranges
            # across both sides); unknown ranges -> sync-free 64-bit hash
            key_stats = getattr(node, "key_stats", {})
            merged_stats = []
            for (lk, rk), lc, rc in zip(node.criteria, lkeys, rkeys):
                ls_, rs_ = key_stats.get(lk), key_stats.get(rk)
                merged_stats.append(_merge_range(ls_, rs_))
            layout = K.static_layout(rkeys, merged_stats)
            rkey = K.pack_with_layout(rkeys, rsel, layout)
            lkey = K.pack_with_layout(lkeys, lsel, layout)
            if layout is not None:
                self.guards.append(K.layout_range_guard(rkeys, rsel, layout))
                self.guards.append(K.layout_range_guard(lkeys, lsel, layout))
        else:
            rkey, layout = K.pack_keys(rkeys, rsel, extra_cols=lkeys)
            lkey = K.pack_with_layout(lkeys, lsel, layout)
        if index_ridx is None:
            build_order = None
            if layout is not None and self._ordering_enabled() \
                    and self._build_presorted(node, right, rkeys):
                # presorted build: the packed build key is fully
                # nondecreasing (sorted input, masked rows — sentinels —
                # confined to a suffix, e.g. a static aggregate's exists
                # tail), so the build argsort is the identity.  Certain
                # (runtime-channel) claims skip the dynamic host check;
                # static mode guards every claim — a reasoning bug
                # becomes a dynamic fallback, never wrong matches.
                certain = self._build_order_certain(node, right, rkeys)
                if self.static:
                    self.guards.append(K.monotone_guard(rkey))
                    build_order = jnp.arange(rkey.shape[0],
                                             dtype=jnp.int32)
                    self._count("sorts_elided")
                elif certain or not bool(K.monotone_guard(rkey)):
                    build_order = jnp.arange(rkey.shape[0],
                                             dtype=jnp.int32)
                    self._count("sorts_elided")
                else:
                    self._count("ordering_guard_trips")
            if build_order is None:
                # fingerprint over the COMPONENTS of rsel (base sel +
                # key validities, already in fp) so the two probes of a
                # shared build subtree hash alike
                fp, refs = self._key_fp(rkeys, right.sel, layout)
                build_order = self._memo_pair(rkey, fp, refs)[1]
            order, lb, ub = K.build_probe(rkey, lkey,
                                          build_order=build_order)
            self._count("sorts_taken", 2)  # composite sort + co-sort home
            counts = ub - lb

        if jt == "MARK":  # filter-free by construction (planner)
            # Presto semiJoinOutput NULL semantics: TRUE on match;
            # without a match the mark is NULL (not FALSE) when the
            # probe key is NULL or the build side contains any NULL —
            # `x NOT IN (sub)` must then filter the row, not keep it
            # (reference: SemiJoinNode / MarkDistinct null handling)
            merged = dict(left.columns)
            found = counts > 0
            lvalid = None
            for c in lkeys:
                if c.valid is not None:
                    v_ = jnp.asarray(c.valid)
                    lvalid = v_ if lvalid is None else (lvalid & v_)
            rnull = None
            for c in rkeys:
                if c.valid is not None:
                    has = jnp.any(right.sel & ~jnp.asarray(c.valid))
                    rnull = has if rnull is None else (rnull | has)
            if lvalid is None and rnull is None:
                mvalid = None  # keys can't be NULL: mark is 2-valued
            else:
                ok = jnp.ones_like(found) if lvalid is None else lvalid
                if rnull is not None:
                    ok = ok & ~rnull
                # An empty subquery makes the mark definitively FALSE no
                # matter what the probe key is: `NULL NOT IN (empty)` is
                # TRUE, so the mark must be valid-FALSE, not NULL.  Use
                # right.sel (all build rows), not rsel — a build of only
                # NULL keys is NOT empty and must keep the NULL mark.
                build_nonempty = jnp.any(right.sel)
                mvalid = found | ok | ~build_nonempty
            merged[node.mark] = Column(found, mvalid, T.BOOLEAN, None)
            return Batch(merged, left.sel)

        if jt in ("SEMI", "ANTI") and node.filter is None:
            found = counts > 0
            sel = left.sel & (found if jt == "SEMI" else ~found)
            return left.with_sel(sel)

        if index_ridx is not None:
            max_matches = 1  # dense unique build: at most one match,
            # no guard and (in dynamic mode) no max-count host sync
        elif self.static:
            if getattr(node, "build_unique", False):
                max_matches = 1
                if counts.shape[0]:
                    self.guards.append(jnp.max(counts) > 1)
            else:
                bound = getattr(node, "fanout_bound", None)
                if bound is None:
                    raise StaticFallback(
                        f"join fanout unbounded ({node.join_type} on {node.criteria})")
                if counts.shape[0]:
                    self.guards.append(jnp.max(counts) > bound)
                return self._expanding_join_static(left, right, node, order, lb,
                                                   counts, bound)
        else:
            max_matches = int(jnp.max(counts)) if counts.shape[0] else 0

        if max_matches <= 1 and jt in ("INNER", "LEFT", "SEMI", "ANTI"):
            found = counts > 0
            if index_ridx is not None:
                ridx = index_ridx
            else:
                match_pos = jnp.clip(lb, 0, max(order.shape[0] - 1, 0))
                ridx = order[match_pos]
            rbatch = K.gather_batch(right, ridx, idx_valid=found)
            merged = dict(left.columns)
            merged.update(rbatch.columns)
            if node.filter is not None:
                fb = Batch(merged, left.sel)
                fmask = eval_predicate(node.filter, fb, self.ctx)
                found = found & fmask
                # data is independent of the match mask — only the
                # validity tightens, so refresh masks without re-gathering
                for name, c in rbatch.columns.items():
                    v = found if c.valid is None else (c.valid & found)
                    merged[name] = Column(c.data, v, c.type, c.dictionary)
            if jt == "SEMI":
                return left.with_sel(left.sel & found)
            if jt == "ANTI":
                return left.with_sel(left.sel & ~found)
            if jt == "INNER":
                return Batch(merged, left.sel & found)
            return Batch(merged, left.sel)  # LEFT

        # one-to-many: expand
        return self._expanding_join(left, right, node, order, lb, counts)

    def _expanding_join_static(self, left: Batch, right: Batch, node: P.Join,
                               order, lb, counts, bound: int) -> Batch:
        """One-to-many join with a STATIC per-probe-row slot layout: probe
        row i owns output slots [i*F, (i+1)*F), F = connector fanout bound
        (e.g. <=7 lineitems per order).  Unmatched slots are masked, not
        skipped — shape stays compile-time constant."""
        jt = node.join_type
        n = left.capacity
        total = n * bound
        if total > 100_000_000:
            raise StaticFallback(
                f"static expansion too large: {n} x fanout {bound}")
        counts = jnp.where(left.sel, counts, 0)
        lidx = jnp.repeat(jnp.arange(n, dtype=jnp.int32), bound,
                          total_repeat_length=total)
        k = jnp.tile(jnp.arange(bound, dtype=jnp.int32), n)
        cnt_l, lb_l = K.take_rows(
            [jnp.minimum(counts, bound).astype(jnp.int32),
             lb.astype(jnp.int32)], lidx, presorted=True)
        slot_live = k < cnt_l
        rpos = jnp.clip(lb_l + k, 0, max(order.shape[0] - 1, 0))
        ridx = order[rpos]
        if self._order_ok(node) and GA.sort_order_worthwhile(
                total, K.batch_word_width(right) - K.batch_word_width(left)):
            # sort-order materialization: every consumer up the tree is
            # order-insensitive, so the join output simply STAYS in
            # build-index order — the wide right side gathers
            # sequentially and nobody pays the way back.  The slot
            # arithmetic (k, slot_live) and the probe indices ride the
            # one planning sort.
            ridx, (lidx, k, slot_live) = K.sort_order_plan(
                ridx, lidx, k, slot_live)
            lbatch = K.gather_batch(left, lidx)
            rbatch = K.gather_batch(right, ridx, idx_valid=slot_live,
                                    presorted=True)
        else:
            # lidx is repeat(arange): nondecreasing by construction
            lbatch = K.gather_batch(left, lidx, presorted=True)
            rbatch = K.gather_batch(right, ridx, idx_valid=slot_live)
        merged = dict(lbatch.columns)
        merged.update(rbatch.columns)
        out = Batch(merged, lbatch.sel & slot_live)
        match_ok = out.sel
        if node.filter is not None:
            match_ok = match_ok & eval_predicate(node.filter, out, self.ctx)
        if jt == "INNER":
            return out.with_sel(match_ok)
        if jt in ("SEMI", "ANTI"):
            hit = K.segment_any(match_ok, lidx, n)
            want = hit if jt == "SEMI" else ~hit
            return left.with_sel(left.sel & want)
        if jt == "LEFT":
            any_ok = K.segment_any(match_ok, lidx, n)
            first_slot = k == 0
            keep = jnp.where(any_ok[lidx], match_ok, first_slot & left.sel[lidx])
            rvalid = match_ok
            for name in rbatch.columns:
                c = merged[name]
                v = rvalid if c.valid is None else (c.valid & rvalid)
                merged[name] = Column(c.data, v, c.type, c.dictionary)
            return Batch(merged, keep)
        raise StaticFallback(f"static join type {jt} not supported")

    def _expanding_join(self, left: Batch, right: Batch, node: P.Join,
                        order, lb, counts) -> Batch:
        jt = node.join_type
        counts = jnp.where(left.sel, counts, 0)
        eff_counts = counts
        if jt in ("LEFT", "FULL"):
            eff_counts = jnp.where(left.sel & (counts == 0), 1, counts)
        offsets = jnp.cumsum(eff_counts) - eff_counts
        total = int(jnp.sum(eff_counts))
        if total == 0:
            # empty result with merged schema
            merged = dict(left.columns)
            for name, c in right.columns.items():
                merged[name] = c
            empty = {n: Column(c.data[:0], None if c.valid is None else c.valid[:0],
                               c.type, c.dictionary) for n, c in merged.items()}
            return Batch(empty, jnp.zeros((0,), bool))
        lidx = jnp.repeat(jnp.arange(left.capacity), eff_counts,
                          total_repeat_length=total)
        k = jnp.arange(total) - offsets[lidx]
        has_match = counts[lidx] > 0
        rpos = jnp.clip(lb[lidx] + k, 0, max(order.shape[0] - 1, 0))
        ridx = order[rpos]
        if self._order_ok(node) and GA.sort_order_worthwhile(
                total, K.batch_word_width(right) - K.batch_word_width(left)):
            # sort-order materialization (see _expanding_join_static)
            ridx, (lidx, k, has_match) = K.sort_order_plan(
                ridx, lidx, k, has_match)
            rbatch = K.gather_batch(right, ridx, idx_valid=has_match,
                                    presorted=True)
            lbatch = K.gather_batch(left, lidx)
        else:
            rbatch = K.gather_batch(right, ridx, idx_valid=has_match)
            # lidx is repeat(arange): nondecreasing by construction
            lbatch = K.gather_batch(left, lidx, presorted=True)
        merged = dict(lbatch.columns)
        merged.update(rbatch.columns)
        sel = lbatch.sel
        out = Batch(merged, sel)
        match_ok = has_match
        if node.filter is not None:
            fmask = eval_predicate(node.filter, out, self.ctx)
            match_ok = match_ok & fmask
        if jt == "INNER":
            return out.with_sel(sel & match_ok)
        if jt in ("SEMI", "ANTI"):
            # any passing match per left row?
            hit = K.segment_any(sel & match_ok, lidx, left.capacity)
            want = hit if jt == "SEMI" else ~hit
            return left.with_sel(left.sel & want)
        if jt == "LEFT":
            # keep one row for unmatched-left; for matched rows apply filter;
            # rows whose every match fails the filter must still appear once
            if node.filter is not None:
                any_ok = K.segment_any(sel & match_ok, lidx,
                                       left.capacity)
                first_of_row = k == 0
                keep = jnp.where(any_ok[lidx], match_ok, first_of_row)
                # null out right side where match failed
                rvalid = match_ok
                for name in rbatch.columns:
                    c = merged[name]
                    v = rvalid if c.valid is None else (c.valid & rvalid)
                    merged[name] = Column(c.data, v, c.type, c.dictionary)
                # dedupe unmatched duplicates: keep only first expansion row
                return Batch(merged, sel & keep)
            return out
        raise ExecutionError(f"join type {jt} not implemented")

    def _full_join(self, left: Batch, right: Batch, node: P.Join) -> Batch:
        """FULL = LEFT(l,r) ++ (rows of r with no match, left side typed
        NULL).  The anti pass mirrors probe/build (reference:
        LookupOuterOperator emitting unmatched build rows after probes
        finish).  Static-shape friendly: output capacity is the LEFT
        expansion plus right's capacity, no host syncs added."""
        lnode = P.Join(node.left, node.right, "LEFT", node.criteria,
                       node.filter)
        for attr in ("build_unique", "fanout_bound", "key_stats"):
            if hasattr(node, attr):
                setattr(lnode, attr, getattr(node, attr))
        left_part = self._join_batches(left, right, lnode)
        anode = P.Join(node.right, node.left, "ANTI",
                       [(rk, lk) for lk, rk in node.criteria], node.filter)
        right_anti = self._join_batches(right, left, anode)
        null_left = {}
        for name, c in left.columns.items():
            cap = right_anti.capacity
            null_left[name] = Column(
                jnp.zeros((cap,), c.data.dtype),
                jnp.zeros((cap,), bool), c.type, c.dictionary)
        # column order must match left_part's (left cols, then right cols)
        ro_cols = dict(null_left)
        ro_cols.update(right_anti.columns)
        right_only = Batch(ro_cols, right_anti.sel)
        return K.concat_batches([left_part, right_only])

    def _cross_join(self, left: Batch, right: Batch, node: P.Join) -> Batch:
        if not self.static:  # compaction needs a host sync
            left = K.compact(left)
            right = K.compact(right)
        nl, nr = left.capacity, right.capacity
        if nl * nr > 50_000_000:
            if self.static:
                # uncompacted capacities can be huge where the compacted
                # cross join is tiny — let the dynamic path try
                raise StaticFallback(f"static cross join too large: {nl} x {nr}")
            raise ExecutionError(f"cross join too large: {nl} x {nr}")
        lidx = jnp.repeat(jnp.arange(nl), nr, total_repeat_length=max(nl * nr, 1))
        ridx = jnp.tile(jnp.arange(nr), nl)[:max(nl * nr, 1)]
        if nl * nr == 0:
            lidx, ridx = lidx[:0], ridx[:0]
        lbatch = K.gather_batch(left, lidx)
        rbatch = K.gather_batch(right, ridx)
        merged = dict(lbatch.columns)
        merged.update(rbatch.columns)
        sel = lbatch.sel & rbatch.sel
        out = Batch(merged, sel)
        if node.filter is not None:
            out = out.with_sel(sel & eval_predicate(node.filter, out, self.ctx))
        return out

    # ---- sort / limit -------------------------------------------------
    def _sort_perm(self, b: Batch, key_spec) -> jnp.ndarray:
        """sort_perm through the permutation memo: an identical sort of
        the same batch (same key columns, same sel, same directions)
        replays the cached permutation."""
        keys = [(b.columns[s], asc, nf) for s, asc, nf in key_spec]
        fp, refs = self._key_fp([c for c, _, _ in keys], b.sel,
                                [("sort", s, bool(asc), nf)
                                 for s, asc, nf in key_spec])
        if not self._ordering_enabled():
            fp = None
        entry = self._perm_memo.get(fp) if fp is not None else None
        if entry is not None:
            self._count("sort_memo_hits")
            self._count("sorts_elided")
            return entry[1]
        self._count("sorts_taken")
        perm = K.sort_perm(b, keys)
        if fp is not None:
            self._perm_memo[fp] = (refs, perm)
        return perm

    def _exec_sort(self, node: P.Sort) -> Batch:
        b = self.exec_node(node.source)
        if self._ordering_enabled() and self._order_satisfies(b, node.keys):
            # input provably sorted (runtime-certain channel: grouped /
            # sorted output upstream): the Sort node is a no-op — live
            # rows already surface in order, masked rows stay hidden
            self._count("sorts_elided")
            return b
        perm = self._sort_perm(b, node.keys)
        out = K.gather_batch(b, perm)
        self._note_order(out, tuple((s, asc) for s, asc, _nf in node.keys))
        return out

    def _exec_topn(self, node: P.TopN) -> Batch:
        """TopN = key-only sort + k-row gather (reference: TopNOperator's
        bounded heap).  The previous full-sort-then-mask shape paid a
        full-capacity gather of EVERY output column to keep k rows —
        ~half of Q3's single-chip wall time at 6M capacity."""
        b = self.exec_node(node.source)
        if self._ordering_enabled() and self._order_satisfies(b, node.keys):
            # already ordered: TopN degenerates to LIMIT (rank mask)
            self._count("sorts_elided")
            out = self._limit(b, node.count)
            self._copy_order(b, out)
            return out
        k = min(int(node.count), b.capacity)
        perm = self._sort_perm(b, node.keys)  # masked rows sort last
        sorted_keys = tuple((s, asc) for s, asc, _nf in node.keys)
        if k == b.capacity:  # LIMIT >= capacity: plain sort
            out = K.gather_batch(b, perm)
            self._note_order(out, sorted_keys)
            return out
        idx = perm[:k]
        out = K.gather_batch(b, idx)
        live_total = jnp.sum(jnp.asarray(b.sel).astype(jnp.int32)) \
            if b.capacity else jnp.int32(0)
        sel = jnp.arange(k, dtype=jnp.int32) < live_total
        out = Batch(out.columns, out.sel & sel)
        self._note_order(out, sorted_keys)
        return out

    def _exec_limit(self, node: P.Limit) -> Batch:
        b = self.exec_node(node.source)
        out = self._limit(b, node.count)
        self._copy_order(b, out)  # rank mask: rows never move
        return out

    def _limit(self, b: Batch, n: int) -> Batch:
        # int32 rank: capacity < 2^31, and i64 cumsum runs emulated on TPU;
        # clamp the count host-side so a giant LIMIT cannot wrap int32
        n = min(int(n), b.capacity)
        rank = jnp.cumsum(b.sel.astype(jnp.int32))
        return b.with_sel(b.sel & (rank <= n))

    # ---- set ops ------------------------------------------------------
    def _exec_unnest(self, node: P.Unnest) -> Batch:
        """Lateral explode (reference: UnnestOperator)."""
        if self.static:
            return self._unnest_static(node)
        b = self.exec_node(node.source)
        v = eval_expr(node.array_expr, b, self.ctx)
        col = to_column(v, b.capacity)
        codes = np.asarray(col.data)
        sel = np.asarray(b.sel)
        live = sel if col.valid is None else (sel & np.asarray(col.valid))
        dvals = col.dictionary.values if col.dictionary is not None else []
        lens = np.asarray([len(t) for t in dvals], dtype=np.int64)
        counts = np.where(live, lens[np.clip(codes, 0, max(len(dvals) - 1, 0))]
                          if len(dvals) else 0, 0)
        total = int(counts.sum())
        idx = np.repeat(np.arange(b.capacity), counts)
        offs = np.concatenate([[0], np.cumsum(counts)])
        k = np.arange(total) - offs[idx]
        elems = []
        for row in np.flatnonzero(counts):
            elems.extend(dvals[codes[row]])
        from presto_tpu.batch import column_from_numpy

        if total == 0:
            elem_col = column_from_numpy(
                np.empty(0, dtype=object if node.elem_type.is_string
                         else node.elem_type.numpy_dtype()), node.elem_type)
            out = K.gather_batch(b, jnp.zeros((0,), jnp.int64))
        else:
            arr = np.asarray(elems, dtype=object) \
                if node.elem_type.is_string else \
                np.asarray(elems, dtype=node.elem_type.numpy_dtype())
            elem_col = column_from_numpy(arr, node.elem_type)
            out = K.gather_batch(b, jnp.asarray(idx))
        cols = dict(out.columns)
        cols[node.out_sym] = elem_col
        if node.ordinality_sym:
            cols[node.ordinality_sym] = Column(
                jnp.asarray(k + 1, jnp.int64), None, T.BIGINT)
        return Batch(cols, jnp.ones((max(total, 0),), bool) if total else
                     jnp.zeros((0,), bool))

    def _unnest_static(self, node: P.Unnest) -> Batch:
        """Static-shape UNNEST: ARRAY columns are int32 codes into a
        host tuple dictionary, which is a TRACE-TIME constant — so the
        ragged expansion precomputes, per dictionary entry, a padded
        (dict_size, maxlen) element matrix + lengths host-side, and the
        traced program is two gathers with a slot-liveness mask.  The
        fanout bound is maxlen (static, from the dictionary), the
        LazyBlock-style analog of UnnestOperator's per-page expansion."""
        b = self.exec_node(node.source)
        v = eval_expr(node.array_expr, b, self.ctx)
        col = to_column(v, b.capacity)
        if col.dictionary is None:
            raise StaticFallback("UNNEST over a non-dictionary array")
        dvals = col.dictionary.values
        lens_h = np.asarray([len(t) for t in dvals], dtype=np.int32)
        maxlen = int(lens_h.max()) if len(lens_h) else 0
        n = b.capacity
        total = n * max(maxlen, 1)
        if total > 50_000_000:
            raise StaticFallback(
                f"static UNNEST expansion too large: {n} x {maxlen}")
        elem_t = node.elem_type
        dsize = max(len(dvals), 1)
        mat_valid = np.zeros((dsize, max(maxlen, 1)), dtype=bool)
        if elem_t.is_string or elem_t.name in ("ARRAY", "MAP", "ROW",
                                               "JSON"):
            uniq = {e for t in dvals for e in t if e is not None}
            # string element dictionaries keep the lex==code-order
            # invariant; nested tuples use repr order (not compared)
            flat = sorted(uniq) if elem_t.is_string else sorted(uniq,
                                                                key=repr)
            edict_vals = np.empty(len(flat), dtype=object)
            edict_vals[:] = flat
            index = {e: i for i, e in enumerate(flat)}
            mat = np.zeros((dsize, max(maxlen, 1)), dtype=np.int32)
            for di, t in enumerate(dvals):
                for k_, e in enumerate(t):
                    if e is not None:
                        mat[di, k_] = index[e]
                        mat_valid[di, k_] = True
            from presto_tpu.batch import Dictionary as _Dict

            edict = _Dict(edict_vals)
        else:
            mat = np.zeros((dsize, max(maxlen, 1)), dtype=elem_t.numpy_dtype())
            for di, t in enumerate(dvals):
                for k_, e in enumerate(t):
                    if e is not None:
                        mat[di, k_] = e
                        mat_valid[di, k_] = True
            edict = None
        codes = jnp.clip(jnp.asarray(col.data), 0, dsize - 1)
        live = b.sel if col.valid is None else (b.sel & col.valid)
        if maxlen == 0:
            out = K.gather_batch(b, jnp.zeros((0,), jnp.int32))
            cols = dict(out.columns)
            cols[node.out_sym] = Column(
                jnp.zeros((0,), mat.dtype), None, elem_t, edict)
            if node.ordinality_sym:
                cols[node.ordinality_sym] = Column(
                    jnp.zeros((0,), jnp.int64), None, T.BIGINT)
            return Batch(cols, jnp.zeros((0,), bool))
        lidx = jnp.repeat(jnp.arange(n, dtype=jnp.int32), maxlen,
                          total_repeat_length=n * maxlen)
        k = jnp.tile(jnp.arange(maxlen, dtype=jnp.int32), n)
        code_l = codes[lidx]
        slot_live = live[lidx] & (k < jnp.asarray(lens_h)[code_l])
        elem_data = jnp.asarray(mat)[code_l, k]
        elem_valid = slot_live & jnp.asarray(mat_valid)[code_l, k]
        out = K.gather_batch(b, lidx, idx_valid=slot_live)
        cols = dict(out.columns)
        cols[node.out_sym] = Column(elem_data, elem_valid, elem_t, edict)
        if node.ordinality_sym:
            cols[node.ordinality_sym] = Column(
                (k + 1).astype(jnp.int64), None, T.BIGINT)
        return Batch(cols, out.sel)

    def _exec_union(self, node: P.Union) -> Batch:
        parts = []
        for src, mapping in zip(node.sources_, node.mappings):
            b = self.exec_node(src)
            cols = {}
            for out_sym in node.symbols:
                c = b.columns[mapping[out_sym]]
                cols[out_sym] = c
            parts.append(Batch(cols, b.sel))
        return K.concat_batches(parts)

    def _exec_output(self, node: P.Output) -> Batch:
        b = self.exec_node(node.source)
        return b.select([s for s in node.symbols])

    # ---- write pipeline (exec/writer.py; reference:
    # TableWriterOperator + TableFinishOperator) -----------------------
    def _exec_tablewriter(self, node) -> Batch:
        ctx = getattr(self, "write_ctx", None)
        if ctx is None:
            raise ExecutionError(
                "TableWriter requires a write context — write statements "
                "execute through exec/writer.run_write")
        from presto_tpu.exec import writer as W

        inner = node.source  # the query's Output node
        b = self.exec_node(inner)
        arrays, types = W._host_arrays(inner, b)
        try:
            n = ctx.write_page(arrays, types)
        except W.WriteError as e:
            raise ExecutionError(str(e)) from e
        return batch_from_numpy({node.rows_symbol:
                                 np.asarray([n], dtype=np.int64)},
                                {node.rows_symbol: T.BIGINT})

    def _exec_tablefinish(self, node) -> Batch:
        b = self.exec_node(node.source)
        ctx = getattr(self, "write_ctx", None)
        if ctx is not None:
            from presto_tpu.exec import writer as W

            try:
                ctx.finish()  # commit: staged files publish atomically
            except W.WriteError as e:
                raise ExecutionError(str(e)) from e
        return b


def _hll_m(a: ir.AggCall) -> int:
    """Register count for an approx_distinct call: the optional second
    argument is a max-standard-error LITERAL (reference:
    ApproximateCountDistinctAggregation's maxStandardError)."""
    if len(a.args) >= 2 and isinstance(a.args[1], ir.Lit) \
            and a.args[1].value is not None:
        return K.hll_m_for_error(float(a.args[1].value))
    return 1024


def _tuples_to_dict_column(tuples: np.ndarray, valid, typ) -> Column:
    """Canonicalize host object tuples into a sorted-unique dictionary
    column (shared by array_agg/map_agg/multimap_agg; the operator-side
    twin of functions.scalar._tuple_dict_normalize)."""
    from presto_tpu.batch import Dictionary as _Dict

    uniq = sorted(set(tuples.tolist()), key=repr)
    cmap = {t: i for i, t in enumerate(uniq)}
    codes = np.fromiter((cmap[t] for t in tuples.tolist()),
                        np.int32, len(tuples))
    u = np.empty(len(uniq), dtype=object)
    u[:] = uniq
    return Column(jnp.asarray(codes), valid, typ, _Dict(u))


def scan_batch(table, node: P.TableScan, f32: bool = False,
               runtime_domains=None) -> Batch:
    """Read + ingest a table's columns, with a per-table device-column
    cache (upload + dictionary-encode once per process; reference analog:
    a connector page source feeding a cache — here the 'page' is the whole
    column and lives in HBM).  f32=True stores DOUBLE columns as float32
    (see the float32_compute session property).  `runtime_domains`
    (dynamic filtering) intersect with the statically pushed-down
    scan_domains for zone-map stripe pruning — query-specific, so the
    read bypasses the device cache exactly like a static domain scan."""
    base = getattr(table, "_device_cols", None)
    if base is None:
        base = table._device_cols = {}
    f32cache = None
    if f32:
        # only DOUBLE columns differ in f32 mode; everything else shares
        # the base cache (no duplicate uploads / HBM residency)
        f32cache = getattr(table, "_device_cols_f32", None)
        if f32cache is None:
            f32cache = table._device_cols_f32 = {}

    def cache_for(colname):
        # virtual pushdown columns are not in the schema (BOOLEAN)
        t = table.schema.get(colname)
        if f32 and t is not None and t.name == "DOUBLE":
            return f32cache
        return base

    needed = list(dict.fromkeys(node.assignments.values()))
    domains = getattr(node, "scan_domains", None)
    if runtime_domains and getattr(table, "supports_domain_pushdown",
                                   False):
        from presto_tpu.plan.domains import merge_domain_maps

        domains = merge_domain_maps(domains or {}, runtime_domains)
    if domains and getattr(table, "supports_domain_pushdown", False):
        # selective scan: the reader prunes stripes/row groups on the
        # pushed-down domains, so the result is QUERY-specific — it
        # bypasses the per-table device cache entirely (all needed
        # columns in ONE read call keeps row alignment)
        from presto_tpu.batch import column_from_numpy

        data = table.read(needed, domains=domains)
        cols = {}
        n = 0
        for sym, src in node.assignments.items():
            t = node.types[sym]
            col = column_from_numpy(data[src], t)
            if f32 and t.name == "DOUBLE":
                col = Column(col.data.astype(jnp.float32), col.valid,
                             col.type, col.dictionary)
            cols[sym] = Column(col.data, col.valid, t, col.dictionary)
            n = col.data.shape[0]
        return Batch(cols, jnp.ones((n,), bool))
    missing = [c for c in needed if c not in cache_for(c)]
    if missing:
        dev = None
        if hasattr(table, "device_columns"):
            # generator connectors produce columns ON DEVICE (one jitted
            # program, no host materialization or H2D upload)
            dev = table.device_columns(missing, f32=f32)
        if dev is not None:
            for c in missing:
                cache_for(c)[c] = dev[c]
        else:
            from presto_tpu.batch import column_from_numpy

            data = table.read(missing)
            for c in missing:
                t = table.schema.get(c, T.BOOLEAN)  # virtual: BOOLEAN
                col = column_from_numpy(data[c], t)
                if f32 and t.name == "DOUBLE":
                    col = Column(col.data.astype(jnp.float32), col.valid,
                                 col.type, col.dictionary)
                cache_for(c)[c] = col
    cols = {}
    n = None
    for sym, col in node.assignments.items():
        c = cache_for(col)[col]
        cols[sym] = Column(c.data, c.valid, node.types[sym], c.dictionary)
        n = c.data.shape[0]
    # ONE shared all-live sel per (table, capacity): scans of the same
    # table hand out identical (data, sel) array objects, which is what
    # lets the executor's sort-permutation memo fingerprint two scans of
    # the same key column as the same sort
    sel_key = ("__sel__", n or 0)
    sel = base.get(sel_key)
    if sel is None:
        sel = base[sel_key] = jnp.ones((n or 0,), bool)
    return Batch(cols, sel)


def _merge_range(a, b):
    """Union of two ColStats ranges (None-safe) for shared join-key packing."""
    from presto_tpu.plan.stats import ColStats

    if a is None or b is None or a.min is None or b.min is None \
            or a.max is None or b.max is None:
        return None
    return ColStats(min=min(a.min, b.min), max=max(a.max, b.max))


def _unify_key_dictionaries(lkeys: List[Column], rkeys: List[Column]):
    """Join keys that are string columns with different dictionaries are
    re-encoded into a merged dictionary so code equality == string equality."""
    from presto_tpu.batch import Dictionary
    from presto_tpu.exec.colval import translate_codes

    lout, rout = [], []
    for lc, rc in zip(lkeys, rkeys):
        if not lc.type.is_string or lc.dictionary is rc.dictionary:
            lout.append(lc)
            rout.append(rc)
            continue
        merged = Dictionary(np.unique(np.concatenate(
            [lc.dictionary.values, rc.dictionary.values])))
        llut = jnp.asarray(translate_codes(lc.dictionary, merged))
        rlut = jnp.asarray(translate_codes(rc.dictionary, merged))
        lout.append(Column(llut[jnp.clip(lc.data, 0, len(lc.dictionary) - 1)],
                           lc.valid, lc.type, merged))
        rout.append(Column(rlut[jnp.clip(rc.data, 0, len(rc.dictionary) - 1)],
                           rc.valid, rc.type, merged))
    return lout, rout


def _single_value(b: Batch):
    arrays, sel = to_numpy(b)
    sym = next(iter(arrays))
    vals = arrays[sym][sel]
    if len(vals) == 0:
        return 0, False
    if len(vals) > 1:
        raise ExecutionError("scalar subquery returned more than one row")
    v = vals[0]
    if np.ma.is_masked(v):
        return 0, False
    if isinstance(v, np.generic):
        v = v.item()
    return v, True
