"""Gather-aware kernel family: blocked (windowed) gathers for the
random-index materialization passes that dominate the chunked path.

Round-5 op-level profiling (docs/PERF.md) showed 4-5 random-gather
passes per chunk ARE the SF100 chunk program: TPU random gathers run at
a fixed ~45ns/index against ~300GB/s sequential HBM, so at 8M indices a
single materialization pass costs ~360ms while the sorts around it cost
~25ms.  This mirrors the memory-access-bound finding of *Global Hash
Tables Strike Back!* (random access, not hashing, dominates parallel
GROUP BY): the win is restructuring data movement, not faster scalar
code.

The family (routing lives in kernels.take_rows):

1. **Sort-order staging** — sort the indices once (co-sorting the
   request positions), gather in ASCENDING index order, and carry the
   rows home through ONE co-sort keyed on the positions (kernels.
   unpermute: payload operands ride a lax.sort nearly free, while an
   inverse-permutation gather would pay the full random-index cost a
   second time).  Ascending indices alone already help the DMA engine;
   the Pallas kernel below makes the locality explicit.

2. **Pallas block-gather** — with the indices sorted, each block of
   `_IB` consecutive indices covers a narrow source range.  The kernel
   pulls one aligned `W`-row source window per grid step through VMEM
   (a SEQUENTIAL HBM read, double-buffered by the Pallas pipeline via a
   scalar-prefetched window table) and picks rows VMEM-locally.  A
   runtime coverage check guards the static window size: skewed index
   blocks whose span exceeds `W` fall back — inside the same compiled
   program, via lax.cond — to the plain ascending-order XLA gather,
   which is always correct.

3. **Sort-order materialization** (exec/chunked.py + executor join
   sites) — when every consumer of the gathered batch is
   order-insensitive (aggregation, semi-join membership), the caller
   pre-permutes ALL row-aligned operands with kernels.sort_order_plan
   and skips the inverse permutation entirely: the batch simply STAYS
   in sorted-gather order.  This is the TPU analog of the reference's
   PagesIndex sort-order materialization (operator/PagesIndex.java,
   getSortedPages): produce output in the order the machine likes, not
   the order the rows arrived in.

CPU test meshes run the kernel under the Pallas interpreter; routing
constants were pinned with the gather microbench in tools/roofline.py
(swept over index count x row width, see docs/PERF.md round 6).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.exec import compile_cache

# ---------------------------------------------------------------------------
# routing constants (pinned by tools/roofline.py's gather sweep)
# ---------------------------------------------------------------------------

# below this index count the flat packed gather wins: two extra sorts
# (~25ms each at 6-8M rows, much less below) only amortize against the
# ~45ns/index random-gather constant once the index count is large
_STAGED_MIN_INDICES = 1 << 20

# staged request-order gathers pay one co-sort carrying all row words;
# payload operands are nearly free, so TWO u32 words (one i64 column)
# already clear the bar — same crossover the packed gather uses
_STAGED_MIN_WORDS = 2

# indices per Pallas grid step (one output block)
_IB = 1024

# the largest aligned source window one grid step may pull through VMEM
# (W * words * 4B; 8192 x 16 words = 512KB, comfortably inside VMEM
# next to the index and output blocks)
_MAX_WINDOW = 8192

# window sizing: expected span of _IB sorted indices is _IB * n/m rows;
# 2x headroom absorbs mild skew before the coverage cond bails
_WINDOW_SLACK = 2


def _env_mode() -> str:
    """PRESTO_TPU_GATHER: '' (auto: staged on TPU, flat elsewhere) |
    'flat' (disable staging) | 'sorted' (staging without the Pallas
    kernel — the safety valve if Mosaic ever rejects the kernel on a
    new TPU generation) | 'force' (staging even off-TPU: the CPU
    equivalence tests, which also shrink the routing constants)."""
    return os.environ.get("PRESTO_TPU_GATHER", "")


def _staging_enabled() -> bool:
    """Auto mode stages only on TPU: the blocked kernel runs in Pallas
    INTERPRET mode everywhere else, where a production-sized grid
    (1M+ indices / _IB) unrolls into an XLA CPU program that takes
    effectively forever to compile (observed: tpcds q37's static-bound
    join expansion hanging the CPU tier).  Tests opt in explicitly
    with PRESTO_TPU_GATHER=force after shrinking the constants."""
    mode = _env_mode()
    if mode == "flat":
        return False
    if mode in ("force", "sorted"):
        return True
    return jax.default_backend() == "tpu"


def gather_route(n: int, m: int, words: int,
                 presorted: bool = False) -> str:
    """Static routing for an m-index gather from an n-row, `words`-wide
    u32 source: 'flat' (XLA packed gather in request order) or 'staged'
    (ascending-order staging, Pallas-windowed when density allows).
    All inputs are trace-time constants — the route never host-syncs.

    presorted indices skip the sort AND the unpermute, so staging wins
    at any width; request-order gathers must clear _STAGED_MIN_WORDS to
    amortize the co-sort home."""
    if not _staging_enabled():
        return "flat"
    if m < _STAGED_MIN_INDICES or n <= 0 or words <= 0:
        return "flat"
    if not presorted and words < _STAGED_MIN_WORDS:
        return "flat"
    return "staged"


def sort_order_worthwhile(m: int, gain_words: int) -> bool:
    """Should a join pre-permute its expansion into build-index order
    (kernels.sort_order_plan)?  The permutation trades the wide side's
    random gather for a sequential one but turns the (previously
    ascending) probe-side expansion random, so it pays off only when
    the build rows are WIDER than the probe rows and the expansion is
    big enough to clear the staging threshold."""
    return (_staging_enabled() and m >= _STAGED_MIN_INDICES
            and gain_words > 0)


def window_rows(n: int, m: int) -> int | None:
    """Aligned VMEM window size (power of two) for a blocked gather, or
    None when the indices are too sparse for any window up to
    _MAX_WINDOW to cover a sorted block — staging then runs as the
    plain ascending-order gather (still the sort-order win, just
    without the explicit VMEM windows)."""
    if n <= 0 or m <= 0:
        return None
    span = _WINDOW_SLACK * _IB * n / m
    W = 1 << int(np.ceil(np.log2(max(span, _IB))))
    if W > _MAX_WINDOW:
        return None
    return int(W)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@partial(compile_cache.static_jit, static_argnames=("W", "IB"))
def _blocked_gather_call(blk, idx2, src, *, W: int, IB: int):
    """One Pallas launch: grid step i copies source window
    [blk[i]*W, blk[i]*W + W) into VMEM (sequential DMA, pipelined by
    the scalar-prefetched window table) and gathers its _IB indices
    VMEM-locally.  Caller guarantees coverage: every index in block i
    lies inside that window (checked by staged_gather's lax.cond)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m_pad = idx2.shape[1]
    w = src.shape[1]

    def kernel(blk_ref, idx_ref, src_ref, out_ref):
        i = pl.program_id(0)
        base = blk_ref[i] * np.int32(W)
        local = jnp.clip(idx_ref[0, :] - base, np.int32(0), np.int32(W - 1))
        # in-VMEM row pick: Mosaic lowers the dynamic take onto the VPU
        # (sublane gather); the HBM side of this step was the ONE
        # sequential window copy above
        out_ref[...] = jnp.take(src_ref[...], local, axis=0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m_pad // IB,),
        in_specs=[
            pl.BlockSpec((1, IB), lambda i, blk_ref: (0, i)),
            pl.BlockSpec((W, w), lambda i, blk_ref: (blk_ref[i], 0)),
        ],
        out_specs=pl.BlockSpec((IB, w), lambda i, blk_ref: (i, 0)),
    )
    # the engine runs with x64 on, but every operand and constant here
    # is explicitly 32-bit (u32/i32), so the kernel traces Mosaic-clean
    # without an x64-off scope (which would split the trace across two
    # promotion regimes — the interpreter rejects that)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, w), jnp.uint32),
        interpret=_interpret(),
    )(blk, idx2, src)


def staged_gather(src: jnp.ndarray, sidx: jnp.ndarray) -> jnp.ndarray:
    """Gather rows of a (n, w) u32 matrix at ASCENDING i32 indices.
    Routes through the Pallas block-gather when the density supports a
    VMEM window; a runtime coverage check falls back (lax.cond, no host
    sync) to the plain ascending-order XLA gather on skew.  Indices
    must be pre-clipped to [0, n)."""
    n, w = src.shape
    m = sidx.shape[0]
    W = window_rows(n, m)
    if W is None or m < _IB or _env_mode() == "sorted":
        return src[sidx]
    m_pad = -(-m // _IB) * _IB
    if m_pad != m:
        # edge-pad keeps the tail ascending (coverage math stays valid)
        sidx = jnp.pad(sidx, (0, m_pad - m), mode="edge")
    n_pad = -(-n // W) * W
    src_p = jnp.pad(src, ((0, n_pad - n), (0, 0))) if n_pad != n else src
    blk = (sidx[::_IB] // W).astype(jnp.int32)
    ends = sidx[_IB - 1::_IB]
    covered = jnp.all(ends < (blk + 1) * W)
    idx2 = sidx.reshape(1, -1)
    out = jax.lax.cond(
        covered,
        lambda a: _blocked_gather_call(a[0], a[1], a[2], W=W, IB=_IB),
        lambda a: a[2][a[1][0, :]],
        (blk, idx2, src_p))
    return out[:m]
